"""Async pipeline executor.

Reference analog: GStreamer's streaming model — every pad push runs on a
streaming thread, ``queue`` elements create stage boundaries, backpressure is
"push blocks until downstream returns" (SURVEY §1: "There is no 'scheduler'
layer — scheduling *is* GStreamer").  The TPU build supplies that analog
explicitly:

* each planned **stage** (an element, or a fused group of device elements —
  see plan.py) runs on its own runner thread with ONE bounded input queue;
* upstream pushes block when the queue is full → backpressure;
* EOS/error/caps events travel in-band through the same queues;
* device stages keep payloads as jax Arrays in HBM between stages (zero-copy),
  and the driver thread never blocks on device completion except at sinks —
  XLA's async dispatch overlaps H2D/compute/D2H exactly where the reference
  relied on GStreamer thread concurrency.

The executor is deliberately thread-based, not asyncio: stages do real
blocking work (device dispatch, host preprocessing) and the GIL is released
inside numpy/JAX, so threads give true overlap with far less machinery.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Deque, Dict, List, Optional, Tuple, Union

from ..core.buffer import Buffer, Event, batch_signature
from ..core.caps import Caps, MediaType
from ..core.config import get_config
from ..core.log import Timer, logger, metrics
from ..core.registry import KIND_ELEMENT, get as registry_get
from ..elements.base import Element, SinkElement, SourceElement, SRC
from ..utils import locks, tracing
from ..utils.armor import META_POISON as _META_POISON
from .graph import PipelineGraph
from .parser import parse as parse_launch
from .plan import Stage, plan_stages

log = logger(__name__)

#: in-band shutdown sentinel: Pipeline.stop() closes every stage queue with
#: one of these, so blocked getters wake instantly (no polling)
_POISON = object()


class PipelineError(RuntimeError):
    pass


class _StageQueue:
    """Bounded stage input queue with stop-aware blocking.

    Replaces the seed's ``queue.Queue`` + 0.1 s timeout polling: putters
    and getters block on condition variables, and :meth:`close` (called by
    ``Pipeline.stop()``) wakes every waiter at once — shutdown latency
    drops from worst-case ~100 ms per hop to ~0, and idle stages burn no
    CPU.  ``close`` also appends a ``(None, _POISON)`` item past the
    capacity bound so a getter that arrives later still returns
    immediately.

    TWO condition variables over one lock (queue.Queue's design), not one
    shared cv: a single cv needs ``notify_all`` on every put/get to be
    lost-wakeup-safe (a ``notify`` intended for a getter can land on a
    blocked putter, who re-waits without passing it on) — and that wakes
    every blocked producer per buffer, N-1 of which immediately re-block.
    With ``_not_empty``/``_not_full`` each put/get wakes exactly the ONE
    waiter that can make progress; ``notify_all`` survives only in
    :meth:`close`, where waking everyone is the point."""

    #: nns-tsan lock discipline (lint --threads verifies statically,
    #: NNS_TPU_TSAN=1 verifies live — docs/ANALYSIS.md "Threads pass")
    _GUARDED_BY = {"_dq": "_lock", "_closed": "_lock"}

    def __init__(self, capacity: int):
        self._dq: Deque = collections.deque()
        self._cap = max(1, capacity)
        self._lock = locks.make_lock("StageQueue._lock")
        self._not_empty = locks.make_condition(self._lock,
                                               name="StageQueue._not_empty")
        self._not_full = locks.make_condition(self._lock,
                                              name="StageQueue._not_full")
        self._closed = False

    def put(self, item) -> bool:
        """Block until space (backpressure); False = pipeline stopping and
        the item was shed."""
        with self._lock:
            while len(self._dq) >= self._cap:
                if self._closed:
                    return False
                self._not_full.wait()
            if self._closed:
                return False
            self._dq.append(item)
            self._not_empty.notify()
            return True

    def get(self, timeout: Optional[float] = None):
        """Block until an item arrives; ``(None, _POISON)`` once closed and
        drained; None on timeout (used by the batch linger wait)."""
        with self._lock:
            while not self._dq:
                if self._closed:
                    return (None, _POISON)
                if not self._not_empty.wait(timeout=timeout):
                    return None
            item = self._dq.popleft()
            self._not_full.notify()
            return item

    def get_nowait(self):
        """Non-blocking get; None when empty (the opportunistic drain)."""
        with self._lock:
            if not self._dq:
                return None
            item = self._dq.popleft()
            self._not_full.notify()
            return item

    def close(self) -> None:
        with self._lock:
            if not self._closed:
                self._closed = True
                self._dq.append((None, _POISON))
            self._not_empty.notify_all()
            self._not_full.notify_all()

    def qsize(self) -> int:
        with self._lock:
            return len(self._dq)

    def tenant_depths(self) -> Dict[str, int]:
        """Queued-buffer count per tenant (``meta['_tenant']``) — the
        sampler's per-tenant ``queue_depth`` source.  Cold path: scans a
        snapshot of the deque (bounded by capacity) under the lock."""
        with self._lock:
            items = list(self._dq)
        depths: Dict[str, int] = {}
        for it in items:
            if not (isinstance(it, tuple) and len(it) == 2):
                continue
            buf = it[1]
            if isinstance(buf, Buffer):
                ten = buf.meta.get(tracing.META_TENANT)
                if ten is not None:
                    depths[ten] = depths.get(ten, 0) + 1
        return depths


class _Port:
    """Destination of an edge: a stage's queue + the pad name inside it."""

    def __init__(self, stage: "_Runner", pad: str):
        self.stage = stage
        self.pad = pad


class _Runner:
    """One streaming thread driving one planned stage."""

    def __init__(self, pipeline: "Pipeline", stage: Stage, capacity: int):
        self.pipeline = pipeline
        self.stage = stage
        self.element = stage.element
        self.queue = _StageQueue(capacity)
        self.out_ports: Dict[str, List[_Port]] = {}
        self.thread = threading.Thread(
            target=self._run, name=f"nns-{self.element.name}", daemon=True
        )
        # Elements with their own receiver threads (query client) emit
        # downstream asynchronously, not just from process() returns.
        if getattr(self.element, "wants_async_emit", False):
            self.element._async_emit = self._emit
        self.in_pads: List[str] = []
        self._eos_pads: set = set()
        self._pending: Dict[str, List[Buffer]] = {}
        # Adaptive micro-batching: only device stages the planner marked
        # batchable drain >1 buffer; batch_max=1 keeps the exact seed path.
        # No ladder-top clamp anymore: bucket_for() LADDER-ROUNDS above
        # the top bucket (multiples of it), so a batch_max past the top
        # drains bigger dispatches with a still-bounded program census —
        # pipeline/batching.ladder() mirrors the exact compiled set.
        self.batch_max = pipeline.batch_max if stage.batchable else 1
        self.batch_linger_s = pipeline.batch_linger_ms / 1e3
        if stage.batchable:
            # elements build their BatchRunner lazily; hand them the
            # pipeline's bucket ladder the same way _async_emit is attached
            self.element._batch_buckets = pipeline.batch_buckets
            if pipeline.adaptive_buckets and self.batch_max > 1:
                # Adaptive ladder (docs/BATCHING.md "Adaptive ladder"):
                # per-stage, warm-startable, budget-closed.  Attached to
                # the ELEMENT like _batch_buckets; the lazy BatchRunner
                # reads it at first batched dispatch.
                from .batching import AdaptiveLadder, ladder as _ladder

                self.element._batch_ladder = AdaptiveLadder(
                    _ladder(self.batch_max, pipeline.batch_buckets),
                    budget=pipeline._ladder_budget,
                    warm=pipeline.bucket_ladders.get(self.element.name),
                    name=self.element.name)
        # In-flight dispatch window: a batching device stage may hold this
        # many dispatched-but-unemitted micro-batches, so the next drain
        # overlaps the previous (async) dispatch instead of waiting behind
        # the downstream feed.  Emission order is the FIFO deque's.
        self.dispatch_depth = (max(1, pipeline.dispatch_depth)
                               if self.batch_max > 1 else 1)
        self._inflight: Deque[Tuple[list, int, int]] = collections.deque()
        # Hot-path metric names built ONCE (the seed built f-strings per
        # buffer in _run_stream/_emit).
        name = self.element.name
        self._nm = name
        self._m_in = f"{name}.in"
        self._m_out = f"{name}.out"
        self._m_dropped = f"{name}.dropped"
        self._m_proc = f"{name}.proc"
        self._m_push = f"{name}.push"
        self._m_occupancy = f"{name}.batch_occupancy"
        self._m_qwait = f"{name}.queue_wait"
        self._m_e2e = f"{name}.e2e_latency"
        self._m_restarts = f"{name}.restarts"
        self._restarts = 0  # elastic in-place restarts taken so far
        #: _drain_batch pushback held ACROSS a restart: a carried item
        #: (often the EOS event) popped before the fault must survive
        #: re-entry, or a restarted stage would drop it and hang the
        #: pipeline waiting for an EOS nobody holds anymore
        self._carry = None
        #: buffers in the hands of process()/process_batch() right now —
        #: what a restart actually loses (counted into .dropped)
        self._proc_n = 0
        # Flight recorder (docs/OBSERVABILITY.md): None when trace_mode is
        # off — every instrumentation site below reduces to one pointer
        # check, and no meta stamps are written (the untraced code path).
        self._tr = tracing.recorder if pipeline.trace_mode != "off" else None
        # Attached to the ELEMENT the same way _batch_buckets is, so the
        # sink's fetch span and the lazy BatchRunner's shard span follow
        # THIS pipeline's trace_mode, not whatever another pipeline in the
        # process switched the global recorder to.
        self.element._trace_rec = self._tr
        # nns-xray registry handle (None = off): the fused program,
        # BatchRunner buckets, and framework jit paths read it at build
        # time.  A folded device source wraps a FusedElement that is NOT
        # in pipeline.elements — forward both handles to it.
        self.element._xray = pipeline._xray_reg
        fused_inner = getattr(self.element, "fused", None)
        if fused_inner is not None:
            fused_inner._xray = pipeline._xray_reg
            fused_inner._trace_rec = self._tr
        self._is_sink = isinstance(self.element, SinkElement)
        self._last_sink_ns = 0  # sampler reads: staleness watermark
        self._max_pts = None  # watermark_pts gauge is a high-water mark
        self._gauge_tenants: set = set()  # tenants with a depth gauge

    # -- wiring ------------------------------------------------------------
    def connect(self, out_pad: str, port: _Port) -> None:
        self.out_ports.setdefault(out_pad, []).append(port)

    # -- data plane --------------------------------------------------------
    def feed(self, pad: str, item: Union[Buffer, Event]) -> None:
        """Blocking put (backpressure point); sheds the item when the
        pipeline is stopping."""
        if self._tr is not None and isinstance(item, Buffer):
            # Queue-wait span start, keyed by the CONSUMING stage so
            # fan-out is exact: a tee'd buffer shares one meta dict
            # across branches, but each branch's consumer pops only its
            # own stamp.  The stamp map is rebuilt (copy + own entry)
            # rather than mutated in place so two buffers that INHERITED
            # one map (meta copies of a shared frame) fed into the same
            # stage never overwrite each other's start time.
            stamps = item.meta.get(tracing.META_ENQUEUE_NS)
            base = stamps if isinstance(stamps, dict) else {}
            item.meta[tracing.META_ENQUEUE_NS] = {
                **base, self._nm: time.monotonic_ns()}
        self.queue.put((pad, item))

    def _emit(self, outs: List[Tuple[str, Union[Buffer, Event]]]) -> None:
        for out_pad, item in outs:
            ports = self.out_ports.get(out_pad, [])
            if not ports and isinstance(item, Buffer):
                metrics.count(self._m_dropped)
                continue
            for port in ports:
                # Deferred host-post buffers stay lazy all the way to sinks
                # (resolved in the app thread); any mid-pipeline host element
                # needs the real payload now.
                if (
                    isinstance(item, Buffer)
                    and "_host_post" in item.meta
                    and not isinstance(port.stage.element, SinkElement)
                ):
                    item = item.resolve()
                port.stage.feed(port.pad, item)

    def _broadcast(self, item) -> None:
        for ports in self.out_ports.values():
            for port in ports:
                port.stage.feed(port.pad, item)

    # -- main loop ---------------------------------------------------------
    def _run(self) -> None:
        el = self.element
        while True:
            try:
                if isinstance(el, SourceElement):
                    self._run_source()
                else:
                    self._run_stream()
                return
            except Exception as e:  # noqa: BLE001 - must not kill process
                if (self.stage.restartable
                        and not isinstance(el, SourceElement)
                        # restart ONLY faults raised inside process()/
                        # process_batch() (_proc_n is set around exactly
                        # those calls): an exception while handling an
                        # already-consumed EVENT (EOS -> finalize) has
                        # irreversibly eaten it, and re-entering the
                        # loop would block on an empty queue forever
                        # instead of broadcasting EOS
                        and self._proc_n > 0
                        and self._restarts
                        < self.pipeline.max_stage_restarts
                        and not self.pipeline._stopping.is_set()):
                    # Elastic stage restart (docs/SERVING.md "Elastic
                    # serving"): a pure/stateless stage holds no cross-
                    # buffer state, so re-entering its loop after an
                    # exception loses exactly the one buffer that
                    # triggered it.  Prior in-flight batches completed
                    # fine — deliver them first so ordering holds.
                    self._restarts += 1
                    metrics.count(self._m_restarts)
                    metrics.count(self._m_dropped, max(1, self._proc_n))
                    self._proc_n = 0
                    log.warning(
                        "stage %s failed (%r); restarting in place "
                        "(%d/%d)", el.name, e, self._restarts,
                        self.pipeline.max_stage_restarts)
                    try:
                        self._flush_inflight()
                    except Exception:  # noqa: BLE001
                        log.exception(
                            "in-flight flush failed for %s", el.name)
                    continue
                log.exception("stage %s failed", el.name)
                self.pipeline._record_error(el.name, e)
                try:
                    # Batches dispatched BEFORE the failing one completed
                    # fine and are still held in the in-flight window —
                    # deliver them (downstream queues are open on this
                    # path) before the error/EOS, exactly what
                    # dispatch_depth=1 would have done.
                    self._flush_inflight()
                except Exception:  # noqa: BLE001 - must still broadcast
                    log.exception("in-flight flush failed for %s", el.name)
                self._broadcast(Event.error(e))
                self._broadcast(Event.eos())
                return

    def _run_source(self) -> None:
        el = self.element
        tr = self._tr
        for item in el.generate():
            if self.pipeline._stopping.is_set():
                break
            if tr is not None:
                buf = item[1] if isinstance(item, tuple) else item
                if isinstance(buf, Buffer):
                    # INGRESS: the per-buffer trace id is born here and
                    # rides Buffer.meta through every derived buffer
                    # downstream (with_tensors copies meta; the runner
                    # back-fills fresh Buffers — see _propagate_trace).
                    tid = buf.meta.get(tracing.META_TRACE_ID)
                    if tid is None:
                        tid = tracing.next_trace_id()
                        buf.meta[tracing.META_TRACE_ID] = tid
                    t = time.monotonic_ns()
                    buf.meta[tracing.META_INGRESS_NS] = t
                    # the pipeline's default tenant is stamped HERE —
                    # inside the traced branch only, so the off path
                    # stays stamp-free (an element-level tenant, e.g.
                    # appsrc tenant= or the query wire meta, is app data
                    # and rides regardless of trace mode)
                    ten = buf.meta.get(tracing.META_TENANT)
                    if ten is None and self.pipeline.tenant is not None:
                        ten = self.pipeline.tenant
                        buf.meta[tracing.META_TENANT] = ten
                    if ten is None:
                        tr.record("ingress", self._nm, tid, t, 0,
                                  pts=buf.pts)
                    else:
                        tr.record("ingress", self._nm, tid, t, 0,
                                  pts=buf.pts, tenant=ten)
            with Timer(self._m_push):
                self._emit([(SRC, item)] if not isinstance(item, tuple) else [item])
            metrics.count(self._m_out)
        self._emit(el.finalize())
        self._broadcast(Event.eos())

    def _drain_batch(self, pad: str, first: Buffer):
        """Opportunistically drain up to batch_max-1 more already-queued
        compatible buffers (same pad, same tensor signature).  No waiting
        by default — latency is never traded for occupancy unless
        batch_linger_ms > 0.  Returns (batch, carry): ``carry`` is the
        first non-stackable item popped (an event, another pad, a
        different spec), which must be handled AFTER the batch so stream
        order is preserved."""
        batch = [first]
        sig = batch_signature(first)
        deadline = None
        while len(batch) < self.batch_max:
            nxt = self.queue.get_nowait()
            if nxt is None:
                if self.batch_linger_s <= 0.0:
                    break
                if deadline is None:
                    deadline = time.monotonic() + self.batch_linger_s
                remaining = deadline - time.monotonic()
                if remaining <= 0.0:
                    break
                nxt = self.queue.get(timeout=remaining)
                if nxt is None:
                    break
            npad, nitem = nxt
            if (nitem is _POISON or isinstance(nitem, Event)
                    or npad != pad or batch_signature(nitem) != sig):
                return batch, nxt
            batch.append(nitem)
        return batch, None

    def _emit_oldest_inflight(self) -> None:
        outs, n, t_disp = self._inflight.popleft()
        if self._tr is not None and t_disp:
            first = next((o for _, o in outs if isinstance(o, Buffer)),
                         None)
            tid = first.meta.get(tracing.META_TRACE_ID) \
                if first is not None else None
            ten = first.meta.get(tracing.META_TENANT) \
                if first is not None else None
            args = {"rows": n}
            if ten is not None:
                args["tenant"] = ten
            self._tr.record("inflight", self._nm, tid, t_disp,
                            time.monotonic_ns() - t_disp, **args)
        self._emit(outs)
        metrics.count(self._m_out, n)

    # -- tracing helpers ---------------------------------------------------
    def _propagate_trace(self, ins: List[Buffer], outs) -> None:
        """Back-fill trace meta onto output buffers an element built from
        scratch (with_tensors already copies meta).  Row-aligned when the
        element emitted one output per input (the batch contract);
        otherwise every output inherits the first input's identity
        (fan-out: tee/demux branches share the frame's trace id)."""
        if not outs:
            return
        aligned = len(outs) == len(ins)
        for i, (_, o) in enumerate(outs):
            if not isinstance(o, Buffer):
                continue
            src = ins[i] if aligned else ins[0]
            if tracing.META_TRACE_ID not in o.meta:
                o.meta[tracing.META_TRACE_ID] = \
                    src.meta.get(tracing.META_TRACE_ID)
            if (tracing.META_INGRESS_NS not in o.meta
                    and tracing.META_INGRESS_NS in src.meta):
                o.meta[tracing.META_INGRESS_NS] = \
                    src.meta[tracing.META_INGRESS_NS]

    def _trace_queue_wait(self, buf: Buffer, end_ns: int) -> Optional[int]:
        """Record the queue-wait span for one consumed buffer; returns its
        trace id.  Pops THIS stage's entry from the per-branch stamp map
        (see :meth:`feed`), so fan-out branches each get their exact wait
        and nothing double-counts."""
        tid = buf.meta.get(tracing.META_TRACE_ID)
        stamps = buf.meta.get(tracing.META_ENQUEUE_NS)
        tq = None
        if isinstance(stamps, dict):
            tq = stamps.pop(self._nm, None)
            if not stamps:
                # drained map: drop the key so delivered buffers (and
                # wire-encoded responses) stay as clean as pre-fan-out
                buf.meta.pop(tracing.META_ENQUEUE_NS, None)
        if tq is not None and end_ns >= tq:
            ten = buf.meta.get(tracing.META_TENANT)
            if ten is None:
                self._tr.record("queue", self._nm, tid, tq, end_ns - tq)
            else:
                self._tr.record("queue", self._nm, tid, tq, end_ns - tq,
                                tenant=ten)
            metrics.observe_latency(self._m_qwait, (end_ns - tq) / 1e9,
                                    tenant=ten)
        return tid

    def _trace_sink_delivery(self, buf: Buffer, end_ns: int) -> None:
        """End-to-end span + staleness/watermark state at sink delivery.
        A tenant on the buffer splits the e2e histogram per tenant and
        puts the span on the tenant's own Chrome-trace track."""
        self._last_sink_ns = end_ns
        if buf.pts is not None and (self._max_pts is None
                                    or buf.pts > self._max_pts):
            # high-water mark, matching the exposed HELP text: mux/tee
            # fan-in can deliver pts out of order
            self._max_pts = buf.pts
            metrics.gauge(f"{self._nm}.watermark_pts", float(buf.pts))
        ts0 = buf.meta.get(tracing.META_INGRESS_NS)
        if ts0 is not None and end_ns >= ts0:
            ten = buf.meta.get(tracing.META_TENANT)
            metrics.observe_latency(self._m_e2e, (end_ns - ts0) / 1e9,
                                    tenant=ten)
            tid = buf.meta.get(tracing.META_TRACE_ID)
            if ten is None:
                self._tr.record("e2e", self._nm, tid, ts0, end_ns - ts0)
            else:
                self._tr.record("e2e", self._nm, tid, ts0, end_ns - ts0,
                                tenant=ten)

    def _trace_batch(self, batch: List[Buffer], outs, tdr0: int,
                     dt: float) -> None:
        """Spans for one micro-batch: per-member queue waits, the batch
        formation window (first buffer in hand -> dispatch), and the
        dispatch span LINKING every member row's trace id — so the
        amortized device time (``per_row_ns``) is attributable per row
        even though XLA saw one program call."""
        tr = self._tr
        tids = [self._trace_queue_wait(b, tdr0) for b in batch]
        n = len(batch)
        dur = int(dt * 1e9)
        disp0 = time.monotonic_ns() - dur
        # per-tenant stage-latency split: each member row's tenant gets
        # the amortized per-row time (the batch's base .proc observation
        # already happened in the caller)
        tens = [b.meta.get(tracing.META_TENANT) for b in batch]
        for ten in tens:
            if ten is not None:
                metrics.observe_latency_labeled(self._m_proc, dt / n, ten)
        if n > 1:
            # row-aligned tenants list (like trace_ids): dominant-span
            # attribution credits each tenant its share of the span
            extra = {"tenants": tens} if any(t is not None
                                             for t in tens) else {}
            tr.record("batch", self._nm, tids[0], tdr0,
                      max(0, disp0 - tdr0), trace_ids=tids, rows=n,
                      **extra)
            tr.record("stage", self._nm, tids[0], disp0, dur,
                      trace_ids=tids, rows=n, per_row_ns=dur // n,
                      **extra)
        else:
            ten = batch[0].meta.get(tracing.META_TENANT)
            if ten is None:
                tr.record("stage", self._nm, tids[0], disp0, dur)
            else:
                tr.record("stage", self._nm, tids[0], disp0, dur,
                          tenant=ten)
        self._propagate_trace(batch, outs)

    def _flush_inflight(self) -> None:
        while self._inflight:
            self._emit_oldest_inflight()

    # -- nns-armor: poison-pill quarantine (docs/ROBUSTNESS.md) ------------
    def _invoke(self, el, pad: str, batch: List[Buffer]):
        """The stage invoke, armored when ``Pipeline(quarantine=...)`` /
        ``nan_guard`` is configured: an exception (or a NaN/Inf output
        under nan_guard) quarantines the triggering request(s) to the
        DLQ and substitutes typed ``abort_reason=poison`` terminators —
        the pipeline keeps serving instead of restarting/failing.
        Sinks keep the pre-armor semantics (a send failure is not a
        poisoned request)."""
        n = len(batch)
        armor = self.pipeline._armor
        if armor is None or self._is_sink:
            return (el.process_batch(pad, batch) if n > 1
                    else el.process(pad, batch[0]))
        try:
            outs = (el.process_batch(pad, batch) if n > 1
                    else el.process(pad, batch[0]))
        except Exception as e:  # noqa: BLE001 - the quarantine contract
            return self._poison_outs(armor, pad, batch, e)
        if armor.nan_guard and outs:
            outs = self._nan_screen(armor, batch, outs)
        return outs

    def _poison_outs(self, armor, pad: str, batch: List[Buffer],
                     err: BaseException):
        """A failed invoke becomes poison terminators — but only for the
        buffers that actually poison.  A failed micro-BATCH is re-invoked
        one buffer at a time (batchable stages are pure by the planner's
        own rules, so re-running the innocent rows is safe): one
        malicious tenant's pill must not quarantine — and breaker-
        penalize — every request that happened to share its dispatch."""
        from ..utils import armor as _armor_mod

        el = self.element
        outs = []
        for b in batch:
            row_err = err
            if len(batch) > 1:
                try:
                    row_outs = el.process(pad, b)
                except Exception as e:  # noqa: BLE001 - the real pill
                    row_err = e
                else:
                    if armor.nan_guard and row_outs:
                        # the retry path must not bypass the screen the
                        # batched path would have applied
                        row_outs = self._nan_screen(armor, [b],
                                                    row_outs)
                    outs.extend(row_outs)
                    continue
            metrics.count(f"{self._nm}.poisoned")
            armor.quarantine(b, error=row_err, stage=self._nm)
            outs.append((SRC, _armor_mod.poison_terminator(b, row_err)))
        return outs

    def _nan_screen(self, armor, batch: List[Buffer], outs):
        """nan_guard: replace non-finite stage outputs with poison
        terminators — row-aligned to inputs when the element honored
        the one-output-per-input batch contract, counting BUFFER
        outputs only (an interleaved event must not shift which source
        request gets quarantined and breaker-penalized)."""
        from ..utils import armor as _armor_mod

        n_buf_outs = sum(1 for _, o in outs if isinstance(o, Buffer))
        aligned = n_buf_outs == len(batch)
        screened = []
        row = 0
        for out_pad, o in outs:
            if not isinstance(o, Buffer):
                screened.append((out_pad, o))
                continue
            if armor.nonfinite(o):
                src = batch[row] if aligned else batch[0]
                err = FloatingPointError(
                    "non-finite stage output (nan_guard)")
                metrics.count(f"{self._nm}.poisoned")
                armor.quarantine(src, error=err, stage=self._nm)
                screened.append(
                    (SRC, _armor_mod.poison_terminator(src, err)))
            else:
                screened.append((out_pad, o))
            row += 1
        return screened

    def _run_stream(self) -> None:
        el = self.element
        all_policy = el.sync_policy == "all" and len(self.in_pads) > 1
        batching = self.batch_max > 1 and not all_policy
        depth = self.dispatch_depth if batching else 1
        # pushback lives on self (not a local) so an elastic restart
        # re-enters with the carried item — losing it would lose an EOS
        while True:
            if self._carry is not None:
                pad, item = self._carry
                self._carry = None
            else:
                nxt = None
                if self._inflight:
                    # Dispatch window open: only keep batches in flight
                    # while more work is ALREADY queued — before blocking,
                    # emit everything held, or idle streams would pay the
                    # window as pure latency.
                    nxt = self.queue.get_nowait()
                    if nxt is None:
                        self._flush_inflight()
                if nxt is None:
                    nxt = self.queue.get()
                pad, item = nxt
            if item is _POISON:
                # stop(): downstream queues are already closed, so the
                # flush sheds — but a future clean-shutdown path stays
                # correct if close semantics ever change.
                self._flush_inflight()
                return
            if isinstance(item, Event):
                # Events are ordering fences: everything dispatched before
                # the event arrived must be emitted before it is handled.
                self._flush_inflight()
                if item.kind == "eos":
                    self._eos_pads.add(pad)
                    if all_policy:
                        self._try_groups()
                    if self._eos_pads >= set(self.in_pads):
                        self._emit(el.finalize())
                        self._broadcast(Event.eos())
                        return
                    continue
                if item.kind == "error":
                    self._broadcast(item)
                    continue
                self._emit(el.on_event(pad, item))
                continue
            if (not self._is_sink and not all_policy
                    and isinstance(item, Buffer)
                    and item.meta.get(_META_POISON)):
                # a poison terminator is an ANSWER riding to the sink
                # (utils/armor.py), never work: forward it untouched so
                # downstream stages cannot crash on its empty payload.
                # NOT on sync_policy="all" stages: skipping the pairing
                # logic would permanently misalign the other pads'
                # streams — a collator fed a terminator pairs (and may
                # fail loudly) instead of silently merging off-by-one.
                self._flush_inflight()
                metrics.count(self._m_in)
                self._emit([(SRC, item)])
                metrics.count(self._m_out)
                continue
            if all_policy:
                metrics.count(self._m_in)
                self._pending.setdefault(pad, []).append(item)
                self._try_groups()
                continue
            tr = self._tr
            if batching:
                tdr0 = time.monotonic_ns() if tr is not None else 0
                batch, self._carry = self._drain_batch(pad, item)
                n = len(batch)
                metrics.count(self._m_in, n)
                # real cumulative histogram (ladder-shaped buckets), not
                # just the quantile reservoir: the adaptive ladder and
                # Prometheus read the same occupancy stream
                metrics.observe_bucketed(self._m_occupancy, float(n))
                t0 = time.perf_counter()
                self._proc_n = n
                outs = self._invoke(el, pad, batch)
                self._proc_n = 0
                # PER-BUFFER proc time: the .proc series must keep one
                # meaning whether batching is on or off (same rule the
                # filter applies to its .invoke series)
                dt = time.perf_counter() - t0
                metrics.observe_latency(self._m_proc, dt / n)
                if tr is not None:
                    self._trace_batch(batch, outs, tdr0, dt)
                if depth > 1:
                    # Software pipeline: XLA dispatch is async, so the
                    # runner loops back to drain the NEXT micro-batch
                    # while this one executes; emission (which may block
                    # on a full downstream queue) is deferred FIFO until
                    # the window fills.
                    self._inflight.append(
                        (outs, n,
                         time.monotonic_ns() if tr is not None else 0))
                    while len(self._inflight) >= depth:
                        self._emit_oldest_inflight()
                else:
                    self._emit(outs)
                    metrics.count(self._m_out, n)
                if self._carry is not None and self._carry[1] is _POISON:
                    self._flush_inflight()
                    return
                continue
            metrics.count(self._m_in)
            self._proc_n = 1
            if tr is None:
                with Timer(self._m_proc):
                    outs = self._invoke(el, pad, [item])
            else:
                now0 = time.monotonic_ns()
                tid = self._trace_queue_wait(item, now0)
                ten = item.meta.get(tracing.META_TENANT)
                t0 = time.perf_counter()
                outs = self._invoke(el, pad, [item])
                dt = time.perf_counter() - t0
                metrics.observe_latency(self._m_proc, dt, tenant=ten)
                dur = int(dt * 1e9)
                if ten is None:
                    tr.record("stage", self._nm, tid, now0, dur)
                else:
                    tr.record("stage", self._nm, tid, now0, dur,
                              tenant=ten)
                self._propagate_trace([item], outs)
                if self._is_sink:
                    self._trace_sink_delivery(item, now0 + dur)
            self._proc_n = 0
            self._emit(outs)
            metrics.count(self._m_out)

    def _try_groups(self) -> None:
        """Collate one buffer per pad (slowest-pad sync; reference:
        tensor_mux sync-mode=slowest).  A pad keeps pairing from its pending
        queue after EOS — data queued before EOS must still pair up.  Once
        any pad is EOS'd AND drained no complete group can ever form again,
        so remaining unpairable buffers are dropped: emitting a partial
        group would violate the element's negotiated caps (e.g. a 2-tensor
        mux emitting 1 tensor)."""
        el = self.element
        while True:
            dead = [
                p
                for p in self.in_pads
                if p in self._eos_pads and not self._pending.get(p)
            ]
            if dead:
                n = sum(len(v) for v in self._pending.values())
                if n:
                    metrics.count(self._m_dropped, n)
                    self._pending.clear()
                return
            if not all(self._pending.get(p) for p in self.in_pads):
                return
            group = {p: self._pending[p].pop(0) for p in self.in_pads}
            tr = self._tr
            if tr is None:
                with Timer(self._m_proc):
                    outs = el.process_group(group)
            else:
                members = list(group.values())
                now0 = time.monotonic_ns()
                tids = [self._trace_queue_wait(b, now0) for b in members]
                t0 = time.perf_counter()
                outs = el.process_group(group)
                dt = time.perf_counter() - t0
                metrics.observe_latency(self._m_proc, dt)
                # collation span LINKS every contributing pad's trace id
                # (the mux/collator fan-in analog of the batch linkage)
                tr.record("stage", self._nm, tids[0], now0, int(dt * 1e9),
                          trace_ids=tids)
                self._propagate_trace([members[0]], outs)
                if self._is_sink:
                    self._trace_sink_delivery(
                        members[0], now0 + int(dt * 1e9))
            self._emit(outs)
            metrics.count(self._m_out)


#: tensor_filter ``framework=`` names that resolve to the llm framework
#: (mirrors analysis/tracecheck.py; kept literal so the hot import path
#: stays free of filters/llm.py)
_LLM_FRAMEWORKS = ("llm", "llamacpp", "llama.cpp")


def _llm_tp_alias(graph: PipelineGraph) -> int:
    """Largest deprecated ``custom=tp:N`` option on any llm tensor_filter
    in the graph (1 = none).  The alias is promoted to
    ``Pipeline(model_parallel=N)`` at construction so the filter runs on
    the pipeline's shared mesh instead of minting a private one."""
    tp = 1
    for node in graph.nodes.values():
        if node.kind != "tensor_filter":
            continue
        if str(node.props.get("framework", "")).lower() \
                not in _LLM_FRAMEWORKS:
            continue
        from ..filters.base import parse_custom_options

        opts = parse_custom_options(str(node.props.get("custom", "")))
        try:
            tp = max(tp, int(opts.get("tp", 1)))
        except (TypeError, ValueError):
            pass  # non-literal tp: the filter's own open() will reject it
    return tp


class Pipeline:
    """Build + run a pipeline graph.

    Accepts a pipeline description string or a parsed PipelineGraph.
    ``fuse=True`` lets the planner merge adjacent device-capable elements
    into single jitted XLA stages.  ``queue_capacity`` bounds each stage's
    input queue (backpressure); ``batch_max`` > 1 additionally lets device
    stages drain up to that many already-queued same-spec buffers into ONE
    bucketed XLA dispatch (``batch_buckets`` bounds the compiled batch
    sizes, ``batch_linger_ms`` optionally waits for stragglers — see
    docs/BATCHING.md).  ``adaptive_buckets`` lets each batchable stage
    refine its OWN ladder online from observed drain occupancies
    (persistent skew mints an exact bucket under a hard census budget —
    docs/BATCHING.md "Adaptive ladder"), and ``bucket_ladders`` warm-
    starts those ladders from a previous run's :meth:`ladder_snapshot`
    export so steady-state deployments compile the refined ladder at
    warmup.  ``data_parallel`` shards those bucketed dispatches
    over the ``data`` axis of a local device mesh (0 = every local device,
    1 = single-device dispatch, N = exactly N chips; the mesh is built
    lazily at :meth:`start`, off the streaming threads, and only
    shard-eligible stages see it), and ``dispatch_depth`` opens an
    in-flight window so a runner drains the next micro-batch while the
    previous one is still executing — see BATCHING.md "Sharded dispatch".
    ``model_parallel`` adds the second mesh axis: the SAME pipeline mesh
    grows a ``model`` dimension (1 = off, N = exactly N ways, 0 = absorb
    every local device ``data`` doesn't claim — see
    ``pipeline/plan.mesh_plan``), shardable stages place their parameters
    per their models' ``param_pspecs`` (sharded over ``model``, replicated
    otherwise), and the llm filter runs tensor-parallel on the shared mesh
    — including its paged KV block pool, sharded over ``model`` on the
    head dim (``custom=tp:N`` is a deprecated alias promoted to this
    knob).  ``NNS_TPU_MODEL_PARALLEL`` / ini ``model_parallel`` configure
    it globally; see docs/BATCHING.md "2-D sharded dispatch".
    ``fetch_depth`` is the OUTPUT-side twin: up to that many sink buffers
    resolve D2H / deferred host_post concurrently on a background pool, so
    fetches overlap the next dispatch instead of serializing in ``pop()``;
    ``donate_ingress`` donates host-fed (appsrc) input buffers to the
    fused program so steady-state H2D reuses HBM; ``reduce_outputs`` lets
    the HBM-residency planner auto-select a model's reduced output (e.g.
    deeplab's native-stride class map) when every downstream consumer's
    caps admit it — see docs/FETCH.md.  The plan is exposed as
    ``Pipeline.residency``.
    ``trace_mode`` (``off``/``ring``/``full``) switches on the per-buffer
    flight recorder: span events for every stage/queue/batch/dispatch
    keyed by trace ids assigned at source ingress, dumped with
    :meth:`dump_trace` as Perfetto-loadable Chrome trace JSON and to the
    log on watchdog fires / stage errors — docs/OBSERVABILITY.md.
    ``xray`` switches on nns-xray predicted-vs-actual reconciliation
    (utils/xray.py): every jit entry point registers its compiles with a
    live program census reconciled against the deep lint's prediction
    (census-drift warnings with signature diffs), per-stage ``mfu`` /
    ``roofline_fraction`` / ``pad_waste_flops`` land in Prometheus and a
    ``device:<stage>`` track in the Chrome trace, and an HBM ledger is
    reconciled per category against the static estimate —
    :meth:`explain` / ``python -m nnstreamer_tpu.tools.doctor`` join it
    all into one report (docs/OBSERVABILITY.md "Predicted vs actual").
    ``tenant`` sets a default tenant identity stamped at source ingress
    (traced runs only) so latency histograms, queue-depth gauges, and
    Chrome-trace tracks split per tenant; ``slo`` attaches a per-tenant
    SLO policy (:mod:`nnstreamer_tpu.utils.slo`) evaluated continuously
    while the pipeline runs, with :meth:`slo_report` as the on-demand
    verdict — docs/SERVING.md "Front door".
    Defaults come from :func:`get_config`.

    ``quarantine`` / ``nan_guard`` / ``journal_replay`` are the
    nns-armor knobs (docs/ROBUSTNESS.md): a DLQ directory (or policy)
    that turns stage-crashing poison-pill requests into quarantined
    records + typed ``abort_reason=poison`` answers with a per-tenant
    repeat-offender circuit breaker; an opt-in NaN/Inf output screen;
    and the restart flag asking every journaled query serversrc to
    re-admit its accepted-but-unanswered WAL entries exactly once.
    ``validate=True`` runs the full static analyzer (caps propagation,
    topology/deadlock, jit-purity — see docs/ANALYSIS.md) over the parsed
    graph before anything is instantiated and raises
    :class:`~nnstreamer_tpu.analysis.PipelineLintError` carrying EVERY
    error at once, instead of the runtime's one-failure-per-start loop.
    ``validate="deep"`` additionally abstractly executes every device
    stage (``jax.eval_shape`` — zero dispatch) so shape/dtype contract
    violations and tracing failures raise HERE too, with this pipeline's
    own batch/sharding knobs feeding the static HBM/recompile budgets
    (docs/ANALYSIS.md "Deep pass").
    """

    def __init__(
        self,
        graph: Union[str, PipelineGraph],
        *,
        fuse: bool = True,
        queue_capacity: Optional[int] = None,
        batch_max: Optional[int] = None,
        batch_buckets: Optional[List[int]] = None,
        batch_linger_ms: Optional[float] = None,
        adaptive_buckets: Optional[bool] = None,
        bucket_ladders: Optional[Dict[str, List[int]]] = None,
        data_parallel: Optional[int] = None,
        model_parallel: Optional[int] = None,
        dispatch_depth: Optional[int] = None,
        fetch_depth: Optional[int] = None,
        donate_ingress: Optional[bool] = None,
        reduce_outputs: Optional[bool] = None,
        trace_mode: Optional[str] = None,
        tenant: Optional[str] = None,
        xray: Optional[bool] = None,
        slo=None,
        max_stage_restarts: Optional[int] = None,
        quarantine=None,
        nan_guard: bool = False,
        journal_replay: bool = False,
        validate: Union[bool, str] = False,
    ):
        if validate:
            # Lint BEFORE strict validation: the analyzer reports every
            # problem at once where parse/validate stop at the first.
            # Strings are parsed ONCE (leniently) and the same graph flows
            # on to graph.validate() below.
            from ..analysis import analyze

            deep = validate == "deep"
            kw = dict(queue_capacity=queue_capacity, deep=deep)
            if deep:
                # the deep pass budgets with THIS pipeline's knobs, not
                # just the global config defaults
                kw.update(batch_max=batch_max, batch_buckets=batch_buckets,
                          adaptive_buckets=adaptive_buckets,
                          data_parallel=data_parallel,
                          model_parallel=model_parallel,
                          dispatch_depth=dispatch_depth)
            if isinstance(graph, str):
                source = graph
                graph = parse_launch(graph, validate=False)
                report = analyze(graph, **kw)
                report.source = source
                report.raise_if_errors()
            else:
                analyze(graph, **kw).raise_if_errors()
        if isinstance(graph, str):
            graph = parse_launch(graph)
        graph.validate()
        # Start the native-lib build (if any) now, off the streaming threads.
        from ..native import prewarm

        prewarm()
        cfg = get_config()
        self.graph = graph
        self.fuse = fuse
        self.capacity = queue_capacity or cfg.queue_capacity
        self.batch_max = max(
            1, batch_max if batch_max is not None else cfg.batch_max)
        self.batch_buckets = list(
            batch_buckets if batch_buckets is not None else cfg.batch_buckets
        ) or None
        self.batch_linger_ms = float(
            batch_linger_ms if batch_linger_ms is not None
            else cfg.batch_linger_ms)
        # Adaptive bucket ladder (docs/BATCHING.md "Adaptive ladder"):
        # per-stage ladders refined from observed occupancies, warm-started
        # from a previous run's ladder_snapshot() export.
        self.adaptive_buckets = bool(
            adaptive_buckets if adaptive_buckets is not None
            else cfg.adaptive_buckets)
        self.bucket_ladders: Dict[str, List[int]] = dict(
            bucket_ladders if bucket_ladders is not None
            else cfg.bucket_ladders)
        self.data_parallel = max(0, int(
            data_parallel if data_parallel is not None
            else cfg.data_parallel))
        self.model_parallel = max(0, int(
            model_parallel if model_parallel is not None
            else cfg.model_parallel))
        self.dispatch_depth = max(1, int(
            dispatch_depth if dispatch_depth is not None
            else cfg.dispatch_depth))
        self.fetch_depth = max(1, int(
            fetch_depth if fetch_depth is not None else cfg.fetch_depth))
        self.donate_ingress = bool(
            donate_ingress if donate_ingress is not None
            else cfg.donate_ingress)
        self.reduce_outputs = bool(
            reduce_outputs if reduce_outputs is not None
            else cfg.reduce_outputs)
        # elastic stage restarts (docs/SERVING.md "Elastic serving"):
        # pure/stateless stages may be restarted in place this many
        # times after an exception before the pipeline fails for real
        self.max_stage_restarts = max(0, int(
            max_stage_restarts if max_stage_restarts is not None
            else cfg.max_stage_restarts))
        self.trace_mode = str(
            trace_mode if trace_mode is not None else cfg.trace_mode)
        if self.trace_mode not in ("off", "ring", "full"):
            raise PipelineError(
                f"trace_mode must be off|ring|full, got {self.trace_mode!r}")
        # default tenant: stamped onto buffers at source ingress when
        # tracing is active (the off path stays stamp-free — see
        # _Runner._run_source and docs/SERVING.md "Front door")
        self.tenant = None if tenant is None else str(tenant)
        # nns-xray predicted-vs-actual reconciliation (utils/xray.py,
        # docs/OBSERVABILITY.md "Predicted vs actual"): when on, every
        # jit entry point registers its compiles with the process-wide
        # program registry, per-stage device time/MFU is attributed, and
        # a reconciler daemon checks the HBM ledger against the deep
        # lint's estimate.  Off = elements hold None, one pointer check
        # per hook (the trace_mode=off discipline).
        self.xray = bool(xray if xray is not None else cfg.xray)
        self._xray_reg = None
        if self.xray:
            from ..utils import xray as _xray_mod

            self._xray_reg = _xray_mod.registry
        # slo policy parsed HERE so a bad config fails at construction
        # (a ValueError naming every schema problem), not inside start()
        # after stage threads are already running
        self._slo_policy = None
        self._slo_engine = None
        if slo is not None:
            from ..utils.slo import load_policy

            try:
                self._slo_policy = load_policy(slo)
            except (ValueError, OSError) as e:
                raise PipelineError(str(e)) from e
        if self.trace_mode != "off":
            # the flight recorder is process-wide (like core.log.metrics);
            # an off pipeline never touches it
            tracing.recorder.configure(self.trace_mode,
                                       cfg.trace_ring_capacity)
        self._stopping = threading.Event()
        self._errors: List[Tuple[str, BaseException]] = []
        self._err_lock = threading.Lock()
        self._started = False

        # nns-armor (docs/ROBUSTNESS.md): ``quarantine=`` (a DLQ
        # directory path / policy dict / QuarantinePolicy) turns a
        # poison-pill request — one whose stage invoke raises — into a
        # quarantined DLQ record + a typed ``abort_reason=poison``
        # answer, with the pipeline serving on; ``nan_guard=True``
        # additionally treats NaN/Inf stage outputs as poison (pays a
        # host check per output).  Repeat offenders trip a per-tenant
        # circuit breaker that flips the query front door's
        # ``tenant_admission`` override to shed.  ``journal_replay=True``
        # asks every journaled serversrc to re-admit its
        # accepted-but-unanswered WAL entries at start().
        self._armor = None
        if quarantine is not None or nan_guard:
            from ..utils import armor as _armor

            policy = _armor.QuarantinePolicy.of(quarantine) \
                if quarantine is not None else _armor.QuarantinePolicy()
            try:
                self._armor = _armor.Armor(
                    policy, nan_guard=nan_guard,
                    apply_admission=self._breaker_admission,
                    recorder=(tracing.recorder
                              if self.trace_mode != "off" else None))
            except ValueError as e:
                raise PipelineError(str(e)) from e
        self._journal_replay = bool(journal_replay)

        # Deprecated ``custom=tp:N`` alias (the llm filter's pre-2-D
        # private-mesh knob): promote it to the pipeline-owned
        # model_parallel BEFORE any element opens, so the filter lands on
        # the shared mesh instead of minting its own.  An explicit
        # pipeline model_parallel (0 or >1) wins over the alias.
        tp_alias = _llm_tp_alias(graph)
        if tp_alias > 1:
            if self.model_parallel == 1:
                log.warning(
                    "tensor_filter llm custom=tp:%d is deprecated — "
                    "promoted to Pipeline(model_parallel=%d); the filter "
                    "now runs tensor-parallel on the pipeline's shared "
                    "(data x model) mesh", tp_alias, tp_alias)
                self.model_parallel = tp_alias
            else:
                log.warning(
                    "custom=tp:%d ignored: the pipeline's explicit "
                    "model_parallel=%d wins (tp: is a deprecated alias)",
                    tp_alias, self.model_parallel)

        # THE pipeline mesh (2-D placement): built lazily, at most once,
        # by _shared_mesh() — from start() for sharded micro-batching, or
        # earlier from a TP consumer's _mesh_provider call during open().
        self._mesh_obj = None
        self._mesh_built = False
        self._mesh_lock = threading.Lock()
        #: resolved (data, model) axis sizes once the mesh is built
        self.mesh_shape: Tuple[int, int] = (1, 1)

        # 1. instantiate elements
        self.elements: Dict[int, Element] = {}
        for node in graph.nodes.values():
            if node.kind == "capsfilter":
                el = _CapsFilter(node.caps)
            else:
                cls = registry_get(KIND_ELEMENT, node.kind)
                el = cls(dict(node.props), name=node.name or f"{node.kind}{node.id}")
            self.elements[node.id] = el
            # 2-D placement: every element gets a lazy accessor to the
            # shared mesh BEFORE negotiation opens any framework — the
            # llm filter's TP path reads it at open() (None unless
            # model_parallel is configured, so dp-only/single-device
            # pipelines stay backend-free here)
            el._mesh_provider = self._model_mesh
            # armor + journal attach (the _trace_rec pattern): the llm
            # serve loop quarantines through el._armor, journaled
            # serversrcs honor the pipeline-level replay flag
            el._armor = self._armor
            # nns-learn: a tensor_trainer with swap-to=<stage> hot-swaps
            # its refreshed params into that serving stage at each epoch
            # boundary through this callback (docs/TRAINING.md)
            el._swap_cb = self.swap_params
            if self._journal_replay:
                el._journal_replay = True

        # 2. HBM-residency pre-pass: mark filters whose downstream
        # consumers ALL admit reduced output geometry, so negotiation
        # below can switch them to the model's reduced variant — "fetch
        # the smaller thing" by default (pipeline/residency.py,
        # docs/FETCH.md).  Runs BEFORE negotiation: it changes the specs.
        from . import residency as _residency

        if self.reduce_outputs:
            _residency.mark_reduced_admissible(graph, self.elements)

        # 3. caps negotiation in topo order
        self._negotiate()

        # 4. plan stages (fusion pass + ingress donation)
        self.stages: List[Stage] = plan_stages(
            graph, self.elements, fuse=fuse,
            donate_ingress=self.donate_ingress)

        # 4b. adaptive-ladder variant budget: the SAME arithmetic the deep
        # analyzer prices the worst-case census with (plan.py), resolved
        # against the planned batchable-stage count — so runtime minting
        # can never exceed what the static report already charged.
        from .batching import ladder as _ladder_fn
        from .plan import adaptive_variant_budget

        self._ladder_budget = adaptive_variant_budget(
            len(_ladder_fn(self.batch_max, self.batch_buckets)),
            sum(1 for s in self.stages if s.batchable),
            cfg.max_compiled_variants)

        # 5. residency plan: what crosses to host per sink edge (logged;
        # exposed as Pipeline.residency for apps/bench/tests)
        self.residency = _residency.plan_residency(
            graph, self.elements, self.stages)
        if self.residency.fetch or self.residency.reduced_outputs:
            log.info("%s", self.residency.render())
        # sinks read the pipeline's fetch window width (same attach
        # pattern as _batch_buckets)
        for el in self.elements.values():
            if isinstance(el, SinkElement):
                el._fetch_depth = self.fetch_depth

        # 6. wire runners
        self._runners: Dict[int, _Runner] = {}
        node_to_stage: Dict[int, Stage] = {}
        for st in self.stages:
            for nid in st.node_ids:
                node_to_stage[nid] = st
        stage_runner: Dict[int, _Runner] = {}
        for st in self.stages:
            r = _Runner(self, st, self.capacity)
            stage_runner[id(st)] = r
            for nid in st.node_ids:
                self._runners[nid] = r
        for e in graph.edges:
            src_stage = node_to_stage[e.src]
            dst_stage = node_to_stage[e.dst]
            if src_stage is dst_stage:
                continue  # fused-internal edge
            r_src = stage_runner[id(src_stage)]
            r_dst = stage_runner[id(dst_stage)]
            out_pad = src_stage.external_out_pad(e)
            in_pad = dst_stage.external_in_pad(e)
            r_src.connect(out_pad, _Port(r_dst, in_pad))
            r_dst.in_pads.append(in_pad)

        self._by_name: Dict[str, Element] = {}
        for nid, el in self.elements.items():
            node = graph.nodes[nid]
            if node.name:
                self._by_name[node.name] = el
            self._by_name.setdefault(el.name, el)

        # A non-source element with no input link can never receive a
        # buffer — almost always a missing '!' between two elements (the
        # parser accepts gst-launch's multi-chain juxtaposition, so this
        # is only detectable once element classes are known).  Fail at
        # construction instead of hanging the first pull.
        from ..elements.base import SourceElement

        for nid, el in self.elements.items():
            if isinstance(el, SourceElement):
                continue
            if not self.graph.in_edges(nid):
                raise PipelineError(
                    f"element {el.name!r} ({self.graph.nodes[nid].kind}) "
                    "has no input link — missing '!' before it?")

    # -- negotiation -------------------------------------------------------
    def _negotiate(self) -> None:
        out_caps: Dict[Tuple[int, str], Caps] = {}
        for node in self.graph.topo_order():
            el = self.elements[node.id]
            in_caps: Dict[str, Caps] = {}
            for e in self.graph.in_edges(node.id):
                in_caps[e.dst_pad] = out_caps.get((e.src, e.src_pad), Caps.any())
            out_pads = sorted({e.src_pad for e in self.graph.out_edges(node.id)}) or [SRC]
            produced = el.configure(in_caps, out_pads)
            for pad in out_pads:
                out_caps[(node.id, pad)] = produced.get(pad, Caps.any())

    # -- control plane -----------------------------------------------------
    def start(self) -> "Pipeline":
        if getattr(self, "_dead", False):
            raise PipelineError(
                "pipeline failed startup validation and was stopped; "
                "build a new Pipeline")
        if self._started:
            return self
        self._started = True
        for el in self.elements.values():
            el._stop_event = self._stopping  # lets blocking sinks shed on stop
            el.start()
        # Reject typo'd properties like gst_parse_launch ("no property X in
        # element"): by now every element (and its lazy start()-time
        # readers) consulted what it understands.
        unknown = {
            el.name: sorted(u)
            for el in self.elements.values()
            if (u := el.unknown_props())
        }
        if unknown:
            self.stop()
            self._dead = True  # elements stopped: this instance is done
            raise PipelineError(
                f"unknown element properties (typo?): {unknown}")
        try:
            mesh = self._build_mesh()
        except Exception:
            # Same contract as the unknown-props failure above: elements
            # already started, so a half-started pipeline must be torn
            # down NOW (serve threads, sockets, opened models) — and a
            # retried start() must not silently return a dead instance.
            self.stop()
            self._dead = True
            raise
        if mesh is not None:
            # Attached to the ELEMENT the same way _batch_buckets is: the
            # element's lazy BatchRunner reads it at first batched
            # dispatch.  Only shard-eligible stages ever see it.
            from ..parallel.mesh import mesh_axis_size

            replicas = mesh_axis_size(mesh, "data")
            for r in {id(r): r for r in self._runners.values()}.values():
                if r.stage.shardable and r.batch_max > 1:
                    r.element._shard_mesh = mesh
                    lad = getattr(r.element, "_batch_ladder", None)
                    if lad is not None:
                        # minted sizes must stay replica-aligned so
                        # shard_bucket_for's rounding is a no-op on them
                        # (2-D mesh rounding still applies)
                        lad.align = max(1, replicas)
        if self._xray_reg is not None:
            # census expectations BEFORE any streaming thread can compile:
            # the predicted budgets use the same shared arithmetic the
            # deep lint prices with (ladder / adaptive budget / shard
            # rounding), so runtime drift is measured against the exact
            # static promise.
            self._install_xray_expectations(
                self.mesh_shape[0] if self._mesh_built else 1)
        for r in {id(r): r for r in self._runners.values()}.values():
            r.thread.start()
        if self.trace_mode != "off":
            # queue-depth / backpressure / staleness gauges, sampled off
            # the streaming threads (docs/OBSERVABILITY.md); daemon +
            # stop-event bound, so teardown never waits on it
            self._sampler = threading.Thread(
                target=self._sample_loop, name="nns-sampler", daemon=True)
            self._sampler.start()
        if self._slo_policy is not None:
            # continuous SLO evaluation off the live histograms: burn-rate
            # / breach gauges per tenant (utils/slo.py).  Requires tracing
            # (the e2e histograms only fill when trace_mode != off).
            self._slo_loop().start()
        if self._xray_reg is not None:
            # the predicted-vs-actual loop: MFU/roofline gauges + the HBM
            # ledger reconciled against the deep-lint estimate, on the
            # SLO engine's cadence; stopped AND joined by stop()
            from ..utils.xray import XrayReconciler

            self._xray_recon = XrayReconciler(self)
            self._xray_recon.start()
        return self

    @property
    def mesh(self):
        """THE pipeline mesh (None before start()/first TP open, or when
        the plan resolves to a single device)."""
        return self._mesh_obj

    def _model_mesh(self):
        """Mesh provider handed to elements (the llm filter's TP path):
        the shared pipeline mesh when a >1 ``model`` axis is configured,
        else None — dp-only and single-device pipelines never touch the
        device backend through this accessor."""
        if self.model_parallel == 1:
            return None
        return self._shared_mesh()

    def _shared_mesh(self):
        """Build (at most once) THE pipeline mesh from the resolved
        ``(data, model)`` plan (``pipeline/plan.mesh_plan`` — the same
        arithmetic the deep lint budgets with).  Returns None when the
        plan degenerates to a single device; raises
        :class:`PipelineError` on an over-ask the host cannot supply."""
        with self._mesh_lock:
            if self._mesh_built:
                return self._mesh_obj
            import jax

            from ..parallel.mesh import make_mesh
            from .plan import mesh_plan

            devs = jax.devices()
            dp, mp = mesh_plan(self.data_parallel, self.model_parallel,
                               self.batch_max, len(devs))
            if dp * mp > len(devs):
                if mp == 1:
                    raise PipelineError(
                        f"data_parallel={dp} needs {dp} local devices, "
                        f"have {len(devs)}")
                raise PipelineError(
                    f"data_parallel={dp} x model_parallel={mp} needs "
                    f"{dp * mp} local devices, have {len(devs)}")
            self.mesh_shape = (dp, mp)
            if dp == 1 and mp == 1:
                self._mesh_obj = None
            else:
                try:
                    self._mesh_obj = make_mesh(
                        data=dp, model=mp, devices=devs[:dp * mp])
                except ValueError as e:
                    raise PipelineError(str(e)) from e
            self._mesh_built = True
            return self._mesh_obj

    def _build_mesh(self):
        """Resolve the 2-D placement to the pipeline mesh, or None for
        single-device dispatch.  Built HERE — on the app thread driving
        start(), never a streaming thread — and lazily: a pipeline with
        no shard-eligible stage (or batch_max=1, or data_parallel=1) and
        no model_parallel config never touches the device backend for
        this feature.  (A TP llm filter may have forced the build
        earlier, at open() — the memoized mesh is reused.)"""
        dp_wanted = (self.batch_max > 1 and self.data_parallel != 1
                     and any(s.shardable for s in self.stages))
        mp_wanted = self.model_parallel != 1
        if not (dp_wanted or mp_wanted or self._mesh_built):
            return None
        return self._shared_mesh()

    def _install_xray_expectations(self, replicas: int) -> None:
        """Install the predicted census for every stage that can compile
        (docs/OBSERVABILITY.md "Predicted vs actual") — the SAME shared
        arithmetic the deep lint prices with: the bucket ladder (plus
        replica rounding under a data mesh) for batchable stages, the
        adaptive mint budget when ladders refine online, and a
        2-program allowance for the single-buffer path (static spec +
        the truncated-tail shape a non-aligned device source can mint).
        invoke-dynamic filters get NO expectation — the lint calls them
        recompile-unbounded, so the live census records without judging.
        The llm serve loop and device aggregator install their own
        (serving_plan / AGGREGATOR_PROGRAMS) at build time."""
        from .batching import ladder as _ladder_fn, shard_bucket_for

        reg = self._xray_reg
        for r in {id(r): r for r in self._runners.values()}.values():
            el = r.element
            target = getattr(el, "fused", el)  # folded-source inner chain
            nm = target.name
            if r.stage.batchable and r.batch_max > 1:
                lad = _ladder_fn(r.batch_max, self.batch_buckets)
                if getattr(el, "_batch_ladder", None) is not None:
                    # adaptive: minted sizes are legal anywhere, the
                    # budget is the closed bound (plan arithmetic)
                    reg.expect(nm, "batch", budget=self._ladder_budget,
                               note="adaptive ladder budget")
                else:
                    allow = set(lad)
                    if replicas > 1:
                        allow |= {shard_bucket_for(b, replicas,
                                                   self.batch_buckets)
                                  for b in lad}
                    reg.expect(nm, "batch", budget=len(allow),
                               allow=allow, note="static bucket ladder")
                reg.expect(nm, "stage", budget=2,
                           note="single-buffer program (+ tail shape)")
            elif (getattr(el, "kind", "") == "fused"
                  or (getattr(el, "kind", "") == "tensor_filter"
                      and not getattr(el, "invoke_dynamic", False))):
                reg.expect(nm, "stage", budget=2,
                           note="single-buffer program (+ tail shape)")

    def stop(self) -> None:
        self._stopping.set()
        if self._slo_engine is not None:
            self._slo_engine.stop()
        recon = getattr(self, "_xray_recon", None)
        if recon is not None:
            recon.stop()  # joins: the thread-shutdown audit counts it
        runners = {id(r): r for r in self._runners.values()}.values()
        # Close every stage queue first: blocked getters receive _POISON
        # and blocked putters shed immediately, so join() below is not
        # racing 0.1 s polls (seed worst case: ~100 ms PER HOP).
        for r in runners:
            r.queue.close()
        for r in runners:
            if r.thread.ident is not None:  # start() may have failed part-way
                r.thread.join(timeout=5.0)
        for el in self.elements.values():
            try:
                el.stop()
            except Exception:  # noqa: BLE001
                log.exception("stop() failed for %s", el.name)
        # the sampler exits on _stopping; JOIN it so stop() returning
        # means every pipeline-owned thread is actually gone (the
        # shutdown audit's contract — daemon status is not cleanup)
        sampler = getattr(self, "_sampler", None)
        if sampler is not None and sampler.is_alive():
            sampler.join(timeout=2.0)

    def wait(self, timeout: Optional[float] = None) -> None:
        """Block until every stage thread finished (sources EOS'd and all
        buffers drained)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        for r in {id(r): r for r in self._runners.values()}.values():
            t = None if deadline is None else max(0.0, deadline - time.monotonic())
            r.thread.join(timeout=t)
            if r.thread.is_alive():
                raise PipelineError(f"stage {r.element.name} did not finish")
        self.check()

    def check(self) -> None:
        with self._err_lock:
            if self._errors:
                name, exc = self._errors[0]
                raise PipelineError(f"stage {name} failed: {exc!r}") from exc

    def _breaker_admission(self, tenant: str, engage: bool) -> None:
        """The armor circuit breaker's lever: flip ``tenant``'s admission
        override to shed on every query-server core of this pipeline
        (PR 11's autoscaler map, reused — docs/ROBUSTNESS.md)."""
        for el in self.elements.values():
            core = getattr(el, "_core", None)
            if core is not None and hasattr(core, "tenant_admission"):
                if engage:
                    # "shed-all": unconditional, unlike the autoscaler's
                    # backlog-conditional "shed" — a poison spewer must
                    # not keep crashing invokes just because the queue
                    # has room
                    core.tenant_admission[tenant] = "shed-all"
                else:
                    core.tenant_admission.pop(tenant, None)

    def _record_error(self, name: str, exc: BaseException) -> None:
        with self._err_lock:
            self._errors.append((name, exc))
        # Post-mortem: every stall/crash report carries the recent span
        # timeline when the flight recorder is on (no-op otherwise).
        tracing.dump_recent_to_log(
            log, reason=f"stage {name} failed: {exc!r}")

    # -- observability -----------------------------------------------------
    def ladder_snapshot(self) -> Dict[str, List[int]]:
        """Export every adaptive stage's CURRENT bucket ladder (base +
        minted sizes) keyed by stage name — feed it back via
        ``Pipeline(bucket_ladders=...)`` / ``Config.bucket_ladders``
        (``NNS_TPU_BUCKET_LADDERS``, ini ``[ladders]``) so a steady-state
        run compiles the refined ladder at warmup instead of re-learning
        it.  Empty when ``adaptive_buckets`` is off."""
        out: Dict[str, List[int]] = {}
        for r in {id(r): r for r in self._runners.values()}.values():
            lad = getattr(r.element, "_batch_ladder", None)
            if lad is not None:
                out[r.element.name] = lad.export()
        return out

    def sample_queues(self) -> None:
        """One sampler tick: queue-depth / in-flight-window gauges per
        stage, staleness watermark per sink (seconds since last delivery).
        Public so apps can sample on their own cadence without the
        tracer's thread."""
        now = time.monotonic_ns()
        for r in {id(r): r for r in self._runners.values()}.values():
            metrics.gauge(f"{r._nm}.queue_depth", float(r.queue.qsize()))
            # per-tenant split of the same gauge; tenants seen on a
            # previous tick but absent now are zeroed, so an idle
            # tenant's labeled depth reads 0, not its last backlog
            depths = r.queue.tenant_depths()
            for ten in r._gauge_tenants.difference(depths):
                metrics.gauge(f"{r._nm}.queue_depth", 0.0, tenant=ten)
            for ten, depth in depths.items():
                metrics.gauge(f"{r._nm}.queue_depth", float(depth),
                              tenant=ten)
            r._gauge_tenants.update(depths)
            if r.dispatch_depth > 1:
                metrics.gauge(f"{r._nm}.inflight_window",
                              float(len(r._inflight)))
            if r._is_sink and r._last_sink_ns:
                metrics.gauge(f"{r._nm}.staleness_s",
                              (now - r._last_sink_ns) / 1e9)

    def _sample_loop(self, period_s: float = 0.1) -> None:
        while not self._stopping.wait(period_s):
            try:
                self.sample_queues()
            except Exception:  # noqa: BLE001 - sampler must never die loud
                log.exception("queue sampler tick failed")

    def explain(self) -> dict:
        """The predicted-vs-actual doctor report (utils/xray.explain):
        plan + mesh, residency, the compiled-program census (deep-lint
        budgets vs the live program set + any drift), the HBM ledger per
        category (measured vs the deep-lint estimate), per-stage
        device-time/MFU attribution, and the SLO verdict when an engine
        is attached.  JSON-serializable; render with
        ``utils.xray.render_report`` or via
        ``python -m nnstreamer_tpu.tools.doctor`` — see
        docs/OBSERVABILITY.md "Predicted vs actual".  Works on any
        pipeline; census/MFU columns fill only under
        ``Pipeline(xray=True)``."""
        from ..utils import xray as _xray_mod

        return _xray_mod.explain(self)

    def dump_trace(self, path: str) -> int:
        """Write the flight recorder's current contents as Chrome
        trace-event JSON (Perfetto / chrome://tracing); returns the span
        count.  See docs/OBSERVABILITY.md and
        ``python -m nnstreamer_tpu.tools.trace``."""
        return tracing.dump_chrome(tracing.recorder.events(), path)

    def _slo_loop(self):
        """Build (once) the SLO engine bound to this pipeline's sinks.
        ``slo=`` accepts an :class:`~nnstreamer_tpu.utils.slo.SLOPolicy`,
        a config dict, or a JSON file path (utils/slo.py) — parsed and
        validated at construction."""
        if self._slo_engine is None:
            from ..utils.slo import SLOEngine, SLOPolicy

            sinks = [el.name for el in self.elements.values()
                     if isinstance(el, SinkElement)]
            self._slo_engine = SLOEngine(
                self._slo_policy or SLOPolicy(), sinks=sinks)
        return self._slo_engine

    def slo_report(self) -> dict:
        """Per-tenant SLO verdict evaluated NOW off the live labeled
        histograms (docs/SERVING.md "Front door"): measured p50/p99/fps
        vs each tenant's objectives, shed counts, error-budget burn rate,
        and — for breaching tenants — the dominant offending span kind
        attributed from the flight-recorder ring.  Requires
        ``trace_mode != off`` for latency/throughput objectives (the e2e
        histograms are only fed when tracing is on)."""
        return self._slo_loop().report()

    # -- elastic serving: drain / handover ---------------------------------
    def serve_streams(self) -> Dict[int, dict]:
        """Continuous-serving streams live on this pipeline:
        ``stream_id -> {"state", "tenant", "slot", "blocks",
        "element"}`` (docs/SERVING.md "Elastic serving")."""
        out: Dict[int, dict] = {}
        for el in self.elements.values():
            table_fn = getattr(el, "serve_streams", None)
            if table_fn is None:
                continue
            try:
                table = table_fn()
            except Exception:  # noqa: BLE001 - discovery must not throw
                continue
            for sid, info in table.items():
                out[sid] = {**info, "element": el.name}
        return out

    def drain_stream(self, stream_id: int, timeout: float = 30.0) -> dict:
        """Serialize one live continuous-serving stream OFF this
        pipeline: its paged KV blocks, slot state, and request meta
        become a host-value snapshot (the trainer/checkpoint.py
        serialization substrate — persist it with
        ``trainer.checkpoint.save_stream_snapshot``), and its slot +
        blocks return to the pool's free list.  :meth:`adopt_stream` on
        another pipeline (or this one, after a versioned-config
        restart) continues the stream — bit-identically for greedy
        decode — so recompile-requiring config changes become
        drain → restart → adopt instead of dropped traffic.  The move
        is host-side values only; neither pipeline's 3-program decode
        census is touched (span: ``elastic.drain``)."""
        for el in self.elements.values():
            table_fn = getattr(el, "serve_streams", None)
            if table_fn is None:
                continue
            try:
                owned = stream_id in table_fn()
            except Exception:  # noqa: BLE001
                continue
            if owned:
                return el.drain_serve_stream(stream_id, timeout)
        raise PipelineError(
            f"no live serve stream {stream_id} on this pipeline "
            f"(known: {sorted(self.serve_streams())})")

    def adopt_stream(self, snapshot: dict, timeout: float = 30.0) -> int:
        """Re-admit a drained stream (:meth:`drain_stream`'s snapshot,
        or one loaded via ``trainer.checkpoint.load_stream_snapshot``)
        into this pipeline's continuous-serving filter.  Returns the
        stream id; the remaining tokens flow to THIS pipeline's sinks
        (span: ``elastic.adopt``)."""
        last_err: Optional[Exception] = None
        for el in self.elements.values():
            adopt_fn = getattr(el, "adopt_serve_stream", None)
            if adopt_fn is None:
                continue
            fw = getattr(el, "fw", None)
            if fw is None or not getattr(fw, "continuous", False):
                continue
            try:
                return adopt_fn(snapshot, timeout=timeout)
            except Exception as e:  # noqa: BLE001 - try other filters
                last_err = e
        if last_err is not None:
            raise PipelineError(
                f"adopt_stream failed: {last_err}") from last_err
        raise PipelineError(
            "no continuous-serving filter on this pipeline to adopt "
            "into (need tensor_filter framework=llm "
            "custom=serve:continuous)")

    # -- nns-learn: train-while-serve param hot-swap -----------------------
    def swap_params(self, stage: str, tree_or_ckpt) -> int:
        """Hot-swap updated parameters into a LIVE serving stage
        (docs/TRAINING.md): ``tree_or_ckpt`` is a param pytree (e.g. a
        trainer's ``export_params()``) or a checkpoint path
        (``trainer/checkpoint.py``).  The swap is a VALUE move executed
        at a dispatch boundary — same tree structure, same per-leaf
        avals, so the stage's compiled programs are untouched and
        NOTHING recompiles (census pinned by nns-xray); a no-op swap is
        bit-identical, a real one serves the new weights from the next
        dispatch.  Returns the stage's new param version (the
        ``<stage>.param_version`` gauge / ``learn.swap`` span twin).

        Raises :class:`PipelineError` for a stage that cannot swap: a
        FUSED chain (its program bakes params into the composed closure
        at build time — run the serving filter unfused, e.g. between
        host elements or with ``fuse=False``) or a framework without a
        parametric dispatch path."""
        el = self.element(stage)
        nid = next((k for k, v in self.elements.items() if v is el), None)
        runner = self._runners.get(nid) if nid is not None else None
        if runner is not None and runner.element is not el:
            raise PipelineError(
                f"stage {stage!r} is fused into {runner.element.name!r} — "
                "the fused program captures params at build time, so a "
                "swap would silently not take; keep hot-swappable "
                "serving filters unfused (fuse=False, or a graph where "
                "the filter is not part of a linear device chain)")
        if runner is not None and runner.batch_max > 1 \
                and runner.stage.batchable:
            # same trap as fusion: the BatchRunner's bucket programs are
            # built from pure_fn() closures that SNAPSHOT params — a
            # swap would bump the version yet keep serving old weights
            raise PipelineError(
                f"stage {stage!r} runs micro-batched (batch_max="
                f"{runner.batch_max}) — bucketed dispatch captures "
                "params at build time, so a swap would silently not "
                "take; run the hot-swappable serving stage with "
                "batch_max=1 (or an llm serve:continuous stage, whose "
                "loop swaps at chunk boundaries)")
        swap = getattr(el, "swap_params", None)
        if swap is None:
            raise PipelineError(
                f"element {stage!r} ({getattr(el, 'kind', '?')}) has no "
                "swappable parameters")
        tree = tree_or_ckpt
        if isinstance(tree_or_ckpt, str):
            from ..trainer.checkpoint import load_checkpoint

            tree, _opt, _step = load_checkpoint(tree_or_ckpt)
        try:
            return int(swap(tree))
        except PipelineError:
            raise
        except Exception as e:  # noqa: BLE001 - typed to the caller
            raise PipelineError(
                f"swap_params({stage!r}) failed: {e}") from e

    def __enter__(self) -> "Pipeline":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- app I/O -----------------------------------------------------------
    def element(self, name: str) -> Element:
        try:
            return self._by_name[name]
        except KeyError:
            raise KeyError(f"no element named {name!r}") from None

    def push(self, name: str, data, pts: Optional[int] = None) -> None:
        el = self.element(name)
        if not hasattr(el, "push"):
            raise PipelineError(f"element {name!r} is not an app source")
        el.push(data, pts=pts)
        self.check()

    def eos(self, name: Optional[str] = None) -> None:
        """Signal end-of-stream on one (or every) app source."""
        targets = [self.element(name)] if name else [
            el for el in self.elements.values() if hasattr(el, "signal_eos")
        ]
        for el in targets:
            if hasattr(el, "signal_eos"):
                el.signal_eos()

    def pull(self, name: str, timeout: float = 30.0):
        el = self.element(name)
        if not hasattr(el, "pop"):
            raise PipelineError(f"element {name!r} is not a pullable sink")
        out = el.pop(timeout=timeout, check=self.check)
        return out


class _CapsFilter(Element):
    """Pseudo-element for inline caps constraints (``video/x-raw,width=...``).

    A capsfilter is a negotiation-time CONSTRAINT, not a runtime
    transform: once :meth:`configure` proved the intersection, every
    buffer passes through untouched.  It therefore exposes the identity
    as its :meth:`device_fn` — so the planner fuses straight THROUGH
    dtype/shape pins instead of splitting the chain on them.  Before
    this, the idiomatic quantized-boundary pin
    (``transform ! other/tensors,types=uint8 ! tensor_filter``) left the
    transform (and any decoder tail behind a post-filter pin) OUTSIDE
    the fused filter dispatch: three stages, two queue hops, and the
    quant row ran 0.2217 MFU against 0.247 for the identical fused graph
    (BENCH_ALL_r5).  The fused identity costs nothing — XLA folds it
    away — and bit-identity with the split path is pinned by tests.
    """

    kind = "capsfilter"

    def __init__(self, caps: Optional[Caps]):
        super().__init__({}, name="capsfilter")
        self.filter_caps = caps or Caps.any()

    def configure(self, in_caps, out_pads):
        self.in_caps = dict(in_caps)
        src = next(iter(in_caps.values()), Caps.any())
        merged = src.intersect(self.filter_caps)
        if merged is None:
            raise PipelineError(
                f"caps filter {self.filter_caps} incompatible with upstream {src}"
            )
        self.out_caps = {p: merged for p in out_pads}
        return self.out_caps

    def process(self, pad, buf):
        return [(SRC, buf)]

    def device_fn(self, in_spec):
        # Identity, provable at plan time: the constraint was enforced at
        # negotiation, so inside a fused program this element is a no-op.
        # The out spec is the MERGED caps' spec when one was negotiated
        # (it may be more specific than upstream's), else the input spec.
        caps = self.out_caps.get(SRC) if self.out_caps else None
        spec = getattr(caps, "spec", None)
        return (lambda arrays: arrays), (spec or in_spec)
