"""Async pipeline executor.

Reference analog: GStreamer's streaming model — every pad push runs on a
streaming thread, ``queue`` elements create stage boundaries, backpressure is
"push blocks until downstream returns" (SURVEY §1: "There is no 'scheduler'
layer — scheduling *is* GStreamer").  The TPU build supplies that analog
explicitly:

* each planned **stage** (an element, or a fused group of device elements —
  see plan.py) runs on its own runner thread with ONE bounded input queue;
* upstream pushes block when the queue is full → backpressure;
* EOS/error/caps events travel in-band through the same queues;
* device stages keep payloads as jax Arrays in HBM between stages (zero-copy),
  and the driver thread never blocks on device completion except at sinks —
  XLA's async dispatch overlaps H2D/compute/D2H exactly where the reference
  relied on GStreamer thread concurrency.

The executor is deliberately thread-based, not asyncio: stages do real
blocking work (device dispatch, host preprocessing) and the GIL is released
inside numpy/JAX, so threads give true overlap with far less machinery.
"""

from __future__ import annotations

import queue as _queue
import threading
import time
from typing import Dict, List, Optional, Tuple, Union

from ..core.buffer import Buffer, Event
from ..core.caps import Caps, MediaType
from ..core.config import get_config
from ..core.log import Timer, logger, metrics
from ..core.registry import KIND_ELEMENT, get as registry_get
from ..elements.base import Element, SinkElement, SourceElement, SRC
from .graph import PipelineGraph
from .parser import parse as parse_launch
from .plan import Stage, plan_stages

log = logger(__name__)

_POISON = object()


class PipelineError(RuntimeError):
    pass


class _Port:
    """Destination of an edge: a stage's queue + the pad name inside it."""

    def __init__(self, stage: "_Runner", pad: str):
        self.stage = stage
        self.pad = pad


class _Runner:
    """One streaming thread driving one planned stage."""

    def __init__(self, pipeline: "Pipeline", stage: Stage, capacity: int):
        self.pipeline = pipeline
        self.stage = stage
        self.element = stage.element
        self.queue: _queue.Queue = _queue.Queue(maxsize=capacity)
        self.out_ports: Dict[str, List[_Port]] = {}
        self.thread = threading.Thread(
            target=self._run, name=f"nns-{self.element.name}", daemon=True
        )
        # Elements with their own receiver threads (query client) emit
        # downstream asynchronously, not just from process() returns.
        if getattr(self.element, "wants_async_emit", False):
            self.element._async_emit = self._emit
        self.in_pads: List[str] = []
        self._eos_pads: set = set()
        self._pending: Dict[str, List[Buffer]] = {}

    # -- wiring ------------------------------------------------------------
    def connect(self, out_pad: str, port: _Port) -> None:
        self.out_ports.setdefault(out_pad, []).append(port)

    # -- data plane --------------------------------------------------------
    def feed(self, pad: str, item: Union[Buffer, Event]) -> None:
        """Blocking put with stop-awareness (backpressure point)."""
        while not self.pipeline._stopping.is_set():
            try:
                self.queue.put((pad, item), timeout=0.1)
                return
            except _queue.Full:
                continue

    def _emit(self, outs: List[Tuple[str, Union[Buffer, Event]]]) -> None:
        for out_pad, item in outs:
            ports = self.out_ports.get(out_pad, [])
            if not ports and isinstance(item, Buffer):
                metrics.count(f"{self.element.name}.dropped")
                continue
            for port in ports:
                # Deferred host-post buffers stay lazy all the way to sinks
                # (resolved in the app thread); any mid-pipeline host element
                # needs the real payload now.
                if (
                    isinstance(item, Buffer)
                    and "_host_post" in item.meta
                    and not isinstance(port.stage.element, SinkElement)
                ):
                    item = item.resolve()
                port.stage.feed(port.pad, item)

    def _broadcast(self, item) -> None:
        for ports in self.out_ports.values():
            for port in ports:
                port.stage.feed(port.pad, item)

    # -- main loop ---------------------------------------------------------
    def _run(self) -> None:
        el = self.element
        try:
            if isinstance(el, SourceElement):
                self._run_source()
            else:
                self._run_stream()
        except Exception as e:  # noqa: BLE001 - must not kill the process
            log.exception("stage %s failed", el.name)
            self.pipeline._record_error(el.name, e)
            self._broadcast(Event.error(e))
            self._broadcast(Event.eos())

    def _run_source(self) -> None:
        el = self.element
        for item in el.generate():
            if self.pipeline._stopping.is_set():
                break
            with Timer(f"{el.name}.push"):
                self._emit([(SRC, item)] if not isinstance(item, tuple) else [item])
            metrics.count(f"{el.name}.out")
        self._emit(el.finalize())
        self._broadcast(Event.eos())

    def _run_stream(self) -> None:
        el = self.element
        all_policy = el.sync_policy == "all" and len(self.in_pads) > 1
        while True:
            try:
                pad, item = self.queue.get(timeout=0.1)
            except _queue.Empty:
                if self.pipeline._stopping.is_set():
                    return
                continue
            if item is _POISON:
                return
            if isinstance(item, Event):
                if item.kind == "eos":
                    self._eos_pads.add(pad)
                    if all_policy:
                        self._try_groups()
                    if self._eos_pads >= set(self.in_pads):
                        self._emit(el.finalize())
                        self._broadcast(Event.eos())
                        return
                    continue
                if item.kind == "error":
                    self._broadcast(item)
                    continue
                self._emit(el.on_event(pad, item))
                continue
            metrics.count(f"{el.name}.in")
            if all_policy:
                self._pending.setdefault(pad, []).append(item)
                self._try_groups()
            else:
                with Timer(f"{el.name}.proc"):
                    outs = el.process(pad, item)
                self._emit(outs)
                metrics.count(f"{el.name}.out")

    def _try_groups(self) -> None:
        """Collate one buffer per pad (slowest-pad sync; reference:
        tensor_mux sync-mode=slowest).  A pad keeps pairing from its pending
        queue after EOS — data queued before EOS must still pair up.  Once
        any pad is EOS'd AND drained no complete group can ever form again,
        so remaining unpairable buffers are dropped: emitting a partial
        group would violate the element's negotiated caps (e.g. a 2-tensor
        mux emitting 1 tensor)."""
        el = self.element
        while True:
            dead = [
                p
                for p in self.in_pads
                if p in self._eos_pads and not self._pending.get(p)
            ]
            if dead:
                n = sum(len(v) for v in self._pending.values())
                if n:
                    metrics.count(f"{el.name}.dropped", n)
                    self._pending.clear()
                return
            if not all(self._pending.get(p) for p in self.in_pads):
                return
            group = {p: self._pending[p].pop(0) for p in self.in_pads}
            with Timer(f"{el.name}.proc"):
                outs = el.process_group(group)
            self._emit(outs)
            metrics.count(f"{el.name}.out")


class Pipeline:
    """Build + run a pipeline graph.

    Accepts a pipeline description string or a parsed PipelineGraph.
    ``fuse=True`` lets the planner merge adjacent device-capable elements
    into single jitted XLA stages.
    """

    def __init__(
        self,
        graph: Union[str, PipelineGraph],
        *,
        fuse: bool = True,
        queue_capacity: Optional[int] = None,
    ):
        if isinstance(graph, str):
            graph = parse_launch(graph)
        graph.validate()
        # Start the native-lib build (if any) now, off the streaming threads.
        from ..native import prewarm

        prewarm()
        self.graph = graph
        self.fuse = fuse
        self.capacity = queue_capacity or get_config().queue_capacity
        self._stopping = threading.Event()
        self._errors: List[Tuple[str, BaseException]] = []
        self._err_lock = threading.Lock()
        self._started = False

        # 1. instantiate elements
        self.elements: Dict[int, Element] = {}
        for node in graph.nodes.values():
            if node.kind == "capsfilter":
                el = _CapsFilter(node.caps)
            else:
                cls = registry_get(KIND_ELEMENT, node.kind)
                el = cls(dict(node.props), name=node.name or f"{node.kind}{node.id}")
            self.elements[node.id] = el

        # 2. caps negotiation in topo order
        self._negotiate()

        # 3. plan stages (fusion pass)
        self.stages: List[Stage] = plan_stages(graph, self.elements, fuse=fuse)

        # 4. wire runners
        self._runners: Dict[int, _Runner] = {}
        node_to_stage: Dict[int, Stage] = {}
        for st in self.stages:
            for nid in st.node_ids:
                node_to_stage[nid] = st
        stage_runner: Dict[int, _Runner] = {}
        for st in self.stages:
            r = _Runner(self, st, self.capacity)
            stage_runner[id(st)] = r
            for nid in st.node_ids:
                self._runners[nid] = r
        for e in graph.edges:
            src_stage = node_to_stage[e.src]
            dst_stage = node_to_stage[e.dst]
            if src_stage is dst_stage:
                continue  # fused-internal edge
            r_src = stage_runner[id(src_stage)]
            r_dst = stage_runner[id(dst_stage)]
            out_pad = src_stage.external_out_pad(e)
            in_pad = dst_stage.external_in_pad(e)
            r_src.connect(out_pad, _Port(r_dst, in_pad))
            r_dst.in_pads.append(in_pad)

        self._by_name: Dict[str, Element] = {}
        for nid, el in self.elements.items():
            node = graph.nodes[nid]
            if node.name:
                self._by_name[node.name] = el
            self._by_name.setdefault(el.name, el)

        # A non-source element with no input link can never receive a
        # buffer — almost always a missing '!' between two elements (the
        # parser accepts gst-launch's multi-chain juxtaposition, so this
        # is only detectable once element classes are known).  Fail at
        # construction instead of hanging the first pull.
        from ..elements.base import SourceElement

        for nid, el in self.elements.items():
            if isinstance(el, SourceElement):
                continue
            if not self.graph.in_edges(nid):
                raise PipelineError(
                    f"element {el.name!r} ({self.graph.nodes[nid].kind}) "
                    "has no input link — missing '!' before it?")

    # -- negotiation -------------------------------------------------------
    def _negotiate(self) -> None:
        out_caps: Dict[Tuple[int, str], Caps] = {}
        for node in self.graph.topo_order():
            el = self.elements[node.id]
            in_caps: Dict[str, Caps] = {}
            for e in self.graph.in_edges(node.id):
                in_caps[e.dst_pad] = out_caps.get((e.src, e.src_pad), Caps.any())
            out_pads = sorted({e.src_pad for e in self.graph.out_edges(node.id)}) or [SRC]
            produced = el.configure(in_caps, out_pads)
            for pad in out_pads:
                out_caps[(node.id, pad)] = produced.get(pad, Caps.any())

    # -- control plane -----------------------------------------------------
    def start(self) -> "Pipeline":
        if getattr(self, "_dead", False):
            raise PipelineError(
                "pipeline failed startup validation and was stopped; "
                "build a new Pipeline")
        if self._started:
            return self
        self._started = True
        for el in self.elements.values():
            el._stop_event = self._stopping  # lets blocking sinks shed on stop
            el.start()
        # Reject typo'd properties like gst_parse_launch ("no property X in
        # element"): by now every element (and its lazy start()-time
        # readers) consulted what it understands.
        unknown = {
            el.name: sorted(u)
            for el in self.elements.values()
            if (u := el.unknown_props())
        }
        if unknown:
            self.stop()
            self._dead = True  # elements stopped: this instance is done
            raise PipelineError(
                f"unknown element properties (typo?): {unknown}")
        for r in {id(r): r for r in self._runners.values()}.values():
            r.thread.start()
        return self

    def stop(self) -> None:
        self._stopping.set()
        for r in {id(r): r for r in self._runners.values()}.values():
            if r.thread.ident is not None:  # start() may have failed part-way
                r.thread.join(timeout=5.0)
        for el in self.elements.values():
            try:
                el.stop()
            except Exception:  # noqa: BLE001
                log.exception("stop() failed for %s", el.name)

    def wait(self, timeout: Optional[float] = None) -> None:
        """Block until every stage thread finished (sources EOS'd and all
        buffers drained)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        for r in {id(r): r for r in self._runners.values()}.values():
            t = None if deadline is None else max(0.0, deadline - time.monotonic())
            r.thread.join(timeout=t)
            if r.thread.is_alive():
                raise PipelineError(f"stage {r.element.name} did not finish")
        self.check()

    def check(self) -> None:
        with self._err_lock:
            if self._errors:
                name, exc = self._errors[0]
                raise PipelineError(f"stage {name} failed: {exc!r}") from exc

    def _record_error(self, name: str, exc: BaseException) -> None:
        with self._err_lock:
            self._errors.append((name, exc))

    def __enter__(self) -> "Pipeline":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- app I/O -----------------------------------------------------------
    def element(self, name: str) -> Element:
        try:
            return self._by_name[name]
        except KeyError:
            raise KeyError(f"no element named {name!r}") from None

    def push(self, name: str, data, pts: Optional[int] = None) -> None:
        el = self.element(name)
        if not hasattr(el, "push"):
            raise PipelineError(f"element {name!r} is not an app source")
        el.push(data, pts=pts)
        self.check()

    def eos(self, name: Optional[str] = None) -> None:
        """Signal end-of-stream on one (or every) app source."""
        targets = [self.element(name)] if name else [
            el for el in self.elements.values() if hasattr(el, "signal_eos")
        ]
        for el in targets:
            if hasattr(el, "signal_eos"):
                el.signal_eos()

    def pull(self, name: str, timeout: float = 30.0):
        el = self.element(name)
        if not hasattr(el, "pop"):
            raise PipelineError(f"element {name!r} is not a pullable sink")
        out = el.pop(timeout=timeout, check=self.check)
        return out


class _CapsFilter(Element):
    """Pseudo-element for inline caps constraints (``video/x-raw,width=...``)."""

    kind = "capsfilter"

    def __init__(self, caps: Optional[Caps]):
        super().__init__({}, name="capsfilter")
        self.filter_caps = caps or Caps.any()

    def configure(self, in_caps, out_pads):
        self.in_caps = dict(in_caps)
        src = next(iter(in_caps.values()), Caps.any())
        merged = src.intersect(self.filter_caps)
        if merged is None:
            raise PipelineError(
                f"caps filter {self.filter_caps} incompatible with upstream {src}"
            )
        self.out_caps = {p: merged for p in out_pads}
        return self.out_caps

    def process(self, pad, buf):
        return [(SRC, buf)]
