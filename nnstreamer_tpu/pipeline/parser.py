"""gst-launch-style pipeline string parser.

Parses the reference's declarative pipeline DSL (the grammar of
``gst_parse_launch`` / ``tools/development/parser`` upstream — reconstructed,
SURVEY §2.8) into a :class:`~nnstreamer_tpu.pipeline.graph.PipelineGraph`.

Supported grammar subset (everything the reference's own test pipelines use):

* chains:            ``a ! b ! c``
* properties:        ``elem key=value key2="quoted value"``
* caps filters:      ``video/x-raw,format=RGB,width=640,framerate=30/1``
* named elements:    ``tee name=t``  then branch refs ``t. ! queue ! ...``
* named pads:        ``mux.sink_0`` / ``demux.src_1``
* multiple chains separated by starting a new element without ``!``

The parser is deliberately strict: unknown syntax raises ParseError with the
offending token, because a silently-misparsed pipeline is how streaming bugs
are born.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

from ..core.caps import Caps, parse_caps_string
from .graph import GraphError, Node, PipelineGraph


class ParseError(ValueError):
    """Pipeline-string syntax error.

    ``pos`` is the 0-based character offset of the offending token in the
    pipeline string (None when no single position applies), so tools — the
    lint CLI in particular — can point a caret at the source.
    """

    def __init__(self, message: str, pos: Optional[int] = None):
        if pos is not None:
            message = f"{message} (at char {pos})"
        super().__init__(message)
        self.pos = pos


#: stand-in for an unresolvable chain-start ref under validate=False:
#: links from it are silently dropped (the analyzer reports the ref itself)
_PHANTOM = object()

_NAME_RE = re.compile(r"^[A-Za-z_][\w\-]*$")
_PROP_RE = re.compile(r"^([A-Za-z_][\w\-]*)=(.*)$", re.S)
# GStreamer per-pad property syntax: sink_1::alpha=0.5
_PAD_PROP_RE = re.compile(r"^([A-Za-z_][\w\-]*::[A-Za-z_][\w\-]*)=(.*)$", re.S)
_REF_RE = re.compile(r"^([A-Za-z_][\w\-]*)\.([\w\-]*)$")
_CAPS_RE = re.compile(r"^[a-z]+/[\w\-\.\+]+")


def _tokenize(text: str) -> List[Tuple[str, int]]:
    """Split on whitespace and '!' outside quotes; quoted spans (single or
    double) keep their content verbatim — including '!' and spaces.
    Returns (token, offset) pairs, offset = 0-based char position of the
    token's first character in ``text`` (diagnostics point there)."""
    toks: List[Tuple[str, int]] = []
    cur: List[str] = []
    start = 0
    quote: Optional[str] = None
    quote_pos = 0
    for i, ch in enumerate(text):
        if quote is not None:
            if ch == quote:
                quote = None
            else:
                cur.append(ch)
            continue
        if ch in "\"'":
            if not cur:
                start = i
            quote = ch
            quote_pos = i
            continue
        if ch.isspace() or ch == "!":
            if cur:
                toks.append(("".join(cur), start))
                cur = []
            if ch == "!":
                toks.append(("!", i))
            continue
        if not cur:
            start = i
        cur.append(ch)
    if quote is not None:
        raise ParseError(
            f"unterminated quote in pipeline string: {text!r}", quote_pos)
    if cur:
        toks.append(("".join(cur), start))
    return toks


def _coerce(v: str):
    if len(v) >= 2 and v[0] in "\"'" and v[-1] == v[0]:
        return v[1:-1]
    try:
        return int(v)
    except ValueError:
        pass
    try:
        return float(v)
    except ValueError:
        pass
    low = v.lower()
    if low in ("true", "false"):
        return low == "true"
    return v


def parse(text: str, *, validate: bool = True) -> PipelineGraph:
    """Parse a pipeline description string into a validated PipelineGraph.

    ``validate=False`` is the static analyzer's entry point: syntax errors
    still raise, but *semantic* problems that validation would reject —
    dangling name refs, cycles, double-linked pads — are left in the graph
    for the analysis passes to report ALL AT ONCE (dangling refs land in
    ``graph.unresolved_refs`` as ``(name, pad, pos)`` tuples).
    """
    toks = _tokenize(text)
    if not toks:
        raise ParseError("empty pipeline description")

    g = PipelineGraph()
    # pending link state
    prev: Optional[Node] = None
    prev_pad = "src"
    want_link = False  # saw '!' and waiting for the next element
    # deferred name refs we couldn't resolve yet
    deferred: List[Tuple[str, str, Node, str, int]] = []  # (name, pad, src_node, src_pad, pos)

    i = 0
    n = len(toks)
    while i < n:
        t, tpos = toks[i]

        if t == "!":
            if prev is None:
                raise ParseError("'!' with no element before it", tpos)
            if want_link:
                raise ParseError("two '!' in a row", tpos)
            want_link = True
            i += 1
            continue

        ref = _REF_RE.match(t)
        if ref and not _PROP_RE.match(t):
            name, pad = ref.group(1), ref.group(2)
            if want_link:
                # prev ! name.pad  => link INTO named element's sink pad
                pad = pad or "sink"
                target = g.by_name.get(name)
                if prev is _PHANTOM:
                    # upstream ref already recorded; the SINK-side ref must
                    # still be checked — a second dangling name here is its
                    # own finding, a resolved one is phantom-fed
                    if target is None:
                        g.unresolved_refs.append((name, pad, tpos))
                    else:
                        g.phantom_fed.add(target.id)
                elif target is None:
                    deferred.append((name, pad, prev, prev_pad, tpos))
                else:
                    g.link(prev, target, prev_pad, pad)
                want_link = False
                prev, prev_pad = None, "src"
            else:
                # chain start: name.pad ! ...  => link FROM named element's src pad
                target = g.by_name.get(name)
                if target is None:
                    if not validate:
                        # record + parse on: the ref'd chain hangs off a
                        # phantom source, so downstream elements still
                        # exist for the analyzer (it reports the dangling
                        # ref AND whatever else is wrong, in one run).
                        g.unresolved_refs.append((name, pad or "src", tpos))
                        prev, prev_pad = _PHANTOM, "src"
                        i += 1
                        continue
                    raise ParseError(
                        f"reference to unknown element {name!r}", tpos)
                prev = target
                prev_pad = pad or _next_src_pad(g, target)
            i += 1
            continue

        if _CAPS_RE.match(t) and "=" not in t.split(",", 1)[0]:
            try:
                caps = parse_caps_string(t)
            except ValueError as e:
                raise ParseError(str(e), tpos) from None
            node = g.add("capsfilter", {}, caps=caps, pos=tpos)
            if want_link:
                if prev is not _PHANTOM:
                    g.link(prev, node, prev_pad, "sink")
                else:
                    g.phantom_fed.add(node.id)
                want_link = False
            prev, prev_pad = node, "src"
            i += 1
            continue

        if _NAME_RE.match(t):
            kind = t
            props: Dict[str, object] = {}
            i += 1
            while i < n:
                if toks[i][0] == "!":
                    break
                pm = _PAD_PROP_RE.match(toks[i][0])
                m = pm or _PROP_RE.match(toks[i][0])
                if not m:
                    break
                key = m.group(1)
                if pm is None:
                    key = key.replace("-", "_")
                else:  # pad props keep the pad name verbatim: sink_1::alpha
                    pad, _, prop = key.partition("::")
                    key = f"{pad}::{prop.replace('-', '_')}"
                props[key] = _coerce(m.group(2))
                i += 1
            try:
                node = g.add(kind, props, pos=tpos)
            except GraphError as e:  # duplicate element name
                raise ParseError(str(e), tpos) from None
            if want_link:
                if prev is not _PHANTOM:
                    g.link(prev, node, prev_pad, "sink")
                else:
                    g.phantom_fed.add(node.id)
                want_link = False
            elif prev is not None:
                pass  # new chain begins
            prev, prev_pad = node, "src"
            continue

        raise ParseError(f"unexpected token {t!r}", tpos)

    if want_link:
        raise ParseError("pipeline ends with '!'", toks[-1][1])

    for name, pad, src_node, src_pad, pos in deferred:
        target = g.by_name.get(name)
        if target is None:
            if not validate:
                g.unresolved_refs.append((name, pad, pos))
                g.phantom_out.add(src_node.id)
                continue
            raise ParseError(f"reference to unknown element {name!r}", pos)
        g.link(src_node, target, src_pad, pad)

    _assign_request_pads(g)
    if validate:
        g.validate()
    return g


_MULTI_SRC = ("tee", "tensor_demux", "tensor_split", "tensor_if")


def _next_src_pad(g: PipelineGraph, node: Node) -> str:
    """Auto-number source pads for tee/demux-style elements referenced as 'name.'."""
    used = {e.src_pad for e in g.out_edges(node.id)}
    if node.kind not in _MULTI_SRC:
        if "src" in used:
            raise ParseError(
                f"element {node.name or node.kind!r} has a single src pad already "
                "linked; insert a tee to branch"
            )
        return "src"
    i = 0
    while f"src_{i}" in used:
        i += 1
    return f"src_{i}"


def _assign_request_pads(g: PipelineGraph) -> None:
    """Give multi-input elements (mux/merge/join) numbered sink pads and
    multi-output elements numbered src pads when linked via default pads."""
    multi_sink = {"tensor_mux", "tensor_merge", "join", "tensor_trainer",
                  "compositor"}
    multi_src = {"tee"}
    for node in g.nodes.values():
        if node.kind in multi_sink:
            counter = 0
            used = {e.dst_pad for e in g.in_edges(node.id) if e.dst_pad != "sink"}
            for idx, e in enumerate(g.edges):
                if e.dst == node.id and e.dst_pad == "sink":
                    while f"sink_{counter}" in used:
                        counter += 1
                    g.edges[idx] = type(e)(e.src, e.src_pad, e.dst, f"sink_{counter}")
                    used.add(f"sink_{counter}")
        if node.kind in multi_src:
            counter = 0
            used = {e.src_pad for e in g.out_edges(node.id) if e.src_pad != "src"}
            for idx, e in enumerate(g.edges):
                if e.src == node.id and e.src_pad == "src":
                    while f"src_{counter}" in used:
                        counter += 1
                    g.edges[idx] = type(e)(e.src, f"src_{counter}", e.dst, e.dst_pad)
                    used.add(f"src_{counter}")
