"""nnstreamer_tpu.pipeline"""
