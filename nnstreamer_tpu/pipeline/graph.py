"""Pipeline graph representation.

The logical dataflow graph a pipeline string parses into.  Reference analog:
GStreamer's GstBin/GstElement/GstPad topology built by gst_parse_launch —
here it is a plain DAG (plus explicit loops via tensor_repo, SURVEY §2.2)
that the planner (pipeline/plan.py) partitions into executable stages and
fused XLA programs.  Nothing in this module touches JAX: it is pure
structure + validation.
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Dict, List, Optional, Tuple

from ..core.caps import Caps


@dataclasses.dataclass
class Node:
    """One element instance in the graph."""

    id: int
    kind: str  # registered element name, e.g. "tensor_converter"
    props: Dict[str, object] = dataclasses.field(default_factory=dict)
    name: Optional[str] = None  # user-assigned name (name=... property)
    caps: Optional[Caps] = None  # for capsfilter pseudo-elements
    #: 0-based character offset of this element's token in the pipeline
    #: string (set by the parser; None for programmatically built graphs) —
    #: lets lint diagnostics point back at the source text.
    pos: Optional[int] = None

    def __str__(self):  # pragma: no cover
        nm = f" name={self.name}" if self.name else ""
        return f"[{self.id}:{self.kind}{nm}]"


@dataclasses.dataclass(frozen=True)
class Edge:
    """A link src(node,pad) -> dst(node,pad).  Pads are string names;
    "src"/"sink" are the default always-pads, "src_%u"/"sink_%u" request pads
    (mux/demux/tee analogs)."""

    src: int
    src_pad: str
    dst: int
    dst_pad: str


class GraphError(ValueError):
    pass


class PipelineGraph:
    def __init__(self):
        self._next_id = itertools.count()
        self.nodes: Dict[int, Node] = {}
        self.edges: List[Edge] = []
        self.by_name: Dict[str, Node] = {}
        #: dangling ``name.pad`` refs the parser could not resolve —
        #: populated only by ``parse(..., validate=False)`` as
        #: (name, pad, pos) tuples for the analyzer to report.
        self.unresolved_refs: List[Tuple[str, str, int]] = []
        #: node ids whose upstream link was dropped because it came from an
        #: unresolved chain-start ref (validate=False only): the dangling
        #: ref IS their input, so the analyzer must not also flag them as
        #: "missing '!'" or unreachable.
        self.phantom_fed: set = set()
        #: node ids whose DOWNSTREAM link was dropped because its target
        #: name never resolved (validate=False only): they did link out,
        #: just to a bad name — no derived leaf-not-sink noise.
        self.phantom_out: set = set()

    # -- construction ------------------------------------------------------
    def add(self, kind: str, props: Optional[Dict[str, object]] = None,
            caps: Optional[Caps] = None, pos: Optional[int] = None) -> Node:
        props = dict(props or {})
        name = props.pop("name", None)
        node = Node(next(self._next_id), kind, props, name, caps, pos)
        self.nodes[node.id] = node
        if name is not None:
            if name in self.by_name:
                raise GraphError(f"duplicate element name {name!r}")
            self.by_name[str(name)] = node
        return node

    def link(self, src: Node, dst: Node, src_pad: str = "src", dst_pad: str = "sink"):
        e = Edge(src.id, src_pad, dst.id, dst_pad)
        self.edges.append(e)
        return e

    # -- queries -----------------------------------------------------------
    def out_edges(self, node_id: int) -> List[Edge]:
        return [e for e in self.edges if e.src == node_id]

    def in_edges(self, node_id: int) -> List[Edge]:
        return [e for e in self.edges if e.dst == node_id]

    def sources(self) -> List[Node]:
        return [n for n in self.nodes.values() if not self.in_edges(n.id)]

    def sinks(self) -> List[Node]:
        return [n for n in self.nodes.values() if not self.out_edges(n.id)]

    def topo_order(self) -> List[Node]:
        """Topological order; repo-loop back-edges (reposrc/reposink pairs by
        slot name) are implicit — reposrc has no in-edge, so the DAG check
        holds even for recurrent pipelines (reference: tensor_repo slots)."""
        indeg = {i: len(self.in_edges(i)) for i in self.nodes}
        ready = sorted(i for i, d in indeg.items() if d == 0)
        out: List[Node] = []
        while ready:
            i = ready.pop(0)
            out.append(self.nodes[i])
            for e in self.out_edges(i):
                indeg[e.dst] -= 1
                if indeg[e.dst] == 0:
                    ready.append(e.dst)
            ready.sort()
        if len(out) != len(self.nodes):
            cyc = self.find_cycle()
            detail = ""
            if cyc:
                detail = " — " + " -> ".join(
                    self.nodes[i].name or f"{self.nodes[i].kind}[{i}]"
                    for i in cyc)
            raise GraphError(
                "pipeline graph has a cycle (use tensor_repo for loops)"
                + detail)
        return out

    def find_cycle(self) -> Optional[List[int]]:
        """Node ids forming one cycle (closed: first == last), or None.
        Used by topo_order's error message and the static analyzer's
        topology pass (which must report, not raise)."""
        WHITE, GREY, BLACK = 0, 1, 2
        color = {i: WHITE for i in self.nodes}
        stack: List[int] = []

        def dfs(i: int) -> Optional[List[int]]:
            color[i] = GREY
            stack.append(i)
            for e in self.out_edges(i):
                if color[e.dst] == GREY:
                    return stack[stack.index(e.dst):] + [e.dst]
                if color[e.dst] == WHITE:
                    got = dfs(e.dst)
                    if got is not None:
                        return got
            stack.pop()
            color[i] = BLACK
            return None

        for i in sorted(self.nodes):
            if color[i] == WHITE:
                got = dfs(i)
                if got is not None:
                    return got
        return None

    def validate(self) -> None:
        if not self.nodes:
            raise GraphError("empty pipeline")
        self.topo_order()
        # pad uniqueness: one edge per (node, pad) endpoint
        seen_src, seen_dst = set(), set()
        for e in self.edges:
            if e.src not in self.nodes or e.dst not in self.nodes:
                raise GraphError(f"edge references unknown node: {e}")
            k = (e.src, e.src_pad)
            if k in seen_src:
                raise GraphError(f"source pad linked twice: {k} (insert a tee)")
            seen_src.add(k)
            k = (e.dst, e.dst_pad)
            if k in seen_dst:
                raise GraphError(f"sink pad linked twice: {k}")
            seen_dst.add(k)

    def __str__(self):  # pragma: no cover
        lines = [str(n) for n in self.nodes.values()]
        lines += [f"  {e.src}.{e.src_pad} -> {e.dst}.{e.dst_pad}" for e in self.edges]
        return "\n".join(lines)
