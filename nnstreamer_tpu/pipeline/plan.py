"""Stage planner: physical execution plan + XLA fusion pass.

This is the capability the reference cannot have (SURVEY §7 "Stage fusion is
the superpower"): contiguous device-capable elements (converter repack,
tensor_transform chains, the jax tensor_filter, decoder math) are grouped
into ONE jitted XLA program.  The element graph stays the *logical* model;
the plan is the *physical* one, with host boundaries only where unavoidable
(app sources, sinks, host-only elements).

Fusion rule: a maximal linear chain of nodes where every element exposes
``device_fn`` for its negotiated input spec, with single in/out edges on the
default pads, collapses into a :class:`FusedElement`.  The composed function
is jitted once, so intermediate tensors never leave HBM and XLA fuses
elementwise stages into the matmul kernels around them; the folded-source
path additionally donates its input buffers (sole ownership is guaranteed
there), letting XLA reuse the generated frame's HBM for outputs.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Tuple

from ..core.buffer import Buffer
from ..core.caps import Caps, MediaType
from ..core.log import logger
from ..core.types import TensorsSpec
from ..elements.base import Element, SourceElement, SRC, SINK
from .graph import Edge, PipelineGraph

log = logger(__name__)


@dataclasses.dataclass
class Stage:
    """One schedulable unit: a single element or a fused chain."""

    element: Element
    node_ids: List[int]
    head: int  # node id receiving external input
    tail: int  # node id producing external output
    #: device stage whose runner may drain a micro-batch from its queue
    #: into one bucketed XLA dispatch (set by the planner; the runtime
    #: additionally requires the pipeline's batch_max > 1)
    batchable: bool = False
    #: batchable stage whose bucketed dispatch may additionally be
    #: SHARDED over the ``data`` axis of a local device mesh: requires a
    #: static negotiated input spec (one sharded program, not one per
    #: signature) and no deferred host_post mapping (its async D2H
    #: ordering is tuned for single-device rows).  The runtime
    #: additionally requires ``data_parallel`` to resolve to > 1.
    shardable: bool = False
    #: PURE/STATELESS stage whose runner thread may be restarted in
    #: place after an exception instead of failing the pipeline (the
    #: elastic stage-restart path, bounded by the pipeline's
    #: ``max_stage_restarts`` — docs/SERVING.md "Elastic serving").
    #: True for fused device chains and single elements whose work is a
    #: pure device fn (the batchable predicate); sources, sinks, and
    #: elements with cross-buffer state (aggregators, async emitters)
    #: stay fail-fast.
    restartable: bool = False

    def external_out_pad(self, edge: Edge) -> str:
        return edge.src_pad

    def external_in_pad(self, edge: Edge) -> str:
        return edge.dst_pad


class FusedElement(Element):
    """A chain of device elements compiled into one jitted function."""

    kind = "fused"

    def __init__(self, elements: List[Element], specs: List[TensorsSpec],
                 donate: bool = False, ingress_put: bool = False):
        super().__init__({}, name="+".join(e.name for e in elements))
        self.chain = elements
        self._fn = None
        self._batcher = None
        self._out_spec: Optional[TensorsSpec] = None
        self._in_spec = specs[0]
        self._specs = list(specs)
        # Host-fed ingress donation (docs/FETCH.md): the stage device_puts
        # the pushed host arrays itself and hands XLA freshly-minted device
        # buffers it solely owns — the donated program then reuses their
        # HBM for outputs, so steady-state H2D stops allocating.  Only set
        # by the planner when the feeding source is a host source with
        # this stage as its single consumer.
        self._ingress_put = ingress_put
        self._donate_active = False  # decided at first _jitted() call
        # Tail element may pair its device_fn with a deferred host mapping
        # (e.g. image_labeling: device argmax -> host label text).  The fused
        # stage emits the tiny device outputs with an async D2H already in
        # flight; the sink resolves `_host_post` in the app thread, so the
        # tunnel's D2H roundtrip adds pipeline depth, not throughput.
        self._host_post = getattr(elements[-1], "host_post", None)
        self._build(specs[0], donate)

    def _build(self, in_spec: TensorsSpec, donate: bool) -> None:
        fns: List[Callable] = []
        spec = in_spec
        for el in self.chain:
            df = el.device_fn(spec)
            if df is None:  # pragma: no cover - planner guarantees fusable
                raise RuntimeError(f"element {el.name} not fusable")
            fn, spec = df
            fns.append(fn)
        self._out_spec = spec

        def composed(arrays: Tuple) -> Tuple:
            for f in fns:
                arrays = f(arrays)
            return arrays

        self._composed = composed
        self._donate = donate

    def _jitted(self):
        """Build the jitted program on FIRST use, not at plan time: the
        donation gate reads jax.default_backend(), which initializes the
        backend — with a dead device tunnel that call blocks forever, and
        pipeline CONSTRUCTION must stay backend-free (the round-3 outage
        is exactly this failure mode)."""
        if self._fn is None:
            import jax

            # Donation is only legal when the caller guarantees sole
            # ownership of the input buffers (the folded-source path: the
            # source mints a fresh device array per batch and this program
            # is its only consumer) — XLA then reuses the input HBM for
            # outputs.  CPU backends can't donate and would warn per
            # compile, so gate it.
            if self._donate and jax.default_backend() not in ("cpu",):
                self._fn = jax.jit(self._composed, donate_argnums=(0,))
                self._donate_active = True
            else:
                self._fn = jax.jit(self._composed)
                self._donate_active = False
            xr = getattr(self, "_xray", None)
            if xr is not None:
                # nns-xray census: the fused chain's single-buffer
                # program (the bucketed twins register via BatchRunner)
                self._fn = xr.track(
                    self._fn, self.name, "stage",
                    rec=getattr(self, "_trace_rec", None))
        return self._fn

    @property
    def out_spec(self) -> TensorsSpec:
        return self._out_spec

    def start(self) -> None:
        for el in self.chain:
            el.start()

    def stop(self) -> None:
        for el in self.chain:
            el.stop()

    def _finish(self, buf: Buffer, out) -> Buffer:
        """Shared output tail for the single and batched paths: spec
        fallback for odd shapes (a truncated tail batch from a device
        source with non-aligned num-buffers has a different leading dim
        than the negotiated spec — let the buffer derive its spec so
        wire/shm consumers see truthful byte counts), plus the deferred
        host-post mapping with its async D2H already in flight."""
        spec = self._out_spec
        if (spec is not None and len(out) and hasattr(out[0], "shape")
                and tuple(out[0].shape) != spec[0].shape):
            spec = None
        new = buf.with_tensors(list(out), spec=spec)
        if self._host_post is not None:
            for t in out:
                if hasattr(t, "copy_to_host_async"):
                    t.copy_to_host_async()
            new.meta["_host_post"] = self._host_post
        return new

    def process(self, pad: str, buf: Buffer):
        # Fused-chain-to-fused-chain hop (the common case): the upstream
        # stage's outputs are ALREADY device arrays, and jit re-wraps its
        # own argument types for free — per-tensor jnp.asarray here only
        # added a host round through the dispatch path (~1.6x the whole
        # call overhead for a 4-tensor buffer, see PR microbench note).
        fn = self._jitted()  # first call decides _donate_active
        ingress_put = self._ingress_put and self._donate_active
        if buf.on_device:
            if ingress_put:
                # The donated program consumes its inputs.  An app CAN
                # push device arrays through appsrc (no host copy to
                # mint fresh ownership from), so force a copy — handing
                # app-owned arrays to donate_argnums would invalidate
                # the caller's references ("Array has been deleted").
                import jax.numpy as jnp

                arrays = tuple(jnp.array(t, copy=True) for t in buf.tensors)
            else:
                arrays = tuple(buf.tensors)
        elif ingress_put:
            # Donated ingress: explicit device_put mints device arrays
            # this call solely owns (the app's numpy frame is copied,
            # never aliased), so the donated program may reuse their HBM
            # for outputs.  When donation is compiled OUT (CPU backend)
            # ingress_put is False and the plain asarray path below
            # avoids paying copies that protect nothing.
            import jax

            arrays = tuple(jax.device_put(t) for t in buf.tensors)
        else:
            import jax.numpy as jnp

            arrays = tuple(jnp.asarray(t) for t in buf.tensors)
        out = fn(arrays)
        return [(SRC, self._finish(buf, out))]

    # -- micro-batching ----------------------------------------------------
    def batch_capable(self) -> bool:
        return True

    def place_params(self, mesh) -> bool:
        """Place every chain element's params onto ``mesh`` (shard over
        the ``model`` axis per each element's pspecs, replicate the
        rest), then rebuild the composed function so its device_fn
        closures capture the placed trees (a stale closure would keep
        dragging the original single-device arrays into every sharded
        dispatch)."""
        moved = False
        for el in self.chain:
            moved = el.place_params(mesh) or moved
        if moved:
            self._fn = None  # re-jit from the recaptured closures
            self._build(self._specs[0], self._donate)
        return moved

    def _shard_prepare(self, mesh):
        """BatchRunner prepare hook: place once, hand back the rebuilt
        composed fn."""
        self.place_params(mesh)
        return self._composed

    def process_batch(self, pad: str, bufs):
        """N same-spec buffers -> ONE bucketed vmapped dispatch of the
        fused program (see pipeline/batching.py); per-buffer outputs keep
        their own pts/meta and order.  With a ``data`` mesh attached by
        the runtime (``_shard_mesh``), the bucketed batch dim is sharded
        across the mesh's chips."""
        from .batching import BatchRunner

        if self._batcher is None:
            mesh = getattr(self, "_shard_mesh", None)
            self._batcher = BatchRunner(
                self._composed, getattr(self, "_batch_buckets", None),
                name=self.name, mesh=mesh,
                prepare=self._shard_prepare if mesh is not None else None,
                tracer=getattr(self, "_trace_rec", None),
                ladder=getattr(self, "_batch_ladder", None),
                xray=getattr(self, "_xray", None))
        rows = self._batcher.run([tuple(b.tensors) for b in bufs])
        return [(SRC, self._finish(buf, row)) for buf, row in zip(bufs, rows)]

    def finalize(self):
        outs = []
        for el in self.chain:
            outs.extend(el.finalize())
        # flushed buffers from mid-pipeline elements are NOT re-run through
        # the remaining fused fns; fusable elements are stateless so
        # finalize() output is empty in practice.
        return outs


class FusedSourceElement(SourceElement):
    """A device-resident source folded into its downstream fused chain.

    When the source generates ON DEVICE (``videotestsrc device=true``,
    ``audiotestsrc device=true``), running it as its own stage buys
    nothing: every batch pays a queue hop and a thread wakeup between two
    async device dispatches.  Folding the source into the fused stage makes
    the whole pipeline front ONE schedulable unit — generate and process
    dispatch back-to-back on the same thread, and the only queue hop left
    on the hot path is the sink's (round-2 bench: host-side stage hops cost
    ~13x the 0.27 ms device time per 64-batch).
    """

    kind = "fused"

    def __init__(self, source: Element, fused: "FusedElement"):
        super().__init__({}, name=f"{source.name}+{fused.name}")
        self.source = source
        self.fused = fused

    # cost-analysis hooks (bench reads the fused program off stage elements)
    @property
    def _fn(self):
        return self.fused._fn

    @property
    def _in_spec(self):
        return self.fused._in_spec

    # No start()/stop() overrides: the pipeline starts/stops the ORIGINAL
    # per-node elements directly (runtime iterates self.elements, not stage
    # wrappers), so overrides here would either never run or double-start.

    def generate(self):
        from ..core.buffer import Buffer as _Buffer

        for item in self.source.generate():
            if not isinstance(item, _Buffer):
                yield item  # events pass through
                continue
            outs = self.fused.process(SINK, item)
            for _, out in outs:
                yield out

    def finalize(self):
        return self.source.finalize() + self.fused.finalize()


#: minted buckets an adaptive ladder may add per stage when no
#: ``max_compiled_variants`` budget is configured (0 = uncapped would
#: leave the recompile census open — never allowed)
ADAPTIVE_EXTRA_DEFAULT = 4


def adaptive_variant_budget(base_len: int, n_batchable: int,
                            max_compiled_variants: int) -> int:
    """Max ladder entries (base + minted) ONE adaptive stage may compile —
    the single home for the arithmetic shared by the runtime (each
    stage's ``AdaptiveLadder.budget``) and the deep analyzer's recompile
    census (which prices the WORST CASE: every adaptive stage at its full
    budget), so the census stays closed by construction: the ladders can
    never mint past what the static report already charged.

    With ``max_compiled_variants`` configured, the budget splits it
    evenly across the pipeline's batchable stages (never below the base
    ladder — refinement may be squeezed out entirely, the census may
    not).  Unconfigured, each stage gets the base ladder plus
    :data:`ADAPTIVE_EXTRA_DEFAULT` minted sizes."""
    if max_compiled_variants > 0:
        return max(base_len, max_compiled_variants // max(1, n_batchable))
    return base_len + ADAPTIVE_EXTRA_DEFAULT


def replication_plan(data_parallel: int, batch_max: int,
                     n_devices: int) -> int:
    """Resolve the configured ``data_parallel`` knob to the ``data``-axis
    replica count a pipeline would actually run with — the ONE place the
    0=auto / 1=off / N=exact semantics live, shared by the runtime's mesh
    builder and the deep analyzer's static HBM/recompile budgeting.
    ``n_devices`` is the local device count (the caller queries it so this
    stays importable without initializing a backend).  Returns 1 whenever
    sharding would be skipped (batch_max=1, dp=1, or a 1-wide mesh); the
    dp > n_devices startup error is the caller's to raise/report.

    2-D placements resolve through :func:`mesh_plan`, which calls this
    for the ``data`` axis after carving out the ``model`` axis."""
    if batch_max <= 1 or data_parallel == 1:
        return 1
    dp = data_parallel or n_devices
    return max(1, dp)


def mesh_plan(data_parallel: int, model_parallel: int, batch_max: int,
              n_devices: int) -> Tuple[int, int]:
    """Resolve the 2-D placement knobs to the ``(data, model)`` axis sizes
    ONE pipeline mesh would be built with — the single home for the
    0=auto / 1=off / N=exact semantics of BOTH axes, shared by the
    runtime's mesh builder (``Pipeline._shared_mesh``) and the deep
    analyzer's static HBM/recompile budgeting.

    * ``model_parallel`` — 1 = off (dp-only, the bit-identical legacy
      path), N = exactly N ways tensor-parallel, 0 = auto: absorb every
      local device the ``data`` axis doesn't claim.  Unlike ``data``,
      the model axis is NOT gated on ``batch_max``: a TP-only pipeline
      (the llm filter) shards weights with no micro-batching at all.
    * ``data_parallel`` — exactly :func:`replication_plan`, sized
      against the devices LEFT after the model axis took its share.
    * both auto (``data=0, model=0`` with batching on) — data wins: the
      historical ``data_parallel=0`` auto-absorb stays what it was.

    Over-asks (dp * mp > n_devices) are returned as requested; raising
    the startup error (or the static diagnostic) is the caller's job."""
    mp_knob = int(model_parallel)
    dp_knob = int(data_parallel)
    if mp_knob == 0:
        dp_res = replication_plan(dp_knob, batch_max, n_devices)
        if dp_knob == 0 and dp_res > 1:
            mp = 1  # both axes auto: data absorbs, dp-only semantics hold
        else:
            mp = max(1, n_devices // max(1, dp_res))
    else:
        mp = max(1, mp_knob)
    dp = replication_plan(dp_knob, batch_max, max(1, n_devices // mp))
    return dp, mp


def _element_batchable(el: Element) -> bool:
    """Can this stage's runner drain micro-batches?  Sources have no input
    queue; batch_capable() must not veto planning by raising (a framework
    that cannot even load will fail loudly at start() instead)."""
    if isinstance(el, SourceElement):
        return False
    try:
        return bool(el.batch_capable())
    except Exception:  # noqa: BLE001 - capability probe only
        return False


def _element_shardable(el: Element, batchable: bool) -> bool:
    """Shard-eligibility for a SINGLE-element stage: batchable, a STATIC
    negotiated input spec (a flexible stream re-specializes per buffer
    signature — sharding would compile a mesh program per signature and
    defeat the bucket ladder), and no deferred host_post mapping."""
    if not batchable or getattr(el, "host_post", None) is not None:
        return False
    caps = el.in_caps.get(SINK)
    spec = caps.spec if caps is not None else None
    return spec is not None and spec.format.value == "static"


def plan_stages(
    graph: PipelineGraph, elements: Dict[int, Element], *, fuse: bool = True,
    donate_ingress: bool = False
) -> List[Stage]:
    """Partition the graph into stages; fuse linear device chains.

    ``donate_ingress`` lets a fused chain fed by a HOST source (appsrc,
    file/camera ingest — not ``device=true`` test sources, which already
    donate via the folded-source path) device_put its input buffers and
    donate them to the compiled program: the planner can prove sole
    ownership when the source has this chain as its only consumer, so XLA
    reuses the ingress HBM for outputs (docs/FETCH.md)."""
    order = graph.topo_order()
    if not fuse:
        stages = []
        for n in order:
            b = _element_batchable(elements[n.id])
            stages.append(Stage(
                elements[n.id], [n.id], n.id, n.id, batchable=b,
                shardable=_element_shardable(elements[n.id], b),
                restartable=b))
        return stages

    def linear(nid: int) -> bool:
        ins = graph.in_edges(nid)
        outs = graph.out_edges(nid)
        return (
            len(ins) == 1
            and len(outs) <= 1
            and ins[0].dst_pad == SINK
            and all(e.src_pad == SRC for e in outs)
        )

    def fusable(nid: int) -> Optional[TensorsSpec]:
        """In-spec if the element can join a fused chain, else None."""
        el = elements[nid]
        caps = el.in_caps.get(SINK)
        if caps is None or caps.media not in (MediaType.TENSORS, MediaType.FLEX_TENSORS):
            return None
        spec = caps.spec
        if spec is None or spec.format.value != "static":
            return None
        if el.device_fn(spec) is None:
            return None
        return spec

    stages: List[Stage] = []
    consumed: set = set()

    def grow(first: int) -> Optional[Tuple[List[int], List[TensorsSpec]]]:
        """Maximal fusable chain from ``first`` (None if it can't fuse)."""
        if first in consumed or not linear(first):
            return None
        spec = fusable(first)
        if spec is None:
            return None
        chain = [first]
        specs = [spec]
        cur_spec = elements[first].device_fn(spec)[1]
        cur = first
        while True:
            outs = graph.out_edges(cur)
            if len(outs) != 1:
                break
            nxt = outs[0].dst
            if nxt in consumed or not linear(nxt):
                break
            el = elements[nxt]
            caps = el.in_caps.get(SINK)
            nspec = caps.spec if caps else None
            nspec = nspec or cur_spec
            if el.device_fn(nspec) is None:
                break
            chain.append(nxt)
            specs.append(nspec)
            cur_spec = el.device_fn(nspec)[1]
            cur = nxt
        return chain, specs

    for node in order:
        if node.id in consumed:
            continue
        el = elements[node.id]
        # Device-resident sources fold into their downstream chain: the
        # whole pipeline front becomes one stage (no queue hop between
        # generate and the fused program).  `device is True` exactly: on
        # tensor_src_iio `device` is a PATH STRING (a blocking host
        # reader), and folding that would serialize I/O with compute.
        if isinstance(el, SourceElement) and getattr(el, "device", None) is True:
            outs = graph.out_edges(node.id)
            if (len(outs) == 1 and outs[0].src_pad == SRC
                    and outs[0].dst_pad == SINK):
                grown = grow(outs[0].dst)
                if grown is not None:
                    chain, specs = grown
                    fe = FusedElement([elements[i] for i in chain], specs,
                                      donate=True)
                    fs = FusedSourceElement(el, fe)
                    log.info("fused device source into XLA stage: %s",
                             fs.name)
                    stages.append(
                        Stage(fs, [node.id] + chain, node.id, chain[-1]))
                    consumed.add(node.id)
                    consumed.update(chain)
                    continue
        grown = grow(node.id)
        if grown is None or len(grown[0]) == 1:
            b = _element_batchable(elements[node.id])
            stages.append(Stage(
                elements[node.id], [node.id], node.id, node.id, batchable=b,
                shardable=_element_shardable(elements[node.id], b),
                restartable=b))
            consumed.add(node.id)
            continue
        chain, specs = grown
        donate = False
        if donate_ingress:
            ins = graph.in_edges(chain[0])
            if len(ins) == 1:
                feeder = elements[ins[0].src]
                # Host source with a single consumer: every pushed buffer
                # is minted fresh by the chain's own device_put and this
                # program is its only reader — donation is legal.  A
                # device=true source folds (and donates) above instead.
                donate = (isinstance(feeder, SourceElement)
                          and getattr(feeder, "device", None) is not True
                          and len(graph.out_edges(ins[0].src)) == 1)
        fe = FusedElement([elements[i] for i in chain], specs,
                          donate=donate, ingress_put=donate)
        if donate:
            log.info("ingress donation enabled for fused stage %s", fe.name)
        log.info("fused %d elements into one XLA stage: %s", len(chain), fe.name)
        # Fused chains negotiated a static spec by construction (fusable()
        # requires it); only a deferred host_post gates sharding.
        stages.append(Stage(fe, chain, chain[0], chain[-1], batchable=True,
                            shardable=fe._host_post is None,
                            restartable=True))
        consumed.update(chain)
    return stages
