"""HBM-residency planner: decide, per sink edge, what actually crosses to host.

Upstream nnstreamer's core promise is that tensors stay pipeline-resident
between elements (PAPER §0).  On TPU the pipeline-resident place is HBM and
the expensive boundary is the D2H link — BENCH_ALL_r5 measured 38 MB/s with
~90 ms small-fetch RTT on the tunneled chip, and the one row below parity
(appsrc classification, 0.761x) spent 27.7 s of a 43 s run stalled on it,
while shipping the 256x-smaller native-stride class map instead of the
full-resolution one bought segmentation 34x.  This module generalizes that
lesson into planner architecture:

* **Fetch plan** (:func:`plan_residency`): for every edge into a sink, the
  planner records statically what is going to cross to host per buffer —
  the fused sink reduction's tiny device outputs when the stage tail pairs
  ``device_fn`` with ``host_post`` (argmax/top-k/NMS/decode already run on
  device), or the negotiated spec's full payload otherwise.  Edges between
  device stages are device-resident by construction (buffers are jax
  Arrays in HBM end to end) and are pinned so by tests.
* **Reduced-output selection** (:func:`mark_reduced_admissible`): when a
  model offers a REDUCED output variant (``ModelBundle.reduced_variant``,
  e.g. deeplab's native-stride score map: the class decision at the
  model's true resolution, of which full res is only a bilinear blow-up)
  and EVERY downstream consumer's negotiated caps admit arbitrary tensor
  geometry (``admits_reduced_payload``), the planner selects it — "fetch
  the 256x-smaller thing" becomes the default, not a hand-tuned
  ``custom=upsample:0`` row.  ``Pipeline(reduce_outputs=False)`` /
  ``NNS_TPU_REDUCE_OUTPUTS=0`` opts out.
* **Pricing** (:func:`fetch_ms` / :func:`compute_floor_ms`): the shared
  arithmetic the deep lint (``analysis/tracecheck.py``) uses to convert
  planned fetch bytes into milliseconds on the calibrated link and flag
  ``fetch-bound`` pipelines before a chip is touched.

See docs/FETCH.md.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

from ..core.log import logger
from ..elements.base import Element, SinkElement, SourceElement

log = logger(__name__)

#: HBM bandwidth (GB/s, v5e spec sheet) behind the static compute-floor
#: roofline: a device stage cannot finish a buffer faster than streaming
#: its params + activations through HBM once.  Deliberately a FLOOR — the
#: ``fetch-bound`` diagnostic only fires when planned D2H time exceeds
#: even this lower bound on compute, so it never over-fires on
#: compute-heavy stages.
HBM_GBPS = 819.0


def fetch_ms(nbytes: int, d2h_mbps: float, rtt_ms: float = 0.0) -> float:
    """Planned D2H milliseconds for one buffer on the calibrated link:
    bandwidth term + one small-fetch roundtrip (every pull that catches
    the prefetcher pays the RTT once)."""
    if d2h_mbps <= 0:
        return 0.0
    return nbytes / (d2h_mbps * 1e6) * 1e3 + max(0.0, rtt_ms)


def compute_floor_ms(touched_bytes: int) -> float:
    """Roofline lower bound on a device stage's per-buffer time: bytes it
    must stream through HBM (params + in/out activations), at
    :data:`HBM_GBPS`."""
    return touched_bytes / (HBM_GBPS * 1e9) * 1e3


@dataclasses.dataclass
class FetchEdge:
    """Planned D2H crossing for one edge into a sink."""

    sink: str  # sink element name
    producer: str  # stage/element label feeding it
    #: planned bytes crossing to host per buffer (-1 = unknown statically:
    #: flexible spec, host-derived payload)
    bytes_per_buffer: int
    #: how the payload was shrunk before crossing (None = raw negotiated
    #: spec crosses): "fused host_post" = device reduction's tiny outputs,
    #: "reduced output" = planner-selected reduced model output
    reduced: Optional[str] = None
    #: pricing (filled only when a calibrated link is configured)
    d2h_ms: float = 0.0
    compute_floor_ms: float = 0.0


@dataclasses.dataclass
class ResidencyPlan:
    """The residency planner's verdict for one pipeline."""

    fetch: List[FetchEdge]
    #: inter-stage edges whose payload stays a device array in HBM
    resident_edges: int = 0
    #: element names whose reduced output variant the planner selected
    reduced_outputs: List[str] = dataclasses.field(default_factory=list)

    def render(self) -> str:
        lines = [f"residency plan: {self.resident_edges} device-resident "
                 f"edge(s)"]
        for name in self.reduced_outputs:
            lines.append(f"  reduced output selected: {name}")
        for e in self.fetch:
            size = ("?" if e.bytes_per_buffer < 0
                    else f"{e.bytes_per_buffer} B")
            via = f" via {e.reduced}" if e.reduced else ""
            lines.append(
                f"  fetch {e.sink} <- {e.producer}: {size}/buffer{via}")
        return "\n".join(lines)


# ---------------------------------------------------------------------------
# reduced-output admissibility
# ---------------------------------------------------------------------------

def _admits_downstream(graph, elements: Dict[int, Element], nid: int,
                       memo: Dict[int, bool]) -> bool:
    """True when EVERY path from ``nid``'s outputs to a sink runs through
    elements that declare ``admits_reduced_payload`` — i.e. no consumer's
    negotiated contract depends on the producer's full output geometry.
    Conservative by default: an element that doesn't opt in vetoes."""
    if nid in memo:
        return memo[nid]
    memo[nid] = False  # cycle-safe: a loop can never reach a sink
    outs = graph.out_edges(nid)
    if not outs:
        memo[nid] = False  # dangling edge: nothing admits
        return False
    for e in outs:
        dst = elements[e.dst]
        if not getattr(dst, "admits_reduced_payload", False):
            return False
        if not isinstance(dst, SinkElement) \
                and not _admits_downstream(graph, elements, e.dst, memo):
            return False
    memo[nid] = True
    return True


def mark_reduced_admissible(graph, elements: Dict[int, Element]) -> List[str]:
    """Pre-negotiation pass: mark every tensor_filter whose downstream
    consumers all admit reduced geometry with ``_reduced_admissible`` so
    its ``configure()`` may switch the framework to the model's reduced
    output variant (if it offers one).  Runs BEFORE caps negotiation —
    the switch changes the negotiated spec.  Returns the marked names."""
    from ..elements.filter import TensorFilter

    memo: Dict[int, bool] = {}
    marked: List[str] = []
    for nid, el in elements.items():
        if not isinstance(el, TensorFilter):
            continue
        if _admits_downstream(graph, elements, nid, memo):
            el._reduced_admissible = True
            marked.append(el.name)
    return marked


# ---------------------------------------------------------------------------
# fetch plan (runtime: post-negotiation, post-stage-planning)
# ---------------------------------------------------------------------------

def _spec_bytes(caps) -> int:
    spec = getattr(caps, "spec", None)
    if spec is None or spec.is_flexible:
        return -1
    try:
        return int(spec.nbytes)
    except (TypeError, ValueError):
        return -1


def plan_residency(graph, elements: Dict[int, Element],
                   stages) -> ResidencyPlan:
    """Build the pipeline's :class:`ResidencyPlan` from the negotiated
    graph and the planned stages.  Per sink edge the planned fetch is:

    * the producing fused stage's DEVICE out spec when its tail pairs
      ``device_fn`` with a deferred ``host_post`` (the fused sink
      reduction: only argmax indices / kept boxes / class ids cross,
      resolved to media on the app side);
    * otherwise the negotiated spec's bytes at the edge (-1 when flexible).
    """
    node_to_stage = {}
    for st in stages:
        for nid in st.node_ids:
            node_to_stage[nid] = st

    def _device_stage(st) -> bool:
        el = st.element
        # device_resident: stateful device elements (the aggregator's HBM
        # ring) that expose no fusable device_fn but still emit device
        # arrays — their downstream edges stay in HBM
        return (st.batchable or getattr(el, "kind", "") == "fused"
                or getattr(el, "device_resident", False)
                or type(el).device_fn is not Element.device_fn)

    fetch: List[FetchEdge] = []
    resident = 0
    reduced_names = [el.name for el in elements.values()
                     if getattr(el, "reduced_output_selected", None)]
    for e in graph.edges:
        src_st = node_to_stage.get(e.src)
        dst_st = node_to_stage.get(e.dst)
        if src_st is None or dst_st is None or src_st is dst_st:
            continue  # fused-internal edge: resident by construction
        dst_el = dst_st.element
        if isinstance(dst_el, SinkElement):
            prod = src_st.element
            # a folded device source wraps the fused chain — the chain
            # carries the host_post / device out spec
            fused = getattr(prod, "fused", prod)
            host_post = getattr(fused, "_host_post", None)
            if host_post is not None and getattr(fused, "_out_spec", None) \
                    is not None:
                spec = fused._out_spec
                nbytes = -1 if spec.is_flexible else int(spec.nbytes)
                fetch.append(FetchEdge(
                    sink=dst_el.name, producer=prod.name,
                    bytes_per_buffer=nbytes, reduced="fused host_post"))
            else:
                src_el = elements.get(e.src)
                caps = (src_el.out_caps.get(e.src_pad)
                        if src_el is not None else None)
                red = ("reduced output"
                       if src_el is not None and getattr(
                           src_el, "reduced_output_selected", None)
                       else None)
                fetch.append(FetchEdge(
                    sink=dst_el.name, producer=src_st.element.name,
                    bytes_per_buffer=_spec_bytes(caps), reduced=red))
        elif _device_stage(src_st) and _device_stage(dst_st) \
                and not isinstance(src_st.element, SourceElement):
            # device stage -> device stage: the payload is a jax Array
            # that never leaves HBM (zero-copy hop, pinned by
            # tests/test_fetch.py)
            resident += 1
    return ResidencyPlan(fetch=fetch, resident_edges=resident,
                         reduced_outputs=reduced_names)
