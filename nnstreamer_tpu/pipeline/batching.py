"""Adaptive micro-batch dispatch: N queued buffers -> ONE jitted XLA call.

The executor's unit of work is one buffer; per-dispatch overhead (python
jit call, XLA launch, tunnel RTT) is paid per buffer.  When a device
stage's queue is backlogged, that overhead dominates small models — the
same lesson PROFILE_LLM_r5 taught at the kernel layer (halving kernel-call
count bought 1.23x decode throughput) applies at the stage layer.

:class:`BatchRunner` wraps a stage's pure per-buffer function
``tuple(arrays) -> tuple(arrays)`` and executes a LIST of per-buffer input
rows as one compiled program:

* the batch is padded up to a small set of **buckets** (default powers of
  two) so XLA compiles one program per bucket, not per occupancy;
* padding repeats the last real row — valid data, no masking, and the
  repeated references cost nothing outside jit;
* stack -> vmap(fn) -> split all happen INSIDE the jitted program, so a
  batch of 8 costs exactly one dispatch (no per-row slice dispatches), and
  the split rows are device buffers that stay in HBM.

Row outputs are bit-equal across occupancies of the same bucket (same
compiled program; pad rows only append rows, never change the math of the
real ones).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..core.buffer import pad_rows, split_rows, stack_tensors
from ..core.log import metrics

#: default bucket ladder; bucket_for() falls back to the exact size above it
DEFAULT_BUCKETS: Tuple[int, ...] = (1, 2, 4, 8, 16, 32, 64, 128, 256)


def bucket_for(n: int, buckets: Optional[Sequence[int]] = None) -> int:
    """Smallest allowed batch size >= n (exact n when above the ladder)."""
    for b in buckets or DEFAULT_BUCKETS:
        if b >= n:
            return b
    return n


class BatchRunner:
    """Per-stage cache of bucketed ``jit(vmap(fn))`` programs.

    ``fn`` is the stage's pure per-buffer function.  jit's own cache
    handles input shape/dtype changes; this cache keys only the bucket
    size (which is baked into the program's split).
    """

    def __init__(self, fn: Callable, buckets: Optional[Sequence[int]] = None,
                 name: Optional[str] = None):
        self.fn = fn
        self.buckets = tuple(sorted(set(buckets))) if buckets else None
        self._progs: Dict[int, Callable] = {}
        self._pad_metric = f"{name}.batch_pad_waste" if name else None

    def run(self, rows: List[Tuple]) -> List[Tuple]:
        """Execute per-buffer input rows as one dispatch; returns one
        output row per input row, in order."""
        n = len(rows)
        bucket = bucket_for(n, self.buckets)
        prog = self._progs.get(bucket)
        if prog is None:
            prog = self._progs[bucket] = self._build(bucket)
        if bucket > n:
            rows = pad_rows(rows, bucket)
            if self._pad_metric:
                metrics.count(self._pad_metric, bucket - n)
        return list(prog(*rows)[:n])

    def _build(self, bucket: int) -> Callable:
        import jax

        fn = self.fn

        def prog(*per_buf):
            stacked = stack_tensors(per_buf)
            outs = jax.vmap(fn)(stacked)
            if not isinstance(outs, (tuple, list)):
                outs = (outs,)
            return tuple(split_rows(tuple(outs), bucket))

        return jax.jit(prog)
