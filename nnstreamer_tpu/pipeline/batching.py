"""Adaptive micro-batch dispatch: N queued buffers -> ONE jitted XLA call.

The executor's unit of work is one buffer; per-dispatch overhead (python
jit call, XLA launch, tunnel RTT) is paid per buffer.  When a device
stage's queue is backlogged, that overhead dominates small models — the
same lesson PROFILE_LLM_r5 taught at the kernel layer (halving kernel-call
count bought 1.23x decode throughput) applies at the stage layer.

:class:`BatchRunner` wraps a stage's pure per-buffer function
``tuple(arrays) -> tuple(arrays)`` and executes a LIST of per-buffer input
rows as one compiled program:

* the batch is padded up to a small set of **buckets** (default powers of
  two) so XLA compiles one program per bucket, not per occupancy;
* padding repeats the last real row — valid data, no masking, and the
  repeated references cost nothing outside jit;
* stack -> vmap(fn) -> split all happen INSIDE the jitted program, so a
  batch of 8 costs exactly one dispatch (no per-row slice dispatches), and
  the split rows are device buffers that stay in HBM.

Row outputs are bit-equal across occupancies of the same bucket (same
compiled program; pad rows only append rows, never change the math of the
real ones).

**Sharded mode** (the mesh-DP tentpole, docs/BATCHING.md "Sharded
dispatch"): given a mesh whose ``data`` axis is > 1, the bucketed batch
becomes the unit of data parallelism — the stacked batch dim is sharded
over the ``data`` axis (``in_shardings``/``out_shardings`` via
``parallel/sharding.data_sharding``), buckets round up to multiples of
the axis size so every replica holds equal rows, and stage parameters
are replicated onto the mesh ONCE before the first sharded dispatch (the
``prepare`` hook), not per call.  ``vmap`` guarantees rows never
interact, so the per-row math — and for elementwise stages the exact
bits — matches the single-device program.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..core.buffer import pad_rows, split_rows, stack_tensors
from ..core.log import metrics

#: default bucket ladder; bucket_for() LADDER-ROUNDS above it (multiples
#: of the top bucket), so programs stay bounded at any batch_max
DEFAULT_BUCKETS: Tuple[int, ...] = (1, 2, 4, 8, 16, 32, 64, 128, 256)


def bucket_for(n: int, buckets: Optional[Sequence[int]] = None) -> int:
    """Smallest allowed batch size >= n.  Above the ladder top the size is
    LADDER-ROUNDED — the next multiple of the top bucket — never the exact
    occupancy: an exact fallback minted one compiled program PER OCCUPANCY
    once ``batch_max`` exceeded the top (a 1000-deep drain could compile
    hundreds of signatures), which is precisely the recompile storm the
    ladder exists to prevent.  Rounding bounds the census at
    ``len(ladder) + batch_max // top`` programs (see :func:`ladder`)."""
    bs = buckets or DEFAULT_BUCKETS
    for b in bs:
        if b >= n:
            return b
    top = bs[-1]
    return top * (-(-n // top))


def ladder(batch_max: int, buckets: Optional[Sequence[int]] = None
           ) -> Tuple[int, ...]:
    """Every bucket size a runner with this ``batch_max`` can ever dispatch
    (ascending).  Mirrors :func:`bucket_for` exactly: sizes above the top
    bucket appear as multiples of the top (the ladder-rounded fallback) up
    to the rounded ``batch_max``, so the set never contains a size the
    runtime cannot produce — and never misses one it can.  This is the
    compiled-signature ladder the deep analyzer multiplies out for its
    recompile census and HBM high-water estimate — one compiled program
    per entry, per stage."""
    bs = tuple(sorted(set(buckets))) if buckets else DEFAULT_BUCKETS
    bm = max(1, batch_max)
    top = bucket_for(bm, bs)
    out = [b for b in bs if b <= top]
    if top > bs[-1]:
        out.extend(range(2 * bs[-1], top + 1, bs[-1]))
    return tuple(out)


def shard_bucket_for(n: int, replicas: int,
                     buckets: Optional[Sequence[int]] = None) -> int:
    """Bucket for a batch sharded over ``replicas``: the ladder bucket,
    rounded UP to a multiple of the replica count so every replica gets
    the same number of rows (XLA SPMD partitions the batch dim evenly —
    a ragged split would be a different program per remainder)."""
    b = bucket_for(n, buckets)
    return b + (-b) % max(1, replicas)


#: occupancy observations of one size before the adaptive ladder mints a
#: bucket for it: high enough that a transient burst shape never costs a
#: compile, low enough that a persistent drain pattern refines within the
#: first seconds of a backlogged run
MINT_AFTER = 24


class AdaptiveLadder:
    """Per-stage bucket ladder refined ONLINE from observed occupancies.

    The static powers-of-two ladder pads every drain up to the next power
    of two — a runner that persistently drains 5–7 rows pays bucket-8
    compute forever (pad-waste is a measured counter:
    ``<stage>.batch_pad_waste``).  This ladder watches the same occupancy
    stream the Prometheus histogram renders (``<stage>.batch_occupancy``,
    cumulative ``_bucket{le=}`` exposition) and MINTS an exact bucket for
    any occupancy observed :data:`MINT_AFTER` times that the current
    ladder would pad — so steady-state skew compiles one right-sized
    program instead of padding into a bigger one.

    Two hard bounds keep the deep-lint recompile census CLOSED:

    * ``budget`` — max ladder entries (base + minted), resolved by
      ``pipeline/plan.adaptive_variant_budget`` from
      ``Config.max_compiled_variants`` so the census the deep pass prices
      is the worst case this ladder can ever reach;
    * ``align`` — minted sizes round up to a multiple of the mesh's
      ``data``-axis width, so :func:`shard_bucket_for`'s replica rounding
      still applies bucket-for-bucket under 2-D placement.

    ``warm`` pre-seeds minted sizes (the export/warm-start path:
    ``Pipeline.ladder_snapshot()`` -> ``Config.bucket_ladders`` /
    ``Pipeline(bucket_ladders=...)``), so a steady-state deployment
    compiles its refined ladder at warmup instead of re-learning it.

    Thread-safety: ``bucket_for``/``observe`` run on the owning stage
    thread; ``sizes``/``export`` may be read from the app thread — the
    ladder tuple is swapped atomically under a small lock.
    """

    _GUARDED_BY = {"_minted": "_lock", "_sizes": "_lock",
                   "_align": "_lock"}

    def __init__(self, base: Optional[Sequence[int]] = None, *,
                 budget: int = 0, align: int = 1,
                 warm: Optional[Sequence[int]] = None,
                 mint_after: int = MINT_AFTER, name: Optional[str] = None):
        self.base: Tuple[int, ...] = (tuple(sorted(set(base))) if base
                                      else DEFAULT_BUCKETS)
        self._align = max(1, align)
        self.budget = max(len(self.base), budget) if budget else 0
        self.mint_after = max(1, mint_after)
        self.name = name
        self._lock = threading.Lock()
        self._counts: Dict[int, int] = {}
        self._minted: set = set()
        self._sizes = self.base
        self._minted_metric = f"{name}.ladder_minted" if name else None
        if warm:
            for s in warm:
                self._mint(int(s))

    @property
    def align(self) -> int:
        return self._align

    @align.setter
    def align(self, value: int) -> None:
        """Re-align every already-minted size to the new replica count.
        Warm-start sizes are minted at construction (align=1 — the mesh
        does not exist yet), and the runtime assigns the real ``data``
        width at start(): a dp=1 snapshot's minted 6 warm-started into a
        dp=4 deployment re-rounds to 8 here (deduping against the base),
        instead of sitting in the ladder as a never-dispatchable entry
        that burns a census budget slot."""
        # nns-tsan unguarded-write: the re-round below READS _align, so
        # the swap must be atomic with it — a racing setter otherwise
        # re-rounds _minted against the other thread's width
        with self._lock:
            self._align = max(1, int(value))
            self._minted = {self._aligned(s) for s in self._minted}
            self._minted.difference_update(self.base)
            self._sizes = tuple(sorted(set(self.base) | self._minted))

    def sizes(self) -> Tuple[int, ...]:
        """The current ladder (base + minted, ascending) — what
        :func:`bucket_for`/:func:`shard_bucket_for` round against and
        what the deep census would count if it could see this run."""
        return self._sizes

    def export(self) -> List[int]:
        """The ladder as a warm-startable list (``Config.bucket_ladders``
        value; feed back via ``Pipeline(bucket_ladders={stage: [...]})``)."""
        return list(self._sizes)

    def _aligned(self, n: int) -> int:
        return n + (-n) % self.align

    def _room(self) -> bool:
        return self.budget <= 0 or len(self._sizes) < self.budget

    def _mint(self, n: int) -> None:
        n = self._aligned(n)
        if n in self._sizes or n <= 0 or not self._room():
            return
        with self._lock:
            self._minted.add(n)
            self._sizes = tuple(sorted(set(self.base) | self._minted))
        if self._minted_metric:
            metrics.count(self._minted_metric)

    def observe(self, n: int) -> None:
        """Record one drain's occupancy; mint an exact (aligned) bucket
        once the same padded occupancy repeats ``mint_after`` times."""
        want = self._aligned(n)
        if want in self._sizes:
            return  # no pad at this occupancy: nothing to refine
        c = self._counts.get(want, 0) + 1
        self._counts[want] = c
        if c >= self.mint_after:
            del self._counts[want]
            self._mint(want)

    def bucket_for(self, n: int) -> int:
        """Observe ``n`` and return its bucket under the CURRENT ladder
        (refinement applies from the next drain on — the dispatch that
        triggered a mint still pads, so bucket choice never races the
        ladder swap)."""
        sizes = self._sizes
        self.observe(n)
        return bucket_for(n, sizes)


class BatchRunner:
    """Per-stage cache of bucketed ``jit(vmap(fn))`` programs.

    ``fn`` is the stage's pure per-buffer function.  jit's own cache
    handles input shape/dtype changes; this cache keys only the bucket
    size (which is baked into the program's split).

    ``mesh`` (with a ``data`` OR ``model`` axis > 1) switches on sharded
    dispatch: the batch dim shards over ``data`` while stage parameters
    are PLACED per their ``param_pspecs`` — sharded over ``model``,
    replicated otherwise.  ``prepare(mesh) -> Optional[new_fn]`` runs
    exactly once before the first sharded dispatch so the stage can place
    its parameters onto the mesh and hand back a fresh closure capturing
    the placed tree.
    """

    def __init__(self, fn: Callable, buckets: Optional[Sequence[int]] = None,
                 name: Optional[str] = None, mesh=None,
                 prepare: Optional[Callable] = None, tracer=None,
                 ladder: Optional[AdaptiveLadder] = None, xray=None):
        self.fn = fn
        self.buckets = tuple(sorted(set(buckets))) if buckets else None
        # adaptive mode: the per-stage AdaptiveLadder replaces the static
        # bucket list for rounding decisions (and observes every drain)
        self.ladder = ladder
        self._name = name or "batch"
        # the owning pipeline's flight recorder (None = that pipeline runs
        # trace_mode=off, even if another pipeline enabled the global one)
        self._tracer = tracer
        # the owning pipeline's nns-xray program registry (None = off:
        # bucket programs compile untracked, one pointer check here)
        self._xray = xray
        self._progs: Dict[int, Callable] = {}
        self._pad_metric = f"{name}.batch_pad_waste" if name else None
        self._waste_flops_metric = (f"{name}.pad_waste_flops"
                                    if name else None)
        self._shard_metric = f"{name}.shard_rows" if name else None
        self._dispatch_metric = f"{name}.shard_dispatch" if name else None
        self.mesh = None
        self.replicas = 1
        self.model_axis = 1
        self._sharding = None
        self._dev_coords = None
        if mesh is not None:
            from ..parallel.mesh import device_coords, mesh_axis_size

            d = mesh_axis_size(mesh, "data")
            m = mesh_axis_size(mesh, "model")
            # a (1, 1) mesh is exactly the unsharded path; a >1 model
            # axis engages the sharded path even at data=1 so the
            # prepare hook can SHARD stage params over `model` (2-D
            # placement, docs/BATCHING.md "2-D sharded dispatch")
            if d > 1 or m > 1:
                from ..parallel.sharding import data_sharding

                self.mesh = mesh
                self.replicas = d
                self.model_axis = m
                # invariant per runner: built once, reused by every
                # dispatch's device_put AND the program's in/out_shardings
                self._sharding = data_sharding(mesh)
                if m > 1:
                    # device id -> (data, model) coordinate: 2-D runs name
                    # per-replica counters by mesh position, not raw id
                    self._dev_coords = device_coords(mesh)
        self._prepare = prepare
        self._prepared = False

    def run(self, rows: List[Tuple]) -> List[Tuple]:
        """Execute per-buffer input rows as one dispatch; returns one
        output row per input row, in order."""
        if self.mesh is not None:
            return self._run_sharded(rows)
        n = len(rows)
        bucket = (self.ladder.bucket_for(n) if self.ladder is not None
                  else bucket_for(n, self.buckets))
        prog = self._progs.get(bucket)
        if prog is None:
            prog = self._progs[bucket] = self._build(bucket)
        if bucket > n:
            rows = pad_rows(rows, bucket)
            if self._pad_metric:
                metrics.count(self._pad_metric, bucket - n)
        out = list(prog(*rows)[:n])
        if self._xray is not None and bucket > n:
            # pad waste priced in FLOPs, not rows: the bucket program's
            # cost analysis split per row times the pad rows appended
            flops = getattr(prog, "flops", 0.0)
            if flops and self._waste_flops_metric:
                metrics.count(self._waste_flops_metric,
                              flops * (bucket - n) / bucket)
        return out

    def _build(self, bucket: int) -> Callable:
        import jax

        fn = self.fn

        def prog(*per_buf):
            stacked = stack_tensors(per_buf)
            outs = jax.vmap(fn)(stacked)
            if not isinstance(outs, (tuple, list)):
                outs = (outs,)
            return tuple(split_rows(tuple(outs), bucket))

        jitted = jax.jit(prog)
        if self._xray is not None:
            # the trigger batch dim is the bucket (stacking happens
            # INSIDE the program, so the registry can't read it off the
            # args) — the census allow-check prices it against the ladder
            jitted = self._xray.track(jitted, self._name, "batch",
                                      rec=self._tracer, rows=bucket)
        return jitted

    # -- sharded dispatch --------------------------------------------------
    def _run_sharded(self, rows: List[Tuple]) -> List[Tuple]:
        """One bucketed dispatch with the batch dim sharded over the mesh's
        ``data`` axis.  Stack and pad happen on host (the stacked arrays
        must carry the sharded layout INTO the program, so the stack can't
        live inside it like the single-device path's does); split rows are
        lazy slices of the sharded outputs."""
        import jax
        import time as _time

        n = len(rows)
        t_trace0 = _time.monotonic_ns() if self._tracer is not None else 0
        if not self._prepared:
            # Param replication is once-per-runner, BEFORE the first
            # program builds: the jitted closure must capture the
            # replicated tree, or every dispatch re-broadcasts weights.
            self._prepared = True
            if self._prepare is not None:
                new_fn = self._prepare(self.mesh)
                if new_fn is not None:
                    self.fn = new_fn
                    self._progs.clear()
        if self.ladder is not None:
            # minted sizes are replica-aligned (AdaptiveLadder.align), so
            # the replica rounding below is a no-op on them — static base
            # buckets still round up exactly as before
            self.ladder.observe(n)
            bucket = shard_bucket_for(n, self.replicas, self.ladder.sizes())
        else:
            bucket = shard_bucket_for(n, self.replicas, self.buckets)
        if bucket > n:
            rows = pad_rows(rows, bucket)
            if self._pad_metric:
                metrics.count(self._pad_metric, bucket - n)
        stacked = tuple(
            jax.device_put(x, self._sharding)
            for x in self._host_stack(rows))
        # ONE program serves every bucket here (see _build_sharded); the
        # cache key is fixed so a prepare()-swapped fn still invalidates.
        prog = self._progs.get(-1)
        if prog is None:
            prog = self._progs[-1] = self._build_sharded()
        outs = prog(*stacked)
        if self._xray is not None and bucket > n:
            # approximation: the tracked program's cost is the LATEST
            # compiled bucket's — steady-state drains sit in one bucket,
            # where this is exact
            flops = getattr(prog, "flops", 0.0)
            if flops and self._waste_flops_metric:
                metrics.count(self._waste_flops_metric,
                              flops * (bucket - n) / bucket)
        if self._dispatch_metric:
            metrics.count(self._dispatch_metric)
            # Per-replica placement counters: read the real shard layout
            # off the first output (proof of N-way placement, not an
            # assumption about what XLA did).  dp-only keeps the legacy
            # `.d<device-id>` names; a 2-D mesh names each chip by its
            # (data, model) coordinate — `.d<di>m<mi>` — so the counters
            # stay truthful when the output is replicated over `model`.
            for s in outs[0].addressable_shards:
                if self._dev_coords is None:
                    key = f"{self._shard_metric}.d{s.device.id}"
                else:
                    di, mi = self._dev_coords[s.device.id]
                    key = f"{self._shard_metric}.d{di}m{mi}"
                metrics.count(key, s.data.shape[0])
        # Reassemble each output with ONE host fetch per tensor, then
        # split into numpy views (free).  Per-row slicing of a
        # data-sharded array is catastrophic — every row becomes a
        # cross-replica gather+broadcast (measured 13x slower end-to-end
        # than not sharding); a device-side gather + in-program split
        # still pays per-row fetch dispatches (measured 0.9x).  The one
        # assembled fetch measured 4.4x vs the single-device path on the
        # same backlogged batch.  Sharded rows therefore continue as HOST
        # arrays — the right trade for the backlogged-serving shape
        # (sinks materialize anyway, and a following sharded stage
        # re-stacks on host zero-copy); keep data_parallel=1 for chains
        # that must stay HBM-resident between unfused device stages.
        import numpy as np

        host = [np.asarray(a) for a in outs]
        if t_trace0:
            # the sharded-dispatch window: stack+device_put+program+fetch
            # as one span (per-row trace ids live one layer up, in the
            # runner's batch span — this is the device-side cost bucket).
            # 2-D runs additionally carry the model-axis width so the
            # span names its full (data, model) placement.
            extra = ({"model": self.model_axis}
                     if self.model_axis > 1 else {})
            self._tracer.record("shard", self._name, None, t_trace0,
                                _time.monotonic_ns() - t_trace0,
                                rows=n, bucket=bucket,
                                replicas=self.replicas, **extra)
        return [tuple(h[i] for h in host) for i in range(n)]

    @staticmethod
    def _host_stack(rows: List[Tuple]) -> Tuple:
        """Stack per-buffer rows for sharded device_put.  All-numpy
        columns (the host-ingest case) stack on HOST — device_put then
        places each shard zero-copy — while device-array columns (a fused
        chain upstream) go through the jnp path."""
        import numpy as np

        k = len(rows[0])
        cols = []
        for t in range(k):
            vals = [r[t] for r in rows]
            if all(isinstance(v, np.ndarray) for v in vals):
                cols.append(np.stack(vals))
            else:
                cols.append(stack_tensors([(v,) for v in vals])[0])
        return tuple(cols)

    def _build_sharded(self) -> Callable:
        """The sharded program: vmap over already-stacked inputs whose
        batch dim carries the data-axis sharding.  One program serves
        every bucket (the batch dim is an input shape, and jit's own
        cache keys shapes) — the bucket ladder still bounds how many
        shapes ever reach it."""
        import jax

        fn = self.fn
        sh = self._sharding

        def prog(*stacked):
            outs = jax.vmap(fn)(stacked)
            if not isinstance(outs, (tuple, list)):
                outs = (outs,)
            return tuple(outs)

        # One sharding broadcasts over all args/outputs (rank-agnostic
        # P("data") — see parallel/sharding.data_sharding).
        jitted = jax.jit(prog, in_shardings=sh, out_shardings=sh)
        if self._xray is not None:
            # ONE jit serves every bucket here (cache keys shapes), so
            # the trigger batch dim is read off the stacked leading dim;
            # the program's cost analysis covers the GLOBAL batch spread
            # over the mesh, so MFU denominates in the aggregate peak
            jitted = self._xray.track(jitted, self._name, "batch",
                                      rec=self._tracer,
                                      rows_from_leading=True,
                                      devices=self.replicas
                                      * self.model_axis)
        return jitted
