"""Adaptive micro-batch dispatch: N queued buffers -> ONE jitted XLA call.

The executor's unit of work is one buffer; per-dispatch overhead (python
jit call, XLA launch, tunnel RTT) is paid per buffer.  When a device
stage's queue is backlogged, that overhead dominates small models — the
same lesson PROFILE_LLM_r5 taught at the kernel layer (halving kernel-call
count bought 1.23x decode throughput) applies at the stage layer.

:class:`BatchRunner` wraps a stage's pure per-buffer function
``tuple(arrays) -> tuple(arrays)`` and executes a LIST of per-buffer input
rows as one compiled program:

* the batch is padded up to a small set of **buckets** (default powers of
  two) so XLA compiles one program per bucket, not per occupancy;
* padding repeats the last real row — valid data, no masking, and the
  repeated references cost nothing outside jit;
* stack -> vmap(fn) -> split all happen INSIDE the jitted program, so a
  batch of 8 costs exactly one dispatch (no per-row slice dispatches), and
  the split rows are device buffers that stay in HBM.

Row outputs are bit-equal across occupancies of the same bucket (same
compiled program; pad rows only append rows, never change the math of the
real ones).

**Sharded mode** (the mesh-DP tentpole, docs/BATCHING.md "Sharded
dispatch"): given a mesh whose ``data`` axis is > 1, the bucketed batch
becomes the unit of data parallelism — the stacked batch dim is sharded
over the ``data`` axis (``in_shardings``/``out_shardings`` via
``parallel/sharding.data_sharding``), buckets round up to multiples of
the axis size so every replica holds equal rows, and stage parameters
are replicated onto the mesh ONCE before the first sharded dispatch (the
``prepare`` hook), not per call.  ``vmap`` guarantees rows never
interact, so the per-row math — and for elementwise stages the exact
bits — matches the single-device program.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..core.buffer import pad_rows, split_rows, stack_tensors
from ..core.log import metrics

#: default bucket ladder; bucket_for() falls back to the exact size above it
DEFAULT_BUCKETS: Tuple[int, ...] = (1, 2, 4, 8, 16, 32, 64, 128, 256)


def bucket_for(n: int, buckets: Optional[Sequence[int]] = None) -> int:
    """Smallest allowed batch size >= n (exact n when above the ladder)."""
    for b in buckets or DEFAULT_BUCKETS:
        if b >= n:
            return b
    return n


def ladder(batch_max: int, buckets: Optional[Sequence[int]] = None
           ) -> Tuple[int, ...]:
    """Every bucket size a runner with this ``batch_max`` can ever dispatch
    (ascending).  Mirrors the runner exactly: ``batch_max`` above the top
    bucket is CLAMPED to it (runtime._Runner caps the drain at the ladder
    top precisely so recompiles stay bounded), so the set never contains a
    size the runtime cannot produce.  This is the compiled-signature
    ladder the deep analyzer multiplies out for its recompile census and
    HBM high-water estimate — one compiled program per entry, per stage."""
    bs = tuple(sorted(set(buckets))) if buckets else DEFAULT_BUCKETS
    top = bucket_for(min(max(1, batch_max), bs[-1]), bs)
    return tuple(b for b in bs if b <= top)


def shard_bucket_for(n: int, replicas: int,
                     buckets: Optional[Sequence[int]] = None) -> int:
    """Bucket for a batch sharded over ``replicas``: the ladder bucket,
    rounded UP to a multiple of the replica count so every replica gets
    the same number of rows (XLA SPMD partitions the batch dim evenly —
    a ragged split would be a different program per remainder)."""
    b = bucket_for(n, buckets)
    return b + (-b) % max(1, replicas)


class BatchRunner:
    """Per-stage cache of bucketed ``jit(vmap(fn))`` programs.

    ``fn`` is the stage's pure per-buffer function.  jit's own cache
    handles input shape/dtype changes; this cache keys only the bucket
    size (which is baked into the program's split).

    ``mesh`` (with a ``data`` OR ``model`` axis > 1) switches on sharded
    dispatch: the batch dim shards over ``data`` while stage parameters
    are PLACED per their ``param_pspecs`` — sharded over ``model``,
    replicated otherwise.  ``prepare(mesh) -> Optional[new_fn]`` runs
    exactly once before the first sharded dispatch so the stage can place
    its parameters onto the mesh and hand back a fresh closure capturing
    the placed tree.
    """

    def __init__(self, fn: Callable, buckets: Optional[Sequence[int]] = None,
                 name: Optional[str] = None, mesh=None,
                 prepare: Optional[Callable] = None, tracer=None):
        self.fn = fn
        self.buckets = tuple(sorted(set(buckets))) if buckets else None
        self._name = name or "batch"
        # the owning pipeline's flight recorder (None = that pipeline runs
        # trace_mode=off, even if another pipeline enabled the global one)
        self._tracer = tracer
        self._progs: Dict[int, Callable] = {}
        self._pad_metric = f"{name}.batch_pad_waste" if name else None
        self._shard_metric = f"{name}.shard_rows" if name else None
        self._dispatch_metric = f"{name}.shard_dispatch" if name else None
        self.mesh = None
        self.replicas = 1
        self.model_axis = 1
        self._sharding = None
        self._dev_coords = None
        if mesh is not None:
            from ..parallel.mesh import device_coords, mesh_axis_size

            d = mesh_axis_size(mesh, "data")
            m = mesh_axis_size(mesh, "model")
            # a (1, 1) mesh is exactly the unsharded path; a >1 model
            # axis engages the sharded path even at data=1 so the
            # prepare hook can SHARD stage params over `model` (2-D
            # placement, docs/BATCHING.md "2-D sharded dispatch")
            if d > 1 or m > 1:
                from ..parallel.sharding import data_sharding

                self.mesh = mesh
                self.replicas = d
                self.model_axis = m
                # invariant per runner: built once, reused by every
                # dispatch's device_put AND the program's in/out_shardings
                self._sharding = data_sharding(mesh)
                if m > 1:
                    # device id -> (data, model) coordinate: 2-D runs name
                    # per-replica counters by mesh position, not raw id
                    self._dev_coords = device_coords(mesh)
        self._prepare = prepare
        self._prepared = False

    def run(self, rows: List[Tuple]) -> List[Tuple]:
        """Execute per-buffer input rows as one dispatch; returns one
        output row per input row, in order."""
        if self.mesh is not None:
            return self._run_sharded(rows)
        n = len(rows)
        bucket = bucket_for(n, self.buckets)
        prog = self._progs.get(bucket)
        if prog is None:
            prog = self._progs[bucket] = self._build(bucket)
        if bucket > n:
            rows = pad_rows(rows, bucket)
            if self._pad_metric:
                metrics.count(self._pad_metric, bucket - n)
        return list(prog(*rows)[:n])

    def _build(self, bucket: int) -> Callable:
        import jax

        fn = self.fn

        def prog(*per_buf):
            stacked = stack_tensors(per_buf)
            outs = jax.vmap(fn)(stacked)
            if not isinstance(outs, (tuple, list)):
                outs = (outs,)
            return tuple(split_rows(tuple(outs), bucket))

        return jax.jit(prog)

    # -- sharded dispatch --------------------------------------------------
    def _run_sharded(self, rows: List[Tuple]) -> List[Tuple]:
        """One bucketed dispatch with the batch dim sharded over the mesh's
        ``data`` axis.  Stack and pad happen on host (the stacked arrays
        must carry the sharded layout INTO the program, so the stack can't
        live inside it like the single-device path's does); split rows are
        lazy slices of the sharded outputs."""
        import jax
        import time as _time

        n = len(rows)
        t_trace0 = _time.monotonic_ns() if self._tracer is not None else 0
        if not self._prepared:
            # Param replication is once-per-runner, BEFORE the first
            # program builds: the jitted closure must capture the
            # replicated tree, or every dispatch re-broadcasts weights.
            self._prepared = True
            if self._prepare is not None:
                new_fn = self._prepare(self.mesh)
                if new_fn is not None:
                    self.fn = new_fn
                    self._progs.clear()
        bucket = shard_bucket_for(n, self.replicas, self.buckets)
        if bucket > n:
            rows = pad_rows(rows, bucket)
            if self._pad_metric:
                metrics.count(self._pad_metric, bucket - n)
        stacked = tuple(
            jax.device_put(x, self._sharding)
            for x in self._host_stack(rows))
        # ONE program serves every bucket here (see _build_sharded); the
        # cache key is fixed so a prepare()-swapped fn still invalidates.
        prog = self._progs.get(-1)
        if prog is None:
            prog = self._progs[-1] = self._build_sharded()
        outs = prog(*stacked)
        if self._dispatch_metric:
            metrics.count(self._dispatch_metric)
            # Per-replica placement counters: read the real shard layout
            # off the first output (proof of N-way placement, not an
            # assumption about what XLA did).  dp-only keeps the legacy
            # `.d<device-id>` names; a 2-D mesh names each chip by its
            # (data, model) coordinate — `.d<di>m<mi>` — so the counters
            # stay truthful when the output is replicated over `model`.
            for s in outs[0].addressable_shards:
                if self._dev_coords is None:
                    key = f"{self._shard_metric}.d{s.device.id}"
                else:
                    di, mi = self._dev_coords[s.device.id]
                    key = f"{self._shard_metric}.d{di}m{mi}"
                metrics.count(key, s.data.shape[0])
        # Reassemble each output with ONE host fetch per tensor, then
        # split into numpy views (free).  Per-row slicing of a
        # data-sharded array is catastrophic — every row becomes a
        # cross-replica gather+broadcast (measured 13x slower end-to-end
        # than not sharding); a device-side gather + in-program split
        # still pays per-row fetch dispatches (measured 0.9x).  The one
        # assembled fetch measured 4.4x vs the single-device path on the
        # same backlogged batch.  Sharded rows therefore continue as HOST
        # arrays — the right trade for the backlogged-serving shape
        # (sinks materialize anyway, and a following sharded stage
        # re-stacks on host zero-copy); keep data_parallel=1 for chains
        # that must stay HBM-resident between unfused device stages.
        import numpy as np

        host = [np.asarray(a) for a in outs]
        if t_trace0:
            # the sharded-dispatch window: stack+device_put+program+fetch
            # as one span (per-row trace ids live one layer up, in the
            # runner's batch span — this is the device-side cost bucket).
            # 2-D runs additionally carry the model-axis width so the
            # span names its full (data, model) placement.
            extra = ({"model": self.model_axis}
                     if self.model_axis > 1 else {})
            self._tracer.record("shard", self._name, None, t_trace0,
                                _time.monotonic_ns() - t_trace0,
                                rows=n, bucket=bucket,
                                replicas=self.replicas, **extra)
        return [tuple(h[i] for h in host) for i in range(n)]

    @staticmethod
    def _host_stack(rows: List[Tuple]) -> Tuple:
        """Stack per-buffer rows for sharded device_put.  All-numpy
        columns (the host-ingest case) stack on HOST — device_put then
        places each shard zero-copy — while device-array columns (a fused
        chain upstream) go through the jnp path."""
        import numpy as np

        k = len(rows[0])
        cols = []
        for t in range(k):
            vals = [r[t] for r in rows]
            if all(isinstance(v, np.ndarray) for v in vals):
                cols.append(np.stack(vals))
            else:
                cols.append(stack_tensors([(v,) for v in vals])[0])
        return tuple(cols)

    def _build_sharded(self) -> Callable:
        """The sharded program: vmap over already-stacked inputs whose
        batch dim carries the data-axis sharding.  One program serves
        every bucket (the batch dim is an input shape, and jit's own
        cache keys shapes) — the bucket ladder still bounds how many
        shapes ever reach it."""
        import jax

        fn = self.fn
        sh = self._sharding

        def prog(*stacked):
            outs = jax.vmap(fn)(stacked)
            if not isinstance(outs, (tuple, list)):
                outs = (outs,)
            return tuple(outs)

        # One sharding broadcasts over all args/outputs (rank-agnostic
        # P("data") — see parallel/sharding.data_sharding).
        return jax.jit(prog, in_shardings=sh, out_shardings=sh)
