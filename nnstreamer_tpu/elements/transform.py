"""tensor_transform: elementwise/layout preprocessing.

Reference analog: ``gst/nnstreamer/elements/gsttensor_transform.c``
(upstream-reconstructed, SURVEY §2.2).  Modes replicated: ``typecast``,
``arithmetic`` (op chain, e.g. ``typecast:float32,add:-127.5,div:127.5``),
``transpose``, ``dimchg``, ``clamp``, ``stand`` (standardization),
``padding``.

TPU-first: every mode is implemented once over a pluggable array namespace
(numpy for the host path and unit tests, jax.numpy inside fused XLA stages).
The reference accelerates these loops with ORC SIMD; here the same math is
traced into the surrounding jitted program, so XLA fuses the normalize chain
into the model's first conv (the north star's "fused XLA preprocess
stages") — zero extra HBM round-trips.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core.buffer import Buffer
from ..core.caps import Caps, MediaType
from ..core.registry import register_element
from ..core.types import TensorSpec, TensorsSpec, dtype_from_name, dtype_name
from .base import ElementError, TransformElement, SRC


def _np_axis(rank: int, dim_index: int) -> int:
    """nnstreamer dim index (innermost-first) -> numpy axis (outermost-first)."""
    return rank - 1 - dim_index


@dataclasses.dataclass
class _ArithOp:
    name: str  # add|sub|mul|div|pow|typecast
    value: object = None
    per_channel_dim: Optional[int] = None  # dim index for vector consts


def _promotes_to_float(op: "_ArithOp") -> bool:
    """Whether applying ``op`` to an integer tensor must lift it to float32.

    Single source of truth for BOTH the spec-derivation path
    (:meth:`TensorTransform._out_spec_one`) and the data path
    (:meth:`Ops.arithmetic`) — they must agree or negotiated caps diverge
    from actual buffer dtypes inside fused stages.
    """
    if op.name == "div":
        return True
    v = op.value
    if isinstance(v, float) and not float(v).is_integer():
        return True
    if isinstance(v, (list, tuple)) and any(not float(e).is_integer() for e in v):
        return True
    return False


def _saturate_cast(xp, x, dtype: np.dtype):
    """Float -> integer cast with ONE pinned semantic on both paths:
    SATURATE at the target's range (what a dtype-quantized boundary
    wants).  Raw ``astype`` diverges between the host and fused paths —
    numpy WRAPS out-of-range values (300.2 -> uint8 44) while XLA's
    ConvertElementType saturates (-> 255) — and with the planner now
    fusing typecast transforms across quantized caps pins, the same
    pipeline could emit different bytes depending on where the cast ran.
    Clamping before the cast makes both backends saturate identically:
    the clamp bounds are exact for <=16-bit targets in float32 and for
    32-bit targets the backend cast saturates at the same edge the
    clamp rounds to.  (NaN stays out of the contract: garbage at a
    quantized boundary either way.)  Pinned by tests/test_transform.py.
    """
    dt = np.dtype(dtype)
    if dt.kind in "iu" and np.dtype(x.dtype).kind == "f" \
            and dt.itemsize <= 4:
        info = np.iinfo(dt)
        if xp is np and dt.itemsize == 4:
            # 32-bit bounds are not exact in float32: numpy would wrap at
            # the rounded edge where XLA saturates — clip in float64
            # (exact for +-2^31/2^32) so both land on the same integer
            x = x.astype(np.float64)
        x = xp.clip(x, info.min, info.max)
    return x.astype(dt)


class Ops:
    """Mode implementations, parameterized by array namespace ``xp``."""

    @staticmethod
    def typecast(xp, x, dtype: np.dtype):
        return _saturate_cast(xp, x, dtype)

    @staticmethod
    def arithmetic(xp, x, ops: Sequence[_ArithOp]):
        for op in ops:
            if op.name == "typecast":
                # same saturating float->int semantics as mode=typecast:
                # an arith chain's trailing requantize (`...,typecast:uint8`)
                # must emit the same bytes fused or on host
                x = _saturate_cast(xp, x, op.value)
                continue
            v = op.value
            # Deterministic promotion shared by host/device paths: float
            # constants lift integer tensors to float32 (numpy would pick
            # float64, jnp float32 — pin one behavior for bit-parity).
            if np.dtype(x.dtype).kind in "iu":
                if _promotes_to_float(op):
                    x = x.astype(np.float32)
                elif isinstance(v, float):
                    v = int(v)
            if op.per_channel_dim is not None and isinstance(v, (list, tuple)):
                vec = xp.asarray(list(v), dtype=x.dtype if x.dtype.kind == "f" else np.float32)
                shape = [1] * x.ndim
                shape[_np_axis(x.ndim, op.per_channel_dim)] = len(v)
                v = vec.reshape(shape)
            if op.name == "add":
                x = x + v
            elif op.name == "sub":
                x = x - v
            elif op.name == "mul":
                x = x * v
            elif op.name == "div":
                x = x / v
            elif op.name == "pow":
                x = x**v
            else:
                raise ElementError(f"unknown arithmetic op {op.name!r}")
        return x

    @staticmethod
    def transpose(xp, x, order: Sequence[int]):
        r = x.ndim
        axes = [_np_axis(r, order[_np_axis(r, a)]) for a in range(r)]
        return xp.transpose(x, axes)

    @staticmethod
    def dimchg(xp, x, frm: int, to: int):
        r = x.ndim
        return xp.moveaxis(x, _np_axis(r, frm), _np_axis(r, to))

    @staticmethod
    def clamp(xp, x, lo: float, hi: float):
        return xp.clip(x, lo, hi)

    @staticmethod
    def stand(xp, x, variant: str, per_channel: bool):
        xf = x.astype(np.float32)
        if per_channel:
            axes = tuple(range(xf.ndim - 1))  # all but channel (innermost dim)
            mean = xf.mean(axis=axes, keepdims=True)
            std = xf.std(axis=axes, keepdims=True)
        else:
            mean = xf.mean()
            std = xf.std()
        if variant == "dc-average":
            return xf - mean
        return (xf - mean) / (std + 1e-10)

    @staticmethod
    def padding(xp, x, pads: Dict[int, Tuple[int, int]]):
        width = [(0, 0)] * x.ndim
        for dim, (before, after) in pads.items():
            if not 0 <= dim < x.ndim:
                raise ElementError(
                    f"padding dim {dim} out of range for rank-{x.ndim} tensor"
                )
            width[_np_axis(x.ndim, dim)] = (before, after)
        return xp.pad(x, width)


def _parse_arith(option: str) -> List[_ArithOp]:
    ops: List[_ArithOp] = []
    for part in option.split(","):
        part = part.strip()
        if not part:
            continue
        if ":" not in part:
            raise ElementError(f"bad arithmetic op {part!r}")
        name, val = part.split(":", 1)
        name = name.strip().lower()
        if name == "typecast":
            ops.append(_ArithOp("typecast", dtype_from_name(val)))
            continue
        ch_dim = None
        if "@" in val:
            val, ch = val.rsplit("@", 1)
            ch_dim = int(ch)
        vals = [float(v) for v in val.split("|")]
        value: object = vals if len(vals) > 1 else vals[0]
        ops.append(_ArithOp(name, value, ch_dim))
    return ops


@register_element("tensor_transform")
class TensorTransform(TransformElement):
    kind = "tensor_transform"
    PAD_TEMPLATES = {"sink": Caps.new(MediaType.TENSORS)}

    def __init__(self, props=None, name=None):
        super().__init__(props, name)
        self.mode = str(self.props.get("mode", "typecast")).lower()
        self.option = str(self.props.get("option", ""))
        self._compiled: Optional[Callable] = None
        self._parse()

    # -- option parsing ----------------------------------------------------
    def _parse(self) -> None:
        m, o = self.mode, self.option
        if m == "typecast":
            self._dtype = dtype_from_name(o or "float32")
        elif m == "arithmetic":
            self._ops = _parse_arith(o)
        elif m == "transpose":
            self._order = [int(v) for v in o.split(":") if v != ""]
        elif m == "dimchg":
            frm, to = o.split(":")
            self._frm, self._to = int(frm), int(to)
        elif m == "clamp":
            lo, hi = o.split(":")
            self._lo, self._hi = float(lo), float(hi)
        elif m == "stand":
            parts = o.split(":") if o else ["default"]
            self._variant = parts[0] or "default"
            self._per_channel = "per-channel" in parts
        elif m == "padding":
            self._pads: Dict[int, Tuple[int, int]] = {}
            for item in o.split(","):
                item = item.strip()
                if not item:
                    continue
                d, b, a = item.split(":")
                self._pads[int(d)] = (int(b), int(a))
        else:
            raise ElementError(f"unknown transform mode {self.mode!r}")

    # -- spec propagation --------------------------------------------------
    def _out_spec_one(self, spec: TensorSpec) -> TensorSpec:
        m = self.mode
        dims, dtype = spec.dims, spec.dtype
        if m == "typecast":
            dtype = self._dtype
        elif m == "arithmetic":
            for op in self._ops:
                if op.name == "typecast":
                    dtype = op.value
                    continue
                if dtype.kind in "iu" and _promotes_to_float(op):
                    dtype = np.dtype(np.float32)
        elif m == "transpose":
            order = self._order + list(range(len(self._order), len(dims)))
            dims = tuple(dims[order[i]] for i in range(len(dims)))
        elif m == "dimchg":
            d = list(dims)
            v = d.pop(self._frm)
            d.insert(self._to, v)
            dims = tuple(d)
        elif m == "stand":
            dtype = np.dtype(np.float32)
        elif m == "padding":
            d = list(dims)
            for dim, (b, a) in self._pads.items():
                if not 0 <= dim < len(d):
                    raise ElementError(
                        f"padding dim {dim} out of range for rank-{len(d)} tensor"
                    )
                d[dim] += b + a
            dims = tuple(d)
        return TensorSpec(dims, dtype, spec.name)

    def out_spec(self, in_spec: TensorsSpec) -> TensorsSpec:
        return in_spec.replace(specs=tuple(self._out_spec_one(s) for s in in_spec))

    def configure(self, in_caps, out_pads):
        self.in_caps = dict(in_caps)
        src = next(iter(in_caps.values()), Caps.any())
        spec = src.spec
        caps = Caps.tensors(self.out_spec(spec) if spec is not None else None)
        self.out_caps = {p: caps for p in out_pads}
        return self.out_caps

    # -- math (shared by host + device paths) ------------------------------
    def _apply(self, xp, x):
        m = self.mode
        if m == "typecast":
            return Ops.typecast(xp, x, self._dtype)
        if m == "arithmetic":
            return Ops.arithmetic(xp, x, self._ops)
        if m == "transpose":
            order = self._order + list(range(len(self._order), x.ndim))
            return Ops.transpose(xp, x, order)
        if m == "dimchg":
            return Ops.dimchg(xp, x, self._frm, self._to)
        if m == "clamp":
            return Ops.clamp(xp, x, self._lo, self._hi)
        if m == "stand":
            return Ops.stand(xp, x, self._variant, self._per_channel)
        if m == "padding":
            return Ops.padding(xp, x, self._pads)
        raise ElementError(self.mode)

    def transform(self, buf: Buffer) -> Buffer:
        outs = [np.asarray(self._apply(np, np.asarray(t))) for t in buf.tensors]
        spec = None
        if buf.spec is not None:
            try:
                spec = self.out_spec(buf.spec)
            except Exception:  # pragma: no cover - spec stays derived
                spec = None
        return buf.with_tensors(outs, spec=spec)

    def device_fn(self, in_spec: TensorsSpec):
        import jax.numpy as jnp

        def fn(arrays: Tuple) -> Tuple:
            return tuple(self._apply(jnp, a) for a in arrays)

        return fn, self.out_spec(in_spec)
