"""Source elements: app feed + deterministic test sources.

Reference analogs: ``appsrc``, ``videotestsrc``, ``audiotestsrc``,
``filesrc`` (GStreamer base plugins used throughout the reference's SSAT
suites as deterministic inputs — SURVEY §4), and ``datareposrc`` lives in
elements/datarepo.py.

TPU-first note: sources are host elements by definition (camera/file/app
ingest).  They produce host numpy buffers; the first fused device stage
downstream does one `device_put` per buffer and everything after stays in
HBM.
"""

from __future__ import annotations

import queue as _queue
import threading
import time as _time
from typing import Iterator, Optional, Union

import numpy as np

from ..core.buffer import Buffer, Event
from ..core.caps import Caps, MediaType, parse_caps_string, video_bpp
from ..core.meta_keys import META_TENANT
from ..core.log import STALL_FLOOR_S
from ..core.log import metrics as _metrics
from ..core.registry import register_element
from ..core.types import TensorsSpec, parse_fraction
from .base import ElementError, SourceElement, SRC


class _InflightCredit:
    """End-to-end admission token (``appsrc max-inflight=N``): released
    the FIRST time this buffer — or any buffer derived from it; meta
    copies share the token by reference — reaches a sink, and as a safety
    net when every derived buffer is garbage-collected (drop/eviction
    paths must never leak a credit and deadlock the pusher)."""

    __slots__ = ("_sem", "_done", "_lock")

    def __init__(self, sem: threading.Semaphore):
        self._sem = sem
        self._done = False
        self._lock = threading.Lock()

    def release(self) -> None:
        with self._lock:
            if self._done:
                return
            self._done = True
        self._sem.release()

    def __del__(self):  # drop-path safety net
        try:
            self.release()
        except Exception:  # noqa: BLE001 - interpreter teardown
            pass


@register_element("appsrc")
class AppSrc(SourceElement):
    """Application-driven source: ``pipeline.push(name, array)`` feeds it.

    Props: ``caps`` (caps string describing what the app will push),
    ``max-buffers`` (feed queue bound), ``block`` (push blocks when full),
    ``max-inflight`` (END-TO-END admission bound: at most N pushed buffers
    anywhere between this source and a sink; push blocks past that.  The
    per-stage queues bound memory, but on a transport-saturated pipeline
    they still let queue-depth x batch-time of latency build up ahead of
    every frame — the reference gets the same effect from short GStreamer
    queues; here one credit spans the whole pipeline),
    ``tenant`` (tenant identity stamped into every pushed buffer's meta —
    rides the query wire so a remote server's per-tenant accounting and
    admission control see it; an explicit prop is app DATA, stamped
    regardless of trace mode — docs/SERVING.md "Front door").
    """

    kind = "appsrc"

    def __init__(self, props=None, name=None):
        super().__init__(props, name)
        cap = self.props.get("caps")
        self._caps = parse_caps_string(str(cap)) if cap else Caps.any()
        self.tenant = str(self.props.get("tenant", "") or "") or None
        self.block = bool(self.props.get("block", True))
        # block=false matches GStreamer appsrc semantics: push never blocks
        # and the feed queue grows unbounded (max-buffers is the bound only
        # in blocking mode — still read unconditionally so the pairing
        # block=false max-buffers=N stays a legal property set).
        cap_n = int(self.props.get("max_buffers", 64))
        self._q: _queue.Queue = _queue.Queue(
            maxsize=cap_n if self.block else 0)
        self._eos = threading.Event()
        n_inflight = int(self.props.get("max_inflight", 0))
        self._inflight_sem = (threading.Semaphore(n_inflight)
                              if n_inflight > 0 else None)

    def configure(self, in_caps, out_pads):
        self.out_caps = {p: self._caps for p in out_pads}
        return self.out_caps

    # -- app API -----------------------------------------------------------
    def push(self, data, pts: Optional[int] = None) -> None:
        if self._eos.is_set():
            raise RuntimeError("appsrc already EOS")
        if isinstance(data, Buffer):
            buf = data
        elif isinstance(data, (list, tuple)):
            buf = Buffer(list(data), pts=pts)
        elif isinstance(data, str):
            buf = Buffer([np.frombuffer(data.encode("utf-8"), np.uint8)], pts=pts)
        elif isinstance(data, (bytes, bytearray)):
            buf = Buffer([np.frombuffer(bytes(data), np.uint8)], pts=pts)
        else:
            buf = Buffer([np.asarray(data)], pts=pts)
        if self.tenant is not None and META_TENANT not in buf.meta:
            buf.meta[META_TENANT] = self.tenant
        if self._inflight_sem is not None:
            stop = getattr(self, "_stop_event", None)
            t0 = _time.perf_counter()
            while not self._inflight_sem.acquire(timeout=0.1):
                if self._eos.is_set() or (stop is not None
                                          and stop.is_set()):
                    raise RuntimeError("appsrc stopping; push abandoned")
            # h2d-wait accounting (the ingress half of the stall split;
            # the sink counts the d2h half): time the PUSH blocked on
            # admission is the transport/backlog wait, distinct from the
            # pull-side fetch wait that used to be conflated with it in
            # one rtt_stalls number.
            wait = _time.perf_counter() - t0
            _metrics.count(f"{self.name}.h2d_wait_ms", wait * 1e3)
            if wait > STALL_FLOOR_S:
                _metrics.count(f"{self.name}.h2d_stalls")
            buf.meta["_inflight_credit"] = _InflightCredit(
                self._inflight_sem)
        self._q.put(buf)

    def signal_eos(self) -> None:
        self._eos.set()

    def generate(self) -> Iterator[Union[Buffer, Event]]:
        stop = getattr(self, "_stop_event", None)
        while True:
            try:
                yield self._q.get(timeout=0.05)
            except _queue.Empty:
                if self._eos.is_set() and self._q.empty():
                    return
                # stop() without EOS: exit instead of pinning the runner
                # thread on the join timeout (pipeline teardown, not EOS)
                if stop is not None and stop.is_set():
                    return


@register_element("videotestsrc")
class VideoTestSrc(SourceElement):
    """Deterministic video frames (reference test pipelines' workhorse).

    Props: ``width``, ``height``, ``format`` (RGB/BGR/RGBA/GRAY8),
    ``num-buffers``, ``pattern`` (``smpte`` gradient, ``ball``, ``black``,
    ``white``, ``random`` with fixed seed), ``framerate``.

    TPU-first extension: ``device=true`` generates the pattern **on
    device** as a jitted XLA program and emits batched ``other/tensors``
    buffers (``batch`` frames per buffer) that stay in HBM — a synthetic
    source with zero host->device traffic, the TPU-native analog of the
    reference benchmarking against videotestsrc.  The gradient/ball math
    is bit-identical to the host path.
    """

    kind = "videotestsrc"

    def __init__(self, props=None, name=None):
        super().__init__(props, name)
        self.width = int(self.props.get("width", 320))
        self.height = int(self.props.get("height", 240))
        self.format = str(self.props.get("format", "RGB"))
        self.num_buffers = int(self.props.get("num_buffers", -1))
        self.pattern = str(self.props.get("pattern", "smpte"))
        self.rate = parse_fraction(self.props.get("framerate", (30, 1)))
        self.device = bool(self.props.get("device", False))
        self.batch = int(self.props.get("batch", 1))

    def configure(self, in_caps, out_pads):
        if self.device:
            c = video_bpp(self.format)
            spec = TensorsSpec.from_string(
                f"{c}:{self.width}:{self.height}:{self.batch}", "uint8"
            )
            caps = Caps.tensors(spec)
        else:
            caps = Caps.new(
                MediaType.VIDEO,
                format=self.format,
                width=self.width,
                height=self.height,
                framerate=self.rate,
            )
        self.out_caps = {p: caps for p in out_pads}
        return self.out_caps

    def _frame(self, i: int) -> np.ndarray:
        c = video_bpp(self.format)
        h, w = self.height, self.width
        if self.pattern == "black":
            f = np.zeros((h, w, c), np.uint8)
        elif self.pattern == "white":
            f = np.full((h, w, c), 255, np.uint8)
        elif self.pattern == "random":
            rng = np.random.default_rng(i)
            f = rng.integers(0, 256, size=(h, w, c), dtype=np.uint8)
        elif self.pattern == "ball":
            f = np.zeros((h, w, c), np.uint8)
            cy = (i * 7) % h
            cx = (i * 11) % w
            yy, xx = np.ogrid[:h, :w]
            mask = (yy - cy) ** 2 + (xx - cx) ** 2 <= (min(h, w) // 8) ** 2
            f[mask] = 255
        else:  # smpte-ish deterministic gradient
            yy, xx = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
            base = (xx * 255 // max(1, w - 1) + yy + i) % 256
            f = np.stack([(base + 85 * k) % 256 for k in range(c)], axis=-1).astype(np.uint8)
        return f

    def _device_batch_fn(self):
        import jax
        import jax.numpy as jnp

        h, w, c = self.height, self.width, video_bpp(self.format)
        pattern = self.pattern

        def one(i):
            yy, xx = jnp.meshgrid(jnp.arange(h), jnp.arange(w), indexing="ij")
            if pattern == "black":
                return jnp.zeros((h, w, c), jnp.uint8)
            if pattern == "white":
                return jnp.full((h, w, c), 255, jnp.uint8)
            if pattern == "random":
                key = jax.random.PRNGKey(0)
                return jax.random.randint(
                    jax.random.fold_in(key, i), (h, w, c), 0, 256, jnp.int32
                ).astype(jnp.uint8)
            if pattern == "ball":
                cy = (i * 7) % h
                cx = (i * 11) % w
                mask = (yy - cy) ** 2 + (xx - cx) ** 2 <= (min(h, w) // 8) ** 2
                f = jnp.zeros((h, w), jnp.uint8)
                f = jnp.where(mask, jnp.uint8(255), f)
                return jnp.broadcast_to(f[:, :, None], (h, w, c))
            # smpte-ish gradient — bit-identical to the host _frame math
            base = (xx * 255 // max(1, w - 1) + yy + i) % 256
            return jnp.stack(
                [(base + 85 * k) % 256 for k in range(c)], axis=-1
            ).astype(jnp.uint8)

        @jax.jit
        def make(i0):
            return jax.vmap(one)(i0 + jnp.arange(self.batch))

        return make

    def generate(self):
        num = self.num_buffers if self.num_buffers >= 0 else 1 << 62
        frame_ns = int(1e9 * self.rate[1] / max(1, self.rate[0]))
        if self.device:
            make = self._device_batch_fn()
            # num-buffers counts FRAMES (host-path contract); the device
            # path emits full batches and truncates the tail batch so the
            # total frame count matches exactly.  The frame index wraps at
            # 2^30 (int32-safe under jit; patterns repeat anyway at far
            # shorter periods, so the seam is invisible).
            emitted = 0
            i = 0
            while emitted < num:
                arr = make((i * self.batch) % (1 << 30))
                take = min(self.batch, num - emitted)
                if take < self.batch:
                    arr = arr[:take]
                yield Buffer([arr], pts=emitted * frame_ns)
                emitted += take
                i += 1
            return
        for i in range(num):
            yield Buffer([self._frame(i)], pts=i * frame_ns)


@register_element("audiotestsrc")
class AudioTestSrc(SourceElement):
    """Deterministic audio: sine wave.  Props: ``freq``, ``samplesperbuffer``,
    ``num-buffers``, ``rate``, ``channels``, ``format`` (S16LE/F32LE/U8).

    TPU-first extension (same shape as videotestsrc's): ``device=true``
    synthesizes the sine **on device** as a jitted XLA program and emits
    batched float32 ``other/tensors`` windows ``[batch, samplesperbuffer]``
    that stay in HBM — zero host->device traffic.  In device mode
    ``num-buffers`` counts WINDOWS (the frame analog), channels=1, and the
    format is float32.
    """

    kind = "audiotestsrc"

    def __init__(self, props=None, name=None):
        super().__init__(props, name)
        self.freq = float(self.props.get("freq", 440.0))
        self.spb = int(self.props.get("samplesperbuffer", 1024))
        self.num_buffers = int(self.props.get("num_buffers", -1))
        self.sample_rate = int(self.props.get("rate", 44100))
        self.channels = int(self.props.get("channels", 1))
        self.format = str(self.props.get("format", "S16LE"))
        self.device = bool(self.props.get("device", False))
        self.batch = int(self.props.get("batch", 1))

    def configure(self, in_caps, out_pads):
        if self.device:
            spec = TensorsSpec.from_string(
                f"{self.spb}:{self.batch}", "float32")
            caps = Caps.tensors(spec)
        else:
            caps = Caps.new(
                MediaType.AUDIO,
                format=self.format,
                rate=self.sample_rate,
                channels=self.channels,
            )
        self.out_caps = {p: caps for p in out_pads}
        return self.out_caps

    def _device_batch_fn(self):
        import jax
        import jax.numpy as jnp

        spb, rate, freq = self.spb, self.sample_rate, self.freq

        def one(n0, j):  # batch row j -> [spb] float32 sine
            # Exact int32 sample index folded by the sample rate: for
            # integer freq, n -> n+rate shifts phase by whole cycles (sin
            # unchanged), and n < rate keeps float32 phase math exact.
            # n0 < rate (caller folds with Python ints — no overflow) and
            # j*spb <= batch*spb, so the sum stays well within int32.
            n = jnp.mod(n0 + j * spb + jnp.arange(spb, dtype=jnp.int32), rate)
            return jnp.sin(2 * jnp.pi * freq * n.astype(jnp.float32) / rate)

        @jax.jit
        def make(n0):
            return jax.vmap(lambda j: one(n0, j))(jnp.arange(self.batch))

        return make

    def generate(self):
        num = self.num_buffers if self.num_buffers >= 0 else 1 << 62
        if self.device:
            make = self._device_batch_fn()
            emitted = 0
            i = 0
            while emitted < num:
                # Base sample index folded by `rate` in exact Python ints
                # (exact wrap: see _device_batch_fn).
                arr = make((i * self.batch * self.spb) % self.sample_rate)
                take = min(self.batch, num - emitted)
                if take < self.batch:
                    arr = arr[:take]
                pts = int(1e9 * emitted * self.spb / self.sample_rate)
                yield Buffer([arr], pts=pts)
                emitted += take
                i += 1
            return
        t0 = 0
        for i in range(num):
            n = np.arange(t0, t0 + self.spb, dtype=np.float64)
            wave = np.sin(2 * np.pi * self.freq * n / self.sample_rate)
            if self.format == "S16LE":
                samples = (wave * 32767).astype(np.int16)
            elif self.format == "U8":
                samples = ((wave * 0.5 + 0.5) * 255).astype(np.uint8)
            else:
                samples = wave.astype(np.float32)
            frame = np.repeat(samples[:, None], self.channels, axis=1)
            pts = int(1e9 * t0 / self.sample_rate)
            t0 += self.spb
            yield Buffer([frame], pts=pts)


@register_element("filesrc")
class FileSrc(SourceElement):
    """Whole-file byte source (``application/octet-stream``).

    Props: ``location``, ``blocksize`` (0 = whole file in one buffer).
    """

    kind = "filesrc"

    def __init__(self, props=None, name=None):
        super().__init__(props, name)
        self.location = str(self.props.get("location", ""))
        self.blocksize = int(self.props.get("blocksize", 0))

    def configure(self, in_caps, out_pads):
        caps = Caps.new(MediaType.OCTET)
        self.out_caps = {p: caps for p in out_pads}
        return self.out_caps

    def generate(self):
        with open(self.location, "rb") as f:
            data = f.read()
        if self.blocksize <= 0:
            yield Buffer([np.frombuffer(data, np.uint8)])
            return
        for off in range(0, len(data), self.blocksize):
            yield Buffer([np.frombuffer(data[off : off + self.blocksize], np.uint8)])


#: IIO scan-element wire formats: name -> (numpy dtype, is_signed)
_IIO_FORMATS = {
    "s16le": np.dtype("<i2"), "u16le": np.dtype("<u2"),
    "s32le": np.dtype("<i4"), "u32le": np.dtype("<u4"),
    "s8": np.dtype("i1"), "u8": np.dtype("u1"),
    "f32le": np.dtype("<f4"), "f64le": np.dtype("<f8"),
}


@register_element("tensor_src_iio")
class TensorSrcIIO(SourceElement):
    """Industrial-I/O sensor source (reference: ``gsttensor_srciio.c``).

    The reference reads buffered scans from an IIO character device
    (``/dev/iio:deviceN``): interleaved per-channel raw samples, converted
    to processed values via each channel's scale/offset, ``buffer-capacity``
    samples per emitted buffer, paced by a trigger.  This element keeps
    those semantics against any byte stream:

    * ``device=<path>`` — a file, FIFO, or char device of interleaved raw
      records; ``device=tcp://host:port`` — the same records over a socket
      (sensors are remote in a TPU-pod deployment).
    * ``scan-format`` (default ``s16le``) — per-channel wire format;
      ``channels`` — channels per record; processed value =
      ``(raw + offset) * scale`` (IIO convention; default offset 0 scale 1).
    * ``buffer-capacity`` samples per emitted ``[capacity, channels]``
      float32 tensor; short tail reads are dropped (a partial scan never
      violates the negotiated caps).
    * ``trigger=data`` (default) emits as soon as a full scan is read;
      ``trigger=timer`` paces emission at ``frequency`` Hz (the reference's
      sysfs-trigger analog).
    * With no ``device``, a pluggable ``sampler`` callable (or the builtin
      deterministic pseudo-sensor) generates samples — the hermetic-test
      mode, also used when no sensor bus exists.
    """

    kind = "tensor_src_iio"

    def __init__(self, props=None, name=None):
        super().__init__(props, name)
        self.frequency = float(self.props.get("frequency", 100.0))
        self.capacity = int(self.props.get("buffer_capacity", 16))
        self.channels = int(self.props.get("channels", 3))
        self.num_buffers = int(self.props.get("num_buffers", 16))
        self.sampler = self.props.get("sampler")  # callable i -> np[channels]
        self.device = str(self.props.get("device", "") or "")
        fmt = str(self.props.get("scan_format", "s16le")).lower()
        if fmt not in _IIO_FORMATS:
            raise ElementError(
                f"{self.name}: unknown scan-format {fmt!r} "
                f"(one of {sorted(_IIO_FORMATS)})")
        self.scan_dtype = _IIO_FORMATS[fmt]
        self.scale = float(self.props.get("scale", 1.0))
        self.offset = float(self.props.get("offset", 0.0))
        self.trigger = str(self.props.get("trigger", "data")).lower()
        if self.trigger not in ("data", "timer"):
            raise ElementError(
                f"{self.name}: trigger must be data|timer, got {self.trigger!r}")
        self._fd = None
        self._sock = None
        self._is_fifo = False
        self._saw_data = False

    def configure(self, in_caps, out_pads):
        spec = TensorsSpec.from_string(
            f"{self.channels}:{self.capacity}", "float32"
        )
        caps = Caps.tensors(spec)
        self.out_caps = {p: caps for p in out_pads}
        return self.out_caps

    # -- device backend ----------------------------------------------------
    def start(self) -> None:
        if not self.device:
            return
        if self.device.startswith("tcp://"):
            import socket as _socket

            host, port = self.device[6:].rsplit(":", 1)
            try:
                sock = _socket.create_connection((host, int(port)), timeout=5.0)
            except OSError as e:
                raise ElementError(
                    f"{self.name}: cannot reach sensor stream "
                    f"{self.device}: {e}") from e
            # Short timeout: _read_scan polls the stop event between
            # recv()s, so a paused sender never blocks pipeline shutdown.
            sock.settimeout(0.2)
            self._sock = sock
            self._fd = None
        else:
            import os as _os

            try:
                # O_NONBLOCK: FIFOs/char devices must never block shutdown —
                # _read_scan polls the stop event between reads.  Harmless
                # for regular files.
                self._fd = _os.open(self.device,
                                    _os.O_RDONLY | _os.O_NONBLOCK)
                import stat as _stat

                self._is_fifo = _stat.S_ISFIFO(_os.fstat(self._fd).st_mode)
            except OSError as e:
                raise ElementError(
                    f"{self.name}: cannot open device {self.device!r}: {e}"
                ) from e

    def stop(self) -> None:
        fd = getattr(self, "_fd", None)
        if fd is not None:
            import os as _os

            try:
                _os.close(fd)
            except OSError:
                pass
            self._fd = None
        sock = getattr(self, "_sock", None)
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
            self._sock = None

    def _read_scan(self, stop) -> Optional[np.ndarray]:
        """One full buffered scan: [capacity, channels] processed float32,
        or None at EOF / short tail / stop.  Both paths poll the stop
        event so a stalled sensor never blocks pipeline shutdown."""
        import os as _os
        import select as _select
        import socket as _socket

        need = self.capacity * self.channels * self.scan_dtype.itemsize
        parts, got = [], 0
        if getattr(self, "_fd", None) is not None:
            fd = self._fd
            while got < need:
                if stop.is_set():
                    return None
                r, _, _ = _select.select([fd], [], [], 0.2)
                if not r:
                    continue
                try:
                    chunk = _os.read(fd, need - got)
                except BlockingIOError:
                    continue
                except OSError:
                    return None
                if chunk == b"":
                    # FIFO before any writer connects reads as EOF: keep
                    # waiting for the sensor until data has flowed once.
                    if self._is_fifo and not self._saw_data:
                        if stop.wait(0.05):
                            return None
                        continue
                    return None  # real EOF
                self._saw_data = True
                parts.append(chunk)
                got += len(chunk)
        else:  # socket: accumulate with stop-aware timeouts
            while got < need:
                if stop.is_set():
                    return None
                try:
                    chunk = self._sock.recv(need - got)
                except _socket.timeout:
                    continue
                except OSError:
                    return None
                if not chunk:
                    return None  # sender closed
                parts.append(chunk)
                got += len(chunk)
        data = b"".join(parts)
        raw = np.frombuffer(data, self.scan_dtype).astype(np.float32)
        raw = raw.reshape(self.capacity, self.channels)
        return (raw + np.float32(self.offset)) * np.float32(self.scale)

    def generate(self):
        import time as _time

        stop = getattr(self, "_stop_event", threading.Event())
        num = self.num_buffers if self.num_buffers >= 0 else 1 << 62
        period = (self.capacity / self.frequency) if self.frequency > 0 else 0.0
        next_t = _time.monotonic()
        if self.device:
            for i in range(num):
                if stop.is_set():
                    return
                scan = self._read_scan(stop)
                if scan is None:
                    return  # sensor stream ended: EOS
                if self.trigger == "timer":
                    next_t += period
                    delay = next_t - _time.monotonic()
                    if delay > 0 and stop.wait(delay):
                        return
                pts = int(1e9 * i * self.capacity / max(self.frequency, 1e-9))
                yield Buffer([scan], pts=pts)
            return
        i = 0
        for _ in range(num):
            rows = []
            for _ in range(self.capacity):
                if callable(self.sampler):
                    rows.append(np.asarray(self.sampler(i), np.float32))
                else:
                    # synthetic: deterministic pseudo-sensor
                    rows.append(
                        np.sin(np.arange(self.channels) + i / self.frequency).astype(
                            np.float32
                        )
                    )
                i += 1
            yield Buffer([np.stack(rows)])


#: v4l2src format name -> (fourcc, bytes per pixel)
_V4L2_FORMATS = {"RGB": ("RGB3", 3), "BGR": ("BGR3", 3),
                 "GRAY8": ("GREY", 1), "YUY2": ("YUYV", 2)}


@register_element("v4l2src")
class V4L2Src(SourceElement):
    """Camera capture — the literal ``v4l2src`` of the north-star
    pipeline (``v4l2src ! tensor_converter ! tensor_filter ! ...``,
    SURVEY §7 design stance).

    Two backends behind one element:

    * ``/dev/videoN`` (a char device): the NATIVE ioctl/mmap streaming
      ring in native/src/nnstpu.cpp (``nns_v4l2_*``) — REQBUFS(MMAP) +
      QBUF/DQBUF, driver-owned buffers, select()-paced.  Construction
      fails loudly when the node is not a streaming capture device.
    * a FIFO / regular file of raw frames (``width*height*bpp`` bytes
      each): the hermetic-test and replay backend, same polling
      discipline as tensor_src_iio (O_NONBLOCK + stop-event checks, so
      a stalled producer never blocks pipeline shutdown).

    Props: ``device`` (default ``/dev/video0``), ``width``/``height``/
    ``format`` (RGB/BGR/GRAY8/YUY2) — caps are fixed at pipeline
    construction, so a driver that substitutes another mode fails
    loudly at start() naming what it offered (silent substitution
    would feed skewed or never-arriving frames downstream); row-padded
    strides (``bytesperline > width*bpp``) are repacked through the
    native stride stripper.  ``num-buffers``, ``framerate``,
    ``io-mode`` (``auto`` | ``native`` | ``raw``).  Emits host video
    frames ``[H, W, bpp]`` uint8; ``tensor_converter`` downstream turns
    them into ``other/tensors`` exactly as it does for videotestsrc.
    """

    kind = "v4l2src"

    def __init__(self, props=None, name=None):
        super().__init__(props, name)
        self.device = str(self.props.get("device", "/dev/video0"))
        self.width = int(self.props.get("width", 640))
        self.height = int(self.props.get("height", 480))
        self.format = str(self.props.get("format", "RGB")).upper()
        if self.format not in _V4L2_FORMATS:
            raise ElementError(
                f"{self.name}: format must be one of "
                f"{sorted(_V4L2_FORMATS)}, got {self.format!r}")
        self.num_buffers = int(self.props.get("num_buffers", -1))
        self.rate = parse_fraction(self.props.get("framerate", (30, 1)))
        self.io_mode = str(self.props.get("io_mode", "auto")).lower()
        if self.io_mode not in ("auto", "native", "raw"):
            raise ElementError(
                f"{self.name}: io-mode must be auto|native|raw, "
                f"got {self.io_mode!r}")
        self.n_bufs = int(self.props.get("n_bufs", 4))
        self._cap = None   # native backend handle
        self._fd = None    # raw backend fd
        self._is_fifo = False
        self._saw_data = False

    def configure(self, in_caps, out_pads):
        caps = Caps.new(
            MediaType.VIDEO,
            format=self.format,
            width=self.width,
            height=self.height,
            framerate=self.rate,
        )
        self.out_caps = {p: caps for p in out_pads}
        return self.out_caps

    def _frame_bytes(self) -> int:
        return self.width * self.height * _V4L2_FORMATS[self.format][1]

    def start(self) -> None:
        import os as _os
        import stat as _stat

        try:
            st = _os.stat(self.device)
        except OSError as e:
            raise ElementError(
                f"{self.name}: cannot stat device {self.device!r}: {e}"
            ) from e
        use_native = (self.io_mode == "native"
                      or (self.io_mode == "auto"
                          and _stat.S_ISCHR(st.st_mode)))
        if use_native:
            from .. import native

            fourcc, _ = _V4L2_FORMATS[self.format]
            try:
                cap = native.V4L2Capture(self.device, self.width,
                                         self.height, fourcc,
                                         n_bufs=self.n_bufs)
            except RuntimeError as e:
                raise ElementError(f"{self.name}: {e}") from e
            # Caps were negotiated at pipeline construction, BEFORE the
            # device opened — a driver substituting format or geometry
            # cannot flow downstream, so it must fail LOUDLY here (the
            # silent alternative: every frame skipped or row-sheared).
            # The error names what the driver offered so the pipeline
            # string can be corrected.
            if (cap.pixfmt != fourcc or cap.width != self.width
                    or cap.height != self.height):
                got = (f"{cap.pixfmt} {cap.width}x{cap.height}")
                cap.close()
                raise ElementError(
                    f"{self.name}: device negotiated {got}, pipeline "
                    f"caps want {fourcc} {self.width}x{self.height} — "
                    "set width/height/format to a mode the device "
                    "supports")
            self._cap = cap
            return
        try:
            self._fd = _os.open(self.device, _os.O_RDONLY | _os.O_NONBLOCK)
            self._is_fifo = _stat.S_ISFIFO(_os.fstat(self._fd).st_mode)
        except OSError as e:
            raise ElementError(
                f"{self.name}: cannot open device {self.device!r}: {e}"
            ) from e

    def stop(self) -> None:
        if self._cap is not None:
            self._cap.close()
            self._cap = None
        if self._fd is not None:
            import os as _os

            try:
                _os.close(self._fd)
            except OSError:
                pass
            self._fd = None

    def _read_raw_frame(self, stop) -> Optional[np.ndarray]:
        """One raw frame from the FIFO/file backend, or None at
        EOF/stop (same polling discipline as tensor_src_iio)."""
        import os as _os
        import select as _select

        need = self._frame_bytes()
        parts, got = [], 0
        while got < need:
            if stop.is_set():
                return None
            r, _, _ = _select.select([self._fd], [], [], 0.2)
            if not r:
                continue
            try:
                chunk = _os.read(self._fd, need - got)
            except BlockingIOError:
                continue
            except OSError:
                return None
            if chunk == b"":
                if self._is_fifo and not self._saw_data:
                    if stop.wait(0.05):
                        return None
                    continue
                return None  # real EOF; a short tail frame is dropped
            self._saw_data = True
            parts.append(chunk)
            got += len(chunk)
        return np.frombuffer(b"".join(parts), np.uint8)

    def generate(self):
        stop = getattr(self, "_stop_event", threading.Event())
        num = self.num_buffers if self.num_buffers >= 0 else 1 << 62
        frame_ns = int(1e9 * self.rate[1] / max(1, self.rate[0]))
        bpp = _V4L2_FORMATS[self.format][1]
        need = self._frame_bytes()
        for i in range(num):
            if stop.is_set():
                return
            if self._cap is not None:
                raw = None
                while raw is None:
                    if stop.is_set():
                        return
                    raw = self._cap.capture(timeout_ms=200)
                row = self.width * bpp
                if self._cap.stride > row:
                    # driver pads rows (bytesperline > width*bpp):
                    # repack through the native stride stripper
                    from .. import native

                    raw = native.strip_stride(raw, self.height, row,
                                              self._cap.stride)
                if raw.nbytes < need:
                    continue  # driver hiccup: skip the short frame
                raw = raw[:need]
            else:
                raw = self._read_raw_frame(stop)
                if raw is None:
                    return  # EOF
            yield Buffer([raw.reshape(self.height, self.width, bpp)],
                         pts=i * frame_ns)
