"""Shared-memory pipeline hand-off elements.

Reference analog: GStreamer's ``shmsink``/``shmsrc`` (used by nnstreamer
deployments to link pipelines across processes on one host without the TCP
stack; upstream-reconstructed, SURVEY §2.7 context).  The TPU build backs
them with the native SPSC ring (``nnstreamer_tpu.native.ShmRing``, C++ —
POSIX shm + lock-free atomics), carrying ``other/tensors`` buffers in the
standard wire format.

``shmsink socket-path=/name`` publishes; ``shmsrc socket-path=/name`` in a
second process (or the same one) consumes.  ``wait-for-connection`` on the
sink and ``is-live`` semantics follow the GStreamer originals loosely: the
sink blocks when the ring is full (backpressure) unless ``drop=true``.
"""

from __future__ import annotations

import time
from typing import Optional

from ..core.buffer import Buffer
from ..core.caps import Caps
from ..core.log import logger, metrics
from ..core.registry import register_element
from ..native import ShmRing, available as native_available
from ..utils.wire import decode_buffer, encode_buffer
from .base import ElementError, SinkElement, SourceElement

log = logger(__name__)


def _ring_name(props) -> str:
    name = str(props.get("socket_path", props.get("name_prop", "")) or "")
    if not name:
        raise ElementError("shm element needs socket-path=<shm name>")
    return name if name.startswith("/") else "/" + name


@register_element("shmsink")
class ShmSink(SinkElement):
    """Publish buffers into a shared-memory ring.

    Props: ``socket-path`` (shm name), ``shm-size`` (slot bytes, default
    1 MiB), ``buffers`` (ring slots, default 8), ``drop`` (drop newest when
    full instead of blocking).
    """

    kind = "shmsink"

    def __init__(self, props=None, name=None):
        super().__init__(props, name)
        if not native_available():
            raise ElementError("shmsink requires the native library")
        self.ring_name = _ring_name(self.props)
        self.slot_bytes = int(self.props.get("shm_size", 1 << 20))
        self.nslots = int(self.props.get("buffers", 8))
        self.drop = bool(self.props.get("drop", False))
        self._ring: Optional[ShmRing] = None

    def start(self) -> None:
        self._ring = ShmRing.create(self.ring_name, self.nslots, self.slot_bytes)

    def stop(self) -> None:
        if self._ring is not None:
            self._ring.close_write()
            self._ring.free()
            self._ring = None

    def process(self, pad, buf: Buffer):
        payload = encode_buffer(buf.resolve().to_host())
        stop = getattr(self, "_stop_event", None)
        while not self._ring.try_put(payload):
            if self.drop:
                metrics.count(f"{self.name}.dropped")
                return []
            if stop is not None and stop.is_set():
                return []
            time.sleep(0.001)  # ring full: backpressure
        metrics.count(f"{self.name}.frames")
        return []

    def finalize(self):
        if self._ring is not None:
            self._ring.close_write()
        return []


@register_element("shmsrc")
class ShmSrc(SourceElement):
    """Consume buffers from a shared-memory ring published by ``shmsink``.

    Props: ``socket-path``, ``num-buffers`` (-1 = until producer closes),
    ``connect-timeout`` seconds to wait for the producer's ring to appear.
    """

    kind = "shmsrc"

    def __init__(self, props=None, name=None):
        super().__init__(props, name)
        if not native_available():
            raise ElementError("shmsrc requires the native library")
        self.ring_name = _ring_name(self.props)
        self.num_buffers = int(self.props.get("num_buffers", -1))
        self.connect_timeout = float(self.props.get("connect_timeout", 10.0))
        self._ring: Optional[ShmRing] = None

    def configure(self, in_caps, out_pads):
        self.out_caps = {p: Caps.any() for p in out_pads}
        return self.out_caps

    def start(self) -> None:
        deadline = time.monotonic() + self.connect_timeout
        while True:
            try:
                self._ring = ShmRing.open(self.ring_name)
                return
            except OSError:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.02)

    def stop(self) -> None:
        if self._ring is not None:
            self._ring.free()
            self._ring = None

    def generate(self):
        n = 0
        stop = getattr(self, "_stop_event", None)
        while self.num_buffers < 0 or n < self.num_buffers:
            data = self._ring.try_get()
            if data is None:
                if self._ring.closed:
                    # Producer EOS'd — but a buffer may have been committed
                    # between our empty read and the close: drain fully.
                    data = self._ring.try_get()
                    if data is None:
                        return
                elif stop is not None and stop.is_set():
                    return
                else:
                    time.sleep(0.001)
                    continue
            buf, _flags = decode_buffer(data)
            metrics.count(f"{self.name}.frames")
            n += 1
            yield buf
