"""edgesrc/edgesink: pub/sub tensor transport between pipelines/hosts.

Reference analog (SURVEY §2.7): ``gst/edge/gstedgesrc.c``/``gstedgesink.c``
publish/subscribe tensor streams through the nnstreamer-edge library (TCP
direct, or MQTT-hybrid broker discovery).  Here the transport is the
framework wire format over TCP: an ``edgesink`` listens and fans every
buffer out to all connected subscribers whose topic matches; an ``edgesrc``
connects, subscribes with a topic, and injects received buffers into its
pipeline.  This is the DCN-side stream feed of the distribution story (the
north-star maps broker transport to DCN streaming into per-host device_put).

Unlike tensor_query there is no response path and no per-message pairing —
fire-and-forget fan-out, matching the reference's pub/sub semantics (slow
subscribers drop: the publisher never backpressures the pipeline).
"""

from __future__ import annotations

import queue as _queue
import socket
import threading
from typing import Dict, Iterator, Optional, Union

from ..core.buffer import Buffer, Event
from ..core.log import logger, metrics
from ..core.registry import register_element
from ..utils import wire
from ..utils.net import TcpListener, client_handshake, server_handshake
from .base import ElementError, SinkElement, SourceElement

log = logger(__name__)

#: Per-subscriber queue EOS marker (publisher reached end of stream).
_EOS = None


@register_element("edgesink")
class EdgeSink(SinkElement):
    """Publish buffers to every connected subscriber.

    Props: ``host`` (bind address), ``port`` (0 = OS-assigned; see
    ``.bound_port``), ``topic``, ``max-queue`` (per-subscriber send queue;
    overflow drops oldest — pub/sub never backpressures).
    """

    kind = "edgesink"

    def __init__(self, props=None, name=None):
        super().__init__(props, name)
        self.host = str(self.props.get("host", "127.0.0.1"))
        self.port = int(self.props.get("port", 0))
        self.topic = str(self.props.get("topic", ""))
        self.max_queue = int(self.props.get("max_queue", 64))
        self._subs: Dict[int, _queue.Queue] = {}
        self._lock = threading.Lock()
        self._next_sub = 0
        self._listener: Optional[TcpListener] = None

    def start(self) -> None:
        self._listener = TcpListener(self.host, self.port, self._sub_session,
                                     name=self.name)

    @property
    def bound_port(self) -> int:
        if self._listener is None:
            raise ElementError("edgesink not started")
        return self._listener.port

    def stop(self) -> None:
        if self._listener is not None:
            self._listener.close()
            self._listener = None
        with self._lock:
            self._subs.clear()

    def _sub_session(self, conn: socket.socket) -> None:
        stopping = self._listener.stopping
        if server_handshake(conn, "subscribe", self.topic) is None:
            return
        conn.settimeout(None)
        q: _queue.Queue = _queue.Queue(maxsize=self.max_queue)
        with self._lock:
            sid = self._next_sub
            self._next_sub += 1
            self._subs[sid] = q
        metrics.count(f"{self.name}.subscribers")
        try:
            while not stopping.is_set():
                try:
                    payload = q.get(timeout=0.2)
                except _queue.Empty:
                    continue
                if payload is _EOS:  # publisher EOS: close -> subscriber EOS
                    return
                wire.write_frame(conn, payload)
        finally:
            with self._lock:
                self._subs.pop(sid, None)

    def _offer(self, q: _queue.Queue, item) -> None:
        """Enqueue without ever blocking the pipeline: overflow drops the
        slow subscriber's oldest frame (pub/sub semantics)."""
        while True:
            try:
                q.put_nowait(item)
                return
            except _queue.Full:
                try:
                    q.get_nowait()
                    metrics.count(f"{self.name}.dropped")
                except _queue.Empty:
                    continue

    def process(self, pad, buf: Buffer):
        with self._lock:
            subs = list(self._subs.values())
        if not subs:
            metrics.count(f"{self.name}.no_subscribers")
            return []  # nobody listening: skip host copy + serialization
        payload = wire.encode_buffer(buf.to_host())
        for q in subs:
            self._offer(q, payload)
        metrics.count(f"{self.name}.published")
        return []

    def finalize(self):
        with self._lock:
            subs = list(self._subs.values())
        for q in subs:
            self._offer(q, _EOS)  # drop-oldest guarantees the marker lands
        return []


@register_element("edgesrc")
class EdgeSrc(SourceElement):
    """Subscribe to an edgesink and inject received buffers.

    Props: ``host``, ``port``, ``topic``, ``num-buffers`` (stop after N;
    -1 = until publisher closes).
    """

    kind = "edgesrc"

    def __init__(self, props=None, name=None):
        super().__init__(props, name)
        self.host = str(self.props.get("host", "127.0.0.1"))
        self.port = int(self.props.get("port", 0))
        self.topic = str(self.props.get("topic", ""))
        self.num_buffers = int(self.props.get("num_buffers", -1))
        self._sock: Optional[socket.socket] = None

    def start(self) -> None:
        if self.port <= 0:
            raise ElementError(f"{self.name}: port property required")
        try:
            self._sock = socket.create_connection((self.host, self.port), timeout=5.0)
            client_handshake(self._sock, "subscribe", topic=self.topic)
        except (OSError, ConnectionError) as e:
            self.stop()
            raise ElementError(
                f"{self.name}: cannot subscribe {self.host}:{self.port}: {e}"
            ) from e
        self._sock.settimeout(0.2)

    def stop(self) -> None:
        sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def generate(self) -> Iterator[Union[Buffer, Event]]:
        stop = getattr(self, "_stop_event", threading.Event())
        count = 0
        while not stop.is_set() and count != self.num_buffers:
            sock = self._sock
            if sock is None:
                return
            try:
                raw = wire.read_frame(sock)
            except socket.timeout:
                continue
            except OSError:
                return
            except ValueError as e:  # corrupt frame (CRC mismatch)
                log.warning("%s: corrupt frame, treating as connection "
                            "loss: %s", self.name, e)
                metrics.count(f"{self.name}.corrupt")
                return
            if raw is None:
                return  # publisher closed: EOS
            try:
                buf, _flags = wire.decode_buffer(raw)
            except ValueError as e:
                log.warning("%s: corrupt payload, treating as connection "
                            "loss: %s", self.name, e)
                metrics.count(f"{self.name}.corrupt")
                return
            metrics.count(f"{self.name}.received")
            yield buf
            count += 1
