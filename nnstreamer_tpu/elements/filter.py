"""tensor_filter: run a model as a stream element.

Reference analog: ``gst/nnstreamer/tensor_filter/gsttensor_filter.c`` +
``tensor_filter_common.c`` (SURVEY §2.3): framework selection (``auto`` walks
the configured priority list), model load at READY, input/output dims from
props or queried from the framework, per-invoke latency/throughput
measurement, ``invoke-dynamic`` flexible output, input/output combination
remapping.  The single-shot no-pipeline path (gsttensor_filter_single.c) is
:class:`SingleShot` below.

TPU-first: when the chosen framework exposes a pure JAX function, the
planner fuses this element with its preprocess/postprocess neighbors into
one jitted XLA program, and buffers stay in HBM across the whole fused span
(the north star's PJRT zero-copy requirement).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..core.buffer import Buffer
from ..core.caps import Caps, MediaType
from ..core.config import get_config
from ..core.log import Timer, logger, metrics
from ..core.registry import KIND_FILTER, get as registry_get, lookup, names, register_element
from ..core.types import TensorFormat, TensorsSpec
from ..filters.base import Framework, FrameworkError, parse_accelerator
from .base import Element, ElementError, SRC

log = logger(__name__)


def _load_framework(props: Dict[str, object]) -> Framework:
    """framework= name or 'auto' (priority list from config)."""
    fw_name = str(props.get("framework", "auto")).lower()
    candidates = (
        get_config().filter_priority if fw_name in ("auto", "") else [fw_name]
    )
    last_err: Optional[Exception] = None
    for cand in candidates:
        cls = lookup(KIND_FILTER, cand)
        if cls is None:
            last_err = KeyError(f"framework {cand!r} not registered")
            continue
        fw: Framework = cls()
        try:
            fw.open(props)
            return fw
        except FrameworkError as e:
            last_err = e
            continue
    raise ElementError(
        f"no framework could open model {props.get('model')!r} "
        f"(tried {candidates}): {last_err}"
    )


@register_element("tensor_filter")
class TensorFilter(Element):
    kind = "tensor_filter"

    def __init__(self, props=None, name=None):
        super().__init__(props, name)
        self.fw: Optional[Framework] = None
        self.accelerators = parse_accelerator(str(self.props.get("accelerator", "")))
        self.invoke_dynamic = bool(self.props.get("invoke_dynamic", False))
        self.latency_report = bool(self.props.get("latency", get_config().enable_latency))
        self._in_spec: Optional[TensorsSpec] = None
        self._out_spec: Optional[TensorsSpec] = None
        self._lat_ema: Optional[float] = None
        self._n_invoked = 0

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        self._ensure_fw()

    def _ensure_fw(self) -> Framework:
        if self.fw is None:
            self.fw = _load_framework(self.props)
        return self.fw

    def stop(self) -> None:
        if self.fw is not None:
            self.fw.close()
            self.fw = None

    # -- negotiation -------------------------------------------------------
    def configure(self, in_caps, out_pads):
        self.in_caps = dict(in_caps)
        fw = self._ensure_fw()
        fw_in, fw_out = fw.get_model_info()

        # explicit props override / fill in what the fw doesn't know
        if self.props.get("input"):
            fw_in = TensorsSpec.from_string(
                str(self.props["input"]), str(self.props.get("inputtype", "float32"))
            )
        if self.props.get("output"):
            fw_out = TensorsSpec.from_string(
                str(self.props["output"]), str(self.props.get("outputtype", "float32"))
            )
        src = next(iter(in_caps.values()), Caps.any())
        up_spec = src.spec
        if fw_in is None:
            fw_in = up_spec
        elif up_spec is not None and not up_spec.is_flexible:
            if len(up_spec) != len(fw_in) or not all(
                a.is_compatible(b) for a, b in zip(up_spec, fw_in)
            ):
                raise ElementError(
                    f"{self.name}: upstream spec {up_spec} does not match model "
                    f"input {fw_in}"
                )
        self._in_spec = fw_in
        if fw_in is not None:
            fw.set_input_spec(fw_in)
            if fw_out is None:
                fw_in2, fw_out = fw.get_model_info()
        self._out_spec = fw_out
        fmt = TensorFormat.FLEXIBLE if self.invoke_dynamic else TensorFormat.STATIC
        if fw_out is not None:
            fw_out = fw_out.replace(format=fmt)
        caps = Caps.tensors(fw_out)
        self.out_caps = {p: caps for p in out_pads}
        return self.out_caps

    # -- streaming ---------------------------------------------------------
    def process(self, pad, buf: Buffer):
        fw = self._ensure_fw()
        if getattr(fw, "streaming", False):
            # Streaming frameworks (llm) emit MANY buffers per input; the
            # runner iterates this generator, so each token flows downstream
            # while the next is still decoding (reference: llamacpp filter
            # streams tokens as flexible tensors).
            def stream():
                t0 = time.perf_counter()
                for i, outs in enumerate(fw.invoke_stream(buf.tensors)):
                    out_buf = buf.with_tensors(list(outs), spec=None)
                    out_buf.meta["stream_index"] = i
                    yield (SRC, out_buf)
                dt = time.perf_counter() - t0
                self._n_invoked += 1
                if self.latency_report:
                    metrics.observe_latency(f"{self.name}.invoke", dt)

            return stream()
        t0 = time.perf_counter()
        outs = fw.invoke(buf.tensors)
        dt = time.perf_counter() - t0
        self._n_invoked += 1
        if self.latency_report:
            metrics.observe_latency(f"{self.name}.invoke", dt)
            self._lat_ema = dt if self._lat_ema is None else 0.9 * self._lat_ema + 0.1 * dt
        spec = self._out_spec if not self.invoke_dynamic else None
        return [(SRC, buf.with_tensors(list(outs), spec=spec))]

    # -- fusion ------------------------------------------------------------
    def device_fn(self, in_spec: TensorsSpec):
        fw = self._ensure_fw()
        fn = fw.pure_fn()
        if fn is None or self.invoke_dynamic:
            return None
        out_spec = self._out_spec
        if out_spec is None:
            _, out_spec = fw.get_model_info()
        if out_spec is None:
            return None
        return fn, out_spec

    # -- introspection (reference: latency/throughput read-only props) -----
    @property
    def latency(self) -> Optional[float]:
        """Moving-average seconds per invoke."""
        return self._lat_ema

    @property
    def throughput(self) -> Optional[float]:
        return (1.0 / self._lat_ema) if self._lat_ema else None


class SingleShot:
    """Invoke a filter without a pipeline.

    Reference analog: ``gsttensor_filter_single.c`` — the basis of the
    external ML C-API's ``ml_single_open``/``ml_single_invoke`` (SURVEY §3.5).

    >>> s = SingleShot(framework="jax", model="mobilenet_v1")
    >>> out = s.invoke(np.zeros((1, 224, 224, 3), np.float32))
    """

    def __init__(self, framework: str = "auto", model: object = "", **props):
        p = dict(props)
        p["framework"] = framework
        p["model"] = model
        self.fw = _load_framework(p)
        self.in_spec, self.out_spec = self.fw.get_model_info()

    def invoke(self, *arrays) -> List[np.ndarray]:
        if len(arrays) == 1 and isinstance(arrays[0], (list, tuple)):
            arrays = tuple(arrays[0])
        outs = self.fw.invoke(list(arrays))
        return [np.asarray(o) for o in outs]

    def close(self) -> None:
        self.fw.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
