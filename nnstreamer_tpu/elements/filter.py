"""tensor_filter: run a model as a stream element.

Reference analog: ``gst/nnstreamer/tensor_filter/gsttensor_filter.c`` +
``tensor_filter_common.c`` (SURVEY §2.3): framework selection (``auto`` walks
the configured priority list), model load at READY, input/output dims from
props or queried from the framework, per-invoke latency/throughput
measurement, ``invoke-dynamic`` flexible output, input/output combination
remapping.  The single-shot no-pipeline path (gsttensor_filter_single.c) is
:class:`SingleShot` below.

TPU-first: when the chosen framework exposes a pure JAX function, the
planner fuses this element with its preprocess/postprocess neighbors into
one jitted XLA program, and buffers stay in HBM across the whole fused span
(the north star's PJRT zero-copy requirement).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..core.buffer import Buffer
from ..core.caps import Caps, MediaType
from ..core.config import get_config
from ..core.log import Timer, logger, metrics
from ..core.meta_keys import META_STREAM_INDEX, META_STREAM_LAST
from ..core.registry import KIND_FILTER, get as registry_get, lookup, names, register_element
from ..core.types import TensorFormat, TensorsSpec
from ..filters.base import Framework, FrameworkError, parse_accelerator
from .base import Element, ElementError, SRC

log = logger(__name__)


def _parse_input_combination(s: str) -> Optional[List[int]]:
    """``input-combination=0,2`` — indices of the incoming buffer's tensors
    fed to the model (reference: tensor_filter_common.c input-combination)."""
    s = s.strip()
    if not s:
        return None
    return [int(v) for v in s.split(",")]


def _parse_output_combination(s: str) -> Optional[List[Tuple[str, int]]]:
    """``output-combination=i0,o0`` — compose the output buffer from input
    tensors (``iN``, pass-through) and model outputs (``oN``); bare digits
    mean ``oN`` (reference: tensor_filter_common.c output-combination)."""
    s = s.strip()
    if not s:
        return None
    combo: List[Tuple[str, int]] = []
    for tok in s.split(","):
        tok = tok.strip().lower()
        if tok.startswith(("i", "o")):
            combo.append((tok[0], int(tok[1:])))
        else:
            combo.append(("o", int(tok)))
    return combo


def _load_framework(props: Dict[str, object],
                    mesh_provider=None) -> Framework:
    """framework= name or 'auto' (priority list from config).

    ``mesh_provider`` is the owning pipeline's shared-mesh accessor
    (``Pipeline._model_mesh``), attached BEFORE open() so a framework
    with a tensor-parallel path (the llm filter) lands on the pipeline's
    ``(data x model)`` mesh instead of minting a private one."""
    fw_name = str(props.get("framework", "auto")).lower()
    candidates = (
        get_config().filter_priority if fw_name in ("auto", "") else [fw_name]
    )
    last_err: Optional[Exception] = None
    for cand in candidates:
        cls = lookup(KIND_FILTER, cand)
        if cls is None:
            last_err = KeyError(f"framework {cand!r} not registered")
            continue
        fw: Framework = cls()
        if mesh_provider is not None:
            fw._mesh_provider = mesh_provider
        try:
            fw.open(props)
            return fw
        except FrameworkError as e:
            last_err = e
            continue
    raise ElementError(
        f"no framework could open model {props.get('model')!r} "
        f"(tried {candidates}): {last_err}"
    )


@register_element("tensor_filter")
class TensorFilter(Element):
    kind = "tensor_filter"
    PAD_TEMPLATES = {"sink": Caps.new(MediaType.TENSORS)}

    def __init__(self, props=None, name=None):
        super().__init__(props, name)
        self.fw: Optional[Framework] = None
        self.accelerators = parse_accelerator(str(self.props.get("accelerator", "")))
        self.invoke_dynamic = bool(self.props.get("invoke_dynamic", False))
        self.latency_report = bool(self.props.get("latency", get_config().enable_latency))
        self._in_spec: Optional[TensorsSpec] = None
        self._out_spec: Optional[TensorsSpec] = None
        #: set by the HBM-residency planner (pipeline/residency.py) BEFORE
        #: negotiation when every downstream consumer admits reduced
        #: output geometry; configure() then asks the framework to switch
        self._reduced_admissible = False
        #: description of the reduced output the planner selected (None =
        #: full output crosses); read by the residency plan and bench
        self.reduced_output_selected: Optional[str] = None
        self._lat_ema: Optional[float] = None
        self._n_invoked = 0
        self._batchers: Dict[int, object] = {}
        #: per-swap version counter (nns-learn train-while-serve)
        self._param_version = 0
        import threading

        self._fw_lock = threading.Lock()  # process vs reload_model swap
        self.input_combination = _parse_input_combination(
            str(self.props.get("input_combination", "")))
        self.output_combination = _parse_output_combination(
            str(self.props.get("output_combination", "")))
        # eager reads: inputtype/outputtype are legal without input/output
        # dims (the conditional reads in configure would otherwise leave
        # them "unknown" to the property check)
        self.props.get("inputtype")
        self.props.get("outputtype")

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        self._ensure_fw()

    def _ensure_fw(self) -> Framework:
        if self.fw is None:
            self.fw = _load_framework(
                self.props,
                mesh_provider=getattr(self, "_mesh_provider", None))
        # armor handoff (the _trace_rec pattern): the llm serve loop's
        # nan_guard quarantines poisoned prompts through the pipeline's
        # DLQ/breaker — docs/ROBUSTNESS.md
        self.fw._armor = getattr(self, "_armor", None)
        # nns-xray handoff: the framework's jitted paths register their
        # compiles under THIS element's stage name (None = off, and the
        # framework never learns xray exists)
        xr = getattr(self, "_xray", None)
        if xr is not None and getattr(self.fw, "_xray", None) is not xr:
            self.fw.attach_xray(xr, self.name,
                                rec=lambda: getattr(self, "_trace_rec",
                                                    None))
        return self.fw

    def stop(self) -> None:
        if self.fw is not None:
            self.fw.close()
            self.fw = None

    # -- negotiation -------------------------------------------------------
    def configure(self, in_caps, out_pads):
        self.in_caps = dict(in_caps)
        fw = self._ensure_fw()
        if getattr(fw, "continuous", False):
            # Continuous-serving frameworks (llm serve:continuous) emit
            # tokens from their own serve thread, decoupled from any one
            # input buffer — same async-emit contract as the query client.
            self.wants_async_emit = True
        if (self._reduced_admissible
                and self.reduced_output_selected is None
                and not self.props.get("output")):
            # Residency planner: every downstream consumer admits reduced
            # geometry and no explicit output= prop pins it — switch the
            # model to its reduced variant (no-op when none exists), so
            # the smaller payload is what negotiation propagates and the
            # sink edge fetches.  docs/FETCH.md "Residency rules".
            desc = fw.select_reduced_output()
            if desc:
                self.reduced_output_selected = desc
                log.info("%s: residency planner selected reduced output: "
                         "%s", self.name, desc)
        fw_in, fw_out = fw.get_model_info()

        # explicit props override / fill in what the fw doesn't know
        if self.props.get("input"):
            fw_in = TensorsSpec.from_string(
                str(self.props["input"]), str(self.props.get("inputtype", "float32"))
            )
        if self.props.get("output"):
            fw_out = TensorsSpec.from_string(
                str(self.props["output"]), str(self.props.get("outputtype", "float32"))
            )
        src = next(iter(in_caps.values()), Caps.any())
        up_spec = src.spec
        self._up_spec = up_spec
        # input-combination selects which upstream tensors feed the model:
        # the spec check applies to the SELECTED subset.
        model_up = up_spec
        if up_spec is not None and self.input_combination is not None:
            if any(i >= len(up_spec) for i in self.input_combination):
                raise ElementError(
                    f"{self.name}: input-combination {self.input_combination} "
                    f"out of range for upstream spec {up_spec}")
            model_up = TensorsSpec(
                tuple(up_spec[i] for i in self.input_combination),
                rate=up_spec.rate)
        if fw_in is None:
            fw_in = model_up
        elif model_up is not None and not model_up.is_flexible:
            if len(model_up) != len(fw_in) or not all(
                a.is_compatible(b) for a, b in zip(model_up, fw_in)
            ):
                raise ElementError(
                    f"{self.name}: upstream spec {model_up} does not match model "
                    f"input {fw_in}"
                )
        self._in_spec = fw_in
        if fw_in is not None:
            fw.set_input_spec(fw_in)
            if fw_out is None:
                fw_in2, fw_out = fw.get_model_info()
        self._out_spec = fw_out
        final_out = self._combined_out_spec(fw_out)
        fmt = TensorFormat.FLEXIBLE if self.invoke_dynamic else TensorFormat.STATIC
        if final_out is not None:
            final_out = final_out.replace(format=fmt)
        caps = Caps.tensors(final_out)
        self.out_caps = {p: caps for p in out_pads}
        return self.out_caps

    def _combined_out_spec(self, fw_out):
        """Output spec after output-combination (iN = upstream tensor,
        oN = model output)."""
        if self.output_combination is None:
            return fw_out
        parts = []
        for tag, i in self.output_combination:
            pool = self._up_spec if tag == "i" else fw_out
            if pool is None or i >= len(pool):
                return None  # unknown statically; derived per buffer
            parts.append(pool[i])
        return TensorsSpec(tuple(parts))

    def _select_inputs(self, tensors):
        if self.input_combination is None:
            return list(tensors)
        if any(i >= len(tensors) for i in self.input_combination):
            raise ElementError(
                f"{self.name}: input-combination {self.input_combination} "
                f"out of range (buffer has {len(tensors)} tensors)")
        return [tensors[i] for i in self.input_combination]

    def _compose_outputs(self, in_tensors, outs):
        if self.output_combination is None:
            return list(outs)
        final = []
        for tag, i in self.output_combination:
            pool = in_tensors if tag == "i" else outs
            if i >= len(pool):
                raise ElementError(
                    f"{self.name}: output-combination {tag}{i} out of range")
            final.append(pool[i])
        return final

    # -- streaming ---------------------------------------------------------
    def process(self, pad, buf: Buffer):
        with self._fw_lock:  # pairs with reload_model's swap
            fw = self._ensure_fw()
        if getattr(fw, "continuous", False):
            # Standing serve loop: enqueue the request (its meta — query
            # connection/msg ids — rides along) and return; the loop's
            # thread emits one buffer per generated token via async emit.
            # The loop's serve.admit/prefill_chunk/decode spans follow
            # THIS pipeline's trace_mode (the element-pinned recorder,
            # same contract as the sink fetch span).
            import functools as _ft

            fw._trace_rec = getattr(self, "_trace_rec", None)
            fw.submit(self._select_inputs(buf.tensors), dict(buf.meta),
                      _ft.partial(self._emit_serve_token, buf))
            self._n_invoked += 1
            return []
        if getattr(fw, "streaming", False):
            # Streaming frameworks (llm) emit MANY buffers per input; the
            # runner iterates this generator, so each token flows downstream
            # while the next is still decoding (reference: llamacpp filter
            # streams tokens as flexible tensors).
            def stream():
                t0 = time.perf_counter()
                ins = self._select_inputs(buf.tensors)
                # One-step lookahead so the FINAL buffer can carry
                # ``stream_last`` — consumers that must know when a
                # request's stream ends (tensor_query streaming responses)
                # need the marker on a data buffer, not a separate event.
                prev = None
                for i, outs in enumerate(fw.invoke_stream(ins)):
                    if prev is not None:
                        yield (SRC, prev)
                    final = self._compose_outputs(buf.tensors, list(outs))
                    out_buf = buf.with_tensors(final, spec=None)
                    out_buf.meta[META_STREAM_INDEX] = i
                    prev = out_buf
                if prev is not None:
                    prev.meta[META_STREAM_LAST] = True
                    yield (SRC, prev)
                dt = time.perf_counter() - t0
                self._n_invoked += 1
                if self.latency_report:
                    metrics.observe_latency(f"{self.name}.invoke", dt)

            return stream()
        t0 = time.perf_counter()
        with self._fw_lock:
            # Held across the invoke so reload_model cannot close the
            # framework out from under an in-flight call; re-read self.fw
            # here — a reload may have swapped it since the earlier peek.
            # No contention cost: invokes are serialized on the stage
            # thread anyway.
            fw = self._ensure_fw()
            outs = fw.invoke(self._select_inputs(buf.tensors))
        dt = time.perf_counter() - t0
        self._n_invoked += 1
        if self.latency_report:
            metrics.observe_latency(f"{self.name}.invoke", dt)
            self._lat_ema = dt if self._lat_ema is None else 0.9 * self._lat_ema + 0.1 * dt
        final = self._compose_outputs(buf.tensors, list(outs))
        spec = None
        if not self.invoke_dynamic:
            spec = self._combined_out_spec(self._out_spec)
        return [(SRC, buf.with_tensors(final, spec=spec))]

    # -- micro-batching ----------------------------------------------------
    def _batchable_fn(self, fw):
        """THE batchability predicate (shared by the plan-time capability
        probe and the dispatch-time re-check): the framework's pure JAX fn
        when one vmapped bucketed dispatch may replace N invokes, else
        None.  Streaming/continuous frameworks emit asynchronously per
        request and invoke-dynamic output shapes vary per buffer — those
        keep the per-buffer path."""
        if (self.invoke_dynamic or getattr(fw, "streaming", False)
                or getattr(fw, "continuous", False)):
            return None
        return fw.pure_fn()

    def batch_capable(self) -> bool:
        try:
            return self._batchable_fn(self._ensure_fw()) is not None
        except Exception:  # noqa: BLE001 - capability probe only
            return False

    # -- nns-learn: train-while-serve param hot-swap ------------------------
    def swap_params(self, tree) -> int:
        """Hot-swap the live model weights as a VALUE move
        (docs/TRAINING.md): delegates to the framework's ``swap_params``
        under ``_fw_lock`` so the swap lands at a DISPATCH BOUNDARY —
        never under an in-flight invoke (continuous frameworks further
        defer to their own chunk boundary via the control-command
        queue).  Bumps and returns the per-stage param version
        (``<name>.param_version`` gauge, ``learn.swap`` span).  Raises
        when the framework's dispatch path is not hot-swappable or the
        tree does not match the serving avals."""
        import time as _time

        t0 = _time.monotonic_ns()
        with self._fw_lock:
            if self._batchers:
                # belt-and-braces twin of the Pipeline-level batch_max
                # guard: bucket programs were built from pure_fn()
                # closures that snapshot params — swapping under them
                # would serve stale weights
                raise FrameworkError(
                    f"{self.name}: micro-batched dispatch captures "
                    "params at build time — hot-swap needs batch_max=1")
            fw = self._ensure_fw()
            fw.swap_params(tree)
            self._param_version += 1
            version = self._param_version
        metrics.count(f"{self.name}.param_swaps")
        metrics.gauge(f"{self.name}.param_version", float(version))
        rec = getattr(self, "_trace_rec", None)
        if rec is not None and rec.active:
            rec.record("learn.swap", self.name, None, t0,
                       _time.monotonic_ns() - t0, version=version)
        return version

    def place_params(self, mesh) -> bool:
        """Place the framework's model params onto ``mesh`` once (the
        sharded-dispatch prepare contract, elements/base.py): with a >1
        ``model`` axis, leaves the bundle's ``param_pspecs`` shard over
        ``model`` are sharded (per-chip weight HBM drops by the axis
        size), the rest replicate; a 1-wide model axis is the exact
        pre-2-D replicate path.  Deliberately lock-free: callers either
        run on the stage thread that serializes with
        process()/process_batch() (the fused-chain path) or already hold
        ``_fw_lock`` (the prepare hook below)."""
        return self._place_fw_params(self.fw or self._ensure_fw(), mesh)

    def _place_fw_params(self, fw, mesh) -> bool:
        bundle = getattr(fw, "bundle", None)
        params = getattr(bundle, "params", None)
        if params is None:
            return False
        from ..parallel.mesh import mesh_axis_size
        from ..parallel.sharding import (placement_split, replicate,
                                         shard_params)

        pspecs = getattr(bundle, "param_pspecs", None)
        if mesh_axis_size(mesh, "model") > 1 and pspecs is not None:
            bundle.params = shard_params(mesh, params, pspecs)
            n_shard, n_rep = placement_split(params, pspecs)
            # shard-vs-replica split: proof of model-axis placement the
            # 2-D tests/operators read next to .param_replications
            metrics.count(f"{self.name}.param_shards", n_shard)
            metrics.count(f"{self.name}.param_replicas", n_rep)
        else:
            # dp-only (or no pspecs): the exact legacy replicate path
            bundle.params = replicate(mesh, params)
        metrics.count(f"{self.name}.param_replications")
        return True

    def process_batch(self, pad: str, bufs):
        """N same-spec buffers -> ONE bucketed vmapped model dispatch.

        Falls back to the per-buffer loop when the (possibly reloaded)
        framework no longer exposes a pure fn.  Latency accounting records
        the batched dispatch as one invoke — the whole point is fewer,
        bigger device calls."""
        t0 = time.perf_counter()
        with self._fw_lock:
            # ONE lock span from framework read to dispatch, like process():
            # a reload_model landing mid-batch must not close the framework
            # whose weights this dispatch is about to use.
            fw = self._ensure_fw()
            fn = self._batchable_fn(fw)
            if fn is not None:
                # keyed by framework identity (pure_fn returns a FRESH
                # closure per call): reload_model swaps the framework
                # instance, and the old jitted buckets must not serve the
                # new weights
                entry = self._batchers.get(id(fw))
                if entry is None:
                    from ..pipeline.batching import BatchRunner

                    mesh = getattr(self, "_shard_mesh", None)
                    prep = None
                    if mesh is not None:
                        # Place THIS framework's params once (shard over
                        # the model axis per pspecs, replicate the rest),
                        # then hand the runner a fresh closure capturing
                        # the placed tree.  fw is bound here: a reload
                        # mid-stream swaps the instance AND the batcher
                        # entry, so the new framework places again (its
                        # params are new arrays).
                        def prep(m, fw=fw):
                            self._place_fw_params(fw, m)
                            return self._batchable_fn(fw)
                    entry = (fw, BatchRunner(
                        fn, getattr(self, "_batch_buckets", None),
                        name=self.name, mesh=mesh, prepare=prep,
                        tracer=getattr(self, "_trace_rec", None),
                        ladder=getattr(self, "_batch_ladder", None),
                        xray=getattr(self, "_xray", None)))
                    self._batchers = {id(fw): entry}  # drop stale programs
                rows = entry[1].run(
                    [tuple(self._select_inputs(b.tensors)) for b in bufs])
        if fn is None:
            # outside the lock: the loop fallback re-acquires it per buffer
            return super().process_batch(pad, bufs)
        # PER-BUFFER service time: latency/throughput introspection must
        # stay comparable whether batching is on or off (throughput keeps
        # meaning buffers/sec, and enabling batching shows the speedup
        # instead of an apparent slowdown from one big sample).
        per = (time.perf_counter() - t0) / len(bufs)
        self._n_invoked += len(bufs)
        if self.latency_report:
            metrics.observe_latency(f"{self.name}.invoke", per)
            self._lat_ema = (per if self._lat_ema is None
                             else 0.9 * self._lat_ema + 0.1 * per)
        spec = None
        if not self.invoke_dynamic:
            spec = self._combined_out_spec(self._out_spec)
        return [
            (SRC, b.with_tensors(
                self._compose_outputs(b.tensors, list(row)), spec=spec))
            for b, row in zip(bufs, rows)
        ]

    def _emit_serve_token(self, src_buf: Buffer, tensors, meta) -> None:
        """Serve-thread callback: one generated token -> one buffer.
        Derives from the ORIGINATING buffer so output-combination props
        apply and pts survives, exactly like the per-request stream
        path; the serve loop's meta (stream ids + request meta) wins."""
        emit = self._async_emit
        if emit is None:
            raise ElementError(f"{self.name}: not attached to a pipeline")
        out = src_buf.with_tensors(
            self._compose_outputs(src_buf.tensors, list(tensors)),
            spec=None)
        out.meta = dict(meta)
        emit([(SRC, out)])

    # -- elastic serving (docs/SERVING.md "Elastic serving") ---------------
    def serve_streams(self) -> Dict[int, dict]:
        """Live/queued continuous-serving streams of this element's
        framework (empty for non-continuous filters)."""
        fw = self.fw
        if fw is None or not getattr(fw, "continuous", False):
            return {}
        return fw.serve_streams()

    def drain_serve_stream(self, stream_id: int,
                           timeout: float = 30.0) -> dict:
        """Serialize one live stream off the standing serve loop (its KV
        blocks + slot state become a host snapshot; the slot frees) —
        the :meth:`Pipeline.drain_stream` element hop."""
        with self._fw_lock:
            fw = self._ensure_fw()
        if not getattr(fw, "continuous", False):
            raise ElementError(
                f"{self.name}: not a continuous-serving filter")
        return fw.drain_stream(stream_id, timeout)

    def adopt_serve_stream(self, snapshot: dict,
                           timeout: float = 30.0) -> int:
        """Re-admit a drained stream into THIS element's serve loop;
        remaining tokens flow downstream exactly like locally admitted
        streams (same async-emit path, the serve meta wins)."""
        import functools as _ft

        with self._fw_lock:
            fw = self._ensure_fw()
        if not getattr(fw, "continuous", False):
            raise ElementError(
                f"{self.name}: not a continuous-serving filter")
        fw._trace_rec = getattr(self, "_trace_rec", None)
        prompt = snapshot.get("prompt")
        src_buf = Buffer([np.asarray(prompt, np.int32) if prompt
                          is not None else np.zeros((1, 0), np.int32)])
        return fw.adopt_stream(
            snapshot, _ft.partial(self._emit_serve_token, src_buf),
            timeout)

    def finalize(self):
        fw = self.fw
        if fw is not None and getattr(fw, "continuous", False):
            # EOS reached the element: every admitted stream must finish
            # (and emit its stream_last) before EOS propagates downstream.
            if not fw.drain(timeout=600):
                raise ElementError(
                    f"{self.name}: continuous serve loop failed to drain")
        return []

    # -- fusion ------------------------------------------------------------
    def device_fn(self, in_spec: TensorsSpec):
        fw = self._ensure_fw()
        fn = fw.pure_fn()
        if fn is None or self.invoke_dynamic:
            return None
        out_spec = self._out_spec
        if out_spec is None:
            _, out_spec = fw.get_model_info()
        if out_spec is None:
            return None
        if self.input_combination is None and self.output_combination is None:
            return fn, out_spec
        # Combinations fuse too: select/compose around the model fn.
        combined = self._combined_out_spec(out_spec)
        if combined is None:
            return None  # statically unknown output: host path handles it

        combo_in, combo_out = self.input_combination, self.output_combination

        def wrapped(arrays):
            model_in = (tuple(arrays[i] for i in combo_in)
                        if combo_in is not None else arrays)
            outs = fn(model_in)
            if combo_out is None:
                return outs
            return tuple(
                (arrays if tag == "i" else outs)[i] for tag, i in combo_out)

        return wrapped, combined

    # -- abstract execution (nns-lint --deep) -------------------------------
    def abstract_invoke(self, in_spec: TensorsSpec):
        """Symbolic trace for the deep analyzer: the model core goes through
        the FRAMEWORK's abstract_invoke (which abstracts params too — a
        checkpoint's weights never materialize for this), and the
        input/output-combination plumbing is applied to the ShapeDtypeStruct
        lists on host, mirroring the wrapped device_fn exactly."""
        fw = self._ensure_fw()
        if self.invoke_dynamic or getattr(fw, "streaming", False) \
                or getattr(fw, "continuous", False):
            return None  # per-buffer/async shapes: nothing static to check
        import jax

        sds = [jax.ShapeDtypeStruct(s.shape, s.dtype) for s in in_spec]
        model_in = ([sds[i] for i in self.input_combination]
                    if self.input_combination is not None else sds)
        model_out = fw.abstract_invoke(model_in)
        if model_out is None:
            return None
        if self.output_combination is None:
            outs = list(model_out)
        else:
            outs = [(sds if tag == "i" else list(model_out))[i]
                    for tag, i in self.output_combination]
        out_spec = self._out_spec
        if out_spec is None:
            _, out_spec = fw.get_model_info()
        declared = (self._combined_out_spec(out_spec)
                    if out_spec is not None else None)
        return outs, declared

    def param_bytes(self) -> int:
        try:
            return int(self._ensure_fw().param_bytes())
        except Exception:  # noqa: BLE001 - accounting probe only
            return 0

    # -- model reload (reference: tensor_filter_common.c ReloadModel) ------
    def reload_model(self, model: Optional[object] = None) -> None:
        """Swap the model without rebuilding the pipeline.

        Builds a fresh framework instance from the element's props (with
        ``model`` overridden when given), verifies the new model's I/O spec
        still matches what was negotiated, then atomically swaps it in —
        in-flight ``process`` calls finish on the old instance.  NOTE: a
        filter already compiled into a FUSED stage keeps running the old
        jitted program (XLA traced it at plan time); reload applies to the
        element's own invoke path, matching the reference's per-element
        semantics.
        """
        props = dict(self.props)
        if model is not None:
            props["model"] = model
        new_fw = _load_framework(
            props, mesh_provider=getattr(self, "_mesh_provider", None))
        new_in, new_out = new_fw.get_model_info()
        for have, new, what in ((self._in_spec, new_in, "input"),
                                (self._out_spec, new_out, "output")):
            if have is not None and new is not None and not have.is_flexible:
                if len(have) != len(new) or not all(
                    a.is_compatible(b) for a, b in zip(have, new)
                ):
                    new_fw.close()
                    raise ElementError(
                        f"{self.name}: reload {what} spec {new} does not "
                        f"match negotiated {have}")
        if new_in is not None:
            new_fw.set_input_spec(self._in_spec or new_in)
        with self._fw_lock:
            # The lock also guards in-flight invokes (process holds it for
            # the whole call), so closing old here cannot race one.
            old, self.fw = self.fw, new_fw
            if old is not None and not getattr(old, "streaming", False):
                old.close()
            # Streaming frameworks may have a live generator still decoding
            # on the old instance: drop the reference and let GC release
            # its device buffers when the stream finishes.
        if model is not None:
            self.props["model"] = model
        log.info("%s: model reloaded (%s)", self.name, props.get("model"))

    # -- introspection (reference: latency/throughput read-only props) -----
    @property
    def latency(self) -> Optional[float]:
        """Moving-average seconds per invoke."""
        return self._lat_ema

    @property
    def throughput(self) -> Optional[float]:
        return (1.0 / self._lat_ema) if self._lat_ema else None


class SingleShot:
    """Invoke a filter without a pipeline.

    Reference analog: ``gsttensor_filter_single.c`` — the basis of the
    external ML C-API's ``ml_single_open``/``ml_single_invoke`` (SURVEY §3.5).

    >>> s = SingleShot(framework="jax", model="mobilenet_v1")
    >>> out = s.invoke(np.zeros((1, 224, 224, 3), np.float32))
    """

    def __init__(self, framework: str = "auto", model: object = "", **props):
        p = dict(props)
        p["framework"] = framework
        p["model"] = model
        self.fw = _load_framework(p)
        self.in_spec, self.out_spec = self.fw.get_model_info()

    def invoke(self, *arrays) -> List[np.ndarray]:
        if len(arrays) == 1 and isinstance(arrays[0], (list, tuple)):
            arrays = tuple(arrays[0])
        outs = self.fw.invoke(list(arrays))
        return [np.asarray(o) for o in outs]

    def close(self) -> None:
        self.fw.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
