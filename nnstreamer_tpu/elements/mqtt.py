"""mqttsrc/mqttsink: broker-routed pub/sub elements.

Reference analog (SURVEY §2.7): ``gst/mqtt/mqttsrc.c``/``mqttsink.c`` —
publish/subscribe GstBuffers through a paho-mqtt broker, with NTP-based
timestamp sync across hosts (``ntputil.c``).  The TPU build talks to the
in-repo :class:`~nnstreamer_tpu.utils.broker.MqttLiteBroker` (same
topology; QoS 0; retained messages) and carries wall-clock epoch in buffer
meta for cross-host pts rebasing (the ntputil analog — hosts here share a
clock, so the offset is measured, not NTP-queried).

Props (both): ``host``, ``port`` (broker address), ``topic``
(``pub-topic``/``sub-topic`` aliases match the reference).
``mqttsink debug-epoch=true`` stamps ``epoch_ns``; ``mqttsrc
sync=rebase`` rewrites pts to the local monotonic timeline using it.
"""

from __future__ import annotations

import socket
import time
from typing import Iterator, Optional, Union

from ..core.buffer import Buffer, Event, now_ns
from ..core.caps import Caps
from ..core.log import logger, metrics
from ..core.registry import register_element
from ..utils import wire
from .base import ElementError, SinkElement, SourceElement

log = logger(__name__)


class BrokerRejected(ElementError):
    """Deterministic broker nack (version/topic): never retried."""


def _connect(host: str, port: int, role: str, topic: str,
             timeout: float) -> socket.socket:
    from ..utils.net import client_handshake

    deadline = time.monotonic() + timeout
    last: Optional[Exception] = None
    while time.monotonic() < deadline:
        try:
            conn = socket.create_connection((host, port), timeout=2.0)
            conn.settimeout(2.0)
            # Shared handshake: carries PROTOCOL_VERSION so frame-layout
            # mismatches are rejected at connect, not mid-stream.
            client_handshake(conn, role, topic=topic)
            conn.settimeout(0.2)
            return conn
        except (ConnectionRefusedError, ConnectionResetError, TimeoutError) as e:
            # Broker not up yet / mid-restart: transient, keep retrying.
            last = e
            time.sleep(0.05)
        except ConnectionError as e:
            # An explicit nack (version/topic rejection) is deterministic —
            # retrying would hammer the broker and bury the reason.
            raise BrokerRejected(
                f"broker {host}:{port} rejected {role}: {e}") from e
        except (OSError, ValueError) as e:
            last = e
            time.sleep(0.05)
    raise ElementError(f"cannot reach broker {host}:{port}: {last}")


@register_element("mqttsink")
class MqttSink(SinkElement):
    kind = "mqttsink"

    def __init__(self, props=None, name=None):
        super().__init__(props, name)
        self.host = str(self.props.get("host", "127.0.0.1"))
        self.port = int(self.props.get("port", 1883))
        self.topic = str(self.props.get("pub_topic", self.props.get("topic", "")))
        self.debug_epoch = bool(self.props.get("debug_epoch", True))
        self.connect_timeout = float(self.props.get("connect_timeout", 10.0))
        self._conn: Optional[socket.socket] = None

    def start(self) -> None:
        self._conn = _connect(self.host, self.port, "pub", self.topic,
                              self.connect_timeout)

    def stop(self) -> None:
        if self._conn is not None:
            try:
                self._conn.close()
            finally:
                self._conn = None

    def process(self, pad, buf: Buffer):
        buf = buf.resolve().to_host()
        buf.meta.setdefault("topic", self.topic)
        if self.debug_epoch:
            buf.meta["epoch_ns"] = time.time_ns()
            buf.meta["mono_ns"] = now_ns()
        try:
            wire.write_frame(self._conn, wire.encode_buffer(buf))
            metrics.count(f"{self.name}.published")
        except OSError as e:
            # MQTT QoS 0: publishing into a dead broker drops, not errors.
            metrics.count(f"{self.name}.dropped")
            log.warning("%s: publish failed: %s", self.name, e)
        return []


@register_element("mqttsrc")
class MqttSrc(SourceElement):
    kind = "mqttsrc"

    def __init__(self, props=None, name=None):
        super().__init__(props, name)
        self.host = str(self.props.get("host", "127.0.0.1"))
        self.port = int(self.props.get("port", 1883))
        self.topic = str(self.props.get("sub_topic", self.props.get("topic", "#")))
        self.num_buffers = int(self.props.get("num_buffers", -1))
        self.sync = str(self.props.get("sync", "none"))  # none | rebase
        self.connect_timeout = float(self.props.get("connect_timeout", 10.0))
        # Reference: nnstreamer-edge reconnects MQTT-hybrid subscribers on
        # broker loss (SURVEY §5.3).  Opt-in: with reconnect=false (default)
        # a closed broker ends the stream immediately (EOS) — no stall.
        self.reconnect = bool(self.props.get("reconnect", False))
        self._conn: Optional[socket.socket] = None

    def configure(self, in_caps, out_pads):
        self.out_caps = {p: Caps.any() for p in out_pads}
        return self.out_caps

    def start(self) -> None:
        self._conn = _connect(self.host, self.port, "sub", self.topic,
                              self.connect_timeout)

    def stop(self) -> None:
        if self._conn is not None:
            try:
                self._conn.close()
            finally:
                self._conn = None

    def _reconnect(self, stop) -> bool:
        metrics.count(f"{self.name}.reconnects")
        if self._conn is not None:
            try:
                self._conn.close()
            except OSError:
                pass
            self._conn = None
        deadline = time.monotonic() + self.connect_timeout
        while time.monotonic() < deadline:
            if stop is not None and stop.is_set():
                return False
            try:
                self._conn = _connect(self.host, self.port, "sub", self.topic, 1.0)
                return True
            except BrokerRejected:
                raise  # deterministic rejection: surface, don't hammer
            except ElementError:
                time.sleep(0.2)
        log.warning("%s: broker did not come back within %.1fs",
                    self.name, self.connect_timeout)
        return False

    def generate(self) -> Iterator[Union[Buffer, Event]]:
        n = 0
        stop = getattr(self, "_stop_event", None)
        while self.num_buffers < 0 or n < self.num_buffers:
            if stop is not None and stop.is_set():
                return
            try:
                frame = wire.read_frame(self._conn)
            except socket.timeout:
                continue
            except (OSError, ValueError) as e:
                log.warning("%s: broker connection lost: %s", self.name, e)
                if self.reconnect and self._reconnect(stop):
                    continue
                return
            if frame is None:  # broker closed the stream
                if self.reconnect and self._reconnect(stop):
                    continue
                return
            buf, _flags = wire.decode_buffer(frame)
            if self.sync == "rebase" and "mono_ns" in buf.meta:
                # ntputil analog: rebase the publisher's monotonic pts onto
                # our timeline using the wall-clock epoch it stamped.
                remote_wall = int(buf.meta.get("epoch_ns", 0))
                offset = time.time_ns() - remote_wall  # transit + clock skew
                buf.pts = (buf.pts or 0) + offset
                buf.meta["transit_ns"] = offset
            metrics.count(f"{self.name}.frames")
            n += 1
            yield buf
