"""Element base classes.

Reference analog: GstElement/GstBaseTransform and the per-element chain
functions (``gst/nnstreamer/elements/gsttensor_*.c``, upstream-reconstructed —
SURVEY §2.2).  The TPU redesign splits an element into:

* **negotiation** — :meth:`Element.configure` maps input :class:`Caps` to
  output Caps once, before streaming starts (GStreamer caps negotiation);
* **streaming** — :meth:`Element.process` handles one buffer push
  (the 🔥 chain function);
* **device stage** — optionally, :meth:`Element.device_fn` exposes the
  element's math as a pure ``arrays -> arrays`` JAX function so the planner
  can fuse adjacent elements into ONE jitted XLA program (the capability the
  reference cannot have; north-star "fused XLA preprocess stages").

Elements that expose ``device_fn`` still implement ``process`` (used in
unfused/host mode and by unit tests); ``process`` must produce bit-identical
results to the fused path.
"""

from __future__ import annotations

from typing import Callable, Dict, Iterator, List, Optional, Tuple, Union

from ..core.buffer import Buffer, Event
from ..core.caps import Caps
from ..core.types import TensorsSpec

#: (out_pad, payload) pairs returned from process/finalize.
Out = List[Tuple[str, Union[Buffer, Event]]]

SRC = "src"
SINK = "sink"


class ElementError(RuntimeError):
    pass


class _TrackedProps(dict):
    """Property dict recording which keys the element consulted.

    Lets the pipeline reject unknown (typo'd) properties at startup the
    way ``gst_parse_launch`` errors on "no property 'foo' in element" —
    without requiring every element to declare a schema: any key the
    element never read by the time the pipeline is up is unknown.
    """

    def __init__(self, *a, **k):
        super().__init__(*a, **k)
        self.accessed = set()

    def get(self, key, default=None):
        self.accessed.add(key)
        return super().get(key, default)

    def __getitem__(self, key):
        self.accessed.add(key)
        return super().__getitem__(key)

    def __contains__(self, key) -> bool:
        self.accessed.add(key)
        return super().__contains__(key)

    def pop(self, key, *a):
        self.accessed.add(key)
        return super().pop(key, *a)

    def setdefault(self, key, default=None):
        self.accessed.add(key)
        return super().setdefault(key, default)

    # Enumerating the dict counts as consuming every key: sub-plugins that
    # forward props wholesale (e.g. the trainer's zoo-model opts via
    # ``props.items()``) understand the full set by construction.
    def _touch_all(self):
        self.accessed.update(super().keys())

    def items(self):
        self._touch_all()
        return super().items()

    def keys(self):
        self._touch_all()
        return super().keys()

    def values(self):
        self._touch_all()
        return super().values()

    def __iter__(self):
        self._touch_all()
        return super().__iter__()

    def copy(self):
        self._touch_all()
        return dict(self)


class Element:
    """Base streaming element."""

    #: registered kind name, set by subclass
    kind: str = "element"
    #: multi-input collation policy: "all" waits for a buffer on every
    #: connected sink pad (mux/merge slowest-sync), "any" processes buffers
    #: as they arrive (join / single-input elements).
    sync_policy: str = "any"
    #: static pad templates for offline analysis (``nnstreamer_tpu.analysis``):
    #: pad name -> Caps template (or a tuple of alternative Caps, mirroring
    #: GstCaps' list-of-structures) describing what the pad can accept or
    #: produce BEFORE negotiation.  ``sink_%u`` / ``src_%u`` entries match
    #: numbered request pads; a missing entry means ANY.  Class-level only —
    #: the analyzer consults it without instantiating the element.
    PAD_TEMPLATES: Dict[str, object] = {}

    @classmethod
    def pad_template(cls, pad: str):
        """Resolve the template for ``pad``: exact name, then the ``%u``
        request-pad pattern (``sink_3`` -> ``sink_%u``), then the default
        always-pad (``sink``/``src``), then ANY."""
        t = cls.PAD_TEMPLATES.get(pad)
        if t is not None:
            return t
        base, sep, idx = pad.rpartition("_")
        if sep and idx.isdigit():
            t = cls.PAD_TEMPLATES.get(f"{base}_%u")
            if t is None:
                t = cls.PAD_TEMPLATES.get(base)
            if t is not None:
                return t
        return Caps.any()

    def __init__(self, props: Optional[Dict[str, object]] = None, name: Optional[str] = None):
        self.props: Dict[str, object] = _TrackedProps(props or {})
        self.name = name or self.kind
        self.in_caps: Dict[str, Caps] = {}
        self.out_caps: Dict[str, Caps] = {}

    def unknown_props(self) -> set:
        """Property keys never consulted by the element (typos).  Checked
        by the pipeline after startup, once every lazy reader has run."""
        p = self.props
        if not isinstance(p, _TrackedProps):
            return set()
        # raw dict.keys: enumerating through the tracked interface would
        # itself mark every key accessed
        return set(dict.keys(p)) - p.accessed

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        """NULL->READY: open resources (reference: element start vmethod)."""

    def stop(self) -> None:
        """READY->NULL: release resources."""

    # -- negotiation -------------------------------------------------------
    def configure(self, in_caps: Dict[str, Caps], out_pads: List[str]) -> Dict[str, Caps]:
        """Map input caps to output caps for each connected out pad.

        Default: passthrough of the (single) input caps to every out pad.
        """
        self.in_caps = dict(in_caps)
        src = next(iter(in_caps.values()), Caps.any())
        caps = {p: src for p in out_pads}
        self.out_caps = caps
        return caps

    # -- streaming ---------------------------------------------------------
    def process(self, pad: str, buf: Buffer) -> Out:
        """Handle one input buffer; return downstream pushes."""
        raise NotImplementedError

    def process_batch(self, pad: str, bufs: List[Buffer]) -> Out:
        """Handle a micro-batch drained from this stage's queue in one call.

        Output order must equal input order.  Default: loop ``process`` —
        host elements keep exact single-buffer semantics; device stages
        (FusedElement, tensor_filter with a pure JAX fn) override with one
        bucketed XLA dispatch.  Only called when the stage was planned
        batchable (see :meth:`batch_capable`) AND the pipeline runs with
        ``batch_max > 1``."""
        outs: Out = []
        for buf in bufs:
            outs.extend(self.process(pad, buf))
        return outs

    def batch_capable(self) -> bool:
        """True when this element benefits from micro-batching (overridden
        by device stages); the planner only marks such stages batchable."""
        return False

    def place_params(self, mesh) -> bool:
        """Place this element's device-resident parameters onto ``mesh``
        per its model's ``param_pspecs``: leaves whose PartitionSpec names
        the ``model`` axis SHARD over it (tensor parallelism — per-chip
        weight HBM drops by the axis size), everything else replicates —
        so sharded micro-batch dispatches never re-broadcast weights per
        call.  Called at most ONCE per stage, from the stage thread,
        before the first sharded dispatch.  Returns True when anything
        was moved.  Default: no parameters (closure constants are baked
        into the compiled program and placed by XLA at compile time).

        Overriders implement THIS hook; :meth:`replicate_params` is the
        pre-2-D name kept as an alias for callers."""
        return False

    def replicate_params(self, mesh) -> bool:
        """Deprecated alias of :meth:`place_params` (the dp-only era name:
        with a 1-wide ``model`` axis, placement IS replication)."""
        return self.place_params(mesh)

    def process_group(self, bufs: Dict[str, Buffer]) -> Out:
        """Handle one collated buffer-per-pad group (sync_policy == "all")."""
        raise NotImplementedError

    def on_event(self, pad: str, event: Event) -> Out:
        """Non-EOS in-band events; default forwards to all out pads."""
        return [(SRC, event)]

    def finalize(self) -> Out:
        """All input pads reached EOS: flush buffered state (before EOS is
        forwarded downstream)."""
        return []

    # -- fusion ------------------------------------------------------------
    def device_fn(
        self, in_spec: TensorsSpec
    ) -> Optional[Tuple[Callable, TensorsSpec]]:
        """Return (pure_fn, out_spec) when this element's streaming math can
        run inside a jitted XLA program.  ``pure_fn`` takes and returns a
        tuple of jax arrays (one per tensor).  None => host-only element."""
        return None

    # -- abstract execution (nns-lint --deep) -------------------------------
    def abstract_invoke(
        self, in_spec: TensorsSpec
    ) -> Optional[Tuple[List, Optional[TensorsSpec]]]:
        """Execute this element's device path SYMBOLICALLY against
        ``in_spec``: trace :meth:`device_fn`'s closure with
        ``jax.ShapeDtypeStruct`` inputs via :func:`jax.eval_shape` — zero
        device dispatch, no buffer ever materializes.  Returns ``(traced
        output ShapeDtypeStructs, declared out spec)`` so the deep analyzer
        (``analysis/tracecheck.py``) can diff what the trace actually
        produces against what negotiation promised downstream.  None when
        the element has no device path for this spec.  Tracing errors
        (ConcretizationTypeError from data-dependent shapes, dtype
        surprises) propagate — the analyzer turns them into diagnostics."""
        df = self.device_fn(in_spec)
        if df is None:
            return None
        fn, declared = df
        import jax

        sds = tuple(jax.ShapeDtypeStruct(s.shape, s.dtype) for s in in_spec)
        out = jax.eval_shape(lambda xs: fn(xs), sds)
        if not isinstance(out, (tuple, list)):
            out = (out,)
        return list(out), declared

    def param_bytes(self) -> int:
        """Bytes of device-resident parameters this element keeps for the
        pipeline's lifetime (model weights); feeds the deep analyzer's
        static HBM high-water estimate.  Default: none."""
        return 0

    def get_property(self, key: str, default=None):
        return self.props.get(key, default)

    def __repr__(self):  # pragma: no cover
        return f"<{type(self).__name__} {self.name!r}>"


class SourceElement(Element):
    """Element with no input pads; drives the pipeline.

    Reference analog: GstBaseSrc (v4l2src/appsrc/videotestsrc...).
    """

    is_source = True

    def generate(self) -> Iterator[Union[Buffer, Event]]:
        """Yield buffers; return to signal EOS."""
        raise NotImplementedError


class SinkElement(Element):
    """Terminal element (reference: GstBaseSink / tensor_sink)."""

    is_sink = True


class TransformElement(Element):
    """1-in/1-out convenience base (reference: GstBaseTransform)."""

    def transform(self, buf: Buffer) -> Buffer:
        raise NotImplementedError

    def process(self, pad: str, buf: Buffer) -> Out:
        return [(SRC, self.transform(buf))]
