"""nnstreamer_tpu.elements"""
