"""tensor_converter: media streams -> other/tensors.

Reference analog: ``gst/nnstreamer/elements/gsttensor_converter.c``
(upstream-reconstructed, SURVEY §2.2): video/x-raw, audio/x-raw, text,
octet-stream (and serialized formats via converter sub-plugins, see
converters/serialize.py) become tensor buffers.  Replicated behaviors:

* video dims ``C:W:H:N`` (innermost-first) => numpy/JAX shape ``(N,H,W,C)``
  — NHWC, the TPU-friendly layout, falls straight out of nnstreamer's own
  dim order;
* row-stride removal: raw video rows padded to 4-byte boundaries are
  repacked densely (reference does the same memcpy dance);
* ``frames-per-tensor``: batch N media frames into one tensor buffer;
* text/octet reshaped per ``input-dim``/``input-type`` props;
* ``other/tensors`` passthrough, flexible -> static when spec is known.

Custom converter sub-plugins (flatbuf/protobuf analogs) are looked up in the
converter registry by ``mode=<name>`` (reference: converter sub-plugins).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ..core.buffer import Buffer
from ..core.caps import Caps, MediaType, audio_dtype, video_bpp
from ..core.registry import KIND_CONVERTER, lookup, register_element
from ..core.types import TensorFormat, TensorSpec, TensorsSpec, dtype_from_name, parse_fraction
from .base import Element, ElementError, SRC


@register_element("tensor_converter")
class TensorConverter(Element):
    kind = "tensor_converter"

    def __init__(self, props=None, name=None):
        super().__init__(props, name)
        self.frames_per_tensor = int(self.props.get("frames_per_tensor", 1))
        self.input_dim = self.props.get("input_dim")
        self.input_type = str(self.props.get("input_type", "uint8"))
        self.mode = self.props.get("mode")  # custom converter sub-plugin
        self._sub = None
        self._media: Optional[MediaType] = None
        self._spec: Optional[TensorsSpec] = None
        self._pending: List[np.ndarray] = []

    # -- negotiation -------------------------------------------------------
    def configure(self, in_caps: Dict[str, Caps], out_pads):
        self.in_caps = dict(in_caps)
        src = next(iter(in_caps.values()), Caps.any())
        self._media = src.media if not src.is_any() else None
        spec: Optional[TensorsSpec] = None

        if self.mode:
            cls = lookup(KIND_CONVERTER, str(self.mode))
            if cls is None:
                raise ElementError(f"unknown converter sub-plugin {self.mode!r}")
            self._sub = cls(self.props)
            spec = getattr(self._sub, "out_spec", None)
        elif self._media == MediaType.VIDEO:
            fmt = src.get("format", "RGB")
            w, h = src.get("width"), src.get("height")
            if isinstance(w, int) and isinstance(h, int) and isinstance(fmt, str):
                c = video_bpp(fmt)
                spec = TensorsSpec.single(
                    TensorSpec((c, w, h, self.frames_per_tensor), np.uint8),
                    rate=parse_fraction(src.get("framerate", (0, 1))),
                )
        elif self._media == MediaType.AUDIO:
            ch = src.get("channels")
            if isinstance(ch, int) and self.frames_per_tensor > 1:
                dt = dtype_from_name(audio_dtype(src.get("format", "S16LE")))
                spec = TensorsSpec.single(
                    TensorSpec((ch, self.frames_per_tensor), dt)
                )
        elif self._media in (MediaType.OCTET, MediaType.TEXT) or self._media is None:
            if self.input_dim:
                spec = TensorsSpec.from_string(str(self.input_dim), self.input_type)
        elif self._media in (MediaType.TENSORS, MediaType.FLEX_TENSORS):
            spec = src.spec

        self._spec = spec
        caps = Caps.tensors(spec)
        self.out_caps = {p: caps for p in out_pads}
        return self.out_caps

    # -- streaming ---------------------------------------------------------
    def process(self, pad, buf: Buffer):
        if self._sub is not None:
            return [(SRC, self._sub.convert(buf))]
        media = self._media
        if media in (MediaType.TENSORS, MediaType.FLEX_TENSORS, None):
            return [(SRC, buf)]
        if media == MediaType.VIDEO:
            return self._video(buf)
        if media == MediaType.AUDIO:
            return self._audio(buf)
        if media == MediaType.TEXT:
            return self._text(buf)
        if media == MediaType.OCTET:
            return self._octet(buf)
        raise ElementError(f"unsupported media {media}")

    def _video(self, buf: Buffer):
        src = next(iter(self.in_caps.values()))
        fmt = src.get("format", "RGB")
        c = video_bpp(fmt)
        w = src.get("width")
        h = src.get("height")
        frame = np.asarray(buf.tensors[0])
        if frame.ndim == 1:  # raw bytes: undo 4-byte row stride padding
            if w is None or h is None:
                raise ElementError("raw video bytes need width/height caps")
            stride = ((w * c + 3) // 4) * 4
            if frame.size == h * stride:
                from ..native import strip_stride

                frame = strip_stride(
                    frame, rows=h, row_bytes=w * c, src_stride=stride
                ).reshape(h, w, c)
            elif frame.size == h * w * c:
                frame = frame.reshape(h, w, c)
            else:
                raise ElementError(
                    f"video buffer size {frame.size} matches neither dense "
                    f"{h*w*c} nor strided {h*stride}"
                )
        if frame.ndim == 2:  # GRAY
            frame = frame[:, :, None]
        if self.frames_per_tensor == 1:
            return [(SRC, buf.with_tensors([frame[None]], spec=self._spec))]
        self._pending.append(frame)
        if len(self._pending) < self.frames_per_tensor:
            return []
        batch = np.stack(self._pending)
        self._pending = []
        return [(SRC, buf.with_tensors([batch], spec=self._spec))]

    def _audio(self, buf: Buffer):
        samples = np.asarray(buf.tensors[0])  # (S, C) interleaved
        if samples.ndim == 1:
            samples = samples[:, None]
        if self.frames_per_tensor <= 1:
            return [(SRC, buf.with_tensors([samples]))]
        self._pending.append(samples)
        total = sum(len(p) for p in self._pending)
        outs = []
        if total >= self.frames_per_tensor:
            cat = np.concatenate(self._pending)
            n = self.frames_per_tensor
            while len(cat) >= n:
                outs.append((SRC, buf.with_tensors([cat[:n]], spec=self._spec)))
                cat = cat[n:]
            self._pending = [cat] if len(cat) else []
        return outs

    def _text(self, buf: Buffer):
        raw = buf.tensors[0]
        if isinstance(raw, str):
            data = np.frombuffer(raw.encode("utf-8"), np.uint8)
        elif isinstance(raw, np.ndarray) and raw.dtype.kind in "US":
            data = np.frombuffer(str(raw).encode("utf-8"), np.uint8)
        else:
            data = np.asarray(raw, np.uint8).ravel()
        if self._spec is not None:
            size = self._spec[0].count
            out = np.zeros(size, np.uint8)
            out[: min(size, data.size)] = data[:size]
            data = out.reshape(self._spec[0].shape)
        return [(SRC, buf.with_tensors([data], spec=self._spec))]

    def _octet(self, buf: Buffer):
        data = np.asarray(buf.tensors[0])
        if self._spec is None:
            raise ElementError("octet-stream conversion needs input-dim/input-type")
        spec = self._spec[0]
        arr = data.ravel().view(spec.dtype)
        n = spec.count
        outs = []
        for off in range(0, arr.size - n + 1, n):
            chunk = arr[off : off + n].reshape(spec.shape)
            outs.append((SRC, buf.with_tensors([chunk], spec=self._spec)))
        return outs

    def finalize(self):
        if self._pending and self._media == MediaType.VIDEO:
            pass  # incomplete batch dropped, as the reference drops partials
        return []
