"""GStreamer-core compatibility elements: queue, videoconvert, videoscale.

Reference pipelines lean on GStreamer base elements the reference repo
does not implement but every example assumes (the stock object-detection
pipeline is ``v4l2src ! videoconvert ! videoscale ! ... ! tensor_filter``;
``queue`` appears wherever a stage boundary is wanted — SURVEY §1 "There
is no scheduler layer: scheduling IS GStreamer").  This module provides
the analogs so reference pipeline strings run as written:

* ``queue`` — in this runtime every element already runs on its own
  stage thread with a bounded feed queue, so ``queue`` is a passthrough
  marker; its GStreamer sizing properties are accepted for compatibility.
* ``videoconvert`` — channel-order/format conversion between the RGB
  family and GRAY8 on ``video/x-raw`` frames (``format=`` selects the
  target; default passthrough).
* ``videoscale`` — resize to ``width=``/``height=`` via nearest (default)
  or bilinear ``method=``.

These are host elements (media boundary, like the reference's); the
tensor path after ``tensor_converter`` is where device fusion begins.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..core.buffer import Buffer
from ..core.caps import Caps, MediaType
from ..core.registry import register_element
from .base import Element, ElementError, SRC


@register_element("queue")
class Queue(Element):
    """Stage-boundary marker (GStreamer ``queue``).

    Threading/buffering is inherent to this runtime (one thread + bounded
    queue per stage), so data passes straight through; the reference's
    sizing/leaky properties are accepted for pipeline-string
    compatibility.
    """

    kind = "queue"

    def __init__(self, props=None, name=None):
        super().__init__(props, name)
        # accepted for compatibility; the runtime's per-stage queues are
        # sized by Pipeline(queue_capacity=...)
        for p in ("max_size_buffers", "max_size_bytes", "max_size_time",
                  "leaky", "silent"):
            self.props.get(p)

    def configure(self, in_caps, out_pads):
        self.in_caps = dict(in_caps)
        src = next(iter(in_caps.values()), Caps.any())
        self.out_caps = {p: src for p in out_pads}
        return self.out_caps

    def process(self, pad, buf):
        return [(SRC, buf)]


#: channel index order of each RGB-family format (None = alpha/pad slot)
_CHANNEL_ORDER = {
    "RGB": (0, 1, 2), "BGR": (2, 1, 0),
    "RGBA": (0, 1, 2, None), "BGRA": (2, 1, 0, None),
    "ARGB": (None, 0, 1, 2), "ABGR": (None, 2, 1, 0),
    "RGBx": (0, 1, 2, None), "BGRx": (2, 1, 0, None),
}

#: 4-channel formats whose 4th slot is PADDING, not alpha: semantically
#: opaque (the compositor must not read the undefined pad byte as alpha)
_PADDED_FMTS = frozenset({"RGBx", "BGRx"})

#: ITU-R BT.601 luma weights (the GStreamer videoconvert default)
_LUMA = np.array([0.299, 0.587, 0.114], np.float32)

#: planar (I420) / semi-planar (NV12) YUV 4:2:0 — the camera-native
#: formats every upstream v4l2src example negotiates before videoconvert.
#: Frames are the flat GStreamer byte layout viewed as [H*3/2, W] uint8
#: (or any shape totalling H*W*3/2 bytes); conversion is BT.601 limited
#: range (Y 16-235, chroma biased at 128), like GStreamer's default.
_YUV_FMTS = frozenset({"I420", "NV12"})


def _yuv_frame_hw(frame: np.ndarray, caps: Optional[Caps]) -> tuple:
    """(height, width) of a YUV frame: caps fields when negotiated, else
    derived from the canonical [H*3/2, W] shape."""
    if caps is not None:
        w = caps.get("width")
        h = caps.get("height")
        if w and h:
            return int(h), int(w)
    if frame.ndim == 2 and (frame.shape[0] * 2) % 3 == 0:
        return frame.shape[0] * 2 // 3, frame.shape[1]
    raise ElementError(
        f"YUV frame of shape {frame.shape} needs width=/height= caps "
        "(cannot derive the plane split)")


def _split_yuv(frame: np.ndarray, h: int, w: int, fmt: str):
    flat = np.asarray(frame, np.uint8).ravel()
    need = h * w * 3 // 2
    if flat.size != need:
        raise ElementError(
            f"{fmt} frame has {flat.size} bytes, {h}x{w} needs {need}")
    if h % 2 or w % 2:
        raise ElementError(f"{fmt} needs even dimensions, got {h}x{w}")
    y = flat[:h * w].reshape(h, w)
    if fmt == "I420":
        q = h * w // 4
        u = flat[h * w:h * w + q].reshape(h // 2, w // 2)
        v = flat[h * w + q:].reshape(h // 2, w // 2)
    else:  # NV12: interleaved UV plane
        uv = flat[h * w:].reshape(h // 2, w // 2, 2)
        u, v = uv[..., 0], uv[..., 1]
    return y, u, v


def _yuv_to_rgb(frame: np.ndarray, h: int, w: int, fmt: str) -> np.ndarray:
    """[flat YUV420] -> [H, W, 3] RGB uint8 (BT.601 limited range)."""
    y, u, v = _split_yuv(frame, h, w, fmt)
    yy = 1.164 * (y.astype(np.float32) - 16.0)
    # chroma upsample: nearest 2x2 (GStreamer's fast path)
    uu = np.repeat(np.repeat(u, 2, 0), 2, 1).astype(np.float32) - 128.0
    vv = np.repeat(np.repeat(v, 2, 0), 2, 1).astype(np.float32) - 128.0
    r = yy + 1.596 * vv
    g = yy - 0.813 * vv - 0.391 * uu
    b = yy + 2.018 * uu
    rgb = np.stack([r, g, b], axis=-1)
    return np.clip(np.round(rgb), 0, 255).astype(np.uint8)


def _rgb_to_yuv(rgb: np.ndarray, fmt: str) -> np.ndarray:
    """[H, W, 3] RGB uint8 -> [H*3/2, W] flat YUV420 (BT.601 limited)."""
    h, w = rgb.shape[:2]
    if h % 2 or w % 2:
        raise ElementError(f"{fmt} needs even dimensions, got {h}x{w}")
    f = rgb.astype(np.float32)
    r, g, b = f[..., 0], f[..., 1], f[..., 2]
    y = 16.0 + 0.257 * r + 0.504 * g + 0.098 * b
    uf = 128.0 - 0.148 * r - 0.291 * g + 0.439 * b
    vf = 128.0 + 0.439 * r - 0.368 * g - 0.071 * b
    # chroma subsample: 2x2 box average
    u = uf.reshape(h // 2, 2, w // 2, 2).mean(axis=(1, 3))
    v = vf.reshape(h // 2, 2, w // 2, 2).mean(axis=(1, 3))
    if fmt == "I420":
        flat = np.concatenate([y.ravel(), u.ravel(), v.ravel()])
    else:  # NV12
        flat = np.concatenate([y.ravel(), np.stack([u, v], -1).ravel()])
    return np.clip(np.round(flat), 0, 255).astype(np.uint8).reshape(
        h * 3 // 2, w)


def _to_rgba(frame: np.ndarray, fmt: str) -> np.ndarray:
    """[H, W, C] in ``fmt`` -> [H, W, 4] RGBA (alpha preserved; opaque for
    alpha-less formats).  ``_CHANNEL_ORDER[fmt][i]`` names which RGB
    component lives in the format's channel ``i`` (None = the alpha/pad
    slot)."""
    if fmt == "GRAY8":
        rgba = np.repeat(frame[..., :1], 4, axis=-1)
        rgba[..., 3] = 255
        return rgba
    order = _CHANNEL_ORDER[fmt]
    rgba = np.full(frame.shape[:2] + (4,), 255, frame.dtype)
    for i, tgt in enumerate(order):
        rgba[..., 3 if tgt is None else tgt] = frame[..., i]
    if fmt in _PADDED_FMTS:  # x slot is padding, not alpha: opaque
        rgba[..., 3] = 255
    return rgba


def _from_rgba(rgba: np.ndarray, fmt: str) -> np.ndarray:
    """[H, W, 4] RGBA -> [H, W, C] in ``fmt`` (alpha carried into alpha
    slots; dropped for alpha-less formats, as GStreamer videoconvert does)."""
    if fmt == "GRAY8":
        y = (rgba[..., :3].astype(np.float32) @ _LUMA).round()
        return np.clip(y, 0, 255).astype(np.uint8)[..., None]
    order = _CHANNEL_ORDER[fmt]
    out = np.empty(rgba.shape[:2] + (len(order),), rgba.dtype)
    for i, tgt in enumerate(order):
        out[..., i] = rgba[..., 3 if tgt is None else tgt]
    return out


def _infer_fmt(caps: Caps, frame: np.ndarray) -> str:
    """Negotiated ``format`` field, else infer from channel count."""
    fmt = caps.get("format") if caps is not None else None
    if not fmt:
        c = 1 if frame.ndim == 2 else frame.shape[-1]
        fmt = {1: "GRAY8", 3: "RGB", 4: "RGBA"}.get(c, "RGB")
    fmt = str(fmt)
    if fmt not in _CHANNEL_ORDER and fmt != "GRAY8" and fmt not in _YUV_FMTS:
        raise ElementError(
            f"compositor: unsupported frame format {fmt!r} "
            "(8-bit RGB family / GRAY8 / I420 / NV12)")
    return fmt


@register_element("compositor")
class Compositor(Element):
    """Alpha-blend overlay streams onto a base video stream.

    Reference usage: the stock detection/pose examples composite the
    ``tensor_decoder`` RGBA overlay onto the camera frames.  ``sink_0``
    is the base frame; every other sink pad is an overlay blended in
    numeric pad order with per-pixel source-over alpha, scaled by the
    GStreamer per-pad property ``sink_N::alpha=<0..1>`` when given.
    Frames are converted through RGBA using each pad's NEGOTIATED format
    (channel-count inference when caps carry no format field), so BGR
    bases and ARGB overlays blend correctly; output format follows the
    base frame.  Sync is slowest-pad, matching the mux machinery.
    """

    kind = "compositor"
    sync_policy = "all"
    PAD_TEMPLATES = {"sink_%u": Caps.new(MediaType.VIDEO)}

    def __init__(self, props=None, name=None):
        super().__init__(props, name)
        self.props.get("background")  # accepted for compatibility
        self._pad_alpha = {}

    def configure(self, in_caps, out_pads):
        self.in_caps = dict(in_caps)
        for pad in in_caps:  # read per-pad alphas while props are checked
            self._pad_alpha[pad] = float(
                self.props.get(f"{pad}::alpha", 1.0))
        base = in_caps.get("sink_0") or next(iter(in_caps.values()), Caps.any())
        self.out_caps = {p: base for p in out_pads}
        return self.out_caps

    def process(self, pad, buf):
        # Single-input compositor is legal in GStreamer: passthrough (the
        # runtime only collates groups when >1 sink pad is linked).
        return [(SRC, buf)]

    def process_group(self, bufs):
        from .routing import _pad_index

        pads = sorted(bufs, key=_pad_index)  # numeric: sink_10 > sink_2
        base_buf = bufs[pads[0]]
        base = np.asarray(base_buf.tensors[0])
        base_fmt = _infer_fmt(self.in_caps.get(pads[0]), base)
        squeeze = False
        if base_fmt in _YUV_FMTS:  # camera-native base: blend in RGB space
            h, w = _yuv_frame_hw(base, self.in_caps.get(pads[0]))
            base = _yuv_to_rgb(base, h, w, base_fmt)
            out = _to_rgba(base, "RGB").astype(np.float32)
        else:
            squeeze = base.ndim == 2
            if squeeze:
                base = base[..., None]
            out = _to_rgba(base, base_fmt).astype(np.float32)
        a0 = self._pad_alpha.get(pads[0], 1.0)
        if a0 != 1.0:  # GStreamer fades the base toward the background
            out[..., :3] *= a0
        meta = dict(base_buf.meta)
        for pad in pads[1:]:
            ov_buf = bufs[pad]
            meta.update(ov_buf.meta)
            ov = np.asarray(ov_buf.tensors[0])
            ov_fmt = _infer_fmt(self.in_caps.get(pad), ov)
            if ov_fmt in _YUV_FMTS:
                oh, ow = _yuv_frame_hw(ov, self.in_caps.get(pad))
                ov = _yuv_to_rgb(ov, oh, ow, ov_fmt)
                ov_fmt = "RGB"
            if ov.ndim == 2:
                ov = ov[..., None]
            if ov.shape[:2] != base.shape[:2]:
                raise ElementError(
                    f"{self.name}: overlay {ov.shape[:2]} != base "
                    f"{base.shape[:2]} (use videoscale)")
            rgba = _to_rgba(ov, ov_fmt).astype(np.float32)
            a = (rgba[..., 3:4] / 255.0) * self._pad_alpha.get(pad, 1.0)
            out[..., :3] = rgba[..., :3] * a + out[..., :3] * (1.0 - a)
        res = np.clip(np.round(out), 0, 255).astype(np.uint8)
        if base_fmt in _YUV_FMTS:  # output format follows the base frame
            res = _rgb_to_yuv(res[..., :3], base_fmt)
        else:
            res = _from_rgba(res, base_fmt)
        if squeeze:
            res = res[..., 0]
        new = base_buf.with_tensors([res], spec=None)
        new.meta.update(meta)
        pts = [b.pts for b in bufs.values() if b.pts is not None]
        new.pts = max(pts) if pts else None
        return [(SRC, new)]


@register_element("videoconvert")
class VideoConvert(Element):
    """Convert ``video/x-raw`` frames between the RGB family, GRAY8, and
    the camera-native YUV 4:2:0 formats (I420 / NV12, BT.601).

    ``format=`` names the output format; without it frames pass through
    (the reference negotiates; this runtime's negotiation is explicit).
    The stock upstream camera pipeline runs verbatim:
    ``v4l2src/appsrc (I420) ! videoconvert format=RGB ! tensor_converter``.
    """

    kind = "videoconvert"
    PAD_TEMPLATES = {"sink": Caps.new(MediaType.VIDEO)}

    def __init__(self, props=None, name=None):
        super().__init__(props, name)
        self.format = str(self.props.get("format", "") or "")
        known = set(_CHANNEL_ORDER) | {"GRAY8"} | _YUV_FMTS
        if self.format and self.format not in known:
            raise ElementError(
                f"{self.name}: unsupported format {self.format!r} "
                f"(one of {sorted(known)})")
        self._in_fmt: Optional[str] = None

    def configure(self, in_caps, out_pads):
        self.in_caps = dict(in_caps)
        src = next(iter(in_caps.values()), Caps.any())
        if src.media not in (MediaType.VIDEO, MediaType.ANY):
            raise ElementError(
                f"{self.name}: needs video/x-raw input, got {src.media}")
        fields = dict(src.dict)
        fields.pop("spec", None)
        self._in_fmt = str(fields.get("format", "RGB"))
        if self.format:
            fields["format"] = self.format
        caps = Caps.new(MediaType.VIDEO, **fields)
        self.out_caps = {p: caps for p in out_pads}
        return self.out_caps

    def process(self, pad, buf: Buffer):
        if not self.format or self.format == self._in_fmt:
            return [(SRC, buf)]
        frame = np.asarray(buf.tensors[0])
        in_fmt = self._in_fmt or "RGB"
        in_caps = next(iter(self.in_caps.values()), None)
        if in_fmt in _YUV_FMTS:
            h, w = _yuv_frame_hw(frame, in_caps)
            rgb = _yuv_to_rgb(frame, h, w, in_fmt)
            if self.format in _YUV_FMTS:
                out = _rgb_to_yuv(rgb, self.format)
            elif self.format == "RGB":
                out = rgb
            else:
                out = _from_rgba(_to_rgba(rgb, "RGB"), self.format)
            return [(SRC, buf.with_tensors([out], spec=None))]
        if frame.ndim == 2:  # GRAY8 without channel dim
            frame = frame[..., None]
        rgba = _to_rgba(frame, in_fmt)
        if self.format in _YUV_FMTS:
            out = _rgb_to_yuv(rgba[..., :3], self.format)
        else:
            out = _from_rgba(rgba, self.format)
        return [(SRC, buf.with_tensors([out], spec=None))]


@register_element("videoscale")
class VideoScale(Element):
    """Resize ``video/x-raw`` frames to ``width=`` x ``height=``.

    ``method=nearest`` (default, GStreamer's 0) or ``method=bilinear``.
    Without width/height props, frames pass through (the reference
    negotiates the size from downstream caps; set them explicitly here).
    """

    kind = "videoscale"
    PAD_TEMPLATES = {"sink": Caps.new(MediaType.VIDEO)}

    def __init__(self, props=None, name=None):
        super().__init__(props, name)
        self.width = int(self.props.get("width", 0))
        self.height = int(self.props.get("height", 0))
        self.method = str(self.props.get("method", "nearest")).lower()
        if self.method not in ("nearest", "bilinear", "0", "1"):
            raise ElementError(
                f"{self.name}: method must be nearest|bilinear")
        if self.method in ("0",):
            self.method = "nearest"
        if self.method in ("1",):
            self.method = "bilinear"

    def configure(self, in_caps, out_pads):
        self.in_caps = dict(in_caps)
        src = next(iter(in_caps.values()), Caps.any())
        if src.media not in (MediaType.VIDEO, MediaType.ANY):
            raise ElementError(
                f"{self.name}: needs video/x-raw input, got {src.media}")
        if str(src.get("format") or "") in _YUV_FMTS:
            raise ElementError(
                f"{self.name}: cannot scale subsampled YUV directly — "
                "insert 'videoconvert format=RGB' upstream")
        fields = dict(src.dict)
        fields.pop("spec", None)
        if self.width:
            fields["width"] = self.width
        if self.height:
            fields["height"] = self.height
        caps = Caps.new(MediaType.VIDEO, **fields)
        self.out_caps = {p: caps for p in out_pads}
        return self.out_caps

    def process(self, pad, buf: Buffer):
        if not (self.width or self.height):
            return [(SRC, buf)]
        frame = np.asarray(buf.tensors[0])
        chan_added = frame.ndim == 2
        if chan_added:  # 2-d gray frame: give it a channel dim for the math
            frame = frame[..., None]
        h, w = frame.shape[:2]
        oh = self.height or h
        ow = self.width or w
        if (oh, ow) == (h, w):
            return [(SRC, buf)]
        if self.method == "nearest":
            yi = (np.arange(oh) * (h / oh)).astype(int).clip(0, h - 1)
            xi = (np.arange(ow) * (w / ow)).astype(int).clip(0, w - 1)
            out = frame[yi[:, None], xi[None, :]]
        else:  # bilinear
            yf = (np.arange(oh) + 0.5) * (h / oh) - 0.5
            xf = (np.arange(ow) + 0.5) * (w / ow) - 0.5
            y0 = np.clip(np.floor(yf).astype(int), 0, h - 1)
            x0 = np.clip(np.floor(xf).astype(int), 0, w - 1)
            y1 = np.clip(y0 + 1, 0, h - 1)
            x1 = np.clip(x0 + 1, 0, w - 1)
            wy = np.clip(yf - y0, 0.0, 1.0)[:, None, None]
            wx = np.clip(xf - x0, 0.0, 1.0)[None, :, None]
            f = frame.astype(np.float32)
            top = f[y0[:, None], x0[None, :]] * (1 - wx) + \
                f[y0[:, None], x1[None, :]] * wx
            bot = f[y1[:, None], x0[None, :]] * (1 - wx) + \
                f[y1[:, None], x1[None, :]] * wx
            out = top * (1 - wy) + bot * wy
            if np.issubdtype(frame.dtype, np.integer):
                info = np.iinfo(frame.dtype)
                out = np.clip(np.round(out), info.min, info.max)
            out = out.astype(frame.dtype)
        if chan_added:
            out = out[..., 0]
        return [(SRC, buf.with_tensors([out], spec=None))]
