"""tensor_src_grpc / tensor_sink_grpc: tensor streams over gRPC.

Reference analog (SURVEY §2.7): ``ext/nnstreamer/extra/nnstreamer_grpc*.cc``
— tensor streams over gRPC in client or server mode with protobuf/flatbuf
payloads, as an alternative transport to nnstreamer-edge TCP.

The elements run a genuine gRPC bidi stream carrying wire-format frames
(no .proto compilation needed: gRPC's generic bytes methods).  Where
``grpcio`` is absent they fail construction with a clear pointer to the
equivalent in-repo transports (edgesrc/edgesink for pub/sub fan-out,
tensor_query_* for request/response) — the reference gates its gRPC
sub-plugin behind meson options the same way.
"""

from __future__ import annotations

from typing import Iterator

from ..core.buffer import Buffer
from ..core.caps import Caps
from ..core.log import logger, metrics
from ..core.registry import register_element
from ..utils import wire
from .base import ElementError, SinkElement, SourceElement

log = logger(__name__)

_SERVICE = "/nnstreamer_tpu.TensorStream/Stream"


def _require_grpc():
    try:
        import grpc

        return grpc
    except ImportError as e:
        raise ElementError(
            "grpcio is not installed in this environment; use edgesrc/"
            "edgesink (pub/sub) or tensor_query_client/serversrc "
            "(request/response) — same tensor wire format over TCP"
        ) from e


@register_element("tensor_sink_grpc")
class TensorSinkGrpc(SinkElement):
    """Stream buffers out over a gRPC bidi call (client side; the paired
    ``tensor_src_grpc`` is the server).  Props: ``host``, ``port``."""

    kind = "tensor_sink_grpc"

    def __init__(self, props=None, name=None):
        super().__init__(props, name)
        self.grpc = _require_grpc()
        if self.props.get("server"):
            raise ElementError(
                "tensor_sink_grpc is the stream's client side; run "
                "tensor_src_grpc as the server instead"
            )
        self.host = str(self.props.get("host", "127.0.0.1"))
        self.port = int(self.props.get("port", 55115))
        self._channel = None
        self._queue = None
        self._call = None

    def start(self) -> None:
        grpc = self.grpc
        import queue as _q

        self._queue = _q.SimpleQueue()
        self._channel = grpc.insecure_channel(f"{self.host}:{self.port}")
        send = self._channel.stream_stream(
            _SERVICE,
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b,
        )

        def frames():
            while True:
                item = self._queue.get()
                if item is None:
                    return
                yield item

        self._call = send(frames())

    def process(self, pad, buf: Buffer):
        if self._queue is None:
            raise ElementError(f"{self.name}: stream already finalized")
        self._queue.put(bytes(wire.encode_buffer(buf.resolve().to_host())))
        metrics.count(f"{self.name}.sent")
        return []

    def finalize(self):
        self._drain()
        return []

    def _drain(self) -> None:
        """End the request stream and wait for the RPC to finish so queued
        tail frames reach the server before the channel drops."""
        if self._queue is not None:
            self._queue.put(None)
            self._queue = None
        if self._call is not None:
            try:
                for _ in self._call:  # response stream ends when server done
                    pass
            except self.grpc.RpcError as e:
                log.warning("%s: stream ended with %s", self.name, e)
            self._call = None

    def stop(self) -> None:
        self._drain()
        if self._channel is not None:
            self._channel.close()
            self._channel = None


@register_element("tensor_src_grpc")
class TensorSrcGrpc(SourceElement):
    """Receive a tensor stream over gRPC.  Props: ``host``, ``port``,
    ``num-buffers``."""

    kind = "tensor_src_grpc"

    def __init__(self, props=None, name=None):
        super().__init__(props, name)
        self.grpc = _require_grpc()
        self.host = str(self.props.get("host", "0.0.0.0"))
        self.port = int(self.props.get("port", 55115))
        self.num_buffers = int(self.props.get("num_buffers", -1))
        self._server = None
        self._rx = None

    def configure(self, in_caps, out_pads):
        self.out_caps = {p: Caps.any() for p in out_pads}
        return self.out_caps

    def start(self) -> None:
        grpc = self.grpc
        import queue as _q
        from concurrent import futures

        self._rx = _q.SimpleQueue()
        rx = self._rx

        class Handler(grpc.GenericRpcHandler):
            def service(self, handler_call_details):
                if handler_call_details.method != _SERVICE:
                    return None

                def stream(request_iterator, context):
                    for frame in request_iterator:
                        rx.put(frame)
                    rx.put(None)
                    return iter(())

                return grpc.stream_stream_rpc_method_handler(
                    stream,
                    request_deserializer=lambda b: b,
                    response_serializer=lambda b: b,
                )

        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=4))
        self._server.add_generic_rpc_handlers((Handler(),))
        self._server.add_insecure_port(f"{self.host}:{self.port}")
        self._server.start()

    def stop(self) -> None:
        if self._server is not None:
            self._server.stop(grace=0.5)
            self._server = None

    def generate(self) -> Iterator[Buffer]:
        import queue as _q

        n = 0
        stop = getattr(self, "_stop_event", None)
        while self.num_buffers < 0 or n < self.num_buffers:
            try:
                frame = self._rx.get(timeout=0.2)
            except _q.Empty:
                if stop is not None and stop.is_set():
                    return
                continue
            if frame is None:
                return
            buf, _flags = wire.decode_buffer(frame)
            metrics.count(f"{self.name}.frames")
            n += 1
            yield buf
