"""tensor_aggregator: window/stride accumulation along a dim.

Reference analog: ``gsttensor_aggregator.c`` (SURVEY §2.2) — concatenate N
frames along an axis with flush control; the time-series/audio windowing
primitive (and the closest thing the reference has to sequence-dimension
machinery, §5.7).

Props (reference names):
* ``frames-in``    — frames contained in one incoming buffer (along the dim)
* ``frames-out``   — frames per outgoing buffer (window size)
* ``frames-flush`` — frames to drop after each output (stride; 0 => frames-out,
                     i.e. non-overlapping windows)
* ``frames-dim``   — nnstreamer dim index to count frames along
* ``concat``       — true (default): one concatenated tensor per window;
                     false: the window's frames stay separate tensors in one
                     buffer (the reference's multi-GstMemory buffer analog)

TPU-first extension — **device mode** (``device=true``, docs/ARCHITECTURE.md
"Streaming state"): the concat/window carry lives as an HBM-RESIDENT ring
between dispatches instead of a host ``np.concatenate``.  The host path
fetches every incoming buffer to host, concatenates, slices, and re-uploads
downstream — for a windowed audio pipeline that is one full D2H+H2D round
trip per window, and BENCH_ALL_r5's speech_commands row idles at 0.0026 MFU
largely on it.  In device mode the ring update runs IN-PROGRAM:

* the carry is a fixed-shape jax Array of ``need + step`` samples along the
  frames axis (``need`` = window, ``step`` = samples per incoming buffer);
* appends are ``lax.dynamic_update_slice`` at a TRACED write offset —
  offsets are runtime values, not shapes, so advancing the window never
  recompiles;
* window emission slices the ring head and advances by ``frames-flush``
  via a static ``jnp.roll`` in the same program.

Exactly THREE programs run for the stage's lifetime (ring init, append,
window+advance) — the same fixed-signature discipline as the continuous
LLM serving loop's 3-program pin — and emitted windows are device arrays:
an ``aggregator ! tensor_filter`` chain passes state filter-ward with ZERO
d2h between window dispatches (pinned by tests/test_aggregator_device.py's
transfer trap).  Window outputs are bit-identical to the host path (pure
data movement, no arithmetic).  The deep lint prices the ring
(``analysis/tracecheck.py``: "agg ring" bytes + the 3-program census) and
the residency planner counts the downstream edge device-resident.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..core.buffer import Buffer
from ..core.caps import Caps, MediaType
from ..core.registry import register_element
from ..core.types import TensorSpec, TensorsSpec
from .base import Element, ElementError, SRC


@register_element("tensor_aggregator")
class TensorAggregator(Element):
    kind = "tensor_aggregator"
    PAD_TEMPLATES = {"sink": Caps.new(MediaType.TENSORS)}

    def __init__(self, props=None, name=None):
        super().__init__(props, name)
        self.frames_in = int(self.props.get("frames_in", 1))
        self.frames_out = int(self.props.get("frames_out", 1))
        self.frames_flush = int(self.props.get("frames_flush", 0)) or self.frames_out
        self.frames_dim = int(self.props.get("frames_dim", 3))
        self.concat = str(self.props.get("concat", "true")).lower() not in (
            "false", "0", "no",
        )
        self.device = str(self.props.get("device", "false")).lower() in (
            "true", "1", "yes",
        )
        if self.device and not self.concat:
            raise ElementError(
                "tensor_aggregator device=true requires concat=true (the "
                "HBM ring carries ONE windowed tensor; multi-tensor "
                "windows stay on the host path)")
        #: read by the residency planner: downstream edges carry device
        #: arrays (the ring head), so they count device-resident
        self.device_resident = self.device
        self._window: Optional[np.ndarray] = None
        self._axis: Optional[int] = None
        # device mode: HBM ring + valid-sample watermark + the 3 jitted
        # programs (built lazily at first buffer — construction and
        # negotiation stay backend-free)
        self._ring = None
        self._valid = 0
        self._progs = None

    def configure(self, in_caps, out_pads):
        self.in_caps = dict(in_caps)
        src = next(iter(in_caps.values()), Caps.any())
        spec = src.spec
        out_spec = None
        if spec is not None and len(spec) == 1:
            dims = list(spec[0].dims)
            if self.frames_dim >= len(dims):
                raise ElementError(
                    f"frames-dim {self.frames_dim} out of range for rank {len(dims)}"
                )
            frame = dims[self.frames_dim] // self.frames_in
            if self.concat:
                dims[self.frames_dim] = frame * self.frames_out
                out_spec = TensorsSpec(
                    (TensorSpec(tuple(dims), spec[0].dtype),), rate=spec.rate
                )
            else:
                dims[self.frames_dim] = frame
                one = TensorSpec(tuple(dims), spec[0].dtype)
                out_spec = TensorsSpec(
                    tuple(one for _ in range(self.frames_out)), rate=spec.rate
                )
        caps = Caps.tensors(out_spec)
        self.out_caps = {p: caps for p in out_pads}
        return self.out_caps

    # -- device mode: HBM-resident ring ------------------------------------
    def _build_device_programs(self, shape, dtype):
        """Build the stage's THREE lifetime programs from the first
        buffer's signature (fixed shapes; the append offset and window
        advance are runtime VALUES, so nothing here ever recompiles
        across window advances — the zero-recompile pin)."""
        import jax
        import jax.numpy as jnp
        from jax import lax

        axis = len(shape) - 1 - self.frames_dim
        step = shape[axis]
        frame_len = step // self.frames_in
        need = self.frames_out * frame_len
        stride = self.frames_flush * frame_len
        ring_shape = list(shape)
        ring_shape[axis] = need + step
        ring_shape = tuple(ring_shape)

        def append(ring, x, valid):
            start = [jnp.int32(0)] * len(shape)
            start[axis] = valid
            return lax.dynamic_update_slice(ring, x, tuple(start))

        def window_advance(ring):
            win = lax.slice_in_dim(ring, 0, need, axis=axis)
            return jnp.roll(ring, -stride, axis=axis), win

        self._dev_axis, self._dev_step = axis, step
        self._dev_need, self._dev_stride = need, stride
        self._progs = {
            "init": jax.jit(lambda: jnp.zeros(ring_shape, dtype)),
            "append": jax.jit(append),
            "window": jax.jit(window_advance),
        }
        xr = getattr(self, "_xray", None)
        if xr is not None:
            # nns-xray: exactly the 3 lifetime programs the deep lint
            # prices (analysis/tracecheck.AGGREGATOR_PROGRAMS) — a 4th
            # compile (a re-specializing upstream) is census drift
            xr.expect(self.name, "agg", budget=3,
                      note="device-aggregator 3-program ring")
            rec = getattr(self, "_trace_rec", None)
            self._progs = {k: xr.track(p, self.name, "agg", rec=rec)
                           for k, p in self._progs.items()}
        return self._progs

    def _process_device(self, buf: Buffer):
        """One ring update per buffer, zero host round-trips: append the
        incoming samples at the valid watermark (in-program), then emit
        every complete window as a DEVICE-array slice of the ring head,
        advancing by the flush stride.  The watermark is a host-side
        Python int — a value the programs take as an argument, never a
        shape — so occupancy changes cost nothing."""
        import jax.numpy as jnp

        if len(buf.tensors) != 1:
            raise ElementError(
                "tensor_aggregator device=true aggregates ONE tensor per "
                f"buffer, got {len(buf.tensors)}")
        x = buf.tensors[0]
        if not hasattr(x, "addressable_shards") \
                and not type(x).__module__.startswith("jax"):
            # host ingest boundary: one H2D here, then the ring never
            # leaves HBM again
            x = jnp.asarray(x)
        progs = self._progs or self._build_device_programs(
            tuple(x.shape), np.dtype(x.dtype))
        if self._ring is None:
            self._ring = progs["init"]()
            self._valid = 0
        self._ring = progs["append"](self._ring, x, self._valid)
        self._valid += self._dev_step
        outs: List = []
        while self._valid >= self._dev_need:
            self._ring, win = progs["window"](self._ring)
            # host semantics: dropping past the end of the window forgets
            # at most what exists (an over-long flush never carries debt)
            self._valid = max(0, self._valid - self._dev_stride)
            outs.append((SRC, buf.with_tensors([win], spec=None)))
        return outs

    def process(self, pad, buf: Buffer):
        if self.device:
            return self._process_device(buf)
        x = np.asarray(buf.tensors[0])
        axis = x.ndim - 1 - self.frames_dim
        if self._window is None:
            self._window = x
            self._axis = axis
        else:
            self._window = np.concatenate([self._window, x], axis=axis)
        outs: List = []
        # one incoming buffer carries frames_in frames; window counts frames
        frame_len = x.shape[axis] // self.frames_in  # samples per frame
        need = self.frames_out * frame_len
        stride = self.frames_flush * frame_len
        while self._window.shape[axis] >= need:
            sl = [slice(None)] * self._window.ndim
            sl[axis] = slice(0, need)
            window = self._window[tuple(sl)]
            if self.concat:
                tensors = [window]
            else:
                tensors = []
                for i in range(self.frames_out):
                    fsl = [slice(None)] * window.ndim
                    fsl[axis] = slice(i * frame_len, (i + 1) * frame_len)
                    tensors.append(window[tuple(fsl)])
            outs.append((SRC, buf.with_tensors(tensors, spec=None)))
            keep = [slice(None)] * self._window.ndim
            keep[axis] = slice(stride, None)
            self._window = self._window[tuple(keep)]
        return outs

    def finalize(self):
        # both paths drop partial windows at EOS (the reference's
        # behavior); device mode also releases the ring's HBM
        self._window = None
        self._ring = None
        self._valid = 0
        return []

    def stop(self) -> None:
        self._ring = None
        self._progs = None
        self._valid = 0
