"""tensor_aggregator: window/stride accumulation along a dim.

Reference analog: ``gsttensor_aggregator.c`` (SURVEY §2.2) — concatenate N
frames along an axis with flush control; the time-series/audio windowing
primitive (and the closest thing the reference has to sequence-dimension
machinery, §5.7).

Props (reference names):
* ``frames-in``    — frames contained in one incoming buffer (along the dim)
* ``frames-out``   — frames per outgoing buffer (window size)
* ``frames-flush`` — frames to drop after each output (stride; 0 => frames-out,
                     i.e. non-overlapping windows)
* ``frames-dim``   — nnstreamer dim index to count frames along
* ``concat``       — true (default): one concatenated tensor per window;
                     false: the window's frames stay separate tensors in one
                     buffer (the reference's multi-GstMemory buffer analog)
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..core.buffer import Buffer
from ..core.caps import Caps, MediaType
from ..core.registry import register_element
from ..core.types import TensorSpec, TensorsSpec
from .base import Element, ElementError, SRC


@register_element("tensor_aggregator")
class TensorAggregator(Element):
    kind = "tensor_aggregator"
    PAD_TEMPLATES = {"sink": Caps.new(MediaType.TENSORS)}

    def __init__(self, props=None, name=None):
        super().__init__(props, name)
        self.frames_in = int(self.props.get("frames_in", 1))
        self.frames_out = int(self.props.get("frames_out", 1))
        self.frames_flush = int(self.props.get("frames_flush", 0)) or self.frames_out
        self.frames_dim = int(self.props.get("frames_dim", 3))
        self.concat = str(self.props.get("concat", "true")).lower() not in (
            "false", "0", "no",
        )
        self._window: Optional[np.ndarray] = None
        self._axis: Optional[int] = None

    def configure(self, in_caps, out_pads):
        self.in_caps = dict(in_caps)
        src = next(iter(in_caps.values()), Caps.any())
        spec = src.spec
        out_spec = None
        if spec is not None and len(spec) == 1:
            dims = list(spec[0].dims)
            if self.frames_dim >= len(dims):
                raise ElementError(
                    f"frames-dim {self.frames_dim} out of range for rank {len(dims)}"
                )
            frame = dims[self.frames_dim] // self.frames_in
            if self.concat:
                dims[self.frames_dim] = frame * self.frames_out
                out_spec = TensorsSpec(
                    (TensorSpec(tuple(dims), spec[0].dtype),), rate=spec.rate
                )
            else:
                dims[self.frames_dim] = frame
                one = TensorSpec(tuple(dims), spec[0].dtype)
                out_spec = TensorsSpec(
                    tuple(one for _ in range(self.frames_out)), rate=spec.rate
                )
        caps = Caps.tensors(out_spec)
        self.out_caps = {p: caps for p in out_pads}
        return self.out_caps

    def process(self, pad, buf: Buffer):
        x = np.asarray(buf.tensors[0])
        axis = x.ndim - 1 - self.frames_dim
        if self._window is None:
            self._window = x
            self._axis = axis
        else:
            self._window = np.concatenate([self._window, x], axis=axis)
        outs: List = []
        # one incoming buffer carries frames_in frames; window counts frames
        frame_len = x.shape[axis] // self.frames_in  # samples per frame
        need = self.frames_out * frame_len
        stride = self.frames_flush * frame_len
        while self._window.shape[axis] >= need:
            sl = [slice(None)] * self._window.ndim
            sl[axis] = slice(0, need)
            window = self._window[tuple(sl)]
            if self.concat:
                tensors = [window]
            else:
                tensors = []
                for i in range(self.frames_out):
                    fsl = [slice(None)] * window.ndim
                    fsl[axis] = slice(i * frame_len, (i + 1) * frame_len)
                    tensors.append(window[tuple(fsl)])
            outs.append((SRC, buf.with_tensors(tensors, spec=None)))
            keep = [slice(None)] * self._window.ndim
            keep[axis] = slice(stride, None)
            self._window = self._window[tuple(keep)]
        return outs

    def finalize(self):
        self._window = None
        return []
