"""Stream routing elements: tee, mux, demux, merge, split, join.

Reference analogs (upstream-reconstructed, SURVEY §2.2/§2.7):
``gsttensor_mux.c`` (many streams -> one other/tensors buffer, slowest-pad
timestamp sync), ``gsttensor_merge.c`` (concat along a dim),
``gsttensor_demux.c`` (``tensorpick``), ``gsttensor_split.c`` (``tensorseg``),
``gst/join/gstjoin.c`` (N:1 first-come forwarding without sync), and
GStreamer core ``tee``.

Axis convention: properties use nnstreamer innermost-first dim indices; the
numpy axis is ``rank-1-dim`` (see core/types.py).
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

from ..core.buffer import Buffer
from ..core.caps import Caps, MediaType
from ..core.log import logger, metrics
from ..core.registry import register_element
from ..core.types import TensorSpec, TensorsSpec
from .base import Element, ElementError, SRC

log = logger(__name__)


@register_element("tee")
class Tee(Element):
    """Copy every input buffer to all linked src pads."""

    kind = "tee"

    def configure(self, in_caps, out_pads):
        self.in_caps = dict(in_caps)
        src = next(iter(in_caps.values()), Caps.any())
        self.out_caps = {p: src for p in out_pads}
        return self.out_caps

    def process(self, pad, buf):
        return [(p, buf) for p in self.out_caps]


class _SyncModes:
    """Timestamp-sync behavior shared by tensor_mux / tensor_merge
    (reference: ``gsttensor_mux.c``/``gsttensor_merge.c`` ``sync-mode``):

    * ``slowest`` (default): emit when EVERY sink pad has contributed; the
      runtime's group collation implements it (sync_policy "all"); output
      pts = max of inputs.
    * ``basepad``: ``sync-option=<pad-index>[:<duration-ns>]``; the base pad
      drives — each base-pad buffer emits one output combining it with the
      most recent buffer seen on every other pad (other pads never gate
      beyond the first buffer).  Output pts = base pad's.  With a
      ``duration`` window the reference discards non-base buffers older
      than ``base_pts - duration`` and waits for fresher ones; here the
      single-latest-buffer analog holds the base buffer (bounded pending
      queue) until every other pad's latest lands inside the window, and
      EOS flushes whatever is pending with the last-seen buffers (the
      reference's end-of-stream behavior).
    * ``refresh``: ANY pad's new buffer emits an output reusing the other
      pads' most recent buffers.  Output pts = the arriving buffer's.

    basepad/refresh switch the element to sync_policy "any" and collate in
    ``process`` (single stage thread — no locking needed).
    """

    def _init_sync(self) -> None:
        self.sync_mode = str(self.props.get("sync_mode", "slowest")).lower()
        if self.sync_mode not in ("slowest", "basepad", "refresh"):
            raise ElementError(
                f"{self.name}: unknown sync-mode {self.sync_mode!r} "
                "(slowest|basepad|refresh)")
        opt = str(self.props.get("sync_option", "") or "0")
        parts = opt.split(":")
        self._base_idx = int(parts[0] or 0)
        self._base_window_ns = (int(parts[1])
                                if len(parts) > 1 and parts[1] else None)
        # Unconditional: a single-sink-pad mux in slowest mode skips the
        # runtime's group collation and reaches process() directly, where
        # latest-buffer collation degenerates to pass-through.
        self._latest: Dict[str, Buffer] = {}
        self._pending_base: List[Buffer] = []
        if self.sync_mode != "slowest":
            self.sync_policy = "any"  # instance overrides the class attr

    def _base_pad(self) -> str:
        pads = sorted(self.in_caps, key=_pad_index)  # numeric: sink_10 > sink_2
        if self._base_idx >= len(pads):
            raise ElementError(
                f"{self.name}: basepad {self._base_idx} out of range "
                f"({len(pads)} sink pads)")
        return pads[self._base_idx]

    def process(self, pad, buf: Buffer):
        # Only reached in basepad/refresh modes (slowest uses the runtime's
        # process_group collation).
        self._latest[pad] = buf
        if self.sync_mode == "basepad":
            if pad == self._base_pad():
                self._pending_base.append(buf)
                # Bounded like the reference's collectpad queues: a pad
                # that never catches up must not grow memory without limit.
                # Counted like every other drop path — a stalled non-base
                # pad must be observable, not silent data loss.
                if len(self._pending_base) > 64:
                    del self._pending_base[0]
                    metrics.count(f"{self.name}.basepad_evicted")
                    log.warning(
                        "%s: basepad queue full (64); evicting oldest "
                        "held base buffer — a non-base pad is stalled",
                        self.name)
            if not set(self.in_caps) <= set(self._latest):
                return []  # caps need every tensor: one-per-pad first
            return self._drain_basepad()
        if not set(self.in_caps) <= set(self._latest):
            return []  # caps need every tensor: wait for one-per-pad first
        return self._emit_with(dict(self._latest), buf)

    def _emit_with(self, group: Dict[str, Buffer], driving: Buffer):
        outs = self.process_group(group)
        for _, o in outs:
            o.pts = driving.pts  # driving buffer's timestamp, not the max
            o.seqno = driving.seqno
        return outs

    def _in_window(self, base_buf: Buffer) -> bool:
        """True when every non-base pad's latest buffer is no staler than
        ``base_pts - duration`` (reference: too-old buffers are discarded
        and the element waits for fresher data on that pad)."""
        if self._base_window_ns is None or base_buf.pts is None:
            return True
        base = self._base_pad()
        for p, lb in self._latest.items():
            if p != base and lb.pts is not None \
                    and lb.pts < base_buf.pts - self._base_window_ns:
                return False
        return True

    def _drain_basepad(self):
        base = self._base_pad()
        outs = []
        while self._pending_base:
            b = self._pending_base[0]
            if not self._in_window(b):
                break  # hold (in order) until the stale pad catches up
            self._pending_base.pop(0)
            group = dict(self._latest)
            group[base] = b
            outs.extend(self._emit_with(group, b))
        return outs

    def finalize(self):
        # EOS: no fresher buffers are coming — flush held base buffers
        # with the last-seen data on the other pads.
        outs = []
        if self.sync_mode == "basepad" \
                and set(self.in_caps) <= set(self._latest):
            base = self._base_pad()
            for b in self._pending_base:
                group = dict(self._latest)
                group[base] = b
                outs.extend(self._emit_with(group, b))
        self._pending_base = []
        return outs


@register_element("tensor_mux")
class TensorMux(_SyncModes, Element):
    """N tensor streams -> one buffer carrying all tensors.

    Props: ``sync-mode=slowest|basepad|refresh`` (see :class:`_SyncModes`),
    ``sync-option`` (basepad index).
    """

    kind = "tensor_mux"
    sync_policy = "all"
    PAD_TEMPLATES = {"sink_%u": Caps.new(MediaType.TENSORS)}

    def __init__(self, props=None, name=None):
        super().__init__(props, name)
        self._init_sync()

    def configure(self, in_caps, out_pads):
        self.in_caps = dict(in_caps)
        specs: List[TensorSpec] = []
        known = True
        for pad in sorted(in_caps):
            s = in_caps[pad].spec
            if s is None:
                known = False
                break
            specs.extend(s.specs)
        caps = Caps.tensors(TensorsSpec(tuple(specs)) if known else None)
        self.out_caps = {p: caps for p in out_pads}
        return self.out_caps

    def process_group(self, bufs: Dict[str, Buffer]):
        tensors = []
        pts = None
        meta: Dict[str, object] = {}
        for pad in sorted(bufs):
            b = bufs[pad]
            tensors.extend(b.tensors)
            meta.update(b.meta)
            if b.pts is not None:
                pts = b.pts if pts is None else max(pts, b.pts)
        out = Buffer(tensors, pts=pts, meta=meta)
        return [(SRC, out)]


@register_element("tensor_demux")
class TensorDemux(Element):
    """One other/tensors buffer -> one stream per (picked) tensor.

    ``tensorpick="0,2"`` selects tensors; out pads are src_0.. in pick order
    (reference: gsttensor_demux.c tensorpick property).

    ``by-meta=<key>`` switches to META ROUTING: the WHOLE buffer goes to
    pad ``src_<int(meta[key])>`` (absent/invalid key -> src_0), tensors
    untouched.  This is the pipeline-native home for per-buffer routing
    decisions an upstream stage stamped as meta — e.g. the continuous
    LLM serve loop's speculative accept/reject flag (``spec_draft`` —
    accepted-draft tokens route to src_1, target-sampled ones to src_0;
    docs/SERVING.md §4c).  Routing reads meta only: device-resident
    tensors never materialize here.
    """

    kind = "tensor_demux"
    PAD_TEMPLATES = {"sink": Caps.new(MediaType.TENSORS)}

    def __init__(self, props=None, name=None):
        super().__init__(props, name)
        pick = str(self.props.get("tensorpick", ""))
        self.pick = [int(v) for v in pick.split(",") if v != ""] if pick else None
        self.by_meta = str(self.props.get("by-meta",
                                          self.props.get("by_meta", "")))

    def configure(self, in_caps, out_pads):
        self.in_caps = dict(in_caps)
        src = next(iter(in_caps.values()), Caps.any())
        spec = src.spec
        self.out_caps = {}
        pads = sorted(out_pads, key=_pad_index)
        for i, p in enumerate(pads):
            if self.by_meta:
                # meta routing passes the whole buffer through: every
                # pad carries the input spec unchanged
                self.out_caps[p] = src
                continue
            sub = None
            if spec is not None:
                idx = self.pick[i] if self.pick else i
                if idx < len(spec):
                    sub = TensorsSpec((spec[idx],), rate=spec.rate)
            self.out_caps[p] = Caps.tensors(sub)
        return self.out_caps

    def process(self, pad, buf: Buffer):
        pads = sorted(self.out_caps, key=_pad_index)
        if self.by_meta:
            try:
                idx = int(buf.meta.get(self.by_meta, 0) or 0)
            except (TypeError, ValueError):
                idx = 0
            idx = max(0, min(idx, len(pads) - 1))
            return [(pads[idx], buf)]
        outs = []
        for i, p in enumerate(pads):
            idx = self.pick[i] if self.pick else i
            if idx >= len(buf.tensors):
                raise ElementError(
                    f"demux pick {idx} out of range (buffer has {len(buf.tensors)})"
                )
            outs.append((p, buf.with_tensors([buf.tensors[idx]], spec=None)))
        return outs


@register_element("tensor_merge")
class TensorMerge(_SyncModes, Element):
    """Concatenate one tensor from each sink pad along a dim.

    Props: ``mode=linear`` (only mode, as upstream), ``option=<dim>`` —
    nnstreamer dim index to concat along (reference: gsttensor_merge.c),
    ``sync-mode=slowest|basepad|refresh`` + ``sync-option`` (see
    :class:`_SyncModes`).
    """

    kind = "tensor_merge"
    sync_policy = "all"
    PAD_TEMPLATES = {"sink_%u": Caps.new(MediaType.TENSORS)}

    def __init__(self, props=None, name=None):
        super().__init__(props, name)
        self.dim = int(self.props.get("option", 0))
        self._init_sync()

    def configure(self, in_caps, out_pads):
        self.in_caps = dict(in_caps)
        spec = None
        in_specs = []
        for pad in sorted(in_caps):
            s = in_caps[pad].spec
            if s is None or len(s) != 1:
                in_specs = None
                break
            in_specs.append(s[0])
        if in_specs:
            rank = in_specs[0].rank
            if self.dim >= rank:
                raise ElementError(f"merge dim {self.dim} out of range (rank {rank})")
            dims = list(in_specs[0].dims)
            dims[self.dim] = sum(s.dims[self.dim] for s in in_specs)
            spec = TensorsSpec((TensorSpec(tuple(dims), in_specs[0].dtype),))
        caps = Caps.tensors(spec)
        self.out_caps = {p: caps for p in out_pads}
        return self.out_caps

    def process_group(self, bufs: Dict[str, Buffer]):
        arrays = [np.asarray(bufs[p].tensors[0]) for p in sorted(bufs)]
        rank = arrays[0].ndim
        axis = rank - 1 - self.dim
        out = np.concatenate(arrays, axis=axis)
        pts = max((b.pts for b in bufs.values() if b.pts is not None), default=None)
        return [(SRC, Buffer([out], pts=pts))]


@register_element("tensor_split")
class TensorSplit(Element):
    """Split one tensor into segments along a dim.

    Props: ``tensorseg="2,3,4"`` (sizes along the dim; reference encodes full
    per-output dims — sizes along one dim express the same splits),
    ``dim=<nnstreamer dim index>`` (default 0, the innermost).
    """

    kind = "tensor_split"
    PAD_TEMPLATES = {"sink": Caps.new(MediaType.TENSORS)}

    def __init__(self, props=None, name=None):
        super().__init__(props, name)
        seg = str(self.props.get("tensorseg", ""))
        self.segments = [int(v) for v in seg.replace(":", ",").split(",") if v != ""]
        self.dim = int(self.props.get("dim", 0))

    def configure(self, in_caps, out_pads):
        self.in_caps = dict(in_caps)
        src = next(iter(in_caps.values()), Caps.any())
        spec = src.spec
        self.out_caps = {}
        pads = sorted(out_pads, key=_pad_index)
        if self.segments and len(pads) != len(self.segments):
            raise ElementError(
                f"split has {len(pads)} out pads but {len(self.segments)} segments"
                " — every segment needs a linked pad (unlinked segments would"
                " silently drop data)"
            )
        for i, p in enumerate(pads):
            sub = None
            if spec is not None and len(spec) == 1 and self.segments:
                dims = list(spec[0].dims)
                if self.dim >= len(dims):
                    raise ElementError(f"split dim {self.dim} out of range")
                dims[self.dim] = self.segments[i]
                sub = TensorsSpec((TensorSpec(tuple(dims), spec[0].dtype),))
            self.out_caps[p] = Caps.tensors(sub)
        return self.out_caps

    def process(self, pad, buf: Buffer):
        x = np.asarray(buf.tensors[0])
        axis = x.ndim - 1 - self.dim
        sizes = self.segments or [x.shape[axis]]
        if sum(sizes) != x.shape[axis]:
            raise ElementError(
                f"split sizes {sizes} do not cover dim size {x.shape[axis]}"
            )
        pads = sorted(self.out_caps, key=_pad_index)
        outs = []
        off = 0
        for i, p in enumerate(pads):
            n = sizes[i]
            sl = [slice(None)] * x.ndim
            sl[axis] = slice(off, off + n)
            outs.append((p, buf.with_tensors([x[tuple(sl)]], spec=None)))
            off += n
        return outs


@register_element("join")
class Join(Element):
    """N:1 first-come forwarding without sync (reference: gst/join/gstjoin.c),
    used to reunite branches after conditional offloading."""

    kind = "join"
    sync_policy = "any"

    def process(self, pad, buf):
        return [(SRC, buf)]


def _pad_index(pad: str) -> int:
    if "_" in pad:
        try:
            return int(pad.rsplit("_", 1)[1])
        except ValueError:
            return 0
    return 0
