"""tensor_trainer: on-device training as a pipeline element (nns-learn).

Reference analog: ``gst/nnstreamer/elements/gsttensor_trainer.c`` (SURVEY
§2.2, upstream-reconstructed): receives (input, label) tensor pairs from the
stream, drives a trainer sub-plugin through push_data/start/stop/save-model,
and emits per-epoch training stats (loss/accuracy) downstream as tensors.

Element semantics kept: ``num-inputs``/``num-labels`` split each incoming
buffer's tensors; ``num-training-samples``+``num-validation-samples`` define
an epoch; each completed epoch runs a training pass and pushes ONE stats
buffer (float64 [4]: training_loss, training_acc, val_loss, val_acc);
``model-save-path`` is written at EOS (and on explicit ``ready-to-complete``).

TPU-first differences (docs/TRAINING.md): samples stream into the jax
sub-plugin's device-resident window (no host epoch accumulation), the
update step is a fixed-signature jitted program (closed 3-program census),
``checkpoint-every=N`` writes step-versioned fsync'd checkpoints every N
epochs so a killed pipeline resumes bit-identically via
``model-load-path``, and ``swap-to=<stage>`` hot-swaps each epoch's
refreshed params into a live serving stage (``Pipeline.swap_params``) —
train-while-serve.  Stats buffers ride the flight-recorder/tenant rails:
they inherit the triggering sample's trace id + tenant and each epoch
records a ``learn.step`` span (``learn.ckpt`` per checkpoint write), so
trainer activity joins the Perfetto timeline like every other stage.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

import numpy as np

from ..core.buffer import Buffer, Event
from ..core.caps import Caps
from ..core.log import metrics
from ..core.registry import get as registry_get, register_element, KIND_TRAINER
from ..core.types import TensorSpec, TensorsSpec
from ..utils import tracing
from .base import Element, ElementError, Out, SRC

STATS_SPEC = TensorsSpec.single(TensorSpec(name="stats", dtype="float64", dims=(4,)))


@register_element("tensor_trainer")
class TensorTrainer(Element):
    """Training element.

    Props: ``framework`` (trainer sub-plugin, default ``jax``), ``model``
    (model-config passed to the sub-plugin), ``model-save-path``,
    ``model-load-path`` (resume), ``num-inputs`` (default 1), ``num-labels``
    (default 1), ``num-training-samples``, ``num-validation-samples``,
    ``epochs`` (stop after N epochs; further data is ignored),
    ``checkpoint-every`` (write a step-versioned fsync'd checkpoint to
    ``model-save-path`` every N completed epochs; 0 = only at EOS),
    ``swap-to`` (serving stage name: hot-swap refreshed params into it
    after every epoch — requires the pipeline-attached swap callback),
    plus sub-plugin props (``optimizer``, ``learning-rate``, ``loss``,
    ``batch-size``, ``mesh``, ``host-accumulate``...) forwarded verbatim.
    """

    kind = "tensor_trainer"
    #: inputs and labels may arrive muxed in one buffer or on separate sink
    #: pads (``in.sink_0`` data, ``in.sink_1`` labels) — collate when multi.
    sync_policy = "all"

    def __init__(self, props=None, name=None):
        super().__init__(props, name)
        self.num_inputs = int(self.props.get("num_inputs", 1))
        self.num_labels = int(self.props.get("num_labels", 1))
        self.n_train = int(self.props.get("num_training_samples", 0))
        self.n_valid = int(self.props.get("num_validation_samples", 0))
        self.epochs = int(self.props.get("epochs", 1))
        self.save_path = str(self.props.get("model_save_path", "") or "")
        self.fw_name = str(self.props.get("framework", "jax"))
        self.checkpoint_every = int(self.props.get("checkpoint_every", 0))
        self.swap_to = str(self.props.get("swap_to", "") or "")
        # Reference: tensor_trainer arms nnstreamer_watchdog around the
        # sub-plugin; a wedged train step must surface, not hang the stage.
        self.wd_timeout = float(self.props.get("watchdog_timeout", 0.0))
        self.trainer = None
        self._pushed = 0
        self._epochs_done = 0
        self._stats_pts = 0
        self._hung: Optional[str] = None
        #: the most recent input buffer's trace identity (trace id,
        #: ingress ns, tenant): stamped onto emitted stats buffers so
        #: learn.* activity joins the request timeline (nns-trace)
        self._last_tid = None
        self._last_ingress = None
        self._last_tenant = None

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        cls = registry_get(KIND_TRAINER, self.fw_name)
        self.trainer = cls()
        self.trainer.open(self.props)
        xr = getattr(self, "_xray", None)
        attach = getattr(self.trainer, "attach_xray", None)
        if xr is not None and attach is not None:
            # the Framework.attach_xray handoff: the trainer's 3-program
            # census registers under <name>.learn with budget-1
            # expectations (utils/xray.py)
            attach(xr, self.name,
                   rec=lambda: getattr(self, "_trace_rec", None))

    def stop(self) -> None:
        if self.trainer is not None:
            self.trainer.close()

    def configure(self, in_caps, out_pads):
        self.in_caps = dict(in_caps)
        caps = Caps.tensors(STATS_SPEC)
        self.out_caps = {p: caps for p in out_pads}
        return self.out_caps

    # -- accounting (deep lint / nns-xray HBM ledger) -----------------------
    def param_bytes(self) -> int:
        fn = getattr(self.trainer, "param_nbytes", None)
        return int(fn()) if fn is not None else 0

    def train_state_bytes(self) -> int:
        """Device-resident training state (optimizer moments + streaming
        window) — the live side of the ledger's ``train_state`` category
        (utils/xray.measure_hbm)."""
        fn = getattr(self.trainer, "train_state_bytes", None)
        return int(fn()) if fn is not None else 0

    # -- streaming ---------------------------------------------------------
    def _epoch_size(self) -> int:
        if self.n_train <= 0:
            raise ElementError(
                "tensor_trainer requires num-training-samples > 0"
            )
        return self.n_train + self.n_valid

    def process(self, pad: str, buf: Buffer) -> Out:
        if self._epochs_done >= self.epochs:
            return []  # training complete; drain remaining pushes
        want = self.num_inputs + self.num_labels
        if len(buf.tensors) != want:
            raise ElementError(
                f"tensor_trainer expects {want} tensors/buffer "
                f"(num-inputs={self.num_inputs} + num-labels={self.num_labels}), "
                f"got {len(buf.tensors)}"
            )
        # remember the triggering request's identity for the stats
        # buffer + learn.step span (written only when tracing stamped
        # the meta — the off path stays stamp-free)
        tid = buf.meta.get(tracing.META_TRACE_ID)
        if tid is not None:
            self._last_tid = tid
            self._last_ingress = buf.meta.get(tracing.META_INGRESS_NS)
        ten = buf.meta.get(tracing.META_TENANT)
        if ten is not None:
            self._last_tenant = ten
        inputs = buf.tensors[: self.num_inputs]
        labels = buf.tensors[self.num_inputs :]
        pos = self._pushed % self._epoch_size()
        is_validation = pos >= self.n_train
        self.trainer.push_data(inputs, labels, is_validation)
        self._pushed += 1

        out: Out = []
        if self._pushed % self._epoch_size() == 0:
            out.extend(self._run_epoch())
        return out

    def process_group(self, bufs: Dict[str, Buffer]) -> Out:
        tensors: List = []
        pads = sorted(bufs)
        for pad in pads:
            tensors.extend(bufs[pad].tensors)
        # pts/meta (trace id, tenant) from the SAME sorted-first pad the
        # tensor order starts with — dict insertion order could name a
        # different pad and misattribute learn.* spans
        first = bufs[pads[0]]
        merged = Buffer(tensors, pts=first.pts, meta=dict(first.meta))
        return self.process("sink", merged)

    def _run_epoch(self) -> Out:
        if self._hung:
            raise ElementError(self._hung)
        t0 = time.monotonic_ns()
        if self.wd_timeout > 0:
            from ..utils.watchdog import call_with_watchdog

            try:
                stats = call_with_watchdog(
                    self.trainer.train_epoch, self.wd_timeout,
                    what=f"{self.name} trainer epoch",
                )
            except TimeoutError as e:
                self._hung = str(e)
                raise ElementError(self._hung) from e
        else:
            stats = self.trainer.train_epoch()
        self._epochs_done += 1
        metrics.count(f"{self.name}.epochs")
        rec = getattr(self, "_trace_rec", None)
        if rec is not None and rec.active:
            # learn.step: one span per trained epoch on the trainer's
            # own track, joined to the LAST contributing request's trace
            # id (the batch-span linkage convention) + tenant
            args = {"epoch": self._epochs_done,
                    "step": getattr(self.trainer, "step", 0),
                    "loss": stats.get("training_loss")}
            if self._last_tenant is not None:
                args["tenant"] = self._last_tenant
            rec.record("learn.step", self.name, self._last_tid, t0,
                       time.monotonic_ns() - t0, **args)
        arr = np.array(
            [
                stats.get("training_loss", np.nan),
                stats.get("training_accuracy", np.nan),
                stats.get("validation_loss", np.nan),
                stats.get("validation_accuracy", np.nan),
            ],
            dtype=np.float64,
        )
        self._stats_pts += 1
        stats_buf = Buffer([arr], spec=STATS_SPEC, pts=self._stats_pts)
        # the stats buffer rides the flight-recorder/tenant rails: it
        # inherits the triggering sample's identity so downstream sinks'
        # e2e spans and per-tenant histograms see trainer emissions
        # (satellite: trainer stats were invisible to nns-trace)
        if self._last_tid is not None:
            stats_buf.meta[tracing.META_TRACE_ID] = self._last_tid
            if self._last_ingress is not None:
                stats_buf.meta[tracing.META_INGRESS_NS] = self._last_ingress
        if self._last_tenant is not None:
            stats_buf.meta[tracing.META_TENANT] = self._last_tenant
        out: Out = [(SRC, stats_buf)]
        if self.checkpoint_every > 0 and self.save_path \
                and self._epochs_done % self.checkpoint_every == 0 \
                and self._epochs_done < self.epochs:
            self._checkpoint(versioned=True)
        if self.swap_to:
            self._swap_into_serving()
        if self._epochs_done >= self.epochs:
            self._save()
        return out

    def _swap_into_serving(self) -> None:
        """Train-while-serve: push the refreshed param tree into the
        ``swap-to`` serving stage through the pipeline-attached swap
        callback (``Pipeline.swap_params`` — a VALUE move at the serving
        stage's dispatch boundary, zero recompiles)."""
        cb = getattr(self, "_swap_cb", None)
        if cb is None:
            raise ElementError(
                f"{self.name}: swap-to={self.swap_to!r} needs the "
                "pipeline swap callback (run inside a Pipeline)")
        export = getattr(self.trainer, "export_params", None)
        tree = export() if export is not None else self.trainer.params
        version = cb(self.swap_to, tree)
        metrics.gauge(f"{self.name}.swap_version", float(version))

    def _checkpoint(self, versioned: bool = False) -> None:
        """One fsync'd checkpoint write (+ a step-versioned sibling so a
        rollback target survives the next overwrite), span-stamped
        ``learn.ckpt``."""
        t0 = time.monotonic_ns()
        path = self.trainer.save(self.save_path)
        if versioned:
            step = int(getattr(self.trainer, "step", 0))
            self.trainer.save(f"{self.save_path}.step{step}")
        metrics.count(f"{self.name}.ckpt_writes")
        rec = getattr(self, "_trace_rec", None)
        if rec is not None and rec.active:
            rec.record("learn.ckpt", self.name, self._last_tid, t0,
                       time.monotonic_ns() - t0,
                       step=int(getattr(self.trainer, "step", 0)),
                       path=path)

    def _save(self) -> None:
        if self.save_path and self.trainer is not None:
            self._checkpoint()
            self._saved_at_epoch = self._epochs_done

    def finalize(self) -> Out:
        out: Out = []
        # Partial epoch at EOS: train on what arrived (reference flushes the
        # queue into the sub-plugin and stops).
        if self._epochs_done < self.epochs and self.trainer is not None:
            n_train, n_valid = self.trainer.queued()
            if n_train:
                out.extend(self._run_epoch())
        if getattr(self, "_saved_at_epoch", None) != self._epochs_done:
            self._save()
        return out

    def on_event(self, pad: str, event: Event) -> Out:
        if event.kind == "ready-to-complete":
            self._save()
            return []
        return super().on_event(pad, event)
