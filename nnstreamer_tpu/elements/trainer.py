"""tensor_trainer: on-device training as a pipeline element.

Reference analog: ``gst/nnstreamer/elements/gsttensor_trainer.c`` (SURVEY
§2.2, upstream-reconstructed): receives (input, label) tensor pairs from the
stream, drives a trainer sub-plugin through push_data/start/stop/save-model,
and emits per-epoch training stats (loss/accuracy) downstream as tensors.

Element semantics kept: ``num-inputs``/``num-labels`` split each incoming
buffer's tensors; ``num-training-samples``+``num-validation-samples`` define
an epoch; each completed epoch runs a training pass and pushes ONE stats
buffer (float64 [4]: training_loss, training_acc, val_loss, val_acc);
``model-save-path`` is written at EOS (and on explicit ``ready-to-complete``).

TPU-first difference: the epoch is not handed to a library thread (the
reference queues into nntrainer's own event loop); the minibatch loop is a
jitted optax scan executed synchronously — deterministic, testable, and the
stats buffer is ready the moment the epoch's XLA program returns.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ..core.buffer import Buffer, Event
from ..core.caps import Caps
from ..core.registry import get as registry_get, register_element, KIND_TRAINER
from ..core.types import TensorSpec, TensorsSpec
from .base import Element, ElementError, Out, SRC

STATS_SPEC = TensorsSpec.single(TensorSpec(name="stats", dtype="float64", dims=(4,)))


@register_element("tensor_trainer")
class TensorTrainer(Element):
    """Training element.

    Props: ``framework`` (trainer sub-plugin, default ``jax``), ``model``
    (model-config passed to the sub-plugin), ``model-save-path``,
    ``model-load-path`` (resume), ``num-inputs`` (default 1), ``num-labels``
    (default 1), ``num-training-samples``, ``num-validation-samples``,
    ``epochs`` (stop after N epochs; further data is ignored), plus
    sub-plugin props (``optimizer``, ``learning-rate``, ``loss``,
    ``batch-size``, ``mesh``...) forwarded verbatim.
    """

    kind = "tensor_trainer"
    #: inputs and labels may arrive muxed in one buffer or on separate sink
    #: pads (``in.sink_0`` data, ``in.sink_1`` labels) — collate when multi.
    sync_policy = "all"

    def __init__(self, props=None, name=None):
        super().__init__(props, name)
        self.num_inputs = int(self.props.get("num_inputs", 1))
        self.num_labels = int(self.props.get("num_labels", 1))
        self.n_train = int(self.props.get("num_training_samples", 0))
        self.n_valid = int(self.props.get("num_validation_samples", 0))
        self.epochs = int(self.props.get("epochs", 1))
        self.save_path = str(self.props.get("model_save_path", "") or "")
        self.fw_name = str(self.props.get("framework", "jax"))
        # Reference: tensor_trainer arms nnstreamer_watchdog around the
        # sub-plugin; a wedged train step must surface, not hang the stage.
        self.wd_timeout = float(self.props.get("watchdog_timeout", 0.0))
        self.trainer = None
        self._pushed = 0
        self._epochs_done = 0
        self._stats_pts = 0
        self._hung: Optional[str] = None

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        cls = registry_get(KIND_TRAINER, self.fw_name)
        self.trainer = cls()
        self.trainer.open(self.props)

    def stop(self) -> None:
        if self.trainer is not None:
            self.trainer.close()

    def configure(self, in_caps, out_pads):
        self.in_caps = dict(in_caps)
        caps = Caps.tensors(STATS_SPEC)
        self.out_caps = {p: caps for p in out_pads}
        return self.out_caps

    # -- streaming ---------------------------------------------------------
    def _epoch_size(self) -> int:
        if self.n_train <= 0:
            raise ElementError(
                "tensor_trainer requires num-training-samples > 0"
            )
        return self.n_train + self.n_valid

    def process(self, pad: str, buf: Buffer) -> Out:
        if self._epochs_done >= self.epochs:
            return []  # training complete; drain remaining pushes
        want = self.num_inputs + self.num_labels
        if len(buf.tensors) != want:
            raise ElementError(
                f"tensor_trainer expects {want} tensors/buffer "
                f"(num-inputs={self.num_inputs} + num-labels={self.num_labels}), "
                f"got {len(buf.tensors)}"
            )
        inputs = buf.tensors[: self.num_inputs]
        labels = buf.tensors[self.num_inputs :]
        pos = self._pushed % self._epoch_size()
        is_validation = pos >= self.n_train
        self.trainer.push_data(inputs, labels, is_validation)
        self._pushed += 1

        out: Out = []
        if self._pushed % self._epoch_size() == 0:
            out.extend(self._run_epoch())
        return out

    def process_group(self, bufs: Dict[str, Buffer]) -> Out:
        tensors: List = []
        for pad in sorted(bufs):
            tensors.extend(bufs[pad].tensors)
        merged = Buffer(tensors, pts=next(iter(bufs.values())).pts)
        return self.process("sink", merged)

    def _run_epoch(self) -> Out:
        if self._hung:
            raise ElementError(self._hung)
        if self.wd_timeout > 0:
            from ..utils.watchdog import call_with_watchdog

            try:
                stats = call_with_watchdog(
                    self.trainer.train_epoch, self.wd_timeout,
                    what=f"{self.name} trainer epoch",
                )
            except TimeoutError as e:
                self._hung = str(e)
                raise ElementError(self._hung) from e
        else:
            stats = self.trainer.train_epoch()
        self._epochs_done += 1
        arr = np.array(
            [
                stats.get("training_loss", np.nan),
                stats.get("training_accuracy", np.nan),
                stats.get("validation_loss", np.nan),
                stats.get("validation_accuracy", np.nan),
            ],
            dtype=np.float64,
        )
        self._stats_pts += 1
        out: Out = [(SRC, Buffer([arr], spec=STATS_SPEC, pts=self._stats_pts))]
        if self._epochs_done >= self.epochs:
            self._save()
        return out

    def _save(self) -> None:
        if self.save_path and self.trainer is not None:
            self.trainer.save(self.save_path)
            self._saved_at_epoch = self._epochs_done

    def finalize(self) -> Out:
        out: Out = []
        # Partial epoch at EOS: train on what arrived (reference flushes the
        # queue into the sub-plugin and stops).
        if self._epochs_done < self.epochs and self.trainer is not None:
            n_train, n_valid = self.trainer.queued()
            if n_train:
                out.extend(self._run_epoch())
        if getattr(self, "_saved_at_epoch", None) != self._epochs_done:
            self._save()
        return out

    def on_event(self, pad: str, event: Event) -> Out:
        if event.kind == "ready-to-complete":
            self._save()
            return []
        return super().on_event(pad, event)
