"""tensor_debug: passthrough that logs caps/shape/timing metadata.

Reference analog: ``gsttensor_debug.c`` (SURVEY §2.2).
"""

from __future__ import annotations

import numpy as np

from ..core.log import logger
from ..core.registry import register_element
from .base import Element, SRC

log = logger(__name__)


@register_element("tensor_debug")
class TensorDebug(Element):
    kind = "tensor_debug"

    def __init__(self, props=None, name=None):
        super().__init__(props, name)
        self.console = bool(self.props.get("console", False))
        self.count = 0

    def process(self, pad, buf):
        self.count += 1
        desc = ", ".join(
            f"{tuple(np.asarray(t).shape)}:{np.asarray(t).dtype}" for t in buf.tensors
        )
        msg = f"[{self.name}] #{self.count} pts={buf.pts} tensors=[{desc}] meta={list(buf.meta)}"
        if self.console:
            print(msg)
        else:
            log.info("%s", msg)
        return [(SRC, buf)]
