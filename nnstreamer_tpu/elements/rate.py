"""tensor_rateadjust: throttle/duplicate frames to a target rate.

Reference analog: ``gsttensor_rateadjust.c`` / ``tensor_rate`` (SURVEY §2.2):
drop or duplicate buffers so the output stream hits ``framerate=N/D``, with
QoS counters (in/out/dropped/duplicated) exposed as properties.
"""

from __future__ import annotations

from typing import Optional

from ..core.buffer import Buffer
from ..core.caps import Caps, MediaType
from ..core.registry import register_element
from ..core.types import parse_fraction
from .base import Element, SRC


@register_element("tensor_rateadjust", aliases=("tensor_rate",))
class TensorRateAdjust(Element):
    kind = "tensor_rateadjust"
    PAD_TEMPLATES = {"sink": Caps.new(MediaType.TENSORS)}

    def __init__(self, props=None, name=None):
        super().__init__(props, name)
        self.target = parse_fraction(self.props.get("framerate", "30/1"))
        self.silent = bool(self.props.get("silent", True))
        self.n_in = 0
        self.n_out = 0
        self.n_dropped = 0
        self.n_duplicated = 0
        self._next_pts: Optional[int] = None  # next output slot in ns

    def configure(self, in_caps, out_pads):
        self.in_caps = dict(in_caps)
        src = next(iter(in_caps.values()), Caps.any())
        spec = src.spec
        if spec is not None:
            spec = spec.replace(rate=self.target)
        self.out_caps = {p: Caps.tensors(spec) for p in out_pads}
        return self.out_caps

    def process(self, pad, buf: Buffer):
        self.n_in += 1
        num, den = self.target
        if num <= 0 or buf.pts is None:
            self.n_out += 1
            return [(SRC, buf)]
        frame_ns = int(1e9 * den / num)
        if self._next_pts is None:
            # Anchor the slot clock at the first observed pts — streams need
            # not start at t=0 (mid-stream segments, live sources).
            self._next_pts = buf.pts
        outs = []
        # emit one copy per output slot covered by this input's timestamp;
        # drop inputs that land before the next slot.
        while buf.pts >= self._next_pts:
            out = buf.with_tensors(buf.tensors, spec=buf.spec)
            out.pts = self._next_pts
            outs.append((SRC, out))
            self._next_pts += frame_ns
            self.n_out += 1
            if len(outs) > 1:
                self.n_duplicated += 1
        if not outs:
            self.n_dropped += 1
        return outs
