"""datareposrc / datareposink: file-backed training datasets.

Reference analog: ``gst/datarepo/gstdatareposrc.c`` / ``gstdatareposink.c``
(SURVEY §2.8, upstream-reconstructed): raw fixed-size samples in one binary
file described by a small JSON meta (the reference stores ``gst_caps``,
``total_samples``, ``sample_size``), with ``start-sample-index`` /
``stop-sample-index`` / ``epochs`` / ``is-shuffle`` dataset iteration —
that plus trainer ``model-save-path`` is the reference's whole
checkpoint/resume story (SURVEY §5.4).

JSON meta here::

    {"dims": "4:1,1:1", "types": "float32,int32",
     "total_samples": 120, "sample_size": 20}

(dims/types are our caps-string equivalents of ``gst_caps``; sample_size is
the byte length of one sample = all tensors concatenated.)

Manifest file lists (nns-learn, docs/TRAINING.md): the meta may carry a
``"files"`` list instead of a single ``location`` data file::

    {"dims": "4,1", "types": "float32,int32", "sample_size": 20,
     "files": ["shard0.bin", "shard1.bin"]}

Relative entries resolve against the meta's own directory; each file must
hold a whole number of samples and the dataset is their concatenation in
list order — the replay contract for a ``datareposink``-captured stream
split across shards.
"""

from __future__ import annotations

import json
import os
from typing import Iterator, List, Optional, Union

import numpy as np

from ..core.buffer import Buffer, Event
from ..core.caps import Caps
from ..core.registry import register_element
from ..core.types import TensorsSpec, dtype_name
from .base import Element, ElementError, Out, SinkElement, SourceElement


@register_element("datareposrc")
class DataRepoSrc(SourceElement):
    """Reads (input, label) samples from a binary file + JSON meta.

    Props: ``location`` (data file; optional when the meta carries a
    ``files`` manifest list), ``json`` (meta file),
    ``start-sample-index``, ``stop-sample-index`` (inclusive; -1 = last),
    ``epochs`` (dataset repetitions; each epoch re-emits the samples — the
    reference drives multi-epoch training this way), ``is-shuffle``
    (per-epoch deterministic shuffle: epoch k's order is a pure function
    of ``(shuffle-seed, k)``, so replays reproduce exactly while every
    epoch still sees a DIFFERENT order), ``shuffle-seed`` (default 0).
    """

    kind = "datareposrc"

    def __init__(self, props=None, name=None):
        super().__init__(props, name)
        self.location = str(self.props.get("location", ""))
        self.json_path = str(self.props.get("json", ""))
        self.start_idx = int(self.props.get("start_sample_index", 0))
        self.stop_idx = int(self.props.get("stop_sample_index", -1))
        self.epochs = int(self.props.get("epochs", 1))
        self.shuffle = str(self.props.get("is_shuffle", "false")).lower() in (
            "true",
            "1",
            "yes",
        )
        self.shuffle_seed = int(self.props.get("shuffle_seed", 0))
        self.spec: Optional[TensorsSpec] = None
        self._meta = None
        self._files: List[str] = []

    def _load_meta(self):
        if self._meta is not None:
            return
        if not self.json_path:
            raise ElementError("datareposrc requires json= meta path")
        with open(self.json_path, "r") as f:
            self._meta = json.load(f)
        self.spec = TensorsSpec.from_string(
            self._meta["dims"], self._meta.get("types", "uint8")
        )
        expect = sum(s.nbytes for s in self.spec)
        size = int(self._meta.get("sample_size", expect))
        if size != expect:
            raise ElementError(
                f"datarepo meta sample_size={size} != spec bytes {expect}"
            )
        files = self._meta.get("files")
        if files:
            base = os.path.dirname(os.path.abspath(self.json_path))
            self._files = [
                f if os.path.isabs(f) else os.path.join(base, f)
                for f in files
            ]
        elif self.location:
            self._files = [self.location]
        else:
            raise ElementError(
                "datareposrc needs location= or a 'files' manifest list "
                "in the json meta")

    def configure(self, in_caps, out_pads):
        self._load_meta()
        caps = Caps.tensors(self.spec)
        self.out_caps = {p: caps for p in out_pads}
        return self.out_caps

    def generate(self) -> Iterator[Union[Buffer, Event]]:
        self._load_meta()
        sample_size = sum(s.nbytes for s in self.spec)
        # Memory-map the dataset: samples are zero-copy views into the OS
        # page cache (the reference's C reader streams from the file; a
        # Python read() would materialize the WHOLE set in process RAM and
        # copy every sample).  Views stay valid while the mappings are
        # held.  With a manifest ``files`` list the dataset is the
        # concatenation of the shards, each holding whole samples.
        sizes = [os.path.getsize(f) for f in self._files]
        for f, fsize in zip(self._files, sizes):
            if fsize % sample_size:
                raise ElementError(
                    f"datarepo shard {f} holds {fsize} bytes — not a "
                    f"whole number of {sample_size}-byte samples")
        file_samples = [fsize // sample_size for fsize in sizes]
        avail = sum(file_samples)
        total = int(self._meta.get("total_samples", avail))
        stop = total - 1 if self.stop_idx < 0 else min(self.stop_idx, total - 1)
        # Size check BEFORE the empty-file return: a truncated/zero file
        # whose meta still claims samples must error, not yield nothing.
        if stop + 1 > avail:
            raise ElementError(
                f"datarepo file(s) holds {sum(sizes)} bytes; meta claims "
                f"{total} samples of {sample_size}")
        indices = list(range(self.start_idx, stop + 1))
        if not indices or avail == 0:
            return  # empty dataset (mmap of an empty file is an error)
        maps = [np.memmap(f, dtype=np.uint8, mode="r")
                for f, fsize in zip(self._files, sizes) if fsize]
        # global sample index -> (mapping, local offset)
        starts: List[int] = []
        acc = 0
        for n in file_samples:
            if n:
                starts.append(acc)
                acc += n
        import bisect

        for epoch in range(self.epochs):
            order = list(indices)
            if self.shuffle:
                # epoch k's order is a pure function of (seed, k):
                # deterministic replay across runs, different order per
                # epoch — the reference's is-shuffle semantics
                np.random.default_rng(
                    (self.shuffle_seed, epoch)).shuffle(order)
            for i in order:
                fi = bisect.bisect_right(starts, i) - 1
                off = (i - starts[fi]) * sample_size
                data = maps[fi]
                tensors: List[np.ndarray] = []
                pos = off
                for s in self.spec:
                    n = s.nbytes
                    arr = data[pos : pos + n].view(s.dtype).reshape(s.shape)
                    tensors.append(arr)
                    pos += n
                yield Buffer(tensors, spec=self.spec, meta={"sample_index": i, "epoch": epoch})


@register_element("datareposink")
class DataRepoSink(SinkElement):
    """Writes incoming sample buffers to a binary file + JSON meta at EOS.

    Props: ``location``, ``json``, ``manifest`` (``true`` = the meta
    also lists the data file under ``files`` — a standalone manifest a
    ``datareposrc json=`` replays with no ``location=`` prop, the
    capture→replay contract for training on recorded live streams,
    docs/TRAINING.md).
    """

    kind = "datareposink"

    def __init__(self, props=None, name=None):
        super().__init__(props, name)
        self.location = str(self.props.get("location", ""))
        self.json_path = str(self.props.get("json", ""))
        self.manifest = str(self.props.get("manifest", "false")).lower() in (
            "true", "1", "yes")
        self._f = None
        self._count = 0
        self._spec: Optional[TensorsSpec] = None

    def start(self) -> None:
        os.makedirs(os.path.dirname(os.path.abspath(self.location)) or ".", exist_ok=True)
        self._f = open(self.location, "wb")
        self._count = 0

    def process(self, pad: str, buf: Buffer) -> Out:
        buf = buf.resolve()
        if self._spec is None:
            self._spec = buf.spec
        for t in buf.tensors:
            self._f.write(np.ascontiguousarray(np.asarray(t)).tobytes())
        self._count += 1
        return []

    def finalize(self) -> Out:
        self._write_meta()
        return []

    def stop(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None

    def _write_meta(self) -> None:
        if self._f is not None:
            self._f.flush()
        if not self.json_path or self._spec is None:
            return
        sample_size = sum(s.nbytes for s in self._spec)
        meta = {
            "dims": ",".join(
                ":".join(str(d) for d in s.dims) for s in self._spec
            ),
            "types": ",".join(dtype_name(s.dtype) for s in self._spec),
            "total_samples": self._count,
            "sample_size": sample_size,
        }
        if self.manifest:
            # relative to the meta's directory when co-located (the
            # datareposrc resolution rule — the pair stays relocatable),
            # absolute otherwise; either way the captured set replays by
            # json= alone
            base = os.path.dirname(os.path.abspath(self.json_path))
            loc = os.path.abspath(self.location)
            meta["files"] = [os.path.basename(loc)
                             if os.path.dirname(loc) == base else loc]
        with open(self.json_path, "w") as f:
            json.dump(meta, f)
