"""tensor_query elements: offload inference to a remote pipeline.

Reference analog (SURVEY §2.7, §3.3): ``tensor_query_client`` serializes
input tensors, sends them to an "edge server" over nnstreamer-edge TCP,
receives results asynchronously matched by message id (GstMetaQuery), and
pushes them downstream; ``tensor_query_serversrc`` listens and injects
received tensors into the server-side pipeline; ``tensor_query_serversink``
returns each result to the client connection recorded in the buffer's meta.
Multiple clients are served concurrently.

TPU-first translation: the wire is the framework's own tensor wire format
(utils/wire.py) over a DCN-style TCP stream — this is the host-level feed
layer of the distribution story (intra-pod scale-out is jax collectives over
ICI, see parallel/).  A server pipeline typically batches client frames and
runs a mesh-sharded ``tensor_filter``, so one logical query server is a
pod-sharded service (north star: "tensor_query data-parallel pod sharding").

Protocol (all frames length-prefixed, utils/wire.read_frame/write_frame):

  client->server  JSON hello  {"type":"hello","caps":str,"topic":str}
  server->client  JSON ack    {"type":"ack","caps":str}
  client->server  tensor frame (wire buffer; meta["_query_msg"]=msg id)
  server->client  tensor frame (same msg id echoed in meta)
"""

from __future__ import annotations

import collections
import json
import queue as _queue
import random
import socket
import threading
import time

import numpy as np
from typing import Deque, Dict, Iterator, List, Optional, Tuple, Union

from ..core.buffer import Buffer, Event
from ..core.caps import Caps
from ..core.log import logger, metrics
from ..core import meta_keys
from ..core.registry import register_element
from ..utils import elastic, tracing as _tracing, wire
from ..utils.armor import META_POISON
from ..utils.net import (TcpListener, client_handshake, parse_control,
                         server_handshake)
from .base import Element, ElementError, SourceElement, SinkElement, SRC

log = logger(__name__)

# Protocol meta keys are declared once in core/meta_keys.py (the nns-proto
# lint's alphabet source of truth); the short module aliases below keep
# call sites readable.
_META_MSG = meta_keys.META_QUERY_MSG
_META_CONN = meta_keys.META_QUERY_CONN
#: journal seqno of an accepted request (docs/ROBUSTNESS.md): stamped by
#: the serversrc reader when a request journal is configured, consumed
#: (ack + strip) by the serversink when the answer leaves
_META_JSEQ = meta_keys.META_JOURNAL_SEQ
#: marks a buffer re-admitted by journal replay (its original
#: connection died with the previous process; the serversink acks it
#: as answered instead of warning about the missing conn)
_META_REPLAY = meta_keys.META_JOURNAL_REPLAY
#: tenant identity riding the wire meta (core/meta_keys.META_TENANT):
#: stamped by the client (``tenant=`` prop / appsrc / hello fallback),
#: read by the server for per-tenant accounting + admission decisions
_META_TENANT = meta_keys.META_TENANT
#: serversrc batching: list of per-request meta dicts riding one stacked
#: buffer; serversink splits output rows back to each client.
_META_BATCH = meta_keys.META_QUERY_BATCH
# server verdict / streaming response flags (same registry)
_META_SHED = meta_keys.META_SHED
_META_WIRE_REJECT = meta_keys.META_WIRE_REJECT
_META_ERROR = meta_keys.META_ERROR
_META_ABORT = meta_keys.META_ABORT_REASON
_META_SIDX = meta_keys.META_STREAM_INDEX
_META_SLAST = meta_keys.META_STREAM_LAST
_META_SABORT = meta_keys.META_STREAM_ABORTED
_META_TQ = meta_keys.META_ENQUEUE_NS
#: distributed trace context (nns-weave, docs/OBSERVABILITY.md): the
#: client's epoch-prefixed trace id rides requests as _tparent, is
#: adopted server-side as the trace id (after the _tid scrub below) and
#: echoed on every response/token so both rings share one id
_META_TID = meta_keys.META_TRACE_ID
_META_TPARENT = meta_keys.META_TRACE_PARENT

#: Placeholder in ``_done`` for a fully-streamed request: advances the
#: in-order cursor without emitting (its buffers already went downstream).
_STREAM_DONE = object()

# Server cores shared between a serversrc and its serversink, keyed by the
# ``id`` property (reference: query server data registry paired by server id).
_servers: Dict[int, "_ServerCore"] = {}
_servers_lock = threading.Lock()


class _ServerCore:
    """TCP listener + per-connection readers feeding one inbound queue.

    The serversrc drains ``inbound``; the serversink routes responses back
    through ``send()`` using the connection id stamped into buffer meta
    (the GstMetaQuery analog).

    **Admission control** (docs/SERVING.md "Front door"): ``max_backlog``
    bounds the inbound queue; when it is full the ``admission`` policy
    decides what happens instead of the reader blocking the TCP stream
    behind an unbounded backlog:

    * ``block`` — the pre-admission behavior: the reader stalls until
      space frees (TCP backpressure propagates to the client's send);
    * ``shed`` — the request is DROPPED and the client receives an
      immediate empty response with ``meta["shed"]=True`` (same msg id),
      so it is never left waiting out its timeout.  Every shed is
      counted (``query_server.shed``, split per tenant) and
      span-stamped ``admit.shed`` with the victim's trace id;
    * ``downgrade`` — the request moves to a bounded LOW-PRIORITY lane
      drained only when the main queue is empty (counted as
      ``query_server.downgraded`` + ``admit.downgrade`` span); if the
      low lane is also full, it sheds as above.
    """

    _GUARDED_BY = {"_conns": "_lock", "_conn_locks": "_lock",
                   "_conn_tenants": "_lock", "_next_conn": "_lock"}

    def __init__(self, host: str, port: int, topic: str = "",
                 max_backlog: int = 256, admission: str = "block",
                 on_admit_event=None, send_buf: int = 0, journal=None):
        self.topic = topic
        self.admission = admission
        self.max_backlog = max_backlog
        #: durable request journal (utils/journal.Journal, or None):
        #: accepted requests append their wire payload BEFORE entering
        #: the pipeline; the serversink acks the entry when the answer
        #: leaves — docs/ROBUSTNESS.md "Durable request journal"
        self.journal = journal
        #: per-tenant admission OVERRIDE (tenant -> "shed"|"downgrade"):
        #: the autoscaler's host-value lever (utils/elastic.Autoscaler
        #: ``admission:`` action) — a burning tenant class can be
        #: flipped to shed while everyone else keeps the configured
        #: policy, and flipped back when its burn rate recovers
        self.tenant_admission: Dict[str, str] = {}
        #: per-connection SO_SNDBUF (0 = OS default).  Bounds how much
        #: of a wedged client's unread response stream the kernel
        #: absorbs before sends hit the socket timeout and the
        #: connection is dropped (the wedge_tenant chaos profile).
        self.send_buf = int(send_buf)
        self.inbound: _queue.Queue = _queue.Queue(maxsize=max_backlog)
        self.lowprio: _queue.Queue = _queue.Queue(maxsize=max_backlog)
        #: serversrc hook: called as (kind, buf, backlog) for every
        #: "shed"/"downgrade" decision (span stamping with the element's
        #: own recorder — the core stays pipeline-agnostic)
        self.on_admit_event = on_admit_event
        self._conns: Dict[int, socket.socket] = {}
        self._conn_locks: Dict[int, threading.Lock] = {}
        self._conn_tenants: Dict[int, str] = {}
        self._next_conn = 0
        self._lock = threading.Lock()
        self._listener = TcpListener(host, port, self._reader, name="query")
        self.port = self._listener.port

    @property
    def _stopping(self) -> threading.Event:
        return self._listener.stopping

    def _reader(self, conn: socket.socket) -> None:
        hello = server_handshake(conn, "hello", self.topic)
        if hello is None:
            log.warning("query: connection rejected at handshake")
            return
        conn.settimeout(0.2)
        if self.send_buf > 0:
            try:
                conn.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF,
                                self.send_buf)
            except OSError:
                pass
        conn_tenant = str(hello.get("tenant", "") or "") or None
        with self._lock:
            cid = self._next_conn
            self._next_conn += 1
            self._conns[cid] = conn
            self._conn_locks[cid] = threading.Lock()
            if conn_tenant is not None:
                self._conn_tenants[cid] = conn_tenant
        try:
            while not self._stopping.is_set():
                try:
                    raw = wire.read_frame(conn)
                except socket.timeout:
                    continue
                except wire.WireError as e:
                    # FRAMING-level violation (forged length / CRC
                    # mismatch): the byte stream can no longer be
                    # trusted to resync — count it and drop the
                    # connection.  Payload-level violations below are
                    # recoverable per frame.
                    self._wire_reject(cid, None, conn_tenant, e,
                                      fatal=True)
                    return
                if raw is None:
                    return
                ctrl = parse_control(raw)
                if ctrl is not None:
                    # post-handshake JSON control frame.  Today's only
                    # kind: the nns-weave clock echo (a traced client
                    # refreshes its offset estimate mid-connection);
                    # unknown kinds are ignored for forward compat.
                    if ctrl.get("type") == "clock" \
                            and isinstance(ctrl.get("t0"), int):
                        self.send(cid, json.dumps(
                            {"type": "clock_ack", "t0": ctrl["t0"],
                             "t1": time.monotonic_ns(),
                             "epoch": _tracing.trace_epoch(),
                             "t2": time.monotonic_ns()}).encode("utf-8"))
                    continue
                try:
                    buf, _flags = wire.decode_buffer(raw)
                except wire.WireError as e:
                    # ONE malformed frame must not tear down the whole
                    # connection: answer a typed reject (best-effort
                    # msg-id salvage so the client's slot resolves
                    # instead of timing out) and keep reading.
                    self._wire_reject(cid, raw, conn_tenant, e)
                    continue
                # stream ids are SERVER-minted (filters/llm.py submit
                # overwrites them): a client-supplied value would let one
                # tenant cancel another's live stream through the
                # dead-connection backchannel
                buf.meta.pop(elastic.META_STREAM_ID, None)
                # same trust boundary for the armor/journal plumbing
                # keys: never client-suppliable ("_poison" would let a
                # tenant bypass stage invokes AND force an inflight
                # flush per request on every batching stage)
                buf.meta.pop(_META_JSEQ, None)
                buf.meta.pop(_META_REPLAY, None)
                buf.meta.pop(META_POISON, None)
                # distributed trace context: a client-stamped _tid is
                # NEVER trusted (it would alias this server's own ids);
                # the _tparent context is adopted as the server-side
                # trace id only while tracing is active, and restored so
                # it rides every response back.  Off mode: scrub only,
                # zero stamps.
                buf.meta.pop(_META_TID, None)
                tparent = buf.meta.pop(_META_TPARENT, None)
                if _tracing.recorder.active \
                        and isinstance(tparent, int) \
                        and 0 < tparent < (1 << 63):
                    buf.meta[_META_TID] = tparent
                    buf.meta[_META_TPARENT] = tparent
                frame_had_tenant = _META_TENANT in buf.meta
                if conn_tenant is not None:
                    # per-frame meta wins; the hello tenant is the
                    # per-connection fallback
                    buf.meta.setdefault(_META_TENANT, conn_tenant)
                metrics.count("query_server.in",
                              tenant=buf.meta.get(_META_TENANT))
                if self.journal is not None:
                    # journal BEFORE admission: an accepted request must
                    # be durable before any work happens on it.  A shed
                    # decision acks immediately below (it was answered).
                    # A hello-fallback tenant is stamped into the
                    # journaled payload (re-encode) — a replayed entry
                    # must keep its tenant identity for quota/SLO/
                    # breaker attribution even though the original
                    # frame bytes lack the key.  The conn id is NOT
                    # stamped yet, so the record stays connection-free.
                    tenant = buf.meta.get(_META_TENANT)
                    jraw = (wire.encode_buffer(buf)
                            if (tenant is not None
                                and not frame_had_tenant) else raw)
                    seq = self.journal.append(jraw, tenant=tenant)
                    if seq:  # 0 = journal already closed (shutdown)
                        buf.meta[_META_JSEQ] = seq
                        if self.on_admit_event is not None:
                            self.on_admit_event("journal", buf, seq)
                buf.meta[_META_CONN] = cid
                self._admit(buf)
        finally:
            self.drop_conn(cid)

    def _wire_reject(self, cid: int, raw: Optional[bytes], conn_tenant,
                     err: wire.WireError, fatal: bool = False) -> None:
        """Count + answer one rejected wire frame (docs/ROBUSTNESS.md).
        ``fatal`` marks framing-level violations, where no answer can be
        routed (the stream is desynced) and the caller drops the
        connection."""
        meta = wire.salvage_meta(raw) if raw is not None else None
        tenant = ((meta or {}).get(_META_TENANT) or conn_tenant)
        metrics.count("query_server.wire_rejects", tenant=tenant)
        log.warning("query: rejected wire frame from conn %d "
                    "(tenant=%s%s): %s", cid, tenant,
                    ", connection dropped" if fatal else "", err)
        if self.on_admit_event is not None:
            victim = Buffer([], meta=dict(meta or {}))
            if tenant is not None:
                victim.meta.setdefault(_META_TENANT, tenant)
            self.on_admit_event("wire_reject", victim,
                                str(err)[:200])
        if fatal:
            return
        mid = (meta or {}).get(_META_MSG)
        if mid is None:
            return  # nothing to route the reject to
        notice = Buffer([], meta={
            _META_MSG: mid, _META_WIRE_REJECT: True,
            _META_ABORT: meta_keys.ABORT_REASON_WIRE,
            _META_ERROR: str(err)[:200]})
        if tenant is not None:
            notice.meta[_META_TENANT] = tenant
        self.send(int(cid), wire.encode_buffer(notice))

    # -- admission ---------------------------------------------------------
    def backlog(self) -> int:
        return self.inbound.qsize() + self.lowprio.qsize()

    def _admit(self, buf: Buffer) -> str:
        """Admit one request per the (tenant-overridable) policy;
        returns the decision: ``"ok"`` | ``"downgrade"`` | ``"shed"``."""
        # per-tenant override first (the autoscaler's admission action),
        # then the element-configured policy
        policy = self.admission
        tenant = buf.meta.get(_META_TENANT)
        if tenant is not None and self.tenant_admission:
            policy = self.tenant_admission.get(tenant, policy)
        if policy == "shed-all":
            # the armor circuit breaker's override (docs/ROBUSTNESS.md):
            # a repeat poison offender is shed UNCONDITIONALLY, not just
            # under backlog pressure like the autoscaler's "shed"
            self._shed(buf)
            metrics.gauge("query_server.backlog", float(self.backlog()))
            return "shed"
        if policy == "block":
            while not self._stopping.is_set():
                try:
                    self.inbound.put(buf, timeout=0.1)
                    break
                except _queue.Full:
                    continue
            metrics.gauge("query_server.backlog", float(self.backlog()))
            return "ok"
        decision = "ok"
        try:
            self.inbound.put_nowait(buf)
        except _queue.Full:
            if policy == "downgrade":
                try:
                    self.lowprio.put_nowait(buf)
                except _queue.Full:
                    self._shed(buf)
                    decision = "shed"
                else:
                    decision = "downgrade"
                    metrics.count("query_server.downgraded",
                                  tenant=buf.meta.get(_META_TENANT))
                    if self.on_admit_event is not None:
                        self.on_admit_event("downgrade", buf,
                                            self.backlog())
            else:
                self._shed(buf)
                decision = "shed"
        metrics.gauge("query_server.backlog", float(self.backlog()))
        return decision

    def _shed(self, buf: Buffer) -> None:
        """Drop one request at admission: count it per tenant, notify the
        serversrc (span), and answer the client immediately with an empty
        ``shed`` response so its slot never waits out the timeout."""
        tenant = buf.meta.get(_META_TENANT)
        metrics.count("query_server.shed", tenant=tenant)
        if self.on_admit_event is not None:
            self.on_admit_event("shed", buf, self.backlog())
        seq = buf.meta.get(_META_JSEQ)
        if seq is not None and self.journal is not None:
            # a shed IS the answer: the journal entry must not replay
            self.journal.ack(int(seq))
        cid = buf.meta.get(_META_CONN)
        mid = buf.meta.get(_META_MSG)
        if cid is None or mid is None:
            return  # nothing to answer (not a query-framed request)
        notice = Buffer([], meta={_META_MSG: mid, _META_SHED: True})
        if tenant is not None:
            notice.meta[_META_TENANT] = tenant
        self.send(int(cid), wire.encode_buffer(notice))

    def pop_request(self, timeout: float) -> Optional[Buffer]:
        """Next admitted request: the main queue strictly first, the
        low-priority lane only when the main queue is empty."""
        try:
            return self.inbound.get(timeout=timeout)
        except _queue.Empty:
            try:
                return self.lowprio.get_nowait()
            except _queue.Empty:
                return None

    def send(self, cid: int, payload: bytes) -> bool:
        with self._lock:
            conn = self._conns.get(cid)
            lk = self._conn_locks.get(cid)
        if conn is None:
            return False
        try:
            with lk:
                wire.write_frame(conn, payload)
            return True
        except OSError:
            self.drop_conn(cid)
            return False

    def drop_conn(self, cid: int) -> None:
        with self._lock:
            conn = self._conns.pop(cid, None)
            self._conn_locks.pop(cid, None)
            self._conn_tenants.pop(cid, None)
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass

    def close(self) -> None:
        self._listener.close()
        with self._lock:
            conns = list(self._conns)
        for cid in conns:
            self.drop_conn(cid)


def _get_server(sid: int) -> Optional[_ServerCore]:
    with _servers_lock:
        return _servers.get(sid)


@register_element("tensor_query_serversrc")
class TensorQueryServerSrc(SourceElement):
    """Listen for query clients; push received tensors into the pipeline.

    Props: ``host`` (default 127.0.0.1), ``port`` (0 = OS-assigned; read the
    bound port via ``.bound_port``), ``id`` (pairs with the serversink of the
    same id), ``topic`` (optional capability filter), ``admission``
    (``block`` | ``shed`` | ``downgrade`` — what happens when the inbound
    backlog reaches ``max-backlog``; see :class:`_ServerCore` and
    docs/SERVING.md "Front door"), ``max-backlog`` (inbound queue bound,
    default 256).

    **Dynamic batching** (TPU-first; no reference analog — the reference
    serves one request per invoke): ``max-batch=N`` with
    ``batch-window-ms=W`` collects up to N concurrent client requests
    (first arrival opens a W-ms window), stacks them along a new leading
    batch axis, and emits ONE buffer — the downstream filter runs a single
    batched fused invoke instead of N sequential ones, which is how the
    MXU wants to be fed.  ``batch-pad=true`` (default) pads partial groups
    to N by repeating the last row so XLA sees one static shape (no
    recompile churn); the serversink drops padded rows.  Only
    same-shape/dtype requests share a group; a mismatch flushes the group.
    Requires the served model to be batch-leading and the pipeline's
    filter to accept [N, ...] inputs.  Streaming filters compose too:
    an ``llm`` filter behind ``max-batch=N`` decodes N concurrent
    same-length prompts in ONE lax.scan loop and streams each client its
    own row of every token (ids only when batched — per-row byte pieces
    are not batch-leading; clients detokenize ids themselves).
    """

    kind = "tensor_query_serversrc"

    def __init__(self, props=None, name=None):
        super().__init__(props, name)
        self.host = str(self.props.get("host", "127.0.0.1"))
        self.port = int(self.props.get("port", 0))
        self.sid = int(self.props.get("id", 0))
        self.topic = str(self.props.get("topic", ""))
        self.max_batch = int(self.props.get("max_batch", 1))
        self.batch_window_s = float(self.props.get("batch_window_ms", 2.0)) / 1e3
        self.batch_pad = bool(self.props.get("batch_pad", True))
        if self.max_batch < 1:
            raise ElementError(f"{self.name}: max-batch must be >= 1")
        self.admission = str(self.props.get("admission", "block")).lower()
        if self.admission not in ("block", "shed", "downgrade"):
            raise ElementError(
                f"{self.name}: admission must be block|shed|downgrade, "
                f"got {self.admission!r}")
        self.max_backlog = int(self.props.get("max_backlog", 256))
        if self.max_backlog < 1:
            raise ElementError(f"{self.name}: max-backlog must be >= 1")
        # ``send-buf`` bounds per-connection kernel send buffering (0 =
        # OS default); see _ServerCore.send_buf
        self.send_buf = int(self.props.get("send_buf", 0))
        # Durable request journal (docs/ROBUSTNESS.md): ``journal=DIR``
        # appends every accepted request's wire payload to a
        # segment-rotated CRC'd WAL before the pipeline sees it;
        # ``journal-fsync=off|batch|always`` picks the durability/
        # latency trade; ``journal-replay=true`` (or the pipeline-level
        # ``Pipeline(journal_replay=True)`` attach) re-admits the
        # accepted-but-unanswered entries at start().
        self.journal_dir = str(self.props.get("journal", "") or "")
        self.journal_fsync = str(
            self.props.get("journal_fsync", "batch")).lower()
        self.journal_segment_bytes = int(
            self.props.get("journal_segment_bytes", 8 << 20))
        self.journal_replay = bool(self.props.get("journal_replay",
                                                  False))
        if self.journal_dir:
            from ..utils.journal import FSYNC_MODES

            if self.journal_fsync not in FSYNC_MODES:
                raise ElementError(
                    f"{self.name}: journal-fsync must be one of "
                    f"{FSYNC_MODES}, got {self.journal_fsync!r}")
        self._journal = None
        self._core: Optional[_ServerCore] = None
        self._carry: Optional[Buffer] = None  # shape-mismatch pushback
        #: journal-replay buffers awaiting re-admission, drained FIRST
        #: by generate() (normal backpressure — see _replay_journal)
        self._replay: Deque[Buffer] = collections.deque()

    def _on_admit_event(self, kind: str, buf: Buffer, detail) -> None:
        """Span-stamp one admission decision with the victim's trace id
        (minted here when the client did not send one) — follows THIS
        pipeline's trace mode via the element-pinned recorder.  Beside
        the shed/downgrade decisions, the core reports ``journal``
        (detail = the appended seqno -> ``journal.append`` span) and
        ``wire_reject`` (counted only; no taxonomy span)."""
        if kind == "wire_reject":
            return  # counted in query_server.wire_rejects; no span kind
        tracer = getattr(self, "_trace_rec", None)
        if tracer is None:
            return
        if kind == "journal":
            args = {"seq": detail}
            ten = buf.meta.get(_META_TENANT)
            if ten is not None:
                args["tenant"] = ten
            tracer.record("journal.append", self.name,
                          buf.meta.get("_tid"), time.monotonic_ns(), 0,
                          **args)
            return
        tid = buf.meta.get("_tid")
        if tid is None:
            from ..utils import tracing as _tracing

            # stamp the minted id back onto the buffer: a DOWNGRADED
            # request flows on into the pipeline, and ingress reuses a
            # pre-existing _tid — so the admission span and the request's
            # later spans share one timeline
            tid = buf.meta["_tid"] = _tracing.next_trace_id()
        args = {"msg": buf.meta.get(_META_MSG), "backlog": detail}
        ten = buf.meta.get(_META_TENANT)
        if ten is not None:
            args["tenant"] = ten
        tracer.record(f"admit.{kind}", self.name, tid,
                      time.monotonic_ns(), 0, **args)

    def start(self) -> None:
        with _servers_lock:
            if self.sid in _servers:
                raise ElementError(f"query server id={self.sid} already running")
        if self.journal_dir:
            from ..utils.journal import Journal

            self._journal = Journal(
                self.journal_dir, fsync=self.journal_fsync,
                segment_bytes=self.journal_segment_bytes)
        try:
            core = _ServerCore(self.host, self.port, topic=self.topic,
                               max_backlog=self.max_backlog,
                               admission=self.admission,
                               on_admit_event=self._on_admit_event,
                               send_buf=self.send_buf,
                               journal=self._journal)
            with _servers_lock:
                if self.sid in _servers:  # lost a construction race
                    core.close()
                    raise ElementError(
                        f"query server id={self.sid} already running")
                _servers[self.sid] = core
        except BaseException:
            # a failed bind / lost sid race must not leak the opened
            # journal (segment fd + the fsync=batch flusher thread)
            if self._journal is not None:
                self._journal.close()
                self._journal = None
            raise
        self._core = core
        # journal replay BEFORE any new connection's traffic: the
        # previous process's accepted-but-unanswered requests re-enter
        # the inbound queue exactly once (seqno dedup in the journal)
        if self._journal is not None and (
                self.journal_replay
                or getattr(self, "_journal_replay", False)):
            self._replay_journal()

    def _replay_journal(self) -> None:
        """Stage the journal's recovery snapshot for :meth:`generate`.

        Two deliberate properties (docs/ROBUSTNESS.md): the source is
        the snapshot ``Journal.__init__`` captured BEFORE the listener
        existed — a reconnected client's resend, accepted once the
        port is live again, is a new entry and can never be admitted a
        second time by a later directory re-scan — and the buffers are
        handed to the source's own ``generate`` loop rather than the
        bounded inbound queue, so a backlog of unanswered entries
        larger than ``max-backlog`` drains through normal pipeline
        backpressure instead of deadlocking ``start()`` with no runner
        thread alive to consume the queue."""
        from ..utils import wire as _wire

        replayed = skipped = 0
        for seq, payload in self._journal.recovered_unanswered:
            try:
                buf, _flags = _wire.decode_buffer(payload)
            except _wire.WireError as e:
                # CRC'd journal bytes failing the (possibly tightened)
                # wire limits: ack + skip, never crash the restart
                log.warning("%s: journal entry %d unreplayable (%s); "
                            "acked as dropped", self.name, seq, e)
                self._journal.ack(seq)
                skipped += 1
                continue
            buf.meta.pop(_META_CONN, None)  # the old conn died with the
            buf.meta.pop(elastic.META_STREAM_ID, None)  # old process
            # the live reader's trust boundary applies to REPLAYED
            # bytes too: the journal may hold the original frame's
            # meta verbatim, and a client-minted poison marker must
            # not ride back in and retire the entry unprocessed
            buf.meta.pop(META_POISON, None)
            buf.meta[_META_JSEQ] = seq
            buf.meta[_META_REPLAY] = True
            metrics.count("query_server.replayed",
                          tenant=buf.meta.get(_META_TENANT))
            replayed += 1
            self._replay.append(buf)
        # release the snapshot's payload bytes: staged buffers hold the
        # only copy now (a large window must not stay pinned twice)
        self._journal.recovered_unanswered = []
        if replayed or skipped:
            log.info("%s: journal replay re-admitted %d unanswered "
                     "request(s) (%d unreplayable)", self.name,
                     replayed, skipped)
        tracer = getattr(self, "_trace_rec", None)
        if tracer is not None:
            tracer.record("journal.replay", self.name, None,
                          time.monotonic_ns(), 0, entries=replayed,
                          acked_skipped=skipped)

    def stop(self) -> None:
        # Idempotent: after the first stop ``self._core`` is None, and
        # ``_servers.get(sid) is None`` must NOT match it (that del
        # raised KeyError on double-stop before the elastic PR).
        with _servers_lock:
            if self._core is not None \
                    and _servers.get(self.sid) is self._core:
                del _servers[self.sid]
        if self._core is not None:
            self._core.close()
            self._core = None
        # undrained replay buffers stay unanswered in the journal and
        # simply replay again on the next start
        self._replay.clear()
        if self._journal is not None:
            self._journal.close()
            self._journal = None

    @property
    def bound_port(self) -> int:
        if self._core is None:
            raise ElementError("serversrc not started")
        return self._core.port

    def generate(self) -> Iterator[Union[Buffer, Event]]:
        stop = getattr(self, "_stop_event", threading.Event())
        while not stop.is_set():
            first = self._carry
            self._carry = None
            if first is None and self._replay:
                # journal-replayed requests re-admit ahead of new
                # traffic, through the same (batching) path
                first = self._replay.popleft()
            if first is None:
                first = self._core.pop_request(timeout=0.1)
                if first is None:
                    continue
            if self.max_batch <= 1:
                yield first
                continue
            yield self._collect_group(first)

    @staticmethod
    def _sig(buf: Buffer):
        sig = []
        for t in buf.tensors:
            a = np.asarray(t)
            sig.append((a.shape, a.dtype.str))
        return tuple(sig)

    def _collect_group(self, first: Buffer) -> Buffer:
        """Stack up to max-batch same-shape requests arriving within the
        window opened by ``first`` into one batch-leading buffer."""
        stop = getattr(self, "_stop_event", threading.Event())
        group = [first]
        sig = self._sig(first)
        deadline = time.monotonic() + self.batch_window_s
        while len(group) < self.max_batch and not stop.is_set():
            # 0.1s slices keep shutdown responsive inside a long window.
            remaining = min(0.1, deadline - time.monotonic())
            if remaining <= 0:
                break
            nxt = self._core.pop_request(timeout=remaining)
            if nxt is None:
                continue
            if self._sig(nxt) != sig:
                self._carry = nxt  # different shape: flush, regroup next
                break
            group.append(nxt)
        valid = len(group)
        # occupancy = batched / (batch_groups * max_batch): how full the
        # dynamic batches actually run (serving-capacity observability).
        # Counted for EVERY flushed group — including batch-pad=false solo
        # flushes, where under-occupancy is precisely the signal.
        metrics.count("query_server.batched", valid)
        metrics.count("query_server.batch_groups")
        if valid == 1 and not self.batch_pad:
            return first
        rows = group
        if self.batch_pad and valid < self.max_batch:
            rows = group + [group[-1]] * (self.max_batch - valid)
        tensors = [
            np.stack([np.asarray(b.tensors[i]) for b in rows])
            for i in range(len(first.tensors))
        ]
        metas = [dict(b.meta) for b in group]
        return Buffer(tensors, pts=first.pts, meta={_META_BATCH: metas})


@register_element("tensor_query_serversink")
class TensorQueryServerSink(SinkElement):
    """Return each result buffer to the client connection recorded in its
    meta.  Props: ``id`` (matches the serversrc).

    **Dead-connection backchannel** (docs/SERVING.md "Elastic
    serving"): when a send fails because the client connection died and
    the buffer belongs to a continuous-serving token stream (it carries
    ``stream_index`` + ``stream_id`` meta), the sink cancels the stream
    through :func:`nnstreamer_tpu.utils.elastic.cancel_stream` — the
    serve loop reaps the orphaned slot and its KV blocks back to the
    free list after its ``stream_idle_timeout`` grace instead of
    decoding (and leaking pool capacity) until ``max_new`` runs out."""

    kind = "tensor_query_serversink"

    def __init__(self, props=None, name=None):
        super().__init__(props, name)
        self.sid = int(self.props.get("id", 0))
        self._cancelled_sids: set = set()  # dedupe per-token failures

    def _send_failed(self, meta: Dict) -> None:
        metrics.count(f"{self.name}.dropped")
        stream_id = meta.get(elastic.META_STREAM_ID)
        if _META_SIDX not in meta or stream_id is None \
                or stream_id in self._cancelled_sids:
            return
        if elastic.cancel_stream(stream_id, "dead-connection"):
            self._cancelled_sids.add(stream_id)
            if len(self._cancelled_sids) > 4096:  # bounded memory
                self._cancelled_sids.clear()
            metrics.count(f"{self.name}.streams_cancelled")

    @staticmethod
    def _ack_journal(core, meta: Dict, seq=None,
                     undeliverable: bool = False) -> bool:
        """Mark the request's journal entry answered — once: plain
        responses ack immediately, token streams ack on their final
        (``stream_last``/aborted) buffer only (``Journal.ack`` is
        additionally idempotent, so racing failure paths can't double-
        record).  ``undeliverable=True`` acks regardless of stream
        position: a DEAD client's entry must not pin the WAL's
        prefix GC forever — the answer was produced, the work is not
        lost, and replaying it to a vanished connection buys nothing
        (the reconnected client's resend is a new entry).  Returns
        True when an ack record was written."""
        if seq is None:
            seq = meta.get(_META_JSEQ)
        if seq is None or core.journal is None:
            return False
        if not undeliverable and _META_SIDX in meta \
                and not (meta.get(_META_SLAST)
                         or meta.get(_META_SABORT)):
            return False
        return core.journal.ack(int(seq))

    def process(self, pad, buf: Buffer):
        core = _get_server(self.sid)
        if core is None:
            raise ElementError(f"no query server with id={self.sid}")
        try:
            return self._process_routed(core, buf)
        except BaseException as e:
            # nns-proto unanswered-path: never let an exception strand a
            # routed client into a timeout — answer with a typed
            # ``abort_reason="internal"`` terminator first (double-answer
            # is safe: the client dedupes by msg id and journal acks are
            # idempotent, both model-checked by analysis/statemachine.py
            # exactly-once), then surface the error to the pipeline.
            self._abort_unanswered(core, buf.meta, e)
            raise

    def _process_routed(self, core, buf: Buffer):
        if _META_BATCH in buf.meta:
            return self._send_batched(core, buf)
        cid = buf.meta.get(_META_CONN)
        if cid is None:
            if buf.meta.get(_META_REPLAY) \
                    and buf.meta.get(_META_JSEQ) is not None:
                # journal-replayed request: its client connection died
                # with the previous process.  The answer is recorded
                # (acked) so a further restart never re-processes the
                # entry — the reconnected client's RESEND is a new
                # entry and gets its answer through the normal path.
                # Counted once per REQUEST (the ack write), not once
                # per token buffer of a replayed stream.
                if self._ack_journal(core, buf.meta):
                    metrics.count("query_server.replay_answered",
                                  tenant=buf.meta.get(_META_TENANT))
                return []
            log.warning("%s: buffer without query connection meta; dropped", self.name)
            metrics.count(f"{self.name}.dropped")
            return []
        out = buf.to_host()
        # Do not leak server-side routing or tracer-internal meta back to
        # the client (the queue-stamp map is this pipeline's plumbing).
        out.meta.pop(_META_CONN, None)
        out.meta.pop(_META_TQ, None)
        out.meta.pop(_META_REPLAY, None)
        out.meta.pop(META_POISON, None)  # the typed abort_reason stays
        jseq = out.meta.pop(_META_JSEQ, None)
        if core.send(int(cid), wire.encode_buffer(out)):
            metrics.count("query_server.out",
                          tenant=out.meta.get(_META_TENANT))
            self._reply_span(out.meta)
            self._ack_journal(core, out.meta, jseq)
        else:
            # undeliverable (client gone): ack anyway — the answer was
            # produced; an unacked entry would pin the WAL's prefix GC
            # forever and replay to nobody after the next restart
            self._ack_journal(core, out.meta, jseq, undeliverable=True)
            self._send_failed(out.meta)
        return []

    def _send_batched(self, core, buf: Buffer):
        """Split a dynamically batched result (serversrc ``max-batch``)
        back into one response row per originating request; padded rows
        (rows past the _META_BATCH list) are dropped.  One D2H for the whole
        batch, not one per client."""
        host = buf.to_host()
        metas = host.meta[_META_BATCH]
        tensors = [np.asarray(t) for t in host.tensors]
        for t in tensors:
            if t.ndim == 0 or t.shape[0] < len(metas):
                err = ElementError(
                    f"{self.name}: batched output leading dim "
                    f"{t.shape[:1] or None} < {len(metas)} batched requests "
                    "— the served model must be batch-leading for "
                    "serversrc max-batch")
                # nns-proto unanswered-path: a bare raise here would
                # strand len(metas) clients into timeouts.  Answer each
                # batched request with a typed internal abort, THEN
                # surface the config error.
                for m in metas:
                    self._abort_unanswered(core, m, err)
                raise err
        resp_meta = {k: v for k, v in host.meta.items()
                     if k not in (_META_BATCH, _META_CONN, _META_TQ,
                                  _META_JSEQ, _META_REPLAY,
                                  META_POISON)}
        for i, m in enumerate(metas):
            cid = m.get(_META_CONN)
            jseq = m.get(_META_JSEQ)
            if cid is None:
                if m.get(_META_REPLAY) and jseq is not None:
                    if self._ack_journal(core, m, jseq):
                        metrics.count("query_server.replay_answered",
                                      tenant=m.get(_META_TENANT))
                else:
                    metrics.count(f"{self.name}.dropped")
                continue
            out = Buffer([t[i] for t in tensors], pts=host.pts,
                         meta={**{k: v for k, v in m.items()
                                  if k not in (_META_CONN, _META_JSEQ,
                                               _META_REPLAY)},
                               **resp_meta})
            if core.send(int(cid), wire.encode_buffer(out)):
                metrics.count("query_server.out",
                              tenant=out.meta.get(_META_TENANT))
                self._reply_span(out.meta)
                self._ack_journal(core, out.meta, jseq)
            else:
                self._ack_journal(core, out.meta, jseq,
                                  undeliverable=True)
                self._send_failed(out.meta)
        return []

    def _reply_span(self, out_meta: dict) -> None:
        """``query.reply`` instant for one response/token frame that hit
        the wire — the server end of the nns-weave reply→recv flow
        arrow.  Off mode: the element-pinned recorder is None and this
        is one pointer check."""
        rec = getattr(self, "_trace_rec", None)
        if rec is None:
            return
        args = {"msg": out_meta.get(_META_MSG)}
        ten = out_meta.get(_META_TENANT)
        if ten is not None:
            args["tenant"] = ten
        rec.record("query.reply", self.name, out_meta.get(_META_TID),
                   time.monotonic_ns(), 0, **args)

    def _abort_unanswered(self, core, meta: dict,
                          err: BaseException) -> None:
        """Answer one routed request (or every row of a batch) with a
        typed ``stream_aborted`` / ``abort_reason="internal"`` terminator
        instead of leaving the client to wait out its timeout.  Best
        effort — the client may already be gone — and idempotent: a
        duplicate answer is deduped by msg id client-side and the
        journal ack is a no-op the second time."""
        if _META_BATCH in meta:
            for m in meta[_META_BATCH]:
                self._abort_unanswered(core, m, err)
            return
        cid = meta.get(_META_CONN)
        jseq = meta.get(_META_JSEQ)
        if cid is None or meta.get(_META_MSG) is None:
            # nothing to route an answer to; still release the WAL entry
            self._ack_journal(core, meta, jseq, undeliverable=True)
            return
        term = Buffer([], meta={
            k: v for k, v in meta.items()
            if k not in (_META_CONN, _META_JSEQ, _META_REPLAY,
                         _META_BATCH, _META_TQ, META_POISON)})
        term.meta[_META_SABORT] = True
        term.meta[_META_ABORT] = meta_keys.ABORT_REASON_INTERNAL
        term.meta[_META_ERROR] = str(err)[:200]
        if _META_SIDX in term.meta:
            term.meta[_META_SLAST] = True
        try:
            core.send(int(cid), wire.encode_buffer(term))
        except Exception:
            pass  # answering is best-effort; the error still propagates
        self._ack_journal(core, term.meta, jseq, undeliverable=True)
        metrics.count("query_server.aborted_internal",
                      tenant=term.meta.get(_META_TENANT))


@register_element("tensor_query_client")
class TensorQueryClient(Element):
    """Offload buffers to a query server; push responses downstream in
    request order.

    Props: ``host``/``port`` (server address) or ``hosts=h1:p1,h2:p2``
    (round-robin fan-out over several servers — the reference's coarse
    data-parallel offload, SURVEY §2.9), ``timeout`` (seconds a response
    may take before the timeout policy fires), ``max-in-flight``
    (pipelining window: requests outstanding before ``process`` blocks),
    ``topic``, ``on-timeout`` (``error`` | ``drop``), ``tenant`` (tenant
    identity rides the hello handshake AND every request's wire meta, so
    the server's per-tenant accounting and admission control attribute
    this client's traffic — docs/SERVING.md "Front door").

    A server under ``admission=shed`` may answer a request with an empty
    ``meta["shed"]=True`` response instead of a result; it is delivered
    downstream like any response (the app checks the flag) and counted in
    ``<name>.sheds``.

    Responses arrive on a receiver thread, are re-ordered by message id (the
    reference pairs via GstMetaQuery msg ids), and are pushed downstream
    **asynchronously** in request order — exactly the reference's "(async)
    edge event cb: result arrives -> push result downstream" (SURVEY §3.3).

    Streaming servers (an ``llm`` filter behind the query pair) return MANY
    responses per request, tagged ``stream_index`` with ``stream_last`` on
    the final one.  Streamed responses are delivered immediately in arrival
    order (tokens must not wait on the reorder cursor); request-order
    reordering applies to plain (one-response) requests only, so
    interleaving streamed and plain requests on one client trades strict
    cross-request ordering for live token delivery.  For a streamed
    request, ``timeout`` bounds the INTER-TOKEN gap (each arriving token is
    progress and re-arms the clock), not the total generation time; with
    ``on-timeout=drop`` an aborted stream is terminated downstream by an
    empty ``stream_last`` + ``stream_aborted`` buffer so aggregating
    consumers never hang.
    """

    kind = "tensor_query_client"
    wants_async_emit = True

    def __init__(self, props=None, name=None):
        super().__init__(props, name)
        self.host = str(self.props.get("host", "127.0.0.1"))
        self.port = int(self.props.get("port", 0))
        self.timeout = float(self.props.get("timeout", 10.0))
        self.window = int(self.props.get("max_in_flight", 8))
        self.topic = str(self.props.get("topic", ""))
        self.on_timeout = str(self.props.get("on_timeout", "error"))
        self.tenant = str(self.props.get("tenant", "") or "") or None
        # Reconnect policy (docs/SERVING.md "Elastic serving"):
        # ``reconnect=N`` (default 0 = legacy fail-fast) retries a lost
        # connection up to N times with CAPPED EXPONENTIAL BACKOFF +
        # FULL JITTER — delay_k ~ U(0, min(cap, base * 2^k)) — so a
        # churned server is not hit by a synchronized thundering herd
        # (the BENCH_SOAK_r01 churn profile's reconnect tail).  The same
        # policy retries the initial connect.  On a successful
        # reconnect, outstanding PLAIN requests are resent (the wire
        # protocol is stateless request/response); partially streamed
        # requests cannot resume and are terminated downstream with
        # ``stream_aborted``.  Counters: ``<name>.reconnects``,
        # ``<name>.reconnect_backoff_ms`` (cumulative backoff),
        # ``<name>.resends``.
        self.reconnect = max(0, int(self.props.get("reconnect", 0)))
        self.reconnect_base_ms = float(
            self.props.get("reconnect_base_ms", 20.0))
        self.reconnect_cap_ms = float(
            self.props.get("reconnect_cap_ms", 1000.0))
        self._sock: Optional[socket.socket] = None
        self._send_lock = threading.Lock()
        self._next_msg = 0
        self._emit_next = 0
        self._pending: Dict[int, Tuple[Buffer, float]] = {}  # id -> (orig, t_sent)
        self._done: Dict[int, Buffer] = {}  # msg id -> response awaiting its turn
        self._streaming: set = set()  # mids that have streamed >= 1 response
        self._aborted: set = set()  # timed-out streams: drop late tokens quietly
        self._cv = threading.Condition()
        # Serializes the pop-ready+feed step across the rx thread and the
        # timeout path so in-order delivery holds (never held with _cv).
        self._emit_lock = threading.Lock()
        self._rx_error: Optional[BaseException] = None
        self._socks: List[socket.socket] = []
        self._readers: List[threading.Thread] = []
        self._async_emit = None  # injected by the runtime (wants_async_emit)
        # nns-weave clock refresh watermark (monotonic seconds of the last
        # accepted handshake echo / probe ack on ANY connection)
        self._clock_last = 0.0

    #: seconds between NTP-style clock probes on an idle connection
    CLOCK_REFRESH_S = 5.0

    def _note_clock(self, clk) -> None:
        """Feed one clock sample (handshake echo or probe ack, shape
        ``{"epoch", "offset_ns", "uncertainty_ns"}``) into the
        element-pinned recorder and re-arm the refresh timer; records a
        ``clock.sync`` instant so the residual skew is visible in the
        trace, never hidden.  Off mode: the recorder is None and the
        sample is dropped (no state, no spans)."""
        if not isinstance(clk, dict):
            return
        self._clock_last = time.monotonic()
        rec = getattr(self, "_trace_rec", None)
        if rec is None:
            return
        rec.note_clock(clk["epoch"], clk["offset_ns"],
                       clk["uncertainty_ns"])
        rec.record("clock.sync", self.name, None, time.monotonic_ns(), 0,
                   peer_epoch=clk["epoch"], offset_ns=clk["offset_ns"],
                   uncertainty_ns=clk["uncertainty_ns"])

    def _maybe_clock_probe(self, sock) -> None:
        """Periodic clock refresh: on an idle rx tick, send a ``clock``
        control probe so long-lived connections track drift between the
        peer monotonic bases (the handshake echo only samples once).
        Off mode: one pointer check."""
        if getattr(self, "_trace_rec", None) is None:
            return
        if time.monotonic() - self._clock_last < self.CLOCK_REFRESH_S:
            return
        self._clock_last = time.monotonic()  # re-arm even if the send fails
        probe = json.dumps({"type": "clock", "t0": time.monotonic_ns(),
                            "epoch": _tracing.trace_epoch()}).encode("utf-8")
        try:
            with self._send_lock:
                if self._socks:
                    wire.write_frame(sock, probe)
        except OSError:
            pass  # a dead socket is the reconnect machinery's problem

    def _handle_clock_ack(self, ctrl: dict) -> None:
        """Consume a ``clock_ack`` control frame (t0 echo + server
        receive/send stamps + server trace epoch) into a clock sample."""
        if ctrl.get("type") != "clock_ack":
            return
        t0, t1 = ctrl.get("t0"), ctrl.get("t1")
        t2, epoch = ctrl.get("t2"), ctrl.get("epoch")
        if not all(isinstance(v, int) for v in (t0, t1, t2, epoch)):
            return
        off, unc = _tracing.clock_offset(t0, t1, t2, time.monotonic_ns())
        self._note_clock({"epoch": epoch, "offset_ns": off,
                         "uncertainty_ns": unc})

    def _destinations(self) -> List[Tuple[str, int]]:
        """``hosts="h1:p1,h2:p2"`` (round-robin fan-out, the reference's
        coarse data-parallel offload — SURVEY §2.9) or single host/port."""
        spec = str(self.props.get("hosts", "") or "")
        if not spec:
            if self.port <= 0:
                raise ElementError(f"{self.name}: port property required")
            return [(self.host, self.port)]
        dests = []
        for part in spec.split(","):
            host, _, port = part.strip().rpartition(":")
            try:
                dests.append((host or "127.0.0.1", int(port)))
            except ValueError:
                raise ElementError(
                    f"{self.name}: bad hosts entry {part!r} "
                    "(expected host:port)") from None
        return dests

    def _backoff_sleep(self, attempt: int) -> bool:
        """One capped-exponential full-jitter backoff slice; returns
        False when the pipeline is stopping (abort the retry loop)."""
        delay = random.uniform(0.0, min(
            self.reconnect_cap_ms,
            self.reconnect_base_ms * (1 << min(attempt, 16)))) / 1e3
        metrics.count(f"{self.name}.reconnect_backoff_ms", delay * 1e3)
        stop = getattr(self, "_stop_event", None)
        if stop is not None:
            return not stop.wait(delay)
        time.sleep(delay)
        return True

    def _connect_one(self, host: str, port: int, retries: int,
                     backoff_first: bool = False):
        """``create_connection`` + handshake with the backoff policy;
        returns the connected socket or raises the last error (returns
        None only when the pipeline started stopping mid-backoff)."""
        last: Optional[Exception] = None
        for attempt in range(retries + 1):
            if (attempt or backoff_first) and \
                    not self._backoff_sleep(attempt - (0 if backoff_first
                                                       else 1)):
                return None
            if backoff_first and self._sock is None:
                return None  # stop() ran mid-outage
            try:
                sock = socket.create_connection((host, port), timeout=5.0)
            except OSError as e:
                last = e
                continue
            try:
                hello_fields = dict(caps="other/tensors", topic=self.topic)
                if self.tenant is not None:
                    hello_fields["tenant"] = self.tenant
                ack = client_handshake(sock, "hello", **hello_fields)
            except (ConnectionError, OSError) as e:
                # OSError covers a handshake-phase socket.timeout; close
                # the half-open socket before retrying.
                try:
                    sock.close()
                except OSError:
                    pass
                last = e
                continue
            sock.settimeout(0.2)
            # handshake-piggybacked clock echo (client_handshake
            # synthesizes ack["clock"] from a weave-aware server's stamps)
            self._note_clock(ack.get("clock"))
            return sock
        raise last if last is not None else ElementError(
            f"{self.name}: cannot connect {host}:{port}")

    def start(self) -> None:
        self._socks = []
        self._readers = []
        for host, port in self._destinations():
            try:
                sock = self._connect_one(host, port, self.reconnect)
            except (OSError, ConnectionError) as e:
                self.stop()
                raise ElementError(
                    f"{self.name}: cannot connect {host}:{port}: {e}"
                ) from e
            if sock is None:  # stopping mid-backoff
                self.stop()
                return
            self._socks.append(sock)
        self._sock = self._socks[0]  # back-compat for single-dest callers
        for i, sock in enumerate(self._socks):
            t = threading.Thread(
                target=self._rx_loop, args=(sock, i),
                name=f"{self.name}-rx{i}", daemon=True,
            )
            t.start()
            self._readers.append(t)

    def stop(self) -> None:
        socks, self._socks = getattr(self, "_socks", []), []
        self._sock = None
        for sock in socks:
            try:
                sock.close()
            except OSError:
                pass
        for t in getattr(self, "_readers", []):
            t.join(timeout=2.0)
        self._readers = []

    def _rx_loop(self, sock, idx: int = 0) -> None:
        while True:
            if self._sock is None:  # stop() ran
                return
            try:
                raw = wire.read_frame(sock)
            except socket.timeout:
                self._maybe_clock_probe(sock)
                continue
            except OSError:
                raw = None
            except ValueError as e:  # corrupt frame (CRC mismatch)
                with self._cv:
                    self._rx_error = e
                    self._cv.notify_all()
                return
            if raw is None:
                stop = getattr(self, "_stop_event", None)
                if (self.reconnect > 0 and self._sock is not None
                        and (stop is None or not stop.is_set())):
                    nsock = self._try_reconnect(idx)
                    if nsock is not None:
                        sock = nsock
                        continue
                with self._cv:
                    # Only requests ROUTED TO THIS SOCKET are lost when a
                    # server closes: a fan-out peer going away must not
                    # poison requests pending on healthy servers.  With
                    # reconnect enabled, a reader that EXHAUSTED its
                    # retries is gone for good — record the error even
                    # with nothing pending, or a later send would park
                    # its request forever waiting on a dead reader.
                    n = max(1, len(self._socks))
                    mine = any(m % n == idx for m in self._pending)
                    if (mine or self.reconnect > 0) \
                            and self._rx_error is None:
                        self._rx_error = ConnectionError("query server closed connection")
                    self._cv.notify_all()
                return
            ctrl = parse_control(raw)
            if ctrl is not None:  # clock_ack etc.; never a tensor frame
                self._handle_clock_ack(ctrl)
                continue
            try:
                buf, _flags = wire.decode_buffer(raw)
            except ValueError as e:
                with self._cv:
                    self._rx_error = e
                    self._cv.notify_all()
                return
            try:
                self._handle_response(buf)
            except Exception as e:  # noqa: BLE001 - any escape kills the reader
                # e.g. emit attempted while not attached to a pipeline: an
                # exception escaping here would silently kill the reader
                # thread and outstanding requests would only surface via
                # timeout — record it so _wait_outstanding reports promptly.
                with self._cv:
                    if self._rx_error is None:
                        self._rx_error = e
                    self._cv.notify_all()
                return

    def _try_reconnect(self, idx: int):
        """Replace socket ``idx`` after an outage: capped-exponential
        full-jitter backoff (see __init__), then resend this socket's
        outstanding plain requests and terminate its partial streams.
        Returns the new socket, or None when attempts are exhausted or
        the pipeline is stopping (caller falls through to the legacy
        connection-error path)."""
        dests = self._destinations()
        host, port = dests[idx % len(dests)]
        try:
            sock = self._connect_one(host, port, self.reconnect - 1,
                                     backoff_first=True)
        except (OSError, ConnectionError):
            return None
        if sock is None:
            return None
        with self._send_lock:
            if not self._socks:  # stop() ran while reconnecting
                try:
                    sock.close()
                except OSError:
                    pass
                return None
            old = self._socks[idx]
            self._socks[idx] = sock
            if idx == 0:
                self._sock = sock
        try:
            old.close()
        except OSError:
            pass
        metrics.count(f"{self.name}.reconnects")
        log.info("%s: reconnected to %s:%d", self.name, host, port)
        self._resend_pending(idx)
        return sock

    def _resend_pending(self, idx: int) -> None:
        """The died socket's outstanding requests: plain requests are
        RESENT on the fresh connection (stateless request/response — the
        server treats them as new; their timeout clock restarts), while
        partially streamed requests cannot resume server-side state and
        are terminated downstream exactly like the timeout-drop path."""
        with self._cv:
            n = max(1, len(self._socks))
            resend = []
            for mid in sorted(m for m in self._pending if m % n == idx):
                orig, _t = self._pending[mid]
                if mid in self._streaming:
                    self._pending.pop(mid)
                    self._streaming.discard(mid)
                    term = orig.with_tensors([])
                    term.meta.update({_META_SLAST: True,
                                      _META_SABORT: True})
                    self._done[mid] = term
                else:
                    self._pending[mid] = (orig, time.monotonic())
                    resend.append((mid, orig))
            self._cv.notify_all()
        for mid, orig in resend:
            orig.meta[_META_MSG] = mid
            payload = wire.encode_buffer(orig)
            orig.meta.pop(_META_MSG, None)
            try:
                with self._send_lock:
                    socks = self._socks
                    if not socks:
                        return
                    wire.write_frame(socks[mid % len(socks)], payload)
            except OSError:
                # the replacement died too: the rx loop will notice and
                # run the backoff again (or give up and surface the
                # connection error)
                return
            metrics.count(f"{self.name}.resends")
        self._drain_ready()

    def _handle_response(self, buf: Buffer) -> None:
        """Pair one received response with its request and deliver it.

        A server pipeline with a streaming filter (llm) returns MANY
        responses per request, each tagged stream_index and the final one
        stream_last (the buffers' own meta rides the wire).  Streamed
        responses are delivered in ARRIVAL order immediately — the
        per-request reorder machinery applies to plain responses (config
        #5: "tensor_filter + tensor_query" token streaming).
        """
        mid = int(buf.meta.pop(_META_MSG, -1))
        rec = getattr(self, "_trace_rec", None)
        if rec is not None:
            # ``query.recv`` instant, tid = the echoed parent context so
            # the merge links it to this request's client/server spans
            rec.record("query.recv", self.name,
                       buf.meta.get(_META_TPARENT), time.monotonic_ns(),
                       0, msg=mid)
        streamed = _META_SIDX in buf.meta
        emit_now: Optional[Buffer] = None
        with self._cv:
            entry = self._pending.get(mid)
            if entry is None:
                if mid in self._aborted:
                    # late tokens of a timed-out (dropped) stream
                    if buf.meta.get(_META_SLAST):
                        self._aborted.discard(mid)
                    metrics.count(f"{self.name}.late_dropped")
                else:
                    log.warning("%s: unmatched response msg=%d",
                                self.name, mid)
                return
            orig, _t = entry
            # Response keeps the request's timing identity.
            buf.pts = orig.pts
            buf.seqno = orig.seqno
            if streamed:
                # keep-alive: each token resets the request's timeout
                self._pending[mid] = (orig, time.monotonic())
                self._streaming.add(mid)
                if buf.meta.get(_META_SLAST):
                    self._pending.pop(mid)
                    self._streaming.discard(mid)
                    self._done[mid] = _STREAM_DONE
                emit_now = buf
            else:
                self._pending.pop(mid)
                self._done[mid] = buf
            if buf.meta.get(_META_SHED):
                # the server's admission control dropped this request and
                # answered immediately (docs/SERVING.md "Front door")
                metrics.count(f"{self.name}.sheds")
            abort_reason = buf.meta.get(_META_ABORT)
            if abort_reason == meta_keys.ABORT_REASON_POISON:
                # typed poison terminator (docs/ROBUSTNESS.md): the
                # request crashed a server stage and was quarantined
                metrics.count(f"{self.name}.poisoned")
            elif buf.meta.get(_META_WIRE_REJECT):
                # the server rejected this request's wire frame (typed
                # WireError) — delivered like any response so the app
                # sees abort_reason="wire" instead of a timeout
                metrics.count(f"{self.name}.wire_rejected")
            elif abort_reason is not None:
                # any other typed abort (e.g. "internal"): the server
                # chose answering over silence; its error detail rides
                # the response meta
                log.warning("%s: msg=%d aborted by server (%s): %s",
                            self.name, mid, abort_reason,
                            buf.meta.get(_META_ERROR, ""))
                metrics.count(f"{self.name}.aborted")
            metrics.count(f"{self.name}.responses")
            self._cv.notify_all()
        if emit_now is not None:
            with self._emit_lock:
                if self._async_emit is None:
                    raise ElementError(
                        f"{self.name}: not attached to a pipeline")
                self._async_emit([(SRC, emit_now)])
        self._drain_ready()

    def _drain_ready(self) -> None:
        """Atomically pop in-order completed responses and feed them
        downstream.  Holding ``_emit_lock`` across pop+feed means whichever
        thread pops the current head also delivers it before any other
        thread can pop later items — in-order delivery under concurrency."""
        with self._emit_lock:
            with self._cv:
                ready: List[Buffer] = []
                while self._emit_next in self._done:
                    b = self._done.pop(self._emit_next)
                    if b is not _STREAM_DONE:  # stream already delivered
                        ready.append(b)
                    self._emit_next += 1
                self._cv.notify_all()
            if not ready:
                return
            if self._async_emit is None:  # unit use outside a pipeline
                raise ElementError(f"{self.name}: not attached to a pipeline")
            self._async_emit([(SRC, b) for b in ready])

    def _wait_outstanding(self, below: int) -> None:
        """Block until fewer than ``below`` requests are outstanding,
        enforcing the per-request timeout policy on the head request."""
        stop = getattr(self, "_stop_event", None)
        while True:
            if stop is not None and stop.is_set():
                return  # pipeline stopping: abandon outstanding requests
            drain = False
            with self._cv:
                if self._rx_error is not None:
                    raise ElementError(f"{self.name}: {self._rx_error}")
                outstanding = len(self._pending) + len(self._done)
                if outstanding < below:
                    break
                entry = self._pending.get(self._emit_next)
                if entry is not None:
                    overdue = time.monotonic() - entry[1] - self.timeout
                    if overdue >= 0:
                        mid = self._emit_next
                        self._pending.pop(mid)
                        metrics.count(f"{self.name}.timeouts")
                        if self.on_timeout != "drop":
                            raise ElementError(
                                f"{self.name}: no response for request "
                                f"{mid} within {self.timeout}s"
                            )
                        log.warning("%s: request %d timed out; dropped",
                                    self.name, mid)
                        if mid in self._streaming:
                            # A partial stream already went downstream:
                            # terminate it so aggregating consumers never
                            # hang, and swallow late tokens quietly.  The
                            # terminator goes through _done so the drain
                            # emits it and advances the cursor itself.
                            self._streaming.discard(mid)
                            self._aborted.add(mid)
                            term = entry[0].with_tensors([])
                            term.meta.update({_META_SLAST: True,
                                              _META_SABORT: True})
                            self._done[mid] = term
                        else:
                            self._emit_next += 1
                        drain = True
                    else:
                        self._cv.wait(timeout=min(-overdue, 0.2))
                elif self._emit_next in self._done:
                    drain = True
                else:
                    self._cv.wait(timeout=0.2)
            if drain:
                self._drain_ready()

    def process(self, pad, buf: Buffer):
        self._wait_outstanding(self.window)
        host_buf = buf.to_host()
        if self.tenant is not None and _META_TENANT not in host_buf.meta:
            host_buf.meta[_META_TENANT] = self.tenant
        rec = getattr(self, "_trace_rec", None)
        tid = host_buf.meta.get(_META_TID) if rec is not None else None
        if isinstance(tid, int):
            # distributed parent context: the epoch-prefixed local trace
            # id rides the wire both directions (the server adopts it,
            # every response/token echoes it back)
            host_buf.meta[_META_TPARENT] = tid
        with self._cv:
            mid = self._next_msg
            self._next_msg += 1
            self._pending[mid] = (host_buf, time.monotonic())
        host_buf.meta[_META_MSG] = mid
        payload = wire.encode_buffer(host_buf)
        host_buf.meta.pop(_META_MSG, None)
        try:
            with self._send_lock:
                # Round-robin over destinations: coarse DP fan-out when
                # ``hosts=`` lists several servers; responses re-order by
                # msg id regardless of which server answered.
                socks = self._socks
                if not socks:
                    raise ElementError(f"{self.name}: not connected")
                wire.write_frame(socks[mid % len(socks)], payload)
        except (OSError, AttributeError) as e:
            if self.reconnect > 0:
                # leave the request pending: the rx loop detects the
                # dead socket, reconnects with backoff, and resends it
                # (_resend_pending); only if reconnection exhausts does
                # the connection error surface via _wait_outstanding
                log.warning("%s: send failed (%s); awaiting reconnect",
                            self.name, e)
                metrics.count(f"{self.name}.send_failures")
            else:
                raise ElementError(f"{self.name}: send failed: {e}") from e
        if rec is not None:
            rec.record("query.send", self.name, tid, time.monotonic_ns(),
                       0, msg=mid)
        metrics.count(f"{self.name}.requests")
        return []

    def finalize(self):
        # EOS: every outstanding request must resolve (or time out) before
        # EOS propagates downstream.
        self._wait_outstanding(1)
        # Barrier: the rx thread may have popped the last response but not
        # yet fed it; it feeds under _emit_lock, so taking it once here
        # guarantees delivery happened before EOS follows.
        with self._emit_lock:
            pass
        return []
