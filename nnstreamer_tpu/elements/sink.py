"""Sink elements.

Reference analogs: ``tensor_sink`` (gsttensor_sink.c — appsink-like terminal
emitting new-data signals), ``fakesink``, ``filesink`` (SURVEY §2.2, §4:
"tensor_sink + checksum/golden compare as deterministic sink").

``tensor_sink`` is where device buffers come home: ``pop()`` returns host
numpy arrays by default (one `device_get` at the pipeline edge), or the raw
jax Arrays with ``to_host=False`` for zero-copy handoff into app JAX code.
"""

from __future__ import annotations

import queue as _queue
from typing import Callable, List, Optional

import numpy as np

from ..core.buffer import Buffer
from ..core.log import metrics
from ..core.registry import register_element
from ..utils.tracing import META_TRACE_ID
from .base import SinkElement


def _release_credit(buf) -> None:
    """Free an appsrc max-inflight admission slot: called at REAL
    delivery (pop/callback) or when a drop-mode sink discards the buffer
    — never at mere sink arrival, which async dispatch reaches before
    the batch's H2D/compute has actually happened."""
    credit = getattr(buf, "meta", {}).get("_inflight_credit")
    if credit is not None:
        credit.release()


@register_element("tensor_sink")
class TensorSink(SinkElement):
    """Terminal sink with app-facing pull queue + callbacks.

    Props: ``max-buffers`` (queue bound; oldest dropped when exceeded and
    ``drop=true``), ``emit-signals`` kept for reference familiarity.
    """

    kind = "tensor_sink"
    sync_policy = "any"

    def __init__(self, props=None, name=None):
        super().__init__(props, name)
        cap = int(self.props.get("max_buffers", 1024))
        self.drop = bool(self.props.get("drop", False))
        # accepted for reference familiarity (both the reference's
        # "emit-signal" and appsink's "emit-signals" spellings); callbacks
        # fire regardless
        self.emit_signals = bool(self.props.get(
            "emit_signal", self.props.get("emit_signals", True)))
        self._q: _queue.Queue = _queue.Queue(maxsize=cap)
        self._callbacks: List[Callable[[Buffer], None]] = []
        self.to_host = bool(self.props.get("to_host", True))
        self._resolver = None  # lazy 1-thread host_post resolver
        self._parked = None  # not-yet-done Future seen by try_pop

    def connect_new_data(self, cb: Callable[[Buffer], None]) -> None:
        """Reference: g_signal_connect(sink, "new-data", ...)."""
        self._callbacks.append(cb)

    def process(self, pad, buf: Buffer):
        metrics.count(f"{self.name}.frames")
        # appsrc max-inflight credits release at POP (materialized
        # delivery), not here: stage dispatch is async, so a buffer
        # "arrives" as a device future milliseconds after admission
        # while its H2D/compute still queues behind earlier batches —
        # an arrival-time release would never bound that backlog
        # (measured: p50 e2e 7x the bound x service product).  Dropped
        # buffers release in the discard branch below.
        # Snapshot once: a callback registered mid-stream must not observe
        # half of this method's gating (connect_new_data is a public API
        # with no start-only restriction) — it takes effect next buffer.
        callbacks = list(self._callbacks)
        # <= not <: a bounded queue holding cap buffers still prefetches
        # the one about to block in put() — put() is the backpressure, so
        # outstanding copies stay <= cap+1.  Gating at < cap made every
        # buffer that arrived at a full (small) queue pay a synchronous
        # D2H RTT at pop — a periodic ~1-RTT stall per cap pops that cut
        # the round-3 audio bench 15x on the tunneled chip.
        prefetch_cap = min(16, self._q.maxsize or 16)
        if (self.to_host and not callbacks and not self.drop
                and self._q.qsize() <= prefetch_cap):
            # The app will pop host arrays: start the D2H now so the copy
            # overlaps the queue dwell time instead of being paid inside
            # pop() — over a remote/tunneled device this is a full RTT per
            # buffer off the pull path.  Gated: a drop=true sink may never
            # pop this buffer, and a deeply backed-up unbounded queue
            # (>16 deep) would turn prefetch into unbounded host copies +
            # wasted transfer, so those cases pay the copy lazily at pop.
            for t in buf.tensors:
                if hasattr(t, "copy_to_host_async"):
                    t.copy_to_host_async()
            if "_host_post" in buf.meta:
                # Resolve the deferred decode on a dedicated worker, NOT
                # the stage thread (would stall the pipeline) and NOT the
                # pull thread (was round-2's out.proc hotspot): pop()
                # collects a finished result.  Single worker => FIFO order.
                if self._resolver is None:
                    from concurrent.futures import ThreadPoolExecutor

                    self._resolver = ThreadPoolExecutor(
                        1, thread_name_prefix=f"{self.name}-resolve")
                buf = self._resolver.submit(buf.to_host)
        if callbacks:
            buf = buf.resolve()
            _release_credit(buf)  # callback consumers take delivery here
        for cb in callbacks:
            cb(buf)
        stop = getattr(self, "_stop_event", None)
        while True:
            try:
                self._q.put(buf, timeout=0.1)
                return []
            except _queue.Full:
                if self.drop:
                    try:
                        dropped = self._q.get_nowait()
                    except _queue.Empty:
                        pass
                    else:
                        _release_credit(dropped)  # never popped: free now
                elif stop is not None and stop.is_set():
                    return []  # pipeline stopping: shed instead of deadlocking
                # else: keep blocking — backpressure to the pipeline

    # -- app API -----------------------------------------------------------
    def pop(self, timeout: float = 30.0, check: Optional[Callable] = None) -> Buffer:
        import time as _time

        deadline = _time.monotonic() + timeout
        buf = self._parked  # a Future try_pop saw mid-flight goes first
        while buf is None:
            try:
                buf = self._q.get(timeout=0.1)
                break
            except _queue.Empty:
                if check:
                    check()
                if _time.monotonic() > deadline:
                    raise TimeoutError(f"no buffer at sink {self.name!r} in {timeout}s")
        # pop's timeout bounds ARRIVAL; materialization gets its own full
        # budget (the pre-resolver to_host() here was unbounded — a slow
        # tunneled D2H must not start failing because the queue wait ate
        # the deadline).  A materialization timeout PARKS the item so the
        # frame is retried by the next pop/try_pop, never dropped.
        try:
            out = self._materialize(buf, timeout)
        except TimeoutError:
            self._parked = buf
            raise
        self._parked = None
        _release_credit(out)  # materialized delivery: admission slot frees
        return out

    def try_pop(self) -> Optional[Buffer]:
        """Non-blocking poll: None when no FINISHED buffer is ready.  A
        still-resolving background buffer is parked (single-consumer pull
        API) and returned by the next pop/try_pop once done."""
        import concurrent.futures as _cf

        item = self._parked
        if item is None:
            try:
                item = self._q.get_nowait()
            except _queue.Empty:
                return None
        if isinstance(item, _cf.Future) and not item.done():
            self._parked = item
            return None
        self._parked = None
        out = self._materialize(item, 30.0)
        _release_credit(out)
        return out

    def _materialize(self, item, timeout: float) -> Buffer:
        # set by this pipeline's runner iff ITS trace_mode != off
        tracer = getattr(self, "_trace_rec", None)
        if tracer is not None:
            # host-fetch span: the D2H / deferred host_post cost the app's
            # pop() pays (the last hop of the per-buffer timeline)
            import time as _time

            t0 = _time.monotonic_ns()
            out = self._materialize_inner(item, timeout)
            tracer.record("fetch", self.name,
                          out.meta.get(META_TRACE_ID), t0,
                          _time.monotonic_ns() - t0)
            return out
        return self._materialize_inner(item, timeout)

    def _materialize_inner(self, item, timeout: float) -> Buffer:
        import concurrent.futures as _cf

        if isinstance(item, _cf.Future):  # background-resolved host buffer
            try:
                return item.result(timeout=timeout)
            except _cf.TimeoutError:
                # builtin TimeoutError is pop()'s documented contract (and
                # the two are distinct types on py3.10)
                raise TimeoutError(
                    f"host_post resolution at sink {self.name!r} exceeded "
                    f"{timeout}s") from None
        return item.to_host() if self.to_host else item

    def stop(self) -> None:
        if self._resolver is not None:
            self._resolver.shutdown(wait=False)
            self._resolver = None
        super().stop()

    @property
    def depth(self) -> int:
        return self._q.qsize()


@register_element("fakesink")
class FakeSink(SinkElement):
    """Discard everything (but count it)."""

    kind = "fakesink"

    def __init__(self, props=None, name=None):
        super().__init__(props, name)
        self.count = 0
        self.sync = bool(self.props.get("sync", False))
        self.last: Optional[Buffer] = None

    def process(self, pad, buf):
        # Block until device work for this buffer really finished — without
        # this, "throughput" would measure XLA's async dispatch queue.
        buf.block_until_ready()
        _release_credit(buf)  # ready = really delivered for a fakesink
        self.count += 1
        self.last = buf
        metrics.count(f"{self.name}.frames")
        return []


@register_element("filesink")
class FileSink(SinkElement):
    """Append raw tensor bytes to a file (reference: filesink in SSAT golden
    tests)."""

    kind = "filesink"

    def __init__(self, props=None, name=None):
        super().__init__(props, name)
        self.location = str(self.props.get("location", "out.bin"))
        self._f = None

    def start(self):
        self._f = open(self.location, "wb")

    def stop(self):
        if self._f:
            self._f.close()
            self._f = None

    def process(self, pad, buf):
        for t in buf.resolve().tensors:
            self._f.write(np.asarray(t).tobytes())
        _release_credit(buf)  # bytes on disk = delivered
        return []
