"""Sink elements.

Reference analogs: ``tensor_sink`` (gsttensor_sink.c — appsink-like terminal
emitting new-data signals), ``fakesink``, ``filesink`` (SURVEY §2.2, §4:
"tensor_sink + checksum/golden compare as deterministic sink").

``tensor_sink`` is where device buffers come home: ``pop()`` returns host
numpy arrays by default (one `device_get` at the pipeline edge), or the raw
jax Arrays with ``to_host=False`` for zero-copy handoff into app JAX code.

``fetch_depth`` (config knob / pipeline knob / ``fetch-depth`` prop) is the
sink-side twin of ``dispatch_depth``: up to that many popped-to-be buffers
resolve D2H / deferred ``host_post`` in a background pool concurrently, so
the fetch of buffer N overlaps the dispatch of buffer N+1 instead of being
paid serially inside ``pop()``.  Emission order stays FIFO — the pull queue
holds futures in arrival order whatever order they finish.  docs/FETCH.md.
"""

from __future__ import annotations

import queue as _queue
import threading as _threading
import time as _time
from typing import Callable, List, Optional

import numpy as np

from ..core.buffer import Buffer
from ..core.log import STALL_FLOOR_S as _STALL_FLOOR_S
from ..core.log import logger, metrics
from ..core.registry import register_element
from ..core.meta_keys import META_TENANT, META_TRACE_ID
from ..utils import locks, tracing
from .base import SinkElement

log = logger(__name__)


def _release_credit(buf) -> None:
    """Free an appsrc max-inflight admission slot: called at REAL
    delivery (pop/callback) or when a drop-mode sink discards the buffer
    — never at mere sink arrival, which async dispatch reaches before
    the batch's H2D/compute has actually happened."""
    credit = getattr(buf, "meta", {}).get("_inflight_credit")
    if credit is not None:
        credit.release()


@register_element("tensor_sink")
class TensorSink(SinkElement):
    """Terminal sink with app-facing pull queue + callbacks.

    Props: ``max-buffers`` (queue bound; oldest dropped when exceeded and
    ``drop=true``), ``emit-signals`` kept for reference familiarity.
    """

    kind = "tensor_sink"
    sync_policy = "any"
    #: residency planner (pipeline/residency.py): the pull API hands the
    #: app whatever tensors arrive — reduced geometry included
    admits_reduced_payload = True

    #: nns-tsan lock discipline (lint --threads verifies statically,
    #: NNS_TPU_TSAN=1 verifies live — docs/ANALYSIS.md "Threads pass")
    _GUARDED_BY = {"_pool": "_win_lock", "_pool_stopped": "_win_lock",
                   "_outstanding": "_win_lock", "_win_peak": "_win_lock"}

    def __init__(self, props=None, name=None):
        super().__init__(props, name)
        cap = int(self.props.get("max_buffers", 1024))
        self.drop = bool(self.props.get("drop", False))
        # accepted for reference familiarity (both the reference's
        # "emit-signal" and appsink's "emit-signals" spellings); callbacks
        # fire regardless
        self.emit_signals = bool(self.props.get(
            "emit_signal", self.props.get("emit_signals", True)))
        self._q: _queue.Queue = _queue.Queue(maxsize=cap)
        self._callbacks: List[Callable[[Buffer], None]] = []
        self.to_host = bool(self.props.get("to_host", True))
        # fetch window (docs/FETCH.md): prop > pipeline knob > config
        self._fetch_depth_prop = int(self.props.get("fetch_depth", 0))
        self._pool = None  # lazy fetch_depth-wide resolver pool
        self._pool_stopped = False  # stop() ran: never mint a new pool
        self._outstanding = 0  # submitted-but-unmaterialized window
        # counter shared with pool threads (nns-tsan tracked: the
        # fetch-window gauge race IS the escaped bug that motivated
        # the threads pass — docs/ANALYSIS.md)
        self._win_lock = locks.make_lock("TensorSink._win_lock")
        self._win_peak = 0  # high-water window depth this run
        self._parked = None  # not-yet-done Future seen by try_pop

    def connect_new_data(self, cb: Callable[[Buffer], None]) -> None:
        """Reference: g_signal_connect(sink, "new-data", ...)."""
        self._callbacks.append(cb)

    def process(self, pad, buf: Buffer):
        # frames split per tenant when the buffer carries one (wire meta /
        # appsrc tenant= / traced pipeline default) — the trace-off
        # throughput source for per-tenant accounting
        metrics.count(f"{self.name}.frames",
                      tenant=buf.meta.get(META_TENANT))
        # appsrc max-inflight credits release at POP (materialized
        # delivery), not here: stage dispatch is async, so a buffer
        # "arrives" as a device future milliseconds after admission
        # while its H2D/compute still queues behind earlier batches —
        # an arrival-time release would never bound that backlog
        # (measured: p50 e2e 7x the bound x service product).  Dropped
        # buffers release in the discard branch below.
        # Snapshot once: a callback registered mid-stream must not observe
        # half of this method's gating (connect_new_data is a public API
        # with no start-only restriction) — it takes effect next buffer.
        callbacks = list(self._callbacks)
        # <= not <: a bounded queue holding cap buffers still prefetches
        # the one about to block in put() — put() is the backpressure, so
        # outstanding copies stay <= cap+1.  Gating at < cap made every
        # buffer that arrived at a full (small) queue pay a synchronous
        # D2H RTT at pop — a periodic ~1-RTT stall per cap pops that cut
        # the round-3 audio bench 15x on the tunneled chip.
        prefetch_cap = min(16, self._q.maxsize or 16)
        if (self.to_host and not callbacks and not self.drop
                and self._q.qsize() <= prefetch_cap):
            # The app will pop host arrays: start the D2H now so the copy
            # overlaps the queue dwell time instead of being paid inside
            # pop() — over a remote/tunneled device this is a full RTT per
            # buffer off the pull path.  Gated: a drop=true sink may never
            # pop this buffer, and a deeply backed-up unbounded queue
            # (>16 deep) would turn prefetch into unbounded host copies +
            # wasted transfer, so those cases pay the copy lazily at pop.
            for t in buf.tensors:
                if hasattr(t, "copy_to_host_async"):
                    t.copy_to_host_async()
            # Hand the materialization (D2H wait + deferred host_post) to
            # the fetch window: up to fetch_depth buffers resolve on the
            # pool concurrently, NOT on the stage thread (would stall the
            # pipeline) and NOT the pull thread (was round-2's out.proc
            # hotspot).  pop() collects finished results in FIFO order —
            # the pull queue holds futures in arrival order.  Only when
            # there is something to overlap: an already-host numpy buffer
            # with no deferred host_post resolves for free at pop, and
            # submitting it would mint a pool + pay a future round-trip
            # per buffer in host-only pipelines.
            if buf.on_device or "_host_post" in buf.meta:
                buf = self._submit_fetch(buf)
        if callbacks:
            buf = buf.resolve()
            _release_credit(buf)  # callback consumers take delivery here
        for cb in callbacks:
            cb(buf)
        stop = getattr(self, "_stop_event", None)
        while True:
            try:
                self._q.put(buf, timeout=0.1)
                return []
            except _queue.Full:
                if self.drop:
                    try:
                        dropped = self._q.get_nowait()
                    except _queue.Empty:
                        pass
                    else:
                        _release_credit(dropped)  # never popped: free now
                elif stop is not None and stop.is_set():
                    return []  # pipeline stopping: shed instead of deadlocking
                # else: keep blocking — backpressure to the pipeline

    # -- fetch window (docs/FETCH.md) ---------------------------------------
    @property
    def fetch_depth(self) -> int:
        """Resolved fetch-window width: the element's own ``fetch-depth``
        prop wins, then the pipeline knob the runner attached
        (``_fetch_depth``), then the config default."""
        d = self._fetch_depth_prop
        if d <= 0:
            d = int(getattr(self, "_fetch_depth", 0) or 0)
        if d <= 0:
            from ..core.config import get_config

            d = get_config().fetch_depth
        return max(1, d)

    def _fetch_pool(self):
        # under _win_lock: check-then-create must be atomic with stop()
        # (a stage thread descheduled between check and create would mint
        # a pool stop() never learns about — leaked non-daemon workers)
        with self._win_lock:
            if self._pool is None and not self._pool_stopped:
                from concurrent.futures import ThreadPoolExecutor

                self._pool = ThreadPoolExecutor(
                    self.fetch_depth,
                    thread_name_prefix=f"{self.name}-fetch")
            return self._pool

    def _fetch_done(self, fut) -> None:
        with self._win_lock:  # runs on pool threads, racing _submit_fetch
            locks.assert_guarded(self, "_outstanding")
            self._outstanding -= 1
            # gauge write INSIDE the lock: writes are then ordered by
            # acquisition, so the live series stays truthful as the
            # window drains — an idle scrape reads 0, never a stale
            # depth from a submit/done interleaving
            metrics.gauge(f"{self.name}.fetch_window",
                          float(max(0, self._outstanding)))

    def _submit_fetch(self, buf: Buffer):
        """Submit one buffer's materialization into the fetch window;
        returns the Future (or the buffer unchanged when the pool is
        already shut down — the pop path materializes lazily then)."""
        cell = {"dur": 0.0}

        def job(b=buf, cell=cell):
            t1 = _time.perf_counter()
            out = b.to_host()
            cell["dur"] = _time.perf_counter() - t1
            return out

        pool = self._fetch_pool()
        if pool is None:  # stop() ran: shed to the pop path's lazy to_host
            return buf
        try:
            fut = pool.submit(job)
        except RuntimeError:  # pool shut down mid-stop: shed to lazy path
            return buf
        tid = buf.meta.get(META_TRACE_ID)
        fut._nns_tid = tid
        fut._nns_cell = cell
        # the admission credit must survive a FAILED resolution: pop()'s
        # failure path releases it explicitly (deterministic, vs waiting
        # on the _InflightCredit GC safety net) so a streaming app that
        # catches the error can keep pushing
        fut._nns_credit = buf.meta.get("_inflight_credit")
        # count + gauge + peak under ONE lock hold, BEFORE registering the
        # done-callback: a fast resolve may run _fetch_done inline inside
        # add_done_callback, and gauge writes outside the lock could then
        # land after the drain's 0 — a stale nonzero depth forever
        with self._win_lock:
            self._outstanding += 1
            depth = max(1, self._outstanding)
            metrics.gauge(f"{self.name}.fetch_window", float(depth))
            if depth > self._win_peak:
                self._win_peak = depth
                metrics.gauge(f"{self.name}.fetch_window_peak",
                              float(depth))
        fut.add_done_callback(self._fetch_done)
        tracer = getattr(self, "_trace_rec", None)
        if tracer is not None:
            tracer.record("fetch.window", self.name, tid,
                          _time.monotonic_ns(), 0, depth=depth)
        return fut

    # -- app API -----------------------------------------------------------
    def pop(self, timeout: float = 30.0, check: Optional[Callable] = None) -> Buffer:
        deadline = _time.monotonic() + timeout
        buf = self._parked  # a Future try_pop saw mid-flight goes first
        while buf is None:
            try:
                buf = self._q.get(timeout=0.1)
                break
            except _queue.Empty:
                if check:
                    check()
                if _time.monotonic() > deadline:
                    raise TimeoutError(f"no buffer at sink {self.name!r} in {timeout}s")
        # pop's timeout bounds ARRIVAL; materialization gets its own full
        # budget (the pre-resolver to_host() here was unbounded — a slow
        # tunneled D2H must not start failing because the queue wait ate
        # the deadline).  A materialization timeout PARKS the item so the
        # frame is retried by the next pop/try_pop, never dropped.
        try:
            out = self._materialize(buf, timeout)
        except TimeoutError:
            self._parked = buf
            raise
        self._parked = None
        _release_credit(out)  # materialized delivery: admission slot frees
        return out

    def try_pop(self) -> Optional[Buffer]:
        """Non-blocking poll: None when no FINISHED buffer is ready.  A
        still-resolving background buffer is parked (single-consumer pull
        API) and returned by the next pop/try_pop once done."""
        import concurrent.futures as _cf

        item = self._parked
        if item is None:
            try:
                item = self._q.get_nowait()
            except _queue.Empty:
                return None
        if isinstance(item, _cf.Future) and not item.done():
            self._parked = item
            return None
        self._parked = None
        out = self._materialize(item, 30.0)
        _release_credit(out)
        return out

    def _materialize(self, item, timeout: float) -> Buffer:
        # set by this pipeline's runner iff ITS trace_mode != off
        tracer = getattr(self, "_trace_rec", None)
        if tracer is not None:
            # host-fetch span: the D2H / deferred host_post cost the app's
            # pop() pays (the last hop of the per-buffer timeline)
            t0 = _time.monotonic_ns()
            out = self._materialize_inner(item, timeout)
            ten = out.meta.get(META_TENANT)
            args = {} if ten is None else {"tenant": ten}
            tracer.record("fetch", self.name,
                          out.meta.get(META_TRACE_ID), t0,
                          _time.monotonic_ns() - t0, **args)
            return out
        return self._materialize_inner(item, timeout)

    def _materialize_inner(self, item, timeout: float) -> Buffer:
        import concurrent.futures as _cf

        if isinstance(item, _cf.Future):  # background-resolved host buffer
            t0 = _time.perf_counter()
            tid = getattr(item, "_nns_tid", None)
            try:
                out = item.result(timeout=timeout)
            except _cf.TimeoutError:
                # Post-mortem: the timeout carries the buffer's trace id
                # and dumps the flight-recorder ring, exactly like
                # watchdog fires (no-op when tracing is off).
                tracing.dump_recent_to_log(
                    log, reason=f"fetch/host_post resolution timeout at "
                                f"sink {self.name!r} (trace id {tid})")
                # builtin TimeoutError is pop()'s documented contract (and
                # the two are distinct types on py3.10)
                raise TimeoutError(
                    f"host_post resolution at sink {self.name!r} exceeded "
                    f"{timeout}s (trace id {tid})") from None
            except Exception as e:  # noqa: BLE001 - annotate + re-raise
                tracing.dump_recent_to_log(
                    log, reason=f"fetch/host_post resolution FAILED at "
                                f"sink {self.name!r} (trace id {tid}): "
                                f"{e!r}")
                # the buffer is gone, its admission credit must not be:
                # an app that catches this and keeps streaming would
                # otherwise wedge after max_inflight failures (release()
                # is idempotent; the GC safety net stays the backstop)
                credit = getattr(item, "_nns_credit", None)
                if credit is not None:
                    credit.release()
                raise
            wait = _time.perf_counter() - t0
            dur = getattr(item, "_nns_cell", {"dur": 0.0})["dur"]
            # d2h-wait accounting (the output-side half of the stall
            # split; appsrc counts the h2d side): time the PULL actually
            # blocked, vs fetch time that overlapped pipeline work
            metrics.count(f"{self.name}.d2h_wait_ms", wait * 1e3)
            if wait > _STALL_FLOOR_S:
                metrics.count(f"{self.name}.d2h_stalls")
            metrics.count(f"{self.name}.fetch_overlap_ms",
                          max(0.0, dur - wait) * 1e3)
            return out
        if not self.to_host:
            return item
        t0 = _time.perf_counter()
        out = item.to_host()
        wait = _time.perf_counter() - t0
        metrics.count(f"{self.name}.d2h_wait_ms", wait * 1e3)
        if wait > _STALL_FLOOR_S:
            metrics.count(f"{self.name}.d2h_stalls")
        return out

    def stop(self) -> None:
        with self._win_lock:  # atomic with _fetch_pool's check-then-create
            self._pool_stopped = True  # racing process() must not mint a pool
            pool, self._pool = self._pool, None
        if pool is not None:
            # wait=False + no cancel: already-submitted window entries
            # still resolve, so buffers queued before EOS stay poppable
            pool.shutdown(wait=False)
        super().stop()

    @property
    def depth(self) -> int:
        return self._q.qsize()


@register_element("fakesink")
class FakeSink(SinkElement):
    """Discard everything (but count it)."""

    kind = "fakesink"
    #: residency planner: discarded payloads admit any geometry
    admits_reduced_payload = True

    def __init__(self, props=None, name=None):
        super().__init__(props, name)
        self.count = 0
        self.sync = bool(self.props.get("sync", False))
        self.last: Optional[Buffer] = None

    def process(self, pad, buf):
        # Block until device work for this buffer really finished — without
        # this, "throughput" would measure XLA's async dispatch queue.
        buf.block_until_ready()
        _release_credit(buf)  # ready = really delivered for a fakesink
        self.count += 1
        self.last = buf
        metrics.count(f"{self.name}.frames",
                      tenant=buf.meta.get(META_TENANT))
        return []


@register_element("filesink")
class FileSink(SinkElement):
    """Append raw tensor bytes to a file (reference: filesink in SSAT golden
    tests)."""

    kind = "filesink"

    def __init__(self, props=None, name=None):
        super().__init__(props, name)
        self.location = str(self.props.get("location", "out.bin"))
        self._f = None

    def start(self):
        self._f = open(self.location, "wb")

    def stop(self):
        if self._f:
            self._f.close()
            self._f = None

    def process(self, pad, buf):
        for t in buf.resolve().tensors:
            self._f.write(np.asarray(t).tobytes())
        _release_credit(buf)  # bytes on disk = delivered
        return []
