"""Sparse tensor wire format: tensor_sparse_enc / tensor_sparse_dec.

Reference analog: ``gsttensor_sparseenc.c`` / ``gsttensor_sparsedec.c`` /
``gsttensor_sparseutil.c`` (SURVEY §2.2): COO (index, value) pairs to cut
bandwidth for sparse data before IPC/network hops.

Wire layout per tensor (little-endian), mirroring the reference's
self-describing header idea:

    uint32 magic 0x53505253 ("SPRS") | uint32 rank | uint32 dims[rank]
    | uint32 dtype_name_len | dtype_name utf-8 | uint64 nnz
    | uint32 indices[nnz] (flat, C-order of the numpy shape) | values[nnz]

Encoded output is a single uint8 tensor per input tensor (FLEXIBLE stream).
"""

from __future__ import annotations

import struct
from typing import List

import numpy as np

from ..core.buffer import Buffer
from ..core.caps import Caps, MediaType
from ..core.registry import register_element
from ..core.types import TensorFormat, TensorSpec, TensorsSpec, dtype_from_name, dtype_name
from .base import Element, ElementError, SRC

_MAGIC = 0x53505253


def sparse_encode_array(x: np.ndarray) -> np.ndarray:
    flat = x.ravel()
    nz = np.flatnonzero(flat)
    values = flat[nz]
    name = dtype_name(x.dtype).encode()
    header = struct.pack(
        f"<II{x.ndim}II",
        _MAGIC,
        x.ndim,
        *[int(d) for d in x.shape],
        len(name),
    )
    body = (
        name
        + struct.pack("<Q", len(nz))
        + nz.astype(np.uint32).tobytes()
        + values.tobytes()
    )
    return np.frombuffer(header + body, np.uint8)


def sparse_decode_array(blob: np.ndarray) -> np.ndarray:
    raw = bytes(np.asarray(blob, np.uint8).tobytes())
    magic, rank = struct.unpack_from("<II", raw, 0)
    if magic != _MAGIC:
        raise ElementError("not a sparse-encoded tensor (bad magic)")
    off = 8
    shape = struct.unpack_from(f"<{rank}I", raw, off)
    off += 4 * rank
    (name_len,) = struct.unpack_from("<I", raw, off)
    off += 4
    dtype = dtype_from_name(raw[off : off + name_len].decode())
    off += name_len
    (nnz,) = struct.unpack_from("<Q", raw, off)
    off += 8
    idx = np.frombuffer(raw, np.uint32, count=nnz, offset=off)
    off += 4 * nnz
    values = np.frombuffer(raw, dtype, count=nnz, offset=off)
    out = np.zeros(int(np.prod(shape)) if shape else 1, dtype)
    out[idx] = values
    return out.reshape(shape)


@register_element("tensor_sparse_enc", aliases=("tensor_sparseenc",))
class TensorSparseEnc(Element):
    kind = "tensor_sparse_enc"

    def configure(self, in_caps, out_pads):
        self.in_caps = dict(in_caps)
        caps = Caps.new(MediaType.FLEX_TENSORS)
        self.out_caps = {p: caps for p in out_pads}
        return self.out_caps

    def process(self, pad, buf: Buffer):
        blobs = [sparse_encode_array(np.asarray(t)) for t in buf.tensors]
        spec = TensorsSpec.of(blobs, format=TensorFormat.SPARSE)
        return [(SRC, buf.with_tensors(blobs, spec=spec))]


@register_element("tensor_sparse_dec", aliases=("tensor_sparsedec",))
class TensorSparseDec(Element):
    kind = "tensor_sparse_dec"

    def configure(self, in_caps, out_pads):
        self.in_caps = dict(in_caps)
        self.out_caps = {p: Caps.new(MediaType.TENSORS) for p in out_pads}
        return self.out_caps

    def process(self, pad, buf: Buffer):
        outs = [sparse_decode_array(t) for t in buf.tensors]
        return [(SRC, buf.with_tensors(outs, spec=TensorsSpec.of(outs)))]
