"""tensor_crop: dynamic region cropping driven by a second info stream.

Reference analog: ``gsttensor_crop.c`` (SURVEY §2.2) — two sink pads:
``sink_0`` ("raw") carries data tensors, ``sink_1`` ("info") carries crop
regions [x, y, w, h] produced e.g. by the tensor_region decoder.  Output is
FLEXIBLE (per-buffer shapes: one cropped tensor per region).

The raw tensor is interpreted video-style: dims (C, W, H, N) => numpy
(N, H, W, C); x/y index W/H.
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from ..core.buffer import Buffer
from ..core.caps import Caps, MediaType
from ..core.registry import register_element
from ..core.types import TensorFormat, TensorsSpec
from .base import Element, ElementError, SRC


@register_element("tensor_crop")
class TensorCrop(Element):
    kind = "tensor_crop"
    sync_policy = "all"

    def configure(self, in_caps, out_pads):
        self.in_caps = dict(in_caps)
        caps = Caps.new(MediaType.FLEX_TENSORS)
        self.out_caps = {p: caps for p in out_pads}
        return self.out_caps

    def process_group(self, bufs: Dict[str, Buffer]):
        pads = sorted(bufs)
        if len(pads) < 2:
            raise ElementError("tensor_crop needs raw (sink_0) and info (sink_1) pads")
        raw = np.asarray(bufs[pads[0]].tensors[0])
        info = np.asarray(bufs[pads[1]].tensors[0]).reshape(-1, 4)
        if raw.ndim < 2:
            raise ElementError("tensor_crop raw tensor must be at least rank 2")
        frame = raw
        if frame.ndim == 4:  # (N,H,W,C): crop the first frame of the batch
            frame = frame[0]
        if frame.ndim == 2:
            frame = frame[:, :, None]
        h, w = frame.shape[0], frame.shape[1]
        crops = []
        for x, y, cw, ch in info.astype(np.int64):
            x0 = int(np.clip(x, 0, w))
            y0 = int(np.clip(y, 0, h))
            x1 = int(np.clip(x + cw, 0, w))
            y1 = int(np.clip(y + ch, 0, h))
            crops.append(frame[y0:y1, x0:x1, :])
        base = bufs[pads[0]]
        out = base.with_tensors(crops, spec=TensorsSpec.of(crops, format=TensorFormat.FLEXIBLE))
        return [(SRC, out)]
