"""tensor_decoder shell element.

Reference analog: ``gsttensor_decoder.c`` (SURVEY §2.2): ``other/tensors``
-> media via the decoder sub-plugin named by ``mode=``.
"""

from __future__ import annotations

from ..core.caps import Caps, MediaType
from ..core.registry import KIND_DECODER, get as registry_get, register_element
from .base import Element, ElementError, SRC


@register_element("tensor_decoder")
class TensorDecoder(Element):
    kind = "tensor_decoder"
    PAD_TEMPLATES = {"sink": Caps.new(MediaType.TENSORS)}

    def __init__(self, props=None, name=None):
        super().__init__(props, name)
        mode = self.props.get("mode")
        if not mode:
            raise ElementError("tensor_decoder needs mode=<subplugin>")
        cls = registry_get(KIND_DECODER, str(mode))
        self.decoder = cls(self.props)

    def configure(self, in_caps, out_pads):
        self.in_caps = dict(in_caps)
        src = next(iter(in_caps.values()), Caps.any())
        caps = self.decoder.out_caps(src.spec)
        self.out_caps = {p: caps for p in out_pads}
        return self.out_caps

    def process(self, pad, buf):
        # Tensors go to the decoder as-is (possibly device-resident jax
        # Arrays from an upstream fused stage): decoders that can prefilter
        # on device (bounding_boxes top-k) avoid fetching the full model
        # output; the rest np.asarray what they need.
        out = self.decoder.decode(list(buf.tensors), buf)
        # A decoder may un-batch one buffer into several (bounding_boxes on
        # batched streams emits one video frame per batch row).
        if isinstance(out, list):
            return [(SRC, o) for o in out]
        return [(SRC, out)]

    def device_fn(self, in_spec):
        return self.decoder.device_fn(in_spec)

    @property
    def host_post(self):
        """Deferred host mapping paired with the decoder's device_fn."""
        return self.decoder.host_post

    @property
    def admits_reduced_payload(self):
        """Residency-planner opt-in, delegated to the decoder sub-plugin
        (pipeline/residency.py): True only when the decode is
        geometry-agnostic (e.g. image_segment classmap)."""
        return getattr(self.decoder, "admits_reduced_payload", False)
