"""tensor_repo: named in-process slots enabling pipeline loops/recurrence.

Reference analog: ``gsttensor_reposrc.c`` / ``gsttensor_reposink.c`` /
``tensor_repo.c`` (SURVEY §2.2) — output of iteration N becomes input of
iteration N+1 without a graph cycle (reposrc has no in-edge, so the DAG
check holds; the loop closes through the shared slot).

Slots are process-global, keyed by ``slot-name`` (upstream uses integer
``slot-index``; both accepted).  ``reposrc`` needs an initial value to kick
off the recurrence: ``init-dims``/``init-type`` (zeros) — the reference gets
this from its negotiated caps and empty buffers.
"""

from __future__ import annotations

import queue as _queue
import threading
from typing import Dict, Optional

import numpy as np

from ..core.buffer import Buffer
from ..core.caps import Caps
from ..core.registry import register_element
from ..core.types import TensorsSpec
from .base import Element, SinkElement, SourceElement, SRC


class _Slot:
    def __init__(self):
        self.q: _queue.Queue = _queue.Queue(maxsize=64)
        self.eos = threading.Event()


_slots: Dict[str, _Slot] = {}
_slots_lock = threading.Lock()


def _slot(name: str) -> _Slot:
    with _slots_lock:
        if name not in _slots:
            _slots[name] = _Slot()
        return _slots[name]


def reset_slots() -> None:
    """Test helper: clear all repo slots."""
    with _slots_lock:
        _slots.clear()


def _slot_key(props) -> str:
    return str(props.get("slot_name", props.get("slot_index", "0")))


@register_element("tensor_reposink")
class TensorRepoSink(SinkElement):
    kind = "tensor_reposink"

    def __init__(self, props=None, name=None):
        super().__init__(props, name)
        self._slot = _slot(_slot_key(self.props))

    def start(self):
        # A fresh stream re-arms the slot: without this, a second pipeline
        # reusing the slot name would see the EOS latch from the previous
        # run and end its recurrence immediately.
        self._slot.eos.clear()

    def process(self, pad, buf: Buffer):
        self._slot.q.put(buf.to_host())
        return []

    def stop(self):
        self._slot.eos.set()


@register_element("tensor_reposrc")
class TensorRepoSrc(SourceElement):
    kind = "tensor_reposrc"

    def __init__(self, props=None, name=None):
        super().__init__(props, name)
        self._slot = _slot(_slot_key(self.props))
        self.num_buffers = int(self.props.get("num_buffers", -1))
        self.init_dims = self.props.get("init_dims")
        self.init_type = str(self.props.get("init_type", "float32"))

    def configure(self, in_caps, out_pads):
        spec = None
        if self.init_dims:
            spec = TensorsSpec.from_string(str(self.init_dims), self.init_type)
        self.out_caps = {p: Caps.tensors(spec) for p in out_pads}
        self._spec = spec
        return self.out_caps

    def generate(self):
        emitted = 0
        if self._spec is not None:
            init = [np.zeros(s.shape, s.dtype) for s in self._spec]
            yield Buffer(init, spec=self._spec)
            emitted += 1
        while self.num_buffers < 0 or emitted < self.num_buffers:
            try:
                buf = self._slot.q.get(timeout=0.1)
            except _queue.Empty:
                if self._slot.eos.is_set() and self._slot.q.empty():
                    return
                stop = getattr(self, "_stop_event", None)
                if stop is not None and stop.is_set():
                    return
                continue
            yield buf
            emitted += 1
