"""tensor_if: conditional stream branching on tensor values.

Reference analog: ``gsttensor_if.c`` (SURVEY §2.2): compared-value
(A_VALUE / TENSOR_AVERAGE_VALUE), compared-value-option (tensor:element
indices), supplied-value, operator (EQ/NE/GT/GE/LT/LE/RANGE_*), then/else
actions (PASSTHROUGH / SKIP / TENSORPICK), plus registerable custom
condition callbacks (reference: nnstreamer_if_custom API).

TPU-native extension: ``compared_value=META_VALUE`` gates on a buffer
META key (compared_value_option names it) instead of tensor contents —
zero D2H, the routing surface for per-buffer flags stamped by upstream
stages (e.g. the LLM serve loop's speculative ``spec_draft``
accept/reject flag, docs/SERVING.md §4c).

Pads: ``src_0`` receives the THEN result, ``src_1`` (optional) the ELSE
result; with only one src pad linked, else falls back to SKIP semantics on
that pad (matching the common upstream usage of tensor_if as a gate).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional

import numpy as np

from ..core.buffer import Buffer
from ..core.caps import Caps, MediaType
from ..core.registry import register_element
from ..core.types import TensorsSpec
from .base import Element, ElementError, SRC

_custom_conditions: Dict[str, Callable[[List[np.ndarray]], bool]] = {}
_lock = threading.Lock()


def register_if_condition(name: str, fn: Callable[[List[np.ndarray]], bool]) -> None:
    """Register a custom condition callable (reference: custom condition cb)."""
    with _lock:
        _custom_conditions[name] = fn


_OPERATORS = {
    "EQ": lambda a, b: a == b,
    "NE": lambda a, b: a != b,
    "GT": lambda a, b: a > b,
    "GE": lambda a, b: a >= b,
    "LT": lambda a, b: a < b,
    "LE": lambda a, b: a <= b,
    "RANGE_INCLUSIVE": lambda a, b: b[0] <= a <= b[1],
    "RANGE_EXCLUSIVE": lambda a, b: b[0] < a < b[1],
    "NOT_IN_RANGE_INCLUSIVE": lambda a, b: not (b[0] <= a <= b[1]),
    "NOT_IN_RANGE_EXCLUSIVE": lambda a, b: not (b[0] < a < b[1]),
}


@register_element("tensor_if")
class TensorIf(Element):
    kind = "tensor_if"
    PAD_TEMPLATES = {"sink": Caps.new(MediaType.TENSORS)}

    def __init__(self, props=None, name=None):
        super().__init__(props, name)
        self.compared_value = str(self.props.get("compared_value", "A_VALUE")).upper()
        self.cv_option = str(self.props.get("compared_value_option", "0"))
        self.operator = str(self.props.get("operator", "GT")).upper()
        sv = str(self.props.get("supplied_value", "0"))
        self.supplied = [float(v) for v in sv.split(":") if v != ""]
        self.then_action = str(self.props.get("then", "PASSTHROUGH")).upper()
        self._else_explicit = "else" in self.props
        self.else_action = str(self.props.get("else", "SKIP")).upper()
        self.then_pick = _parse_pick(self.props.get("then_option"))
        self.else_pick = _parse_pick(self.props.get("else_option"))
        self.custom = self.props.get("custom")
        if self.operator not in _OPERATORS:
            raise ElementError(f"unknown tensor_if operator {self.operator!r}")

    def configure(self, in_caps, out_pads):
        self.in_caps = dict(in_caps)
        src = next(iter(in_caps.values()), Caps.any())
        self.out_caps = {p: src for p in out_pads}
        self._pads = sorted(out_pads)
        # two linked src pads: ELSE results flow to src_1 unless the
        # user asked for something explicitly (single-pad default stays
        # SKIP — the upstream gate idiom)
        if not self._else_explicit and len(self._pads) > 1:
            self.else_action = "PASSTHROUGH"
        return self.out_caps

    # -- condition ---------------------------------------------------------
    def _evaluate(self, buf: Buffer) -> bool:
        # Only materialize what the condition reads: tensors may be
        # HBM-resident jax Arrays and np.asarray is a blocking D2H copy.
        if self.custom:
            with _lock:
                fn = _custom_conditions.get(str(self.custom))
            if fn is None:
                raise ElementError(f"no custom tensor_if condition {self.custom!r}")
            return bool(fn([np.asarray(t) for t in buf.tensors]))
        if self.compared_value == "A_VALUE":
            # option "tensor_idx:flat_element_idx" (reference uses dim coords;
            # flat index covers the same selections deterministically)
            parts = [int(v) for v in self.cv_option.split(":") if v != ""]
            t_idx = parts[0] if parts else 0
            e_idx = parts[1] if len(parts) > 1 else 0
            value = float(np.asarray(buf.tensors[t_idx]).ravel()[e_idx])
        elif self.compared_value == "TENSOR_AVERAGE_VALUE":
            t_idx = int(self.cv_option or 0)
            value = float(np.asarray(buf.tensors[t_idx]).astype(np.float64).mean())
        elif self.compared_value == "META_VALUE":
            # Buffer-meta gate: compared_value_option names the meta key
            # (absent keys read 0).  The pipeline-native home for
            # routing on per-buffer decisions an upstream stage stamped
            # — e.g. the continuous LLM serve loop's speculative
            # accept/reject flag ``spec_draft`` (docs/SERVING.md §4c):
            # META_VALUE + operator=GE + supplied_value=1 gates
            # accepted-draft tokens.  Reads NO tensors: device-resident
            # buffers route without a D2H copy.
            raw = buf.meta.get(self.cv_option or "", 0)
            try:
                value = float(raw if raw is not None else 0)
            except (TypeError, ValueError) as e:
                raise ElementError(
                    f"tensor_if META_VALUE key {self.cv_option!r} holds "
                    f"non-numeric {raw!r}") from e
        else:
            raise ElementError(f"unknown compared_value {self.compared_value!r}")
        op = _OPERATORS[self.operator]
        if "RANGE" in self.operator:
            if len(self.supplied) < 2:
                raise ElementError("range operators need supplied-value v1:v2")
            return bool(op(value, (self.supplied[0], self.supplied[1])))
        return bool(op(value, self.supplied[0]))

    # -- streaming ---------------------------------------------------------
    def process(self, pad, buf: Buffer):
        cond = self._evaluate(buf)
        action = self.then_action if cond else self.else_action
        pick = self.then_pick if cond else self.else_pick
        pads = getattr(self, "_pads", [SRC])
        target = pads[0] if cond or len(pads) == 1 else pads[-1]
        if action == "SKIP":
            return []
        if action == "PASSTHROUGH":
            return [(target, buf)]
        if action == "TENSORPICK":
            tensors = [buf.tensors[i] for i in (pick or [0])]
            return [(target, buf.with_tensors(tensors, spec=None))]
        raise ElementError(f"unknown tensor_if action {action!r}")


def _parse_pick(opt) -> Optional[List[int]]:
    if opt in (None, ""):
        return None
    return [int(v) for v in str(opt).replace(":", ",").split(",") if v != ""]
