"""nnstreamer_tpu.converters"""
