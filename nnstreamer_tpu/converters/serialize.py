"""Converter sub-plugins: serialized bytes -> tensors.

Reference analog: ``ext/nnstreamer/tensor_converter/tensor_converter_flatbuf
/_protobuf/_flexbuf/_python3`` (SURVEY §2.6).  Counterparts of
decoders/serialize.py over the same wire format; ``python3`` runs a user
callable (module:function) on the raw buffer.
"""

from __future__ import annotations

import importlib
from typing import Optional

import numpy as np

from ..core.buffer import Buffer
from ..core.registry import register_converter
from ..core.types import TensorsSpec
from ..utils.wire import decode_buffer


class _WireConverter:
    out_spec: Optional[TensorsSpec] = None

    def __init__(self, props):
        self.props = dict(props or {})

    def convert(self, buf: Buffer) -> Buffer:
        raw = bytes(np.asarray(buf.tensors[0], np.uint8).tobytes())
        out, _ = decode_buffer(raw)
        out.pts = buf.pts if out.pts is None else out.pts
        return out


@register_converter("flexbuf")
class FlexbufConverter(_WireConverter):
    pass


@register_converter("flatbuf")
class FlatbufConverter(_WireConverter):
    pass


@register_converter("protobuf")
class ProtobufConverter(_WireConverter):
    pass


@register_converter("python3")
class Python3Converter:
    """User-scripted converter: ``mode=python3 script=module:function`` where
    the callable maps a Buffer to a Buffer (reference:
    tensor_converter_python3.cc running a user script class)."""

    out_spec: Optional[TensorsSpec] = None

    def __init__(self, props):
        self.props = dict(props or {})
        target = str(self.props.get("script", ""))
        if ":" not in target:
            raise ValueError("python3 converter needs script=module:function")
        mod, attr = target.split(":", 1)
        self.fn = getattr(importlib.import_module(mod), attr)

    def convert(self, buf: Buffer) -> Buffer:
        out = self.fn(buf)
        if not isinstance(out, Buffer):
            out = Buffer(list(out) if isinstance(out, (list, tuple)) else [np.asarray(out)])
        return out
