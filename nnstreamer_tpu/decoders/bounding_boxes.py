"""bounding_boxes decoder: detections -> overlay video + meta.

Reference analog: ``tensordec-boundingbox.c`` + per-format modules
(mobilenetssd.cc, yolo.cc — SURVEY §2.5, BASELINE config #2): model output
-> threshold -> NMS -> ``video/x-raw`` RGBA overlay with box rectangles;
label file via option properties.

Input contracts (option1 selects, mirroring the reference's format modes):

* ``ssd`` (default): two tensors — boxes (N,4) corner-format, normalized
  [0,1]; scores (N,C) per-class (class 0 may be background when option
  ``bg`` set).  Our models/ssd.py emits exactly this (decoded anchors are a
  model concern, matching how tflite SSD graphs embed their postprocess).
* ``yolov5``: one tensor (N, 5+C): cx,cy,w,h (normalized), objectness,
  class scores.

Options (reference numbering): option1=format, option2=labels,
option3=score threshold (default 0.5), option4=WIDTH:HEIGHT of output
overlay (default 640:480), option5=iou threshold (default 0.5).

Output: RGBA overlay frame (H,W,4) uint8 + ``buf.meta["detections"]`` =
list of dicts {box, score, class_index, label}.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..core.buffer import Buffer
from ..core.caps import Caps, MediaType
from ..core.registry import register_decoder
from ..core.types import TensorsSpec
from ..ops.nms import center_to_corner, nms_numpy
from .base import Decoder, load_labels

_PALETTE = np.array(
    [
        [230, 25, 75, 255], [60, 180, 75, 255], [255, 225, 25, 255],
        [0, 130, 200, 255], [245, 130, 48, 255], [145, 30, 180, 255],
        [70, 240, 240, 255], [240, 50, 230, 255], [210, 245, 60, 255],
        [250, 190, 190, 255],
    ],
    np.uint8,
)


@register_decoder("bounding_boxes")
class BoundingBoxes(Decoder):
    mode = "bounding_boxes"

    def __init__(self, props):
        super().__init__(props)
        self.format = (self.option(1) or "ssd").lower()
        labels = self.option(2) or "coco-mini"
        self.labels = load_labels(labels)
        self.threshold = float(self.option(3) or 0.5)
        size = self.option(4) or "640:480"
        w, h = size.split(":")
        self.out_w, self.out_h = int(w), int(h)
        self.iou_threshold = float(self.option(5) or 0.5)
        self.max_detections = int(self.option(6) or 100)

    def out_caps(self, in_spec: Optional[TensorsSpec]) -> Caps:
        return Caps.new(
            MediaType.VIDEO, format="RGBA", width=self.out_w, height=self.out_h
        )

    # -- decode ------------------------------------------------------------
    def decode(self, tensors: List[np.ndarray], buf: Buffer):
        # Batched buffers ([B, N, ...] per tensor) decode per frame and are
        # emitted as B separate video buffers — NMS must never mix boxes of
        # different frames, and the negotiated caps (one WxH RGBA frame per
        # buffer) stay truthful.  The reference decodes one frame per
        # buffer; TPU pipelines batch upstream and un-batch here.
        first = np.asarray(tensors[0])
        if first.ndim >= 3:
            outs = []
            for b in range(first.shape[0]):
                overlay, dets = self._decode_one(
                    [np.asarray(t)[b] for t in tensors]
                )
                o = buf.with_tensors([overlay], spec=None)
                o.meta["detections"] = dets
                o.meta["batch_index"] = b
                outs.append(o)
            return outs
        overlay, detections = self._decode_one(tensors)
        out = buf.with_tensors([overlay], spec=None)
        out.meta["detections"] = detections
        return out

    def _decode_one(self, tensors: List[np.ndarray]):
        if self.format in ("ssd", "mobilenet-ssd", "mobilenetv2-ssd"):
            boxes, scores, classes = self._decode_ssd(tensors)
        elif self.format in ("yolov5", "yolov8", "yolo"):
            boxes, scores, classes = self._decode_yolo(tensors)
        else:
            raise ValueError(f"unknown bounding-box format {self.format!r}")

        keep = nms_numpy(boxes, scores, self.iou_threshold, self.max_detections)
        detections = []
        for i in keep:
            x1, y1, x2, y2 = boxes[i]
            ci = int(classes[i])
            detections.append(
                {
                    "box": [float(x1), float(y1), float(x2), float(y2)],
                    "score": float(scores[i]),
                    "class_index": ci,
                    "label": self.labels[ci] if ci < len(self.labels) else str(ci),
                }
            )
        return self._draw(detections), detections

    def _decode_ssd(self, tensors):
        boxes = np.asarray(tensors[0], np.float32).reshape(-1, 4)
        scores_all = np.asarray(tensors[1], np.float32)
        scores_all = scores_all.reshape(boxes.shape[0], -1)
        classes = scores_all.argmax(axis=1)
        scores = scores_all.max(axis=1)
        m = scores >= self.threshold
        return boxes[m], scores[m], classes[m]

    def _decode_yolo(self, tensors):
        pred = np.asarray(tensors[0], np.float32)
        pred = pred.reshape(-1, pred.shape[-1])
        xywh, obj, cls = pred[:, :4], pred[:, 4], pred[:, 5:]
        scores_all = obj[:, None] * cls if cls.size else obj[:, None]
        classes = scores_all.argmax(axis=1)
        scores = scores_all.max(axis=1)
        boxes = center_to_corner(xywh)
        m = scores >= self.threshold
        return boxes[m], scores[m], classes[m]

    def _draw(self, detections) -> np.ndarray:
        overlay = np.zeros((self.out_h, self.out_w, 4), np.uint8)
        t = 2  # line thickness (reference draws 1px rectangles + label text)
        for d in detections:
            x1, y1, x2, y2 = d["box"]
            color = _PALETTE[d["class_index"] % len(_PALETTE)]
            px1 = int(np.clip(x1 * self.out_w, 0, self.out_w - 1))
            px2 = int(np.clip(x2 * self.out_w, 0, self.out_w - 1))
            py1 = int(np.clip(y1 * self.out_h, 0, self.out_h - 1))
            py2 = int(np.clip(y2 * self.out_h, 0, self.out_h - 1))
            overlay[py1 : py1 + t, px1:px2] = color
            overlay[max(0, py2 - t) : py2, px1:px2] = color
            overlay[py1:py2, px1 : px1 + t] = color
            overlay[py1:py2, max(0, px2 - t) : px2] = color
        return overlay
