"""bounding_boxes decoder: detections -> overlay video + meta.

Reference analog: ``tensordec-boundingbox.c`` + per-format modules
(mobilenetssd.cc, yolo.cc — SURVEY §2.5, BASELINE config #2): model output
-> threshold -> NMS -> ``video/x-raw`` RGBA overlay with box rectangles;
label file via option properties.

Input contracts (option1 selects, mirroring the reference's format modes):

* ``ssd`` (default): two tensors — boxes (N,4) corner-format, normalized
  [0,1]; scores (N,C) per-class (class 0 may be background when option
  ``bg`` set).  Our models/ssd.py emits exactly this (decoded anchors are a
  model concern, matching how tflite SSD graphs embed their postprocess).
* ``yolov5``: one tensor (N, 5+C): cx,cy,w,h (normalized), objectness,
  class scores.

Options (reference numbering): option1=format, option2=labels,
option3=score threshold (default 0.5), option4=WIDTH:HEIGHT of output
overlay (default 640:480), option5=iou threshold (default 0.5),
option6=max detections, option7=NMS placement (host|device),
option8=model input size for pixel-coordinate boxes,
option9=output form (overlay|tensors).

Output: RGBA overlay frame (H,W,4) uint8 + ``buf.meta["detections"]`` =
list of dicts {box, score, class_index, label}; with option9=tensors,
the detections themselves as tensors (boxes/scores/classes[/valid]) and
no canvas — the indices-not-payloads treatment for headless serving.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..core.buffer import Buffer
from ..core.caps import Caps, MediaType
from ..core.registry import register_decoder
from ..core.types import TensorsSpec
from ..ops.nms import center_to_corner, nms_numpy
from .base import Decoder, load_labels

def _ssd_topk(boxes, scores, k: int):
    """Pure-JAX SSD prefilter shared by the fused device_fn and the unfused
    _device_topk path (they must stay numerically identical — both feed
    ``_decode_one``'s "triple" contract): per-anchor class argmax + top-k.
    boxes [B,N,4], scores [B,N,C] -> ([B,K,4] f32, [B,K] f32, [B,K] i32)."""
    import jax.numpy as jnp
    from jax import lax

    s = scores.reshape(scores.shape[0], scores.shape[1], -1)
    cls = jnp.argmax(s, axis=-1).astype(jnp.int32)
    sc = jnp.max(s, axis=-1)
    top_sc, idx = lax.top_k(sc, k)
    top_b = jnp.take_along_axis(
        boxes.reshape(boxes.shape[0], -1, 4), idx[..., None], axis=1)
    top_c = jnp.take_along_axis(cls, idx, axis=1)
    return (top_b.astype(jnp.float32), top_sc.astype(jnp.float32), top_c)


_PALETTE = np.array(
    [
        [230, 25, 75, 255], [60, 180, 75, 255], [255, 225, 25, 255],
        [0, 130, 200, 255], [245, 130, 48, 255], [145, 30, 180, 255],
        [70, 240, 240, 255], [240, 50, 230, 255], [210, 245, 60, 255],
        [250, 190, 190, 255],
    ],
    np.uint8,
)


@register_decoder("bounding_boxes")
class BoundingBoxes(Decoder):
    mode = "bounding_boxes"

    def __init__(self, props):
        super().__init__(props)
        self.format = (self.option(1) or "ssd").lower()
        labels = self.option(2) or "coco-mini"
        self.labels = load_labels(labels)
        self.threshold = float(self.option(3) or 0.5)
        size = self.option(4) or "640:480"
        w, h = size.split(":")
        self.out_w, self.out_h = int(w), int(h)
        self.iou_threshold = float(self.option(5) or 0.5)
        self.max_detections = int(self.option(6) or 100)
        # option7: where greedy NMS runs when the decoder is fused.
        # "host" (default) = top-k prefilter on device, NMS at the sink
        # edge; "device" = the whole decode (threshold+NMS) inside the
        # fused XLA program via ops.nms.nms_jax — only final detections
        # ever cross to the host.
        nms_opt = (self.option(7) or "host").lower()
        if nms_opt.startswith("nms:"):
            nms_opt = nms_opt[4:]
        if nms_opt not in ("host", "device"):
            raise ValueError(f"option7 (nms placement) must be host|device, "
                             f"got {nms_opt!r}")
        self.nms_mode = nms_opt
        # option8 (yolov8): model-input WIDTH[:HEIGHT] when the tensor
        # carries pixel-coordinate boxes (ultralytics default); unset means
        # normalized [0,1] coords.
        o8 = self.option(8)
        if o8:
            wh = [int(v) for v in str(o8).split(":")]
            mw, mh = (wh[0], wh[0]) if len(wh) == 1 else (wh[0], wh[1])
            self.box_scale = np.asarray([mw, mh, mw, mh], np.float32)
        else:
            self.box_scale = np.float32(1.0)
        # option9: output form.  "overlay" (default) = the reference's
        # video/x-raw RGBA frame with rectangles drawn on the host.
        # "tensors" = ship the detections THEMSELVES (boxes f32 [M,4],
        # scores f32 [M], classes i32 [M]) and skip the canvas — the
        # classification recipe (indices-not-payloads) applied to
        # detection: a batch-256 overlay canvas is ~100 MB of host memset
        # + draw per batch that a headless serving pipeline never looks
        # at.  (The reference has no headless mode; its tensor_region
        # decoder is the precedent for tensor-form decoder output.)
        out_mode = (self.option(9) or "overlay").lower()
        if out_mode not in ("overlay", "tensors"):
            raise ValueError(f"option9 (output form) must be "
                             f"overlay|tensors, got {out_mode!r}")
        self.out_mode = out_mode

    def out_caps(self, in_spec: Optional[TensorsSpec]) -> Caps:
        if self.out_mode == "tensors":
            return Caps.tensors()
        return Caps.new(
            MediaType.VIDEO, format="RGBA", width=self.out_w, height=self.out_h
        )

    # -- decode ------------------------------------------------------------
    def decode(self, tensors: List[np.ndarray], buf: Buffer):
        # Batched buffers ([B, N, ...] per tensor) decode per frame and are
        # emitted as B separate video buffers — NMS must never mix boxes of
        # different frames, and the negotiated caps (one WxH RGBA frame per
        # buffer) stay truthful.  The reference decodes one frame per
        # buffer; TPU pipelines batch upstream and un-batch here.
        ndim = getattr(tensors[0], "ndim", None)
        if ndim is None:
            ndim = np.asarray(tensors[0]).ndim
        if ndim >= 3:
            outs = []
            for b, frame in enumerate(self._split_frames(tensors)):
                dets = self._decode_dets(frame)
                if self.out_mode == "tensors":
                    o = buf.with_tensors(self._det_tensors(dets), spec=None)
                else:
                    o = buf.with_tensors([self._draw(dets)], spec=None)
                o.meta["detections"] = dets
                o.meta["batch_index"] = b
                outs.append(o)
            return outs
        detections = self._decode_dets(tensors)
        if self.out_mode == "tensors":
            out = buf.with_tensors(self._det_tensors(detections), spec=None)
        else:
            out = buf.with_tensors([self._draw(detections)], spec=None)
        out.meta["detections"] = detections
        return out

    @staticmethod
    def _det_tensors(dets) -> List[np.ndarray]:
        """detections list -> (boxes f32 [M,4], scores f32 [M],
        classes i32 [M]) — the option9=tensors output contract."""
        m = len(dets)
        boxes = np.zeros((m, 4), np.float32)
        scores = np.zeros((m,), np.float32)
        classes = np.zeros((m,), np.int32)
        for i, d in enumerate(dets):
            boxes[i] = d["box"]
            scores[i] = d["score"]
            classes[i] = d["class_index"]
        return [boxes, scores, classes]

    def _split_frames(self, tensors):
        """Per-frame inputs for a batched buffer.  SSD-format device arrays
        go through a jitted top-k prefilter FIRST (SURVEY §7 hard-parts:
        "NMS on TPU -> top-k based approximation"): only K=4*max_detections
        candidates per frame cross to the host instead of the full
        [B, N, C] score tensor — the host-side greedy NMS then runs on K
        boxes, not thousands."""
        n = tensors[0].shape[1]
        k = 4 * self.max_detections
        if self.format in ("ssd", "mobilenet-ssd", "mobilenetv2-ssd") and n > k:
            tb, ts, tc = self._device_topk(tensors[0], tensors[1], k)
            return [
                ("triple", (tb[b], ts[b], tc[b])) for b in range(tb.shape[0])
            ]
        host = [np.asarray(t) for t in tensors]  # ONE device fetch per tensor
        return [
            ("raw", [t[b] for t in host]) for b in range(host[0].shape[0])
        ]

    def _device_topk(self, boxes, scores, k: int):
        import jax
        import jax.numpy as jnp

        fn = getattr(self, "_topk_fn", None)
        if fn is None:
            fn = self._topk_fn = jax.jit(
                lambda b, s: _ssd_topk(b, s, k))
        tb, ts, tc = fn(jnp.asarray(boxes), jnp.asarray(scores))
        return np.asarray(tb), np.asarray(ts), np.asarray(tc)

    def _decode_dets(self, frame):
        if isinstance(frame, tuple) and frame[0] == "triple":
            boxes, scores, classes = frame[1]
            m = scores >= self.threshold
            boxes, scores, classes = boxes[m], scores[m], classes[m]
        else:
            tensors = frame[1] if isinstance(frame, tuple) else frame
            if self.format in ("ssd", "mobilenet-ssd", "mobilenetv2-ssd"):
                boxes, scores, classes = self._decode_ssd(tensors)
            elif self.format == "yolov8":
                boxes, scores, classes = self._decode_yolov8(tensors)
            elif self.format in ("yolov5", "yolo"):
                boxes, scores, classes = self._decode_yolo(tensors)
            else:
                raise ValueError(f"unknown bounding-box format {self.format!r}")

        keep = nms_numpy(boxes, scores, self.iou_threshold, self.max_detections)
        detections = []
        for i in keep:
            x1, y1, x2, y2 = boxes[i]
            ci = int(classes[i])
            detections.append(
                {
                    "box": [float(x1), float(y1), float(x2), float(y2)],
                    "score": float(scores[i]),
                    "class_index": ci,
                    "label": self.labels[ci] if ci < len(self.labels) else str(ci),
                }
            )
        return detections

    # -- fusion ------------------------------------------------------------
    # The whole prefilter joins the fused XLA program: per-anchor class
    # argmax + top-k run on device, only [B,K] candidates cross to the host
    # (async D2H started by the fused stage), and threshold/NMS/overlay
    # resolve in ``host_post`` at the sink edge.  The fused path emits ONE
    # buffer per (possibly batched) input with stacked overlays [B,H,W,4]
    # and per-frame ``meta["detections"]`` lists; the unfused host path
    # keeps the reference's one-video-frame-per-buffer un-batching.
    def device_fn(self, in_spec: TensorsSpec):
        import jax.numpy as jnp
        from jax import lax

        from ..core.types import TensorSpec

        fmt = self.format
        if fmt in ("ssd", "mobilenet-ssd", "mobilenetv2-ssd"):
            if len(in_spec) < 2:
                return None
            bshape = in_spec[0].shape  # (B, N, 4)
            if len(bshape) != 3:
                return None
            batch, n = bshape[0], bshape[1]
            k = min(4 * self.max_detections, n)

            def fn(arrays):
                return _ssd_topk(arrays[0], arrays[1], k)

        elif fmt in ("yolov5", "yolov8", "yolo"):
            if len(in_spec) != 1 or len(in_spec[0].shape) != 3:
                return None
            v8 = fmt == "yolov8"
            if v8:
                batch, c4, n = in_spec[0].shape  # channels-first (B,4+C,N)
                if c4 < 5:
                    return None
            else:
                batch, n, width = in_spec[0].shape
                if width < 5:
                    return None
            k = min(4 * self.max_detections, n)
            box_scale = jnp.asarray(self.box_scale, jnp.float32)

            def fn(arrays):
                pred = arrays[0].astype(jnp.float32)
                if v8:
                    pred = jnp.swapaxes(pred, 1, 2)  # -> (B, N, 4+C)
                    xywh = pred[..., :4] / box_scale
                    sc_all = pred[..., 4:]
                else:
                    xywh, obj, cls = (pred[..., :4], pred[..., 4],
                                      pred[..., 5:])
                    sc_all = (obj[..., None] * cls if cls.shape[-1]
                              else obj[..., None])
                classes = jnp.argmax(sc_all, axis=-1).astype(jnp.int32)
                sc = jnp.max(sc_all, axis=-1)
                top_sc, idx = lax.top_k(sc, k)
                cx, cy = xywh[..., 0], xywh[..., 1]
                w2, h2 = xywh[..., 2] / 2, xywh[..., 3] / 2
                boxes = jnp.stack(
                    [cx - w2, cy - h2, cx + w2, cy + h2], axis=-1)
                top_b = jnp.take_along_axis(boxes, idx[..., None], axis=1)
                top_c = jnp.take_along_axis(classes, idx, axis=1)
                return (top_b, top_sc, top_c)

        else:
            return None

        if self.nms_mode == "device":
            import jax

            from ..ops.nms import nms_jax

            m = self.max_detections
            thr, iou_thr = self.threshold, self.iou_threshold
            pack = self.out_mode == "tensors"

            def fn_nms(arrays):
                tb, ts, tc = fn(arrays)
                masked = jnp.where(ts >= thr, ts, -jnp.inf)

                def per_frame(b, s):
                    idx, valid = nms_jax(b, s, iou_thr, m)
                    return (jnp.take(b, idx, axis=0),
                            jnp.where(valid, jnp.take(s, idx), 0.0),
                            idx, valid)

                kb, ks, kidx, kv = jax.vmap(per_frame)(tb, masked)
                kc = jnp.take_along_axis(tc, kidx, axis=1)
                if pack:
                    # ONE [B, M, 7] tensor (x1 y1 x2 y2 score class valid):
                    # the D2H payload crosses the sink edge as a single
                    # transfer — over a tunneled device each separate
                    # tensor pays its own round trip (measured 4x36 ms vs
                    # 15 ms packed per 256-batch)
                    return (jnp.concatenate(
                        [kb, ks[..., None], kc.astype(jnp.float32)[..., None],
                         kv.astype(jnp.float32)[..., None]], axis=-1),)
                return (kb, ks, kc, kv.astype(jnp.uint8))

            if pack:
                out_spec = TensorsSpec((
                    TensorSpec.from_shape((batch, m, 7), np.float32),))
            else:
                out_spec = TensorsSpec((
                    TensorSpec.from_shape((batch, m, 4), np.float32),
                    TensorSpec.from_shape((batch, m), np.float32),
                    TensorSpec.from_shape((batch, m), np.int32),
                    TensorSpec.from_shape((batch, m), np.uint8),
                ))
            return fn_nms, out_spec

        out_spec = TensorsSpec((
            TensorSpec.from_shape((batch, k, 4), np.float32),
            TensorSpec.from_shape((batch, k), np.float32),
            TensorSpec.from_shape((batch, k), np.int32),
        ))
        return fn, out_spec

    def host_post(self, arrays, buf: Buffer) -> Buffer:
        if self.out_mode == "tensors":
            return self._host_post_tensors(arrays, buf)
        tb = np.asarray(arrays[0], np.float32)
        ts = np.asarray(arrays[1], np.float32)
        tc = np.asarray(arrays[2])
        valid = np.asarray(arrays[3]).astype(bool) if len(arrays) > 3 else None
        b = tb.shape[0]
        canvas = np.zeros((b, self.out_h, self.out_w, 4), np.uint8)
        dets = []
        for i in range(b):
            if valid is not None:
                # device-NMS path: arrays ARE the final detections
                d = [
                    {
                        "box": [float(v) for v in tb[i, j]],
                        "score": float(ts[i, j]),
                        "class_index": int(tc[i, j]),
                        "label": (self.labels[int(tc[i, j])]
                                  if int(tc[i, j]) < len(self.labels)
                                  else str(int(tc[i, j]))),
                    }
                    for j in range(tb.shape[1]) if valid[i, j]
                ]
                self._draw_into(canvas[i], d)
            else:
                d = self._decode_dets(("triple", (tb[i], ts[i], tc[i])))
                self._draw_into(canvas[i], d)
            dets.append(d)
        if b == 1:
            new = buf.with_tensors([canvas[0]], spec=None)
            new.meta["detections"] = dets[0]
            return new
        new = buf.with_tensors([canvas], spec=None)
        new.meta["detections"] = dets
        return new

    def _host_post_tensors(self, arrays, buf: Buffer) -> Buffer:
        """option9=tensors sink edge: NO canvas, NO per-detection Python
        dicts — with device NMS ONE packed [B,M,7] array crossed D2H and
        unpacks here into (boxes [B,M,4], scores, classes, valid); with
        host NMS the greedy pass runs here and pads into the same
        layout.  Host work per batch is O(B*M) numpy, not O(B*H*W)
        pixels."""
        if len(arrays) == 1:  # device NMS emitted packed [B, M, 7]
            p = np.asarray(arrays[0], np.float32)
            return buf.with_tensors(
                [np.ascontiguousarray(p[..., :4]),
                 np.ascontiguousarray(p[..., 4]),
                 p[..., 5].astype(np.int32),
                 p[..., 6].astype(np.uint8)], spec=None)
        tb = np.asarray(arrays[0], np.float32)
        ts = np.asarray(arrays[1], np.float32)
        tc = np.asarray(arrays[2])
        b, m = tb.shape[0], self.max_detections
        boxes = np.zeros((b, m, 4), np.float32)
        scores = np.zeros((b, m), np.float32)
        classes = np.zeros((b, m), np.int32)
        valid = np.zeros((b, m), np.uint8)
        for i in range(b):
            d = self._decode_dets(("triple", (tb[i], ts[i], tc[i])))
            for j, det in enumerate(d[:m]):
                boxes[i, j] = det["box"]
                scores[i, j] = det["score"]
                classes[i, j] = det["class_index"]
                valid[i, j] = 1
        return buf.with_tensors([boxes, scores, classes, valid], spec=None)

    def _decode_ssd(self, tensors):
        boxes = np.asarray(tensors[0], np.float32).reshape(-1, 4)
        scores_all = np.asarray(tensors[1], np.float32)
        scores_all = scores_all.reshape(boxes.shape[0], -1)
        classes = scores_all.argmax(axis=1)
        scores = scores_all.max(axis=1)
        m = scores >= self.threshold
        return boxes[m], scores[m], classes[m]

    def _decode_yolo(self, tensors):
        pred = np.asarray(tensors[0], np.float32)
        pred = pred.reshape(-1, pred.shape[-1])
        xywh, obj, cls = pred[:, :4], pred[:, 4], pred[:, 5:]
        scores_all = obj[:, None] * cls if cls.size else obj[:, None]
        classes = scores_all.argmax(axis=1)
        scores = scores_all.max(axis=1)
        boxes = center_to_corner(xywh)
        m = scores >= self.threshold
        return boxes[m], scores[m], classes[m]

    def _decode_yolov8(self, tensors):
        # ultralytics export layout: (4+C, N) channels-first per frame,
        # anchor-free — class scores ARE the confidence (no objectness).
        pred = np.asarray(tensors[0], np.float32)
        if pred.ndim == 3:
            pred = pred.reshape(pred.shape[-2], pred.shape[-1])
        pred = pred.T  # (N, 4+C)
        xywh, cls = pred[:, :4], pred[:, 4:]
        classes = cls.argmax(axis=1)
        scores = cls.max(axis=1)
        boxes = center_to_corner(xywh / self.box_scale)
        m = scores >= self.threshold
        return boxes[m], scores[m], classes[m]

    def _draw(self, detections) -> np.ndarray:
        overlay = np.zeros((self.out_h, self.out_w, 4), np.uint8)
        self._draw_into(overlay, detections)
        return overlay

    def _draw_into(self, overlay: np.ndarray, detections) -> np.ndarray:
        """Draw in place — the batched host_post path allocates ONE
        [B, H, W, 4] canvas and draws each frame into its row view
        (per-frame zeros + a final np.stack copy were ~70% of the
        measured host_post time at batch 64)."""
        t = 2  # line thickness (reference draws 1px rectangles + label text)
        for d in detections:
            x1, y1, x2, y2 = d["box"]
            color = _PALETTE[d["class_index"] % len(_PALETTE)]
            px1 = int(np.clip(x1 * self.out_w, 0, self.out_w - 1))
            px2 = int(np.clip(x2 * self.out_w, 0, self.out_w - 1))
            py1 = int(np.clip(y1 * self.out_h, 0, self.out_h - 1))
            py2 = int(np.clip(y2 * self.out_h, 0, self.out_h - 1))
            overlay[py1 : py1 + t, px1:px2] = color
            overlay[max(0, py2 - t) : py2, px1:px2] = color
            overlay[py1:py2, px1 : px1 + t] = color
            overlay[py1:py2, max(0, px2 - t) : px2] = color
        return overlay
