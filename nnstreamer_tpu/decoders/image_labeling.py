"""image_labeling decoder: scores -> text label.

Reference analog: ``tensordec-imagelabel.c`` (SURVEY §2.5, BASELINE config #1):
argmax over the class-scores tensor, mapped through a labels file, emitted as
``text/x-raw`` (uint8 bytes here) with index/label/score in buffer meta.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..core.buffer import Buffer
from ..core.caps import Caps, MediaType
from ..core.registry import register_decoder
from ..core.types import TensorsSpec
from .base import Decoder, load_labels


@register_decoder("image_labeling")
class ImageLabeling(Decoder):
    mode = "image_labeling"

    def __init__(self, props):
        super().__init__(props)
        labels = self.option(1) or str(props.get("labels", "")) or "imagenet-mini"
        self.labels = load_labels(labels)

    def out_caps(self, in_spec: Optional[TensorsSpec]) -> Caps:
        return Caps.new(MediaType.TEXT)

    def decode(self, tensors: List[np.ndarray], buf: Buffer) -> Buffer:
        scores = np.asarray(tensors[0])
        if scores.ndim >= 2 and scores.shape[0] > 1:
            # Batched scores [B, C]: one label per row (TPU pipelines batch
            # frames; the reference decodes one frame per buffer).
            flat = scores.reshape(scores.shape[0], -1)
            idxs = np.argmax(flat, axis=1)
            names = [
                self.labels[i] if i < len(self.labels) else str(i) for i in idxs
            ]
            text = "\n".join(names)
            new = buf.with_tensors(
                [np.frombuffer(text.encode("utf-8"), np.uint8)], spec=None
            )
            new.meta.update(
                label=names,
                label_index=idxs,
                score=flat[np.arange(len(idxs)), idxs].astype(np.float32),
            )
            return new
        scores = scores.reshape(-1)
        idx = int(np.argmax(scores))
        label = self.labels[idx] if idx < len(self.labels) else str(idx)
        out = np.frombuffer(label.encode("utf-8"), np.uint8)
        new = buf.with_tensors([out], spec=None)
        new.meta.update(
            label=label, label_index=idx, score=float(scores[idx])
        )
        return new

    # No device_fn: the host path emits text, which an XLA program cannot —
    # fused and unfused paths must stay bit-identical (argmax over ~1k floats
    # on host is negligible; the model stays fused upstream).
