"""image_labeling decoder: scores -> text label.

Reference analog: ``tensordec-imagelabel.c`` (SURVEY §2.5, BASELINE config #1):
argmax over the class-scores tensor, mapped through a labels file, emitted as
``text/x-raw`` (uint8 bytes here) with index/label/score in buffer meta.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..core.buffer import Buffer
from ..core.caps import Caps, MediaType
from ..core.registry import register_decoder
from ..core.types import TensorsSpec
from .base import Decoder, load_labels


@register_decoder("image_labeling")
class ImageLabeling(Decoder):
    mode = "image_labeling"

    def __init__(self, props):
        super().__init__(props)
        # read both prop spellings unconditionally (property-check safe)
        opt1 = self.option(1)
        labels_prop = str(props.get("labels", ""))
        labels = opt1 or labels_prop or "imagenet-mini"
        self.labels = load_labels(labels)

    def out_caps(self, in_spec: Optional[TensorsSpec]) -> Caps:
        return Caps.new(MediaType.TEXT)

    def decode(self, tensors: List[np.ndarray], buf: Buffer) -> Buffer:
        scores = np.asarray(tensors[0])
        if scores.ndim >= 2 and scores.shape[0] > 1:
            # Batched scores [B, C]: one label per row (TPU pipelines batch
            # frames; the reference decodes one frame per buffer).
            flat = scores.reshape(scores.shape[0], -1)
            idxs = np.argmax(flat, axis=1)
            names = [
                self.labels[i] if i < len(self.labels) else str(i) for i in idxs
            ]
            text = "\n".join(names)
            new = buf.with_tensors(
                [np.frombuffer(text.encode("utf-8"), np.uint8)], spec=None
            )
            new.meta.update(
                label=names,
                label_index=idxs,
                score=flat[np.arange(len(idxs)), idxs].astype(np.float32),
            )
            return new
        scores = scores.reshape(-1)
        idx = int(np.argmax(scores))
        label = self.labels[idx] if idx < len(self.labels) else str(idx)
        out = np.frombuffer(label.encode("utf-8"), np.uint8)
        new = buf.with_tensors([out], spec=None)
        new.meta.update(
            label=label, label_index=idx, score=float(scores[idx])
        )
        return new

    # Fusion: the argmax+gather runs on device (tiny [B] outputs instead of a
    # [B, classes] logits transfer), and the text/label mapping happens in
    # ``host_post`` at the pipeline edge — so the fused program's D2H is a few
    # hundred bytes and the label lookup never blocks a streaming thread.
    def device_fn(self, in_spec: TensorsSpec):
        import jax.numpy as jnp

        from ..core.types import TensorSpec

        shape = in_spec[0].shape
        batch = shape[0] if len(shape) >= 2 else 1

        def fn(arrays):
            scores = arrays[0]
            # Batch from the RUNTIME shape, not the negotiated spec: a
            # truncated tail batch (num-buffers not batch-aligned) retraces
            # with its own leading dim.
            b = scores.shape[0] if scores.ndim >= 2 else 1
            flat = scores.reshape(b, -1)
            idx = jnp.argmax(flat, axis=1).astype(jnp.int32)
            score = jnp.take_along_axis(flat, idx[:, None], axis=1)[:, 0]
            return (idx, score.astype(jnp.float32))

        out_spec = TensorsSpec(
            (
                TensorSpec.from_shape((batch,), np.int32),
                TensorSpec.from_shape((batch,), np.float32),
            )
        )
        return fn, out_spec

    def host_post(self, arrays, buf: Buffer) -> Buffer:
        idxs = np.asarray(arrays[0]).reshape(-1)
        scores = np.asarray(arrays[1]).reshape(-1)
        names = [
            self.labels[i] if i < len(self.labels) else str(i) for i in idxs
        ]
        if len(idxs) > 1:
            text = "\n".join(names)
            new = buf.with_tensors(
                [np.frombuffer(text.encode("utf-8"), np.uint8)], spec=None
            )
            new.meta.update(
                label=names, label_index=idxs, score=scores.astype(np.float32)
            )
            return new
        new = buf.with_tensors(
            [np.frombuffer(names[0].encode("utf-8"), np.uint8)], spec=None
        )
        new.meta.update(
            label=names[0], label_index=int(idxs[0]), score=float(scores[0])
        )
        return new
