"""image_labeling decoder: scores -> text label.

Reference analog: ``tensordec-imagelabel.c`` (SURVEY §2.5, BASELINE config #1):
argmax over the class-scores tensor, mapped through a labels file, emitted as
``text/x-raw`` (uint8 bytes here) with index/label/score in buffer meta.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..core.buffer import Buffer
from ..core.caps import Caps, MediaType
from ..core.registry import register_decoder
from ..core.types import TensorsSpec
from .base import Decoder, load_labels


@register_decoder("image_labeling")
class ImageLabeling(Decoder):
    mode = "image_labeling"

    def __init__(self, props):
        super().__init__(props)
        labels = self.option(1) or str(props.get("labels", "")) or "imagenet-mini"
        self.labels = load_labels(labels)

    def out_caps(self, in_spec: Optional[TensorsSpec]) -> Caps:
        return Caps.new(MediaType.TEXT)

    def decode(self, tensors: List[np.ndarray], buf: Buffer) -> Buffer:
        scores = tensors[0].reshape(-1)
        idx = int(np.argmax(scores))
        label = self.labels[idx] if idx < len(self.labels) else str(idx)
        out = np.frombuffer(label.encode("utf-8"), np.uint8)
        new = buf.with_tensors([out], spec=None)
        new.meta.update(
            label=label, label_index=idx, score=float(scores[idx])
        )
        return new

    # No device_fn: the host path emits text, which an XLA program cannot —
    # fused and unfused paths must stay bit-identical (argmax over ~1k floats
    # on host is negligible; the model stays fused upstream).
