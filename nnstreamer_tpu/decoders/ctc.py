"""ctc decoder: per-frame vocab logits -> collapsed token ids / text.

Decode-on-edge for streaming speech models (wav2vec2-class CTC heads).
The reference decodes speech OUTSIDE the pipeline (its tensor_decoder has
no CTC mode — this is the framework's decode-on-edge pattern from
tensordec-imagelabel.c applied to sequence logits, SURVEY §2.5).

The TPU payoff is the same as the video decoders': ``device_fn`` reduces
the [B, T, vocab] logits to [B, T] int32 argmax ids INSIDE the fused XLA
program, so D2H shrinks by a factor of vocab (wav2vec2's 1.6 MB logits
per 64-window batch -> ~12 KB of ids) — on a tunneled chip that transfer
was the entire bottleneck (round-2 bench: 405 win/s, D2H-bound).
``host_post`` then does the cheap vectorized CTC collapse (drop repeats,
drop blanks) and optional charmap at the pipeline edge.

Options: ``option1`` = blank id (default 0); ``option2`` = labels file /
charmap name for text output (optional — one character or token per
line, id-indexed).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..core.buffer import Buffer
from ..core.caps import Caps, MediaType
from ..core.registry import register_decoder
from ..core.types import TensorSpec, TensorsSpec
from .base import Decoder, load_labels


def collapse_ctc(ids: np.ndarray, blank: int) -> List[np.ndarray]:
    """[B, T] argmax ids -> per-row collapsed sequences (vectorized:
    repeat-removal and blank-removal are boolean masks, no Python loop
    over T)."""
    ids = np.asarray(ids)
    if ids.ndim == 1:
        ids = ids[None, :]
    keep = np.ones(ids.shape, bool)
    keep[:, 1:] = ids[:, 1:] != ids[:, :-1]
    keep &= ids != blank
    return [row[k] for row, k in zip(ids, keep)]


@register_decoder("ctc")
class CTC(Decoder):
    mode = "ctc"

    def __init__(self, props):
        super().__init__(props)
        self.blank = int(self.option(1) or 0)
        labels = self.option(2)
        self.labels = load_labels(labels) if labels else None

    def out_caps(self, in_spec: Optional[TensorsSpec]) -> Caps:
        return Caps.new(MediaType.TEXT if self.labels else MediaType.TENSORS)

    # -- host path (unfused pipelines) -------------------------------------
    def decode(self, tensors: List[np.ndarray], buf: Buffer) -> Buffer:
        logits = np.asarray(tensors[0])
        if logits.ndim == 2:
            logits = logits[None]
        ids = np.argmax(logits, axis=-1).astype(np.int32)
        return self._emit(ids, buf)

    # -- fused path ---------------------------------------------------------
    def device_fn(self, in_spec: TensorsSpec):
        import jax.numpy as jnp

        shape = in_spec[0].shape if in_spec is not None else None

        def fn(arrays):
            logits = arrays[0]
            if logits.ndim == 2:
                logits = logits[None]
            return (jnp.argmax(logits, axis=-1).astype(jnp.int32),)

        if shape is not None and len(shape) == 3:
            out_spec = TensorsSpec(
                (TensorSpec.from_shape(shape[:2], np.int32),))
        else:
            out_spec = None  # FLEXIBLE upstream: spec derived per buffer
        return fn, out_spec

    def host_post(self, arrays, buf: Buffer) -> Buffer:
        return self._emit(np.asarray(arrays[0]), buf)

    def _emit(self, ids: np.ndarray, buf: Buffer) -> Buffer:
        seqs = collapse_ctc(ids, self.blank)
        if self.labels is not None:
            texts = ["".join(self.labels[i] if i < len(self.labels) else "?"
                             for i in s) for s in seqs]
            joined = "\n".join(texts)
            new = buf.with_tensors(
                [np.frombuffer(joined.encode("utf-8"), np.uint8)], spec=None)
            new.meta.update(tokens=seqs, text=texts)
            return new
        # tensor output: left-packed ids padded with -1 to the longest row
        width = max((len(s) for s in seqs), default=0) or 1
        out = np.full((len(seqs), width), -1, np.int32)
        for r, s in enumerate(seqs):
            out[r, :len(s)] = s
        new = buf.with_tensors([out], spec=None)
        new.meta.update(tokens=seqs,
                        lengths=np.array([len(s) for s in seqs], np.int32))
        return new
