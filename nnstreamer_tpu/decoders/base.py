"""Decoder sub-plugin API.

Reference analog: ``NNStreamerExternalDecoder`` vtable from
``nnstreamer_plugin_api_decoder.h`` (SURVEY §2.5) — the ``tensor_decoder``
shell element dispatches to a sub-plugin chosen by ``mode=``.

Option properties follow the reference convention: ``option1..option9``
carry mode-specific config (labels path, output size, thresholds, ...).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..core.buffer import Buffer
from ..core.caps import Caps
from ..core.types import TensorsSpec


class Decoder:
    """Base decoder sub-plugin: tensors -> media/overlay/meta."""

    mode: str = "base"

    def __init__(self, props: Dict[str, object]):
        # Keep the SAME dict the element was built with (not a copy): the
        # pipeline's unknown-property check needs the decoder's reads of
        # optionN/etc. recorded on the element's tracked props.
        self.props = props if isinstance(props, dict) else dict(props)

    def option(self, n: int, default: str = "") -> str:
        v = self.props.get(f"option{n}", default)
        return str(v) if v is not None else default

    # -- negotiation -------------------------------------------------------
    def out_caps(self, in_spec: Optional[TensorsSpec]) -> Caps:
        return Caps.any()

    # -- decode ------------------------------------------------------------
    def decode(self, tensors: List[np.ndarray], buf: Buffer) -> Buffer:
        raise NotImplementedError

    # -- fusion (optional) -------------------------------------------------
    def device_fn(self, in_spec: TensorsSpec):
        """Pure-JAX decode for fusion; None => host decode."""
        return None

    # When device_fn is provided, ``host_post`` (if also defined) maps the
    # fetched (tiny) device outputs into the final media buffer on the host —
    # lazily, at the pipeline edge, so the D2H roundtrip latency never blocks
    # the streaming threads.  None => device outputs ARE the final payload.
    host_post = None

    #: HBM-residency planner opt-in (pipeline/residency.py): True when this
    #: decoder's output contract survives an upstream model emitting its
    #: REDUCED output geometry (e.g. a native-stride score map instead of
    #: the full-res blow-up).  Conservative default: a decoder that
    #: produces fixed-geometry media (overlays, canvases) must stay False.
    admits_reduced_payload = False


def load_labels(path_or_name: str) -> List[str]:
    """Load a labels file (one label per line, reference format).  A few
    builtin names avoid needing data files in tests: ``imagenet-mini``,
    ``coco-mini``, ``digits``."""
    builtin = {
        "digits": [str(i) for i in range(10)],
        "imagenet-mini": [f"class_{i}" for i in range(1001)],
        "coco-mini": [f"obj_{i}" for i in range(91)],
    }
    if path_or_name in builtin:
        return builtin[path_or_name]
    with open(path_or_name, "r", encoding="utf-8") as f:
        return [line.strip() for line in f if line.strip()]
