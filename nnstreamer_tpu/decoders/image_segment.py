"""image_segment decoder: class map -> colored overlay.

Reference analog: ``tensordec-imagesegment.c`` (SURVEY §2.5): per-pixel class
scores (H,W,C) or class ids (H,W) -> RGBA color overlay.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..core.buffer import Buffer
from ..core.caps import Caps, MediaType
from ..core.registry import register_decoder
from ..core.types import TensorsSpec
from .base import Decoder

_COLORS = np.array(
    [
        [0, 0, 0, 0],  # class 0 = background, transparent
        [230, 25, 75, 160], [60, 180, 75, 160], [255, 225, 25, 160],
        [0, 130, 200, 160], [245, 130, 48, 160], [145, 30, 180, 160],
        [70, 240, 240, 160], [240, 50, 230, 160], [210, 245, 60, 160],
        [250, 190, 190, 160], [0, 128, 128, 160], [230, 190, 255, 160],
        [170, 110, 40, 160], [255, 250, 200, 160], [128, 0, 0, 160],
        [170, 255, 195, 160], [128, 128, 0, 160], [255, 215, 180, 160],
        [0, 0, 128, 160], [128, 128, 128, 160],
    ],
    np.uint8,
)


@register_decoder("image_segment")
class ImageSegment(Decoder):
    mode = "image_segment"

    def out_caps(self, in_spec: Optional[TensorsSpec]) -> Caps:
        return Caps.new(MediaType.VIDEO, format="RGBA")

    def decode(self, tensors: List[np.ndarray], buf: Buffer) -> Buffer:
        x = np.asarray(tensors[0])
        x = np.squeeze(x)
        if x.ndim == 3:  # (H,W,C) scores -> argmax
            classes = x.argmax(axis=-1)
        elif x.ndim == 2:
            classes = x.astype(np.int64)
        else:
            raise ValueError(f"image_segment expects rank 2/3, got {x.shape}")
        overlay = _COLORS[classes % len(_COLORS)]
        out = buf.with_tensors([overlay], spec=None)
        out.meta["class_map"] = classes
        return out
