"""image_segment decoder: class map -> colored overlay.

Reference analog: ``tensordec-imagesegment.c`` (SURVEY §2.5): per-pixel class
scores (H,W,C) or class ids (H,W) -> RGBA color overlay.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..core.buffer import Buffer
from ..core.caps import Caps, MediaType
from ..core.registry import register_decoder
from ..core.types import TensorsSpec
from .base import Decoder

_COLORS = np.array(
    [
        [0, 0, 0, 0],  # class 0 = background, transparent
        [230, 25, 75, 160], [60, 180, 75, 160], [255, 225, 25, 160],
        [0, 130, 200, 160], [245, 130, 48, 160], [145, 30, 180, 160],
        [70, 240, 240, 160], [240, 50, 230, 160], [210, 245, 60, 160],
        [250, 190, 190, 160], [0, 128, 128, 160], [230, 190, 255, 160],
        [170, 110, 40, 160], [255, 250, 200, 160], [128, 0, 0, 160],
        [170, 255, 195, 160], [128, 128, 0, 160], [255, 215, 180, 160],
        [0, 0, 128, 160], [128, 128, 128, 160],
    ],
    np.uint8,
)


@register_decoder("image_segment")
class ImageSegment(Decoder):
    """option1=output form: ``overlay`` (default, the reference's RGBA
    palette composite) or ``classmap`` (the u8 per-pixel class ids
    THEMSELVES, no palette gather and 1/4 the bytes — the
    indices-not-payloads treatment; the consumer composites only the
    frames it displays)."""

    mode = "image_segment"

    def __init__(self, props):
        super().__init__(props)
        out_mode = (self.option(1) or "overlay").lower()
        if out_mode not in ("overlay", "classmap"):
            raise ValueError(f"option1 (output form) must be "
                             f"overlay|classmap, got {out_mode!r}")
        self.out_mode = out_mode
        # classmap output is geometry-agnostic (flexible tensors caps; the
        # argmax works at any spatial stride, and the map IS the class
        # decision) — the residency planner may feed it a native-stride
        # score map.  overlay is fixed-geometry RGBA media: full res only.
        self.admits_reduced_payload = out_mode == "classmap"

    def out_caps(self, in_spec: Optional[TensorsSpec]) -> Caps:
        if self.out_mode == "classmap":
            return Caps.tensors()
        return Caps.new(MediaType.VIDEO, format="RGBA")

    def decode(self, tensors: List[np.ndarray], buf: Buffer) -> Buffer:
        x = np.asarray(tensors[0])
        x = np.squeeze(x)
        if x.ndim == 3:  # (H,W,C) scores -> argmax
            classes = x.argmax(axis=-1)
        elif x.ndim == 2:
            classes = x.astype(np.int64)
        else:
            raise ValueError(f"image_segment expects rank 2/3, got {x.shape}")
        if self.out_mode == "classmap":
            # match device_fn's dtype rule: u8 only when ids fit — a
            # >256-class model must not silently wrap its ids
            n_cls = x.shape[-1] if x.ndim == 3 else \
                int(classes.max(initial=0)) + 1
            dt = np.uint8 if n_cls <= 256 else np.int32
            out = buf.with_tensors([classes.astype(dt)], spec=None)
            out.meta["class_map"] = classes
            return out
        overlay = _COLORS[classes % len(_COLORS)]
        out = buf.with_tensors([overlay], spec=None)
        out.meta["class_map"] = classes
        return out

    # -- fusion ------------------------------------------------------------
    # The per-pixel argmax joins the fused XLA program, so only a 1-byte
    # class id per pixel crosses to the host (vs 4*C score bytes); the
    # palette gather resolves in ``host_post``.  Batched input fuses too
    # (stacked overlays, one buffer) — the host decode path only accepts
    # single frames, matching the reference.
    def device_fn(self, in_spec: TensorsSpec):
        import jax.numpy as jnp

        from ..core.types import TensorSpec

        shape = in_spec[0].shape
        if len(shape) not in (3, 4):
            return None
        classes = shape[-1]
        cls_dtype = np.uint8 if classes <= 256 else np.int32

        def fn(arrays):
            x = arrays[0]
            return (jnp.argmax(x, axis=-1).astype(cls_dtype),)

        out_spec = TensorsSpec(
            (TensorSpec.from_shape(shape[:-1], cls_dtype),))
        return fn, out_spec

    def host_post(self, arrays, buf: Buffer) -> Buffer:
        if self.out_mode == "classmap":
            # the device argmax's u8 map IS the output: no host palette
            # gather, no int64 upcast — D2H stays 1 byte/pixel
            classes = np.asarray(arrays[0])
            if classes.ndim == 3 and classes.shape[0] == 1:
                classes = classes[0]
            out = buf.with_tensors([classes], spec=None)
            out.meta["class_map"] = classes
            return out
        classes = np.asarray(arrays[0]).astype(np.int64)
        if classes.ndim == 3 and classes.shape[0] == 1:
            # Collapse batch-1 like the host decode path (np.squeeze) so
            # the output honors the negotiated one-frame RGBA caps.
            classes = classes[0]
        overlay = _COLORS[classes % len(_COLORS)]
        out = buf.with_tensors([overlay], spec=None)
        out.meta["class_map"] = classes
        return out
