"""Serialization decoders: tensors -> self-describing bytes.

Reference analog: ``tensordec-flatbuf.cc`` / ``tensordec-flexbuf.cc`` /
``tensordec-protobuf.cc`` / ``tensordec-octetstream.c`` (SURVEY §2.5).  All
reference codecs collapse onto the one wire format in utils/wire.py (the
vendored flatbuffers/protobuf libs are an implementation detail of the
reference, not a capability); ``octet_stream`` emits raw bytes.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..core.buffer import Buffer
from ..core.caps import Caps, MediaType
from ..core.registry import register_decoder
from ..core.types import TensorsSpec
from ..utils.wire import encode_buffer
from .base import Decoder


class _WireDecoder(Decoder):
    def out_caps(self, in_spec: Optional[TensorsSpec]) -> Caps:
        return Caps.new(MediaType.OCTET)

    def decode(self, tensors: List[np.ndarray], buf: Buffer) -> Buffer:
        blob = np.frombuffer(encode_buffer(buf), np.uint8)
        return buf.with_tensors([blob], spec=None)


@register_decoder("flexbuf")
class FlexbufDecoder(_WireDecoder):
    mode = "flexbuf"


@register_decoder("flatbuf")
class FlatbufDecoder(_WireDecoder):
    mode = "flatbuf"


@register_decoder("protobuf")
class ProtobufDecoder(_WireDecoder):
    mode = "protobuf"


@register_decoder("octet_stream")
class OctetStream(Decoder):
    mode = "octet_stream"

    def out_caps(self, in_spec: Optional[TensorsSpec]) -> Caps:
        return Caps.new(MediaType.OCTET)

    def decode(self, tensors: List[np.ndarray], buf: Buffer) -> Buffer:
        raw = b"".join(np.ascontiguousarray(t).tobytes() for t in tensors)
        return buf.with_tensors([np.frombuffer(raw, np.uint8)], spec=None)
