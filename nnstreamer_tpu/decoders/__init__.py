"""nnstreamer_tpu.decoders"""
