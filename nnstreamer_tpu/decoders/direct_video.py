"""direct_video decoder: reinterpret a tensor as raw video.

Reference analog: ``tensordec-directvideo.c`` (SURVEY §2.5).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..core.buffer import Buffer
from ..core.caps import Caps, MediaType
from ..core.registry import register_decoder
from ..core.types import TensorsSpec
from .base import Decoder


@register_decoder("direct_video")
class DirectVideo(Decoder):
    mode = "direct_video"

    def out_caps(self, in_spec: Optional[TensorsSpec]) -> Caps:
        fields = {}
        if in_spec is not None and len(in_spec) == 1:
            c, w, h = (list(in_spec[0].dims) + [1, 1, 1])[:3]
            fmt = {1: "GRAY8", 3: "RGB", 4: "RGBA"}.get(c)
            if fmt:
                fields = dict(format=fmt, width=w, height=h)
        return Caps.new(MediaType.VIDEO, **fields)

    def decode(self, tensors: List[np.ndarray], buf: Buffer) -> Buffer:
        frame = np.asarray(tensors[0], np.uint8)
        if frame.ndim == 4:
            frame = frame[0]
        return buf.with_tensors([frame], spec=None)


@register_decoder("tensor_region")
class TensorRegion(Decoder):
    """Crop-region decoder pairing with tensor_crop (reference:
    tensordec-tensor_region.c): top detection -> [x, y, w, h] info tensor in
    pixel units of option1=WIDTH:HEIGHT (default 640:480)."""

    mode = "tensor_region"

    def __init__(self, props):
        super().__init__(props)
        size = self.option(1) or "640:480"
        w, h = size.split(":")
        self.out_w, self.out_h = int(w), int(h)
        self.num = int(self.option(2) or 1)

    def out_caps(self, in_spec: Optional[TensorsSpec]) -> Caps:
        return Caps.tensors(TensorsSpec.from_string(f"4:{self.num}", "uint32"))

    def decode(self, tensors: List[np.ndarray], buf: Buffer) -> Buffer:
        boxes = np.asarray(tensors[0], np.float32).reshape(-1, 4)
        scores = np.asarray(tensors[1], np.float32) if len(tensors) > 1 else None
        if scores is not None:
            order = np.argsort(-scores.reshape(boxes.shape[0], -1).max(axis=1))
            boxes = boxes[order]
        regions = []
        for x1, y1, x2, y2 in boxes[: self.num]:
            regions.append(
                [
                    int(np.clip(x1, 0, 1) * self.out_w),
                    int(np.clip(y1, 0, 1) * self.out_h),
                    int(np.clip(x2 - x1, 0, 1) * self.out_w),
                    int(np.clip(y2 - y1, 0, 1) * self.out_h),
                ]
            )
        while len(regions) < self.num:
            regions.append([0, 0, 0, 0])
        out = np.asarray(regions, np.uint32)
        return buf.with_tensors([out], spec=None)
