"""pose_estimation decoder: heatmaps -> keypoints + skeleton overlay.

Reference analog: ``tensordec-pose.c`` (SURVEY §2.5, BASELINE config #3):
per-keypoint heatmaps -> argmax locations (scaled to output size) -> keypoint
dots + bone lines on an RGBA overlay; keypoints in meta.

Input contract: heatmaps tensor shaped (H', W', K) (numpy order; nnstreamer
dims K:W':H') — PoseNet-style.  Optional second tensor (K, 2) of short-range
offsets is added when present.

Options: option1=labels (keypoint names file), option2=WIDTH:HEIGHT of the
overlay (default 640:480), option3=score threshold, option4=output form
(``overlay`` default | ``tensors``: keypoint coordinates themselves as
(x f32 [K], y f32 [K], score f32 [K]) — batched [B,K] — with no skeleton
canvas; the indices-not-payloads treatment for headless serving).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..core.buffer import Buffer
from ..core.caps import Caps, MediaType
from ..core.registry import register_decoder
from ..core.types import TensorsSpec
from .base import Decoder, load_labels

# COCO-17 skeleton bones (keypoint index pairs)
_BONES = [
    (0, 1), (0, 2), (1, 3), (2, 4), (5, 6), (5, 7), (7, 9), (6, 8), (8, 10),
    (5, 11), (6, 12), (11, 12), (11, 13), (13, 15), (12, 14), (14, 16),
]


@register_decoder("pose_estimation")
class PoseEstimation(Decoder):
    mode = "pose_estimation"

    def __init__(self, props):
        super().__init__(props)
        size = self.option(2) or "640:480"
        w, h = size.split(":")
        self.out_w, self.out_h = int(w), int(h)
        self.threshold = float(self.option(3) or 0.3)
        out_mode = (self.option(4) or "overlay").lower()
        if out_mode not in ("overlay", "tensors"):
            raise ValueError(f"option4 (output form) must be "
                             f"overlay|tensors, got {out_mode!r}")
        self.out_mode = out_mode

    def out_caps(self, in_spec: Optional[TensorsSpec]) -> Caps:
        if self.out_mode == "tensors":
            return Caps.tensors()
        return Caps.new(
            MediaType.VIDEO, format="RGBA", width=self.out_w, height=self.out_h
        )

    def decode(self, tensors: List[np.ndarray], buf: Buffer) -> Buffer:
        hm = np.asarray(tensors[0], np.float32)
        if hm.ndim > 3:
            # Batched heatmaps [..., H', W', K]: decode each frame.
            lead = hm.shape[: hm.ndim - 3]
            n = int(np.prod(lead))
            frames = hm.reshape((n,) + hm.shape[-3:])
            if n > 1:
                rest = [np.asarray(t) for t in tensors[1:]]
                per_frame, kps = [], []
                for i in range(n):
                    sub = [frames[i]] + [
                        t[i] if t.shape[:1] == (n,) else t for t in rest
                    ]
                    o = self._decode_one(sub, buf)
                    per_frame.append(o.tensors)
                    kps.append(o.meta["keypoints"])
                # stack EVERY output tensor across frames: overlay mode has
                # one ([B,H,W,4]); tensors mode has three (px/py/score,
                # each [B,K]) — dropping to tensors[0] alone would lose y
                # and confidence in the batched host path
                stacked = [np.stack([f[t] for f in per_frame])
                           for t in range(len(per_frame[0]))]
                out = buf.with_tensors(stacked, spec=None)
                out.meta["keypoints"] = kps
                return out
            hm = frames[0]
        return self._decode_one([hm] + list(tensors[1:]), buf)

    def _coords(self, idx, off, hh: int, hw: int):
        """Flat heatmap argmax indices [..., K] -> (px, py) overlay pixel
        coords, same leading shape.  The ONLY place the scale/offset math
        lives: the host decode path and the fused ``host_post`` both call
        it, so they cannot diverge."""
        ys, xs = np.unravel_index(idx, (hh, hw))
        px = (xs + 0.5) / hw * self.out_w
        py = (ys + 0.5) / hh * self.out_h
        if off is not None:  # short-range offsets (..., K, 2) in cells
            px = px + off[..., 0] / hw * self.out_w
            py = py + off[..., 1] / hh * self.out_h
        return px, py

    def _keypoints(self, idx, scores, off, hh: int, hw: int):
        """Flat heatmap argmax indices -> keypoint dicts (host path)."""
        px, py = self._coords(idx, off, hh, hw)
        return [
            {"x": float(px[i]), "y": float(py[i]), "score": float(scores[i])}
            for i in range(len(idx))
        ]

    def _decode_one(self, tensors: List[np.ndarray], buf: Buffer) -> Buffer:
        hm = np.asarray(tensors[0], np.float32)
        hh, hw, k = hm.shape
        flat = hm.reshape(-1, k)
        idx = flat.argmax(axis=0)
        scores = flat[idx, np.arange(k)]
        off = (np.asarray(tensors[1], np.float32).reshape(-1, 2)[:k]
               if len(tensors) > 1 else None)
        keypoints = self._keypoints(idx, scores, off, hh, hw)
        if self.out_mode == "tensors":
            px, py = self._coords(idx, off, hh, hw)
            out = buf.with_tensors(
                [px.astype(np.float32), py.astype(np.float32),
                 scores.astype(np.float32)], spec=None)
        else:
            out = buf.with_tensors([self._draw(keypoints)], spec=None)
        out.meta["keypoints"] = keypoints
        return out

    # -- fusion ------------------------------------------------------------
    # Heatmap argmax runs inside the fused XLA program; only [B,K] indices
    # and scores (plus the first-K offset pairs, replicating the host
    # path's math bit-for-bit) cross to the host with async D2H in flight.
    # Keypoint dicts and the skeleton overlay resolve in ``host_post`` at
    # the sink edge.  Batched fused output is ONE buffer with stacked
    # overlays [B,H,W,4] (same shape the host path's batched decode emits).
    def device_fn(self, in_spec: TensorsSpec):
        import jax.numpy as jnp

        from ..core.types import TensorSpec

        shape = in_spec[0].shape
        if len(shape) != 4:
            return None
        batch, hh, hw, k = shape
        self._fused_grid = (hh, hw)
        have_off = len(in_spec) > 1

        pack = self.out_mode == "tensors"

        def fn(arrays):
            hm = arrays[0].astype(jnp.float32)
            b = hm.shape[0]
            flat = hm.reshape(b, -1, k)
            idx = jnp.argmax(flat, axis=1).astype(jnp.int32)  # [B, K]
            score = jnp.take_along_axis(flat, idx[:, None, :], axis=1)[:, 0]
            outs = [idx, score.astype(jnp.float32)]
            if have_off:
                off = arrays[1].astype(jnp.float32).reshape(b, -1, 2)[:, :k]
                outs.append(off)
            if pack:
                # ONE [B, K, 2(+2)] f32 payload (idx, score[, off]): a
                # single D2H transfer instead of 2-3 — each separate
                # tensor pays its own tunnel round trip.  idx as f32 is
                # exact (heatmap cells << 2^24).
                cols = [outs[0].astype(jnp.float32)[..., None],
                        outs[1][..., None]]
                if have_off:
                    cols.append(outs[2])
                return (jnp.concatenate(cols, axis=-1),)
            return tuple(outs)

        if pack:
            return fn, TensorsSpec((TensorSpec.from_shape(
                (batch, k, 4 if have_off else 2), np.float32),))
        specs = [
            TensorSpec.from_shape((batch, k), np.int32),
            TensorSpec.from_shape((batch, k), np.float32),
        ]
        if have_off:
            specs.append(TensorSpec.from_shape((batch, k, 2), np.float32))
        return fn, TensorsSpec(tuple(specs))

    def host_post(self, arrays, buf: Buffer) -> Buffer:
        hh, hw = self._fused_grid
        if len(arrays) == 1:  # packed tensors-mode payload [B, K, 2(+2)]
            p = np.asarray(arrays[0], np.float32)
            idx = p[..., 0].astype(np.int64)
            scores = p[..., 1]
            off = p[..., 2:4] if p.shape[-1] >= 4 else None
        else:
            idx = np.asarray(arrays[0])
            scores = np.asarray(arrays[1], np.float32)
            off = (np.asarray(arrays[2], np.float32)
                   if len(arrays) > 2 else None)
        b, k = idx.shape
        # Batched coordinates via the shared _coords math; the vectorized
        # batch draw replaced a per-frame python loop that dominated the
        # pull path at ~30 ms per 64-batch.
        px, py = self._coords(idx, off, hh, hw)
        if self.out_mode == "tensors":
            # keypoints themselves, no canvas and no per-dict Python:
            # O(B*K) floats cross the sink edge instead of O(B*H*W) pixels
            return buf.with_tensors(
                [px.astype(np.float32), py.astype(np.float32),
                 scores.astype(np.float32)], spec=None)
        kps_all = [
            [
                {"x": float(px[i, j]), "y": float(py[i, j]),
                 "score": float(scores[i, j])}
                for j in range(k)
            ]
            for i in range(b)
        ]
        overlays = self._draw_batch(px, py, scores)  # [B, H, W, 4]
        if b == 1:
            new = buf.with_tensors([overlays[0]], spec=None)
            new.meta["keypoints"] = kps_all[0]
            return new
        new = buf.with_tensors([overlays], spec=None)
        new.meta["keypoints"] = kps_all
        return new

    def _draw_batch(self, px, py, scores, n: int = 64) -> np.ndarray:
        """All frames' overlays in a few vectorized scatters — pixel-equal
        to per-frame :meth:`_draw` (bones first, then dots; same clipping).
        px/py/scores: [B, K] arrays."""
        b, k = px.shape
        h, w = self.out_h, self.out_w
        overlay = np.zeros((b, h, w, 4), np.uint8)
        green = np.array([60, 220, 60, 255], np.uint8)
        white = np.array([255, 255, 255, 255], np.uint8)
        ok = scores >= self.threshold  # [B, K]
        fi = np.arange(b)[:, None]
        for a, c in _BONES:
            if a >= k or c >= k:
                continue
            # [B, n] interpolated line points per frame — np.linspace with
            # array endpoints: bit-identical to the per-frame _line math
            xs = np.linspace(px[:, a], px[:, c], n, axis=1).astype(int)
            ys = np.linspace(py[:, a], py[:, c], n, axis=1).astype(int)
            m = (ok[:, a] & ok[:, c])[:, None] & (xs >= 0) & (xs < w) & \
                (ys >= 0) & (ys < h)
            fr = np.broadcast_to(fi, xs.shape)
            overlay[fr[m], ys[m], xs[m]] = white
        # dots: 6x6 patch at each confident keypoint (rows y-3..y+2)
        dy, dx = np.meshgrid(np.arange(-3, 3), np.arange(-3, 3),
                             indexing="ij")
        yy = py.astype(int)[:, :, None, None] + dy  # [B, K, 6, 6]
        xx = px.astype(int)[:, :, None, None] + dx
        m = ok[:, :, None, None] & (yy >= 0) & (yy < h) & (xx >= 0) & (xx < w)
        fr = np.broadcast_to(np.arange(b)[:, None, None, None], yy.shape)
        overlay[fr[m], yy[m], xx[m]] = green
        return overlay

    def _draw(self, kps) -> np.ndarray:
        overlay = np.zeros((self.out_h, self.out_w, 4), np.uint8)
        green = np.array([60, 220, 60, 255], np.uint8)
        white = np.array([255, 255, 255, 255], np.uint8)
        for a, b in _BONES:
            if a < len(kps) and b < len(kps):
                ka, kb = kps[a], kps[b]
                if ka["score"] >= self.threshold and kb["score"] >= self.threshold:
                    self._line(overlay, ka, kb, white)
        for kp in kps:
            if kp["score"] >= self.threshold:
                x, y = int(kp["x"]), int(kp["y"])
                # clamp BOTH ends: a negative stop (keypoint far off-screen)
                # would wrap around and paint a near-full-width band
                overlay[
                    max(0, y - 3) : max(0, y + 3),
                    max(0, x - 3) : max(0, x + 3),
                ] = green
        return overlay

    def _line(self, img, ka, kb, color, n: int = 64):
        xs = np.linspace(ka["x"], kb["x"], n).astype(int)
        ys = np.linspace(ka["y"], kb["y"], n).astype(int)
        m = (xs >= 0) & (xs < img.shape[1]) & (ys >= 0) & (ys < img.shape[0])
        img[ys[m], xs[m]] = color
