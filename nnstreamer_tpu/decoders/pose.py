"""pose_estimation decoder: heatmaps -> keypoints + skeleton overlay.

Reference analog: ``tensordec-pose.c`` (SURVEY §2.5, BASELINE config #3):
per-keypoint heatmaps -> argmax locations (scaled to output size) -> keypoint
dots + bone lines on an RGBA overlay; keypoints in meta.

Input contract: heatmaps tensor shaped (H', W', K) (numpy order; nnstreamer
dims K:W':H') — PoseNet-style.  Optional second tensor (K, 2) of short-range
offsets is added when present.

Options: option1=labels (keypoint names file), option2=WIDTH:HEIGHT of the
overlay (default 640:480), option3=score threshold.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..core.buffer import Buffer
from ..core.caps import Caps, MediaType
from ..core.registry import register_decoder
from ..core.types import TensorsSpec
from .base import Decoder, load_labels

# COCO-17 skeleton bones (keypoint index pairs)
_BONES = [
    (0, 1), (0, 2), (1, 3), (2, 4), (5, 6), (5, 7), (7, 9), (6, 8), (8, 10),
    (5, 11), (6, 12), (11, 12), (11, 13), (13, 15), (12, 14), (14, 16),
]


@register_decoder("pose_estimation")
class PoseEstimation(Decoder):
    mode = "pose_estimation"

    def __init__(self, props):
        super().__init__(props)
        size = self.option(2) or "640:480"
        w, h = size.split(":")
        self.out_w, self.out_h = int(w), int(h)
        self.threshold = float(self.option(3) or 0.3)

    def out_caps(self, in_spec: Optional[TensorsSpec]) -> Caps:
        return Caps.new(
            MediaType.VIDEO, format="RGBA", width=self.out_w, height=self.out_h
        )

    def decode(self, tensors: List[np.ndarray], buf: Buffer) -> Buffer:
        hm = np.asarray(tensors[0], np.float32)
        if hm.ndim > 3:
            # Batched heatmaps [..., H', W', K]: decode each frame.
            lead = hm.shape[: hm.ndim - 3]
            n = int(np.prod(lead))
            frames = hm.reshape((n,) + hm.shape[-3:])
            if n > 1:
                rest = [np.asarray(t) for t in tensors[1:]]
                overlays, kps = [], []
                for i in range(n):
                    sub = [frames[i]] + [
                        t[i] if t.shape[:1] == (n,) else t for t in rest
                    ]
                    o = self._decode_one(sub, buf)
                    overlays.append(o.tensors[0])
                    kps.append(o.meta["keypoints"])
                out = buf.with_tensors([np.stack(overlays)], spec=None)
                out.meta["keypoints"] = kps
                return out
            hm = frames[0]
        return self._decode_one([hm] + list(tensors[1:]), buf)

    def _decode_one(self, tensors: List[np.ndarray], buf: Buffer) -> Buffer:
        hm = np.asarray(tensors[0], np.float32)
        hh, hw, k = hm.shape
        flat = hm.reshape(-1, k)
        idx = flat.argmax(axis=0)
        scores = flat[idx, np.arange(k)]
        ys, xs = np.unravel_index(idx, (hh, hw))
        # scale heatmap coords to overlay pixels
        px = (xs + 0.5) / hw * self.out_w
        py = (ys + 0.5) / hh * self.out_h
        if len(tensors) > 1:  # short-range offsets (K,2) in heatmap cells
            off = np.asarray(tensors[1], np.float32).reshape(-1, 2)[:k]
            px = px + off[:, 0] / hw * self.out_w
            py = py + off[:, 1] / hh * self.out_h

        keypoints = [
            {"x": float(px[i]), "y": float(py[i]), "score": float(scores[i])}
            for i in range(k)
        ]
        overlay = self._draw(keypoints)
        out = buf.with_tensors([overlay], spec=None)
        out.meta["keypoints"] = keypoints
        return out

    def _draw(self, kps) -> np.ndarray:
        overlay = np.zeros((self.out_h, self.out_w, 4), np.uint8)
        green = np.array([60, 220, 60, 255], np.uint8)
        white = np.array([255, 255, 255, 255], np.uint8)
        for a, b in _BONES:
            if a < len(kps) and b < len(kps):
                ka, kb = kps[a], kps[b]
                if ka["score"] >= self.threshold and kb["score"] >= self.threshold:
                    self._line(overlay, ka, kb, white)
        for kp in kps:
            if kp["score"] >= self.threshold:
                x, y = int(kp["x"]), int(kp["y"])
                overlay[
                    max(0, y - 3) : y + 3, max(0, x - 3) : x + 3
                ] = green
        return overlay

    def _line(self, img, ka, kb, color, n: int = 64):
        xs = np.linspace(ka["x"], kb["x"], n).astype(int)
        ys = np.linspace(ka["y"], kb["y"], n).astype(int)
        m = (xs >= 0) & (xs < img.shape[1]) & (ys >= 0) & (ys < img.shape[0])
        img[ys[m], xs[m]] = color
