"""nns-trace: per-buffer flight recorder + stage span tracing.

Reference analog (SURVEY §5.1): GStreamer tracers / gst-shark attribute
latency per element by hooking pad-push probes.  The TPU build's analog is
a process-wide **flight recorder**: a lock-cheap ring buffer of span
events (stage enter/exit, queue wait, batch-formation linger, in-flight
dispatch window, sharded dispatch, host fetch, end-to-end delivery) keyed
by a per-buffer **trace id** assigned at source ingress and threaded
through ``Buffer.meta`` — so "where did frame N spend its 40 ms?" has an
answer even after the batching/sharding machinery amortized N's device
time across a micro-batch.

Three trace modes (``Config.trace_mode`` / ``Pipeline(trace_mode=...)``):

* ``off``  — the default.  No recorder is installed: every hot-path hook
  reduces to one ``is not None`` check, and no meta stamps are written.
* ``ring`` — always-on flight recorder: the last ``trace_ring_capacity``
  spans in a ``deque(maxlen=...)``.  Appends are GIL-atomic (no lock on
  the hot path); eviction is oldest-first.  This is the post-mortem mode:
  watchdog fires and ``Pipeline._record_error`` dump the recent window to
  the log automatically.
* ``full`` — unbounded event list for short profiling runs that must not
  lose the head of the timeline.

Exports: :func:`to_chrome` renders Chrome trace-event JSON (one track per
stage, flow arrows binding batch dispatch spans to every member row's
trace id) loadable in Perfetto / ``chrome://tracing`` alongside the
``utils.profiler.trace`` xplane; :func:`dump_recent_to_log` formats the
last K seconds for crash reports; ``python -m nnstreamer_tpu.tools.trace``
validates/summarizes dumps.  See docs/OBSERVABILITY.md.

nns-weave (docs/OBSERVABILITY.md "Distributed tracing") extends the
recorder across processes: trace ids carry a random per-process **epoch**
in their high bits (:func:`trace_epoch`, so ids minted by different
processes never collide), NTP-style echoes on the query handshake feed
per-peer clock offsets into :meth:`FlightRecorder.note_clock`,
:func:`dump_ring`/:func:`load_ring` serialize a ring to a wire-codec
framed file, and :func:`merge_rings` joins N dumps into ONE Chrome trace
— one pid per process, offset-corrected timestamps, cross-wire flow
arrows (client ``query.send`` → server ``ingress``, server
``query.reply`` → client ``query.recv``).
"""

from __future__ import annotations

import collections
import itertools
import json
import os
import threading
import time
from typing import Any, Dict, List, NamedTuple, Optional, Sequence

from ..core.meta_keys import (  # noqa: F401  (canonical registry; re-exported)
    META_ENQUEUE_NS, META_INGRESS_NS, META_TENANT, META_TRACE_ID,
)

#: span taxonomy (docs/OBSERVABILITY.md) — kind -> meaning
SPAN_KINDS: Dict[str, str] = {
    "ingress": "trace id born at a source (instant; args carry pts)",
    "queue": "buffer waited in a stage's input queue",
    "batch": "batch formation: first buffer in hand -> dispatch start "
             "(drain + linger)",
    "stage": "element process()/process_batch()/process_group() execution"
             " (batch spans LINK member trace ids; per_row_ns amortizes)",
    "inflight": "dispatched-but-unemitted window (dispatch_depth > 1)",
    "shard": "sharded bucketed dispatch incl. the assembled host fetch "
             "(args: rows, bucket, replicas = data-axis width; 2-D runs "
             "add model = model-axis width, and per-replica counters "
             "carry (data, model) coordinates as .d<di>m<mi>)",
    "fetch": "sink host materialization (D2H / deferred host_post)",
    "fetch.window": "buffer submitted into a sink's async fetch window "
                    "(instant; args: depth = submitted-but-unmaterialized "
                    "fetches; CONCURRENCY is bounded by fetch_depth, the "
                    "backlog only by queue capacity — docs/FETCH.md)",
    "e2e": "source ingress -> sink delivery for one buffer",
    "serve.admit": "continuous LLM serving: prompt admitted into a slot "
                   "(args: slot, tokens, blocks reserved)",
    "serve.prefill_chunk": "continuous LLM serving: one chunked-prefill "
                           "step written into the slot's pool blocks "
                           "(args: slot, pos, final; times the ASYNC "
                           "dispatch — device time overlaps the decode "
                           "chunk by design)",
    "serve.decode": "continuous LLM serving: one paged decode chunk over "
                    "the live slots (args: occupancy, chunk; closes at "
                    "chunk materialization, so it covers the device "
                    "time)",
    "serve.prefix_hit": "continuous LLM serving: an admitted prompt's "
                        "leading blocks matched the prefix cache and "
                        "mapped copy-on-write into its table (instant; "
                        "args: slot, blocks = shared mappings, tokens = "
                        "prefill skipped)",
    "serve.cow_fork": "continuous LLM serving: a shared block a stream "
                      "was about to write got a private copy first "
                      "(args: src, dst pool block ids — an eager value "
                      "move, no program touched)",
    "serve.spec_verify": "continuous LLM serving: one speculative round "
                         "(draft propose + k+1-wide target verify; "
                         "args: occupancy, k; closes at round "
                         "materialization like serve.decode)",
    "admit.shed": "query-server admission shed a request under backlog "
                  "(instant; args: tenant, msg, backlog — the victim's "
                  "trace id is the span tid, minted at shed when the "
                  "client did not stamp one)",
    "admit.downgrade": "query-server admission moved a request to the "
                       "low-priority lane under backlog (instant; args: "
                       "tenant, msg, backlog)",
    "elastic.scale": "autoscaler action edge (utils/elastic.py — "
                     "instant; args: action, tenant, burn, edge = "
                     "engage|relax; rate-limited with hysteresis)",
    "elastic.drain": "live serve stream serialized off its pipeline "
                     "(Pipeline.drain_stream; args: stream_id, state, "
                     "blocks — a host-side value move, the 3-program "
                     "decode census is untouched)",
    "elastic.adopt": "serialized serve stream re-admitted on a pipeline "
                     "(Pipeline.adopt_stream; args: stream_id, state, "
                     "blocks; greedy continuation is bit-identical)",
    "serve.reap": "continuous LLM serving: an orphaned/cancelled "
                  "stream's slot + KV blocks reclaimed to the free "
                  "list (args: slot, stream_id, blocks, reason)",
    "armor.quarantine": "poison-pill quarantine: a request whose stage "
                        "invoke raised (or produced NaN/Inf under "
                        "nan_guard) was serialized to the DLQ and "
                        "answered with abort_reason=poison (instant; "
                        "args: stage, tenant, error, dlq = the record "
                        "file — docs/ROBUSTNESS.md)",
    "armor.breaker": "repeat-offender circuit breaker edge: N poisons "
                     "from one tenant inside the window flipped its "
                     "tenant_admission override to shed (instant; "
                     "args: tenant, threshold, window_s, edge = "
                     "trip|reset)",
    "journal.append": "durable request journal: one accepted request's "
                      "wire payload appended to the WAL (instant; "
                      "args: seq, tenant; fsync policy decides "
                      "durability — docs/ROBUSTNESS.md)",
    "journal.replay": "durable request journal: restart re-admitted "
                      "the accepted-but-unanswered entries "
                      "(instant; args: entries, acked_skipped)",
    "learn.step": "nns-learn: one trained epoch on a tensor_trainer "
                  "stage (args: epoch, step = optimizer step counter, "
                  "loss, tenant; tid = the last contributing sample's "
                  "trace id — docs/TRAINING.md)",
    "learn.swap": "nns-learn: live param hot-swap into a serving stage "
                  "(Pipeline.swap_params — a VALUE move at a dispatch/"
                  "chunk boundary, zero recompiles; args: version = the "
                  "stage's per-swap counter)",
    "learn.ckpt": "nns-learn: one fsync'd step-versioned trainer "
                  "checkpoint write (args: step, path; model-load-path "
                  "resume continues bit-identically)",
    "device": "nns-xray device-time attribution: one tracked-program "
              "dispatch on its own `device:<stage>` track beside the "
              "host spans (args: program, flops from the lowered "
              "program's cost analysis; dur = measured dispatch wall "
              "time — docs/OBSERVABILITY.md 'Predicted vs actual')",
    "xray.drift": "nns-xray census drift: a compiled program escaped "
                  "the deep lint's predicted census (instant; args: "
                  "program, reason; the flight-recorder window is "
                  "dumped to the log alongside)",
    "tsan.inversion": "nns-tsan: a live lock-order inversion or "
                      "guarded-field violation observed by the tracked "
                      "locks (NNS_TPU_TSAN=1; instant; args: reason = "
                      "both acquisition paths; the flight-recorder "
                      "window is dumped to the log alongside — "
                      "docs/ANALYSIS.md 'Threads pass')",
    "query.send": "nns-weave: one request frame written to the query "
                  "wire by the client (args: msg = wire message id; tid "
                  "= the epoch-prefixed trace id stamped as _tparent — "
                  "the merge pairs it with the server's ingress span)",
    "query.recv": "nns-weave: one response/token frame consumed by the "
                  "query client (instant; args: msg; tid = the echoed "
                  "_tparent context — pairs with the server's "
                  "query.reply span in a merged trace)",
    "query.reply": "nns-weave: one response/token frame written to a "
                   "connection by the serversink (instant; args: msg; "
                   "tid = the adopted distributed trace id)",
    "clock.sync": "nns-weave: one NTP-style clock sample against a peer "
                  "(instant; args: epoch = peer trace epoch, offset_ns "
                  "= peer minus local monotonic base, uncertainty_ns = "
                  "half the echo round trip — the residual skew a "
                  "merged timeline carries, never hides)",
}

# Buffer-meta keys the tracer owns (META_TRACE_ID / META_INGRESS_NS /
# META_ENQUEUE_NS, stamped only when tracing is active) and META_TENANT
# (docs/SERVING.md "Front door"; NOT tracer-owned in the off-path sense:
# an app/element that sets it explicitly owns the key, the RUNTIME only
# stamps a pipeline-default tenant at ingress when tracing is active)
# are declared in core/meta_keys.py — the shared protocol registry —
# and re-exported above for the existing importers.

DEFAULT_RING_CAPACITY = 65536

#: random 31-bit process epoch: the high half of every trace id minted by
#: this process, so ids from different processes (a query client and its
#: server, N soak workers) never alias in a merged view.  31 bits keeps
#: ``(epoch << 32) | counter`` inside a signed int64 for the wire codec
#: and Perfetto; zero is reserved (no epoch / pre-weave dumps).
_PROCESS_EPOCH = (int.from_bytes(os.urandom(4), "little") & 0x7FFFFFFF) or 1

_trace_ids = itertools.count(1)


def trace_epoch() -> int:
    """This process's random 31-bit trace epoch (the id high bits; also
    exchanged on the query handshake so clock offsets are keyed by it)."""
    return _PROCESS_EPOCH


def next_trace_id() -> int:
    """Globally-unique per-buffer trace id (assigned at source ingress):
    ``epoch << 32 | local counter``.  The 32-bit counter wraps after 4 G
    ids — far beyond any ring's lifetime — and the random epoch high bits
    keep two processes' ids disjoint without coordination."""
    return (_PROCESS_EPOCH << 32) | (next(_trace_ids) & 0xFFFFFFFF)


def clock_offset(t0: int, t1: int, t2: int, t3: int) -> "tuple[int, int]":
    """NTP-style offset estimate from one echo: the caller stamped ``t0``
    (send) and ``t3`` (receive) on ITS monotonic clock, the peer stamped
    ``t1`` (receive) and ``t2`` (send) on ITS OWN.  Returns
    ``(offset_ns, uncertainty_ns)`` where ``offset = peer - local`` and
    the true offset lies within ``offset ± uncertainty`` (half the
    round-trip minus the peer's hold time) — asymmetric path delay can
    consume the whole bound, which is why merged traces carry it as a
    span arg instead of pretending the correction is exact."""
    offset = ((t1 - t0) + (t2 - t3)) // 2
    delay = (t3 - t0) - (t2 - t1)
    return int(offset), max(0, int(delay // 2))


class Span(NamedTuple):
    """One recorded span.  ``ts``/``dur`` are ``time.monotonic_ns()``
    values (dur 0 = instant event); ``tid`` is the buffer trace id (None
    for spans not attributable to one buffer, e.g. sharded dispatches);
    ``args`` is an optional dict of extras (``trace_ids`` on batch-linked
    spans, ``rows``, ``per_row_ns``, ``pts``)."""

    ts: int
    dur: int
    kind: str
    stage: str
    tid: Optional[int]
    args: Optional[Dict[str, Any]]


class FlightRecorder:
    """Lock-cheap ring buffer of :class:`Span` events.

    The hot path is :meth:`record` → ``deque.append`` — GIL-atomic, so
    concurrent runner threads never contend on a lock, and a bounded
    ``maxlen`` deque evicts oldest-first without allocation churn.  The
    lock below guards only cold operations (configure/clear/snapshot
    consistency of mode flips).  ``active`` is the single attribute every
    instrumentation site checks; with mode ``off`` callers hold ``None``
    instead of the recorder, so the off cost is one pointer test.
    """

    def __init__(self, mode: str = "off",
                 capacity: int = DEFAULT_RING_CAPACITY):
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(maxlen=capacity)
        #: peer trace epoch -> (offset_ns, uncertainty_ns, sampled_at_ns)
        #: fed by the query handshake / periodic clock echoes (cold path)
        self._clock: Dict[int, "tuple[int, int, int]"] = {}
        self.mode = "off"
        self.capacity = capacity
        self.active = False
        if mode != "off":
            self.configure(mode, capacity)

    def configure(self, mode: str,
                  capacity: Optional[int] = None) -> "FlightRecorder":
        """Switch mode (off/ring/full).  ``ring`` bounds the buffer at
        ``capacity`` spans; ``full`` is unbounded; ``off`` stops recording
        but keeps already-captured events readable (post-mortem).

        Re-configuring with the SAME bound keeps the live deque; changing
        it rebuilds the deque (existing spans carried over), and a
        concurrent lock-free ``record`` that already fetched the old
        reference may land its span in the orphan — acceptable for a
        flight recorder (reconfigure happens at pipeline construction,
        not mid-stream, and loses at most the handful of spans in
        flight), and the alternative is a lock on every hot-path append."""
        if mode not in ("off", "ring", "full"):
            raise ValueError(
                f"trace_mode must be off|ring|full, got {mode!r}")
        with self._lock:
            cap = capacity or self.capacity or DEFAULT_RING_CAPACITY
            if mode == "ring" and (self._ring.maxlen != cap):
                self._ring = collections.deque(self._ring, maxlen=cap)
            elif mode == "full" and self._ring.maxlen is not None:
                self._ring = collections.deque(self._ring)
            self.mode = mode
            self.capacity = cap
            self.active = mode != "off"
        return self

    # -- hot path ----------------------------------------------------------
    def record(self, kind: str, stage: str, tid: Optional[int],
               ts_ns: int, dur_ns: int, **args) -> None:
        """Append one span.  No lock: deque.append is GIL-atomic and the
        ring's maxlen does the eviction."""
        self._ring.append(
            Span(ts_ns, dur_ns, kind, stage, tid, args or None))

    # -- cold path ---------------------------------------------------------
    def events(self) -> List[Span]:
        """Snapshot of the current ring, oldest first."""
        return list(self._ring)

    def clear(self) -> None:
        self._ring.clear()
        with self._lock:
            self._clock.clear()

    def note_clock(self, peer_epoch: int, offset_ns: int,
                   uncertainty_ns: int) -> None:
        """Record one clock sample against a peer process (cold path,
        called from the handshake / periodic echo).  A tighter sample
        replaces a looser one; a looser sample only replaces an entry
        older than ~60 s (drift makes stale precision worthless)."""
        with self._lock:
            now = time.monotonic_ns()
            prev = self._clock.get(int(peer_epoch))
            if prev is not None and uncertainty_ns > prev[1] \
                    and now - prev[2] < 60_000_000_000:
                return
            self._clock[int(peer_epoch)] = (
                int(offset_ns), int(uncertainty_ns), now)

    def clock(self) -> Dict[int, "tuple[int, int, int]"]:
        """Snapshot of the per-peer clock table (offset = peer − local)."""
        with self._lock:
            return dict(self._clock)

    def __len__(self) -> int:
        return len(self._ring)

    def recent(self, seconds: float) -> List[Span]:
        """Spans whose END falls within ``seconds`` of the newest event
        (the watchdog post-mortem window)."""
        evs = self.events()
        if not evs:
            return []
        horizon = max(e.ts + e.dur for e in evs) - int(seconds * 1e9)
        return [e for e in evs if e.ts + e.dur >= horizon]


#: the process-wide recorder (one per process, like ``core.log.metrics``);
#: ``Pipeline(trace_mode=...)`` configures it, runners hold it (or None)
recorder = FlightRecorder()


# -- Chrome trace-event export ----------------------------------------------

def to_chrome(events: Sequence[Span]) -> Dict[str, Any]:
    """Render spans as a Chrome trace-event JSON object (Perfetto /
    chrome://tracing 'JSON array format' under ``traceEvents``).

    * one track (tid) per stage, named via thread_name metadata; spans
      whose args carry a ``tenant`` land on that tenant's OWN process
      (pid) — Perfetto groups them as per-tenant track sets named
      ``tenant:<name>``, the per-tenant timeline view of a multi-tenant
      front door (untenanted spans stay on pid 1);
    * spans become complete events (``ph=X``, µs timebase), instants
      (dur 0) become ``ph=i``;
    * every span with linked ``trace_ids`` (a batched dispatch) gets flow
      arrows (``ph=s``/``ph=f``) from each member row's most recent prior
      span — Perfetto draws the per-row attribution the batch amortized;
    * ``traceEvents`` is sorted by ``ts`` (validated by
      :func:`validate_chrome`).
    """
    evs = sorted(events, key=lambda e: (e.ts, e.dur))
    track: Dict[Any, int] = {}
    out: List[Dict[str, Any]] = []
    meta: List[Dict[str, Any]] = [{
        "ph": "M", "pid": 1, "tid": 0, "ts": 0, "name": "process_name",
        "args": {"name": "nnstreamer_tpu"},
    }]
    tenant_pid: Dict[Any, int] = {None: 1}
    last_by_tid: Dict[int, Dict[str, Any]] = {}
    flow_ids = itertools.count(1)
    flows: List[Dict[str, Any]] = []
    for e in evs:
        tenant = (e.args or {}).get("tenant")
        pid = tenant_pid.get(tenant)
        if pid is None:
            pid = tenant_pid[tenant] = len(tenant_pid) + 1
            meta.append({"ph": "M", "pid": pid, "tid": 0, "ts": 0,
                         "name": "process_name",
                         "args": {"name": f"tenant:{tenant}"}})
        t = track.get((pid, e.stage))
        if t is None:
            t = track[(pid, e.stage)] = len(track) + 1
            meta.append({"ph": "M", "pid": pid, "tid": t, "ts": 0,
                         "name": "thread_name", "args": {"name": e.stage}})
        args: Dict[str, Any] = {}
        if e.tid is not None:
            args["trace_id"] = e.tid
        if e.args:
            args.update(e.args)
        rec = {
            "name": e.kind, "cat": e.kind,
            "ph": "X" if e.dur > 0 else "i",
            "ts": e.ts / 1e3, "pid": pid, "tid": t, "args": args,
        }
        if e.dur > 0:
            rec["dur"] = e.dur / 1e3
        else:
            rec["s"] = "t"  # instant scope: thread
        # flow arrows: batch dispatch span -> every member row's previous
        # span (per-row attribution of the amortized device time)
        linked = (e.args or {}).get("trace_ids")
        if linked:
            for member in linked:
                src = last_by_tid.get(member)
                if src is None or src is rec:
                    continue
                fid = next(flow_ids)
                flows.append({
                    "ph": "s", "id": fid, "pid": src["pid"],
                    "tid": src["tid"],
                    "ts": src["ts"] + src.get("dur", 0.0),
                    "name": "row", "cat": "row-link",
                })
                flows.append({
                    "ph": "f", "bp": "e", "id": fid, "pid": pid,
                    "tid": t, "ts": rec["ts"],
                    "name": "row", "cat": "row-link",
                })
        if e.tid is not None:
            last_by_tid[e.tid] = rec
        out.append(rec)
    # flows carry ts of their anchors; merge + resort so the stream stays
    # monotonic in ts (the validator's contract)
    all_events = meta + out + flows
    all_events.sort(key=lambda r: (r["ts"], 0 if r["ph"] == "M" else 1))
    return {"traceEvents": all_events, "displayTimeUnit": "ms",
            "otherData": {"spanKinds": dict(SPAN_KINDS)}}


def dump_chrome(events: Sequence[Span], path: str) -> int:
    """Write :func:`to_chrome` JSON to ``path``; returns the span count."""
    with open(path, "w") as f:
        json.dump(to_chrome(events), f)
    return len(events)


def validate_chrome(obj: Any) -> List[str]:
    """Schema-check a Chrome trace object (as loaded from JSON).  Returns
    a list of problems (empty = valid): ``traceEvents`` list present,
    required keys per event, non-negative durations, and the event stream
    monotonic in ``ts``."""
    problems: List[str] = []
    if not isinstance(obj, dict) or "traceEvents" not in obj:
        return ["top level must be an object with a 'traceEvents' list"]
    evs = obj["traceEvents"]
    if not isinstance(evs, list):
        return ["'traceEvents' must be a list"]
    last_ts = None
    for i, e in enumerate(evs):
        if not isinstance(e, dict):
            problems.append(f"event {i}: not an object")
            continue
        for key in ("ph", "ts", "pid", "tid", "name"):
            if key not in e:
                problems.append(f"event {i}: missing {key!r}")
        ph = e.get("ph")
        ts = e.get("ts")
        if not isinstance(ts, (int, float)):
            problems.append(f"event {i}: ts must be a number")
            continue
        if ph == "X":
            dur = e.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                problems.append(f"event {i}: X event needs dur >= 0")
        if last_ts is not None and ts < last_ts:
            problems.append(
                f"event {i}: ts {ts} < previous {last_ts} (not monotonic)")
        last_ts = ts
    return problems


# -- distributed ring export + merge (nns-weave) -----------------------------

def _json_safe(v: Any) -> Any:
    try:
        json.dumps(v)
        return v
    except (TypeError, ValueError):
        return str(v)


def dump_ring(path: str, rec: Optional[FlightRecorder] = None,
              proc: Optional[str] = None) -> int:
    """Serialize the recorder's ring (plus its per-peer clock table and
    this process's trace epoch) to ``path`` as ONE wire-codec frame:
    the span columns ride as int64 tensors, everything else as wire
    meta.  Works in any mode (a breach post-mortem may dump a recorder
    that was just switched off).  Returns the span count."""
    import numpy as np

    from . import wire
    rec = rec or recorder
    evs = rec.events()
    cols = [
        np.asarray([e.ts for e in evs], np.int64),
        np.asarray([e.dur for e in evs], np.int64),
        np.asarray([-1 if e.tid is None else e.tid for e in evs],
                   np.int64),
    ]
    from ..core.buffer import Buffer
    meta = {
        "weave_ring": 1,
        "epoch": trace_epoch(),
        "proc": proc or f"pid{os.getpid()}",
        "clock": [[pe, off, unc]
                  for pe, (off, unc, _t) in sorted(rec.clock().items())],
        "kind": [e.kind for e in evs],
        "stage": [e.stage for e in evs],
        "args": [({k: _json_safe(v) for k, v in e.args.items()}
                  if e.args else None) for e in evs],
    }
    payload = wire.encode_buffer(Buffer(cols, meta=meta))
    with open(path, "wb") as f:
        f.write(wire.frame_bytes(payload))
    return len(evs)


#: ring dumps are trusted local artifacts, not front-door input — the
#: limits only need to admit a full 64 Ki-span ring with fat args
_RING_LIMITS = None


def _ring_limits():
    global _RING_LIMITS
    if _RING_LIMITS is None:
        from . import wire
        _RING_LIMITS = wire.WireLimits(max_meta_bytes=256 << 20,
                                       max_frame_bytes=1 << 30)
    return _RING_LIMITS


def load_ring(path: str) -> Dict[str, Any]:
    """Read one :func:`dump_ring` file back.  Returns ``{"epoch", "proc",
    "clock": {peer_epoch: (offset_ns, uncertainty_ns)}, "spans"}``.
    Raises :class:`ValueError` (wire rejects are a subclass) on anything
    that is not a framed weave ring dump."""
    from . import wire
    with open(path, "rb") as f:
        raw = f.read()
    payload = wire.unframe_bytes(raw, _ring_limits())
    buf, _flags = wire.decode_buffer(payload, _ring_limits())
    meta = buf.meta
    if meta.get("weave_ring") != 1 or len(buf.tensors) != 3:
        raise ValueError(f"{path}: not a weave ring dump")
    ts, dur, tid = buf.tensors
    kinds, stages, argses = meta["kind"], meta["stage"], meta["args"]
    if not (len(ts) == len(kinds) == len(stages) == len(argses)):
        raise ValueError(f"{path}: ring dump columns disagree on length")
    spans = [
        Span(int(ts[i]), int(dur[i]), kinds[i], stages[i],
             None if int(tid[i]) < 0 else int(tid[i]), argses[i])
        for i in range(len(kinds))
    ]
    return {
        "epoch": int(meta.get("epoch", 0)),
        "proc": str(meta.get("proc", "?")),
        "clock": {int(pe): (int(off), int(unc))
                  for pe, off, unc in meta.get("clock", [])},
        "spans": spans,
    }


def _solve_offsets(rings: Sequence[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Per-ring timebase correction: walk the clock-sample graph (each
    ring's samples are edges epoch → peer with offset = peer − local)
    from ring 0's epoch, accumulating uncertainty.  ``ts_reference =
    ts_local + delta``.  Rings with no path to the reference keep delta 0
    and are flagged unaligned (their skew is unknown, not hidden)."""
    delta: Dict[int, "tuple[int, int]"] = {rings[0]["epoch"]: (0, 0)}
    # adjacency over epochs, both directions of every sample
    edges: Dict[int, List["tuple[int, int, int]"]] = {}
    for r in rings:
        for peer, (off, unc) in r["clock"].items():
            # local -> peer: t_peer = t_local + off
            edges.setdefault(r["epoch"], []).append((peer, off, unc))
            edges.setdefault(peer, []).append((r["epoch"], -off, unc))
    frontier = [rings[0]["epoch"]]
    while frontier:
        ep = frontier.pop()
        d, u = delta[ep]
        for peer, off, unc in edges.get(ep, ()):
            if peer in delta:
                continue
            # ts_ref = t_peer + delta_peer and t_peer = t_local + off
            # with ts_ref = t_local + d  =>  delta_peer = d - off
            delta[peer] = (d - off, u + unc)
            frontier.append(peer)
    out = []
    for r in rings:
        d, u = delta.get(r["epoch"], (0, 0))
        out.append({"proc": r["proc"], "epoch": r["epoch"],
                    "offset_ns": d, "uncertainty_ns": u,
                    "aligned": r["epoch"] in delta})
    return out


def merge_rings(rings: Sequence[Dict[str, Any]]
                ) -> "tuple[Dict[str, Any], Dict[str, Any]]":
    """Join N loaded ring dumps (:func:`load_ring`) into one Chrome trace
    object: one pid per process, per-stage tracks, timestamps corrected
    onto ring 0's timebase via the clock-sample graph, and cross-wire
    flow arrows pairing client ``query.send`` → server ``ingress`` and
    server ``query.reply`` → client ``query.recv`` spans that share a
    (globally-unique) trace id across different processes.  Returns
    ``(chrome_obj, stats)``; the object passes :func:`validate_chrome`."""
    if not rings:
        return to_chrome([]), {"rings": 0, "spans": 0, "arrows": 0}
    align = _solve_offsets(rings)
    meta_evs: List[Dict[str, Any]] = []
    out: List[Dict[str, Any]] = []
    track: Dict[Any, int] = {}
    # tid -> [(ring_idx, rec_dict)] per linkable kind
    ends: Dict[str, Dict[int, List["tuple[int, Dict[str, Any]]"]]] = {
        "query.send": {}, "ingress": {}, "query.reply": {},
        "query.recv": {},
    }
    total = 0
    for i, (r, al) in enumerate(zip(rings, align)):
        pid = i + 1
        meta_evs.append({
            "ph": "M", "pid": pid, "tid": 0, "ts": 0,
            "name": "process_name",
            "args": {"name": f"{r['proc']} epoch={r['epoch']}"},
        })
        d = al["offset_ns"]
        for e in r["spans"]:
            total += 1
            t = track.get((pid, e.stage))
            if t is None:
                t = track[(pid, e.stage)] = len(track) + 1
                meta_evs.append({
                    "ph": "M", "pid": pid, "tid": t, "ts": 0,
                    "name": "thread_name", "args": {"name": e.stage}})
            args: Dict[str, Any] = {}
            if e.tid is not None:
                args["trace_id"] = e.tid
            if e.args:
                args.update(e.args)
            rec = {"name": e.kind, "cat": e.kind,
                   "ph": "X" if e.dur > 0 else "i",
                   "ts": (e.ts + d) / 1e3, "pid": pid, "tid": t,
                   "args": args}
            if e.dur > 0:
                rec["dur"] = e.dur / 1e3
            else:
                rec["s"] = "t"
            if e.tid is not None and e.kind in ends:
                ends[e.kind].setdefault(e.tid, []).append((i, rec))
            out.append(rec)
    # cross-wire flow arrows: same trace id, different process, ordered
    # pairing (one send per request; replies/recvs pair per token)
    flows: List[Dict[str, Any]] = []
    flow_ids = itertools.count(1)
    for src_kind, dst_kind in (("query.send", "ingress"),
                               ("query.reply", "query.recv")):
        for tid, srcs in ends[src_kind].items():
            dsts = [p for p in ends[dst_kind].get(tid, ())]
            if dst_kind == "ingress":
                # the id's epoch prefix names the MINTING ring: its own
                # source-ingress span (same tid, earlier ts) is not a
                # wire adoption and must not eat the ordered pairing
                # slot of the server's adopted-ingress span
                dsts = [p for p in dsts
                        if rings[p[0]]["epoch"] != (tid >> 32)]
            for (si, srec), (di, drec) in zip(sorted(srcs, key=lambda p: p[1]["ts"]),
                                              sorted(dsts, key=lambda p: p[1]["ts"])):
                if si == di:
                    continue  # same process: not a wire crossing
                fid = next(flow_ids)
                unc = (align[si]["uncertainty_ns"]
                       + align[di]["uncertainty_ns"])
                flows.append({
                    "ph": "s", "id": fid, "pid": srec["pid"],
                    "tid": srec["tid"],
                    "ts": srec["ts"] + srec.get("dur", 0.0),
                    "name": "xwire", "cat": "xwire",
                    "args": {"trace_id": tid, "uncertainty_ns": unc}})
                flows.append({
                    "ph": "f", "bp": "e", "id": fid, "pid": drec["pid"],
                    "tid": drec["tid"], "ts": drec["ts"],
                    "name": "xwire", "cat": "xwire",
                    "args": {"trace_id": tid}})
    all_events = meta_evs + out + flows
    all_events.sort(key=lambda r: (r["ts"], 0 if r["ph"] == "M" else 1))
    obj = {"traceEvents": all_events, "displayTimeUnit": "ms",
           "otherData": {"spanKinds": dict(SPAN_KINDS), "weave": align}}
    stats = {"rings": len(rings), "spans": total,
             "arrows": len(flows) // 2,
             "unaligned": [a["proc"] for a in align if not a["aligned"]]}
    return obj, stats


def merge_ring_files(paths: Sequence[str]
                     ) -> "tuple[Dict[str, Any], Dict[str, Any]]":
    """:func:`load_ring` each path, :func:`merge_rings` the lot."""
    return merge_rings([load_ring(p) for p in paths])


# -- post-mortem log dump ----------------------------------------------------

def format_recent(seconds: float = 5.0,
                  rec: Optional[FlightRecorder] = None) -> List[str]:
    """The last ``seconds`` of the ring as human-readable timeline lines
    (newest window, oldest first), relative to the newest event."""
    rec = rec or recorder
    evs = rec.recent(seconds)
    if not evs:
        return []
    t_end = max(e.ts + e.dur for e in evs)
    lines = []
    for e in sorted(evs, key=lambda s: s.ts):
        rel_ms = (e.ts - t_end) / 1e6
        tid = f" #{e.tid}" if e.tid is not None else ""
        extra = ""
        if e.args:
            extra = " " + " ".join(
                f"{k}={v}" for k, v in sorted(e.args.items()))
        lines.append(
            f"  {rel_ms:+10.3f}ms {e.stage:<20s} {e.kind:<8s}"
            f" {e.dur / 1e6:9.3f}ms{tid}{extra}")
    return lines


def dump_recent_to_log(log, seconds: float = 5.0, reason: str = "",
                       rec: Optional[FlightRecorder] = None) -> int:
    """Dump the recent flight-recorder window to ``log`` (a stdlib
    logger) — the watchdog-fire / pipeline-error post-mortem.  No-op when
    the recorder is off or empty; returns the number of spans dumped.
    Never raises (a crash report must not crash)."""
    try:
        rec = rec or recorder
        if not rec.active:
            return 0
        lines = format_recent(seconds, rec)
        if not lines:
            return 0
        head = (f"flight recorder: last {seconds:g}s "
                f"({len(lines)} spans){' — ' + reason if reason else ''}")
        log.error("%s\n%s", head, "\n".join(lines))
        return len(lines)
    except Exception:  # noqa: BLE001 - post-mortem path must not raise
        return 0
