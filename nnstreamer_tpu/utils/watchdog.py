"""Timeout watchdog for hang detection.

Reference analog: ``nnstreamer_watchdog.c`` (SURVEY §2.1/§5.3) — a GLib
timer the trainer/query elements arm around operations that can wedge
(sub-plugin train step, remote response wait); firing raises an element
error instead of hanging the pipeline forever.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from ..core.log import logger
from .tracing import dump_recent_to_log

log = logger(__name__)


class Watchdog:
    """Arm/feed/disarm timer.  If ``timeout`` elapses without a feed, the
    ``on_timeout`` callback fires (once per arming) on the watchdog thread.

    >>> wd = Watchdog(5.0, lambda: pipeline.abort("trainer hung"))
    >>> with wd:                  # armed
    ...     for batch in data:
    ...         step(batch)
    ...         wd.feed()         # still alive
    """

    _GUARDED_BY = {"_timer": "_lock", "_fired": "_lock", "_gen": "_lock"}

    def __init__(self, timeout: float, on_timeout: Callable[[], None]):
        self.timeout = float(timeout)
        self.on_timeout = on_timeout
        self._timer: Optional[threading.Timer] = None
        self._lock = threading.Lock()
        self._fired = False
        # Arming generation: a pending _fire that lost the race against a
        # feed/disarm/re-arm (Timer.cancel cannot stop a callback that has
        # already STARTED and is blocked on our lock) sees a stale
        # generation and returns — it must neither fire with an expired
        # deadline nor double-fire after a re-arm.
        self._gen = 0

    def arm(self) -> "Watchdog":
        with self._lock:
            self._fired = False
            self._schedule_locked()
        return self

    def _schedule_locked(self) -> None:
        if self._timer is not None:
            self._timer.cancel()
        self._gen += 1
        self._timer = threading.Timer(self.timeout, self._fire,
                                      args=(self._gen,))
        self._timer.daemon = True
        self._timer.start()

    def _fire(self, gen: int) -> None:
        with self._lock:
            if self._fired or self._timer is None or gen != self._gen:
                return
            self._fired = True
        # Post-mortem FIRST (never raises, no-op when tracing is off):
        # the hang report carries the flight recorder's recent timeline —
        # including the stalled stage's last span — even if on_timeout
        # aborts the process.
        dump_recent_to_log(
            log, reason=f"watchdog fired after {self.timeout}s")
        self.on_timeout()

    def feed(self) -> None:
        """Reset the countdown (call from the watched loop).

        A documented NO-OP on a watchdog that is disarmed or has already
        FIRED: the timeout callback ran (or is running), and feeding must
        neither resurrect the countdown nor re-fire it — the watched
        operation was already declared hung, and racing a feed against the
        in-flight ``on_timeout`` would otherwise re-arm a timer nobody
        owns.  Re-arm explicitly with :meth:`arm` to reuse the watchdog.
        """
        with self._lock:
            if self._timer is None or self._fired:
                return
            self._schedule_locked()

    def disarm(self) -> None:
        """Stop the countdown.  Safe to call at ANY point relative to the
        timer — including after ``_fire`` has started (the callback either
        completed already or sees the bumped generation and returns): never
        raises, never lets a second fire through."""
        with self._lock:
            self._gen += 1
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None

    @property
    def fired(self) -> bool:
        return self._fired

    def __enter__(self) -> "Watchdog":
        return self.arm()

    def __exit__(self, *exc) -> None:
        self.disarm()


def call_with_watchdog(fn: Callable, timeout: float, what: str = "call"):
    """Run ``fn()`` on a helper thread; raise TimeoutError if it exceeds
    ``timeout`` seconds.  The wedged thread is daemonized (Python cannot
    kill it) — "report, don't recover", like the reference watchdog.  Used
    by tensor_trainer around the sub-plugin epoch."""
    import threading

    box: dict = {}

    def run():
        try:
            box["result"] = fn()
        except BaseException as e:  # noqa: BLE001 - re-raised on the caller
            box["exc"] = e

    t = threading.Thread(target=run, name=f"watchdog-{what}", daemon=True)
    t.start()
    t.join(timeout)
    if t.is_alive():
        dump_recent_to_log(
            log, reason=f"{what} exceeded watchdog timeout {timeout}s")
        raise TimeoutError(f"{what} exceeded watchdog timeout {timeout}s")
    if "exc" in box:
        raise box["exc"]
    return box["result"]
