"""nns-armor: poison-pill quarantine, dead-letter queue, and the
repeat-offender circuit breaker (ISSUE 12, docs/ROBUSTNESS.md).

A public front door sees requests that crash workers as a matter of
course.  Before this module a stage exception either restarted the
stage (PR 11, losing the buffer silently) or killed the pipeline.  With
``Pipeline(quarantine=...)``:

* the triggering request is **quarantined** — serialized via the wire
  codec into a bounded dead-letter-queue directory with the error, the
  tenant, and the flight-recorder ring excerpt attached (``_dlq`` meta),
  so the poison pill is reproducible offline (``decode_buffer`` the
  file back) instead of gone;
* the client receives a typed ``abort_reason=poison`` terminator (the
  serversink routes it by the request's own conn/msg meta) and the
  pipeline keeps serving everyone else;
* N poisons from one tenant inside a sliding window trip a **circuit
  breaker** that flips PR 11's per-tenant ``tenant_admission`` override
  to ``shed`` on every query-server core of the pipeline — the
  repeat offender is auto-shed at admission until the breaker is reset
  (span-stamped ``armor.breaker``).

Everything here is host-side value movement: no jax import, no device
dispatch.
"""

from __future__ import annotations

import collections
import dataclasses
import os
import struct
import threading
import time
import zlib
from typing import Callable, Dict, List, Optional

import numpy as np

from ..core.log import logger, metrics
#: META_POISON marks a poison terminator: runners forward such buffers
#: WITHOUT invoking the stage (they are answers, not work), sinks
#: deliver them like any response.  META_DLQ carries the DLQ record
#: context on a quarantined entry.  Both are declared in the shared
#: protocol registry (core/meta_keys.py) and re-exported here.
from ..core.meta_keys import (  # noqa: F401  (re-export)
    ABORT_REASON_POISON, META_ABORT_REASON, META_DLQ, META_POISON,
    META_STREAM_ABORTED, META_STREAM_INDEX, META_STREAM_LAST,
)
from . import tracing, wire

log = logger(__name__)

_DLQ_PREFIX = "poison-"
_DLQ_SUFFIX = ".nns"

#: DLQ file framing: u32 magic "NDLQ" | u32 crc32(payload) | payload
#: (payload = wire.encode_buffer of the poisoned request + _dlq meta)
DLQ_MAGIC = 0x4E444C51


@dataclasses.dataclass
class QuarantinePolicy:
    """``Pipeline(quarantine=...)`` accepts a directory path, a dict of
    these fields, or an instance.  ``dir`` is the DLQ directory (created
    on first use).  ``max_entries``/``max_bytes`` bound the DLQ —
    oldest entries are evicted first, the poison stream must never fill
    a disk.  ``breaker_threshold`` poisons from ONE tenant within
    ``breaker_window_s`` seconds trip the breaker (0 disables it)."""

    dir: str = ""
    max_entries: int = 256
    max_bytes: int = 64 << 20
    breaker_threshold: int = 3
    breaker_window_s: float = 30.0

    @classmethod
    def of(cls, obj) -> "QuarantinePolicy":
        if isinstance(obj, cls):
            return obj
        if isinstance(obj, str):
            return cls(dir=obj)
        if isinstance(obj, dict):
            unknown = set(obj) - {f.name for f in
                                  dataclasses.fields(cls)}
            if unknown:
                raise ValueError(
                    f"unknown quarantine policy keys {sorted(unknown)}")
            return cls(**obj)
        raise ValueError(
            f"quarantine must be a DLQ directory path, a policy dict, "
            f"or a QuarantinePolicy, got {type(obj).__name__}")


def load_dlq_entry(path: str):
    """Read one DLQ file back into ``(buffer, flags)`` —
    CRC-verified, then :func:`~nnstreamer_tpu.utils.wire.decode_buffer`
    (the quarantined request's tensors + its ``_dlq`` context meta)."""
    with open(path, "rb") as f:
        raw = f.read()
    if len(raw) < 8:
        raise wire.WireError(f"DLQ file {path} too short")
    magic, crc = struct.unpack_from("<II", raw, 0)
    if magic != DLQ_MAGIC:
        raise wire.WireError(f"DLQ file {path} has bad magic")
    payload = raw[8:]
    if zlib.crc32(payload) & 0xFFFFFFFF != crc:
        raise wire.WireError(f"DLQ file {path} failed its CRC")
    return wire.decode_buffer(payload)


class DeadLetterQueue:
    """Bounded directory of quarantined requests."""

    _GUARDED_BY = {"_n": "_lock"}

    def __init__(self, path: str, max_entries: int = 256,
                 max_bytes: int = 64 << 20):
        self.path = path
        self.max_entries = max(1, int(max_entries))
        self.max_bytes = max(1 << 12, int(max_bytes))
        self._lock = threading.Lock()
        self._n = 0

    def entries(self) -> List[str]:
        try:
            names = os.listdir(self.path)
        except FileNotFoundError:
            return []
        return sorted(os.path.join(self.path, n) for n in names
                      if n.startswith(_DLQ_PREFIX)
                      and n.endswith(_DLQ_SUFFIX))

    def _evict_locked(self, incoming_bytes: int) -> None:
        entries = self.entries()
        total = 0
        sizes = {}
        for p in entries:
            try:
                sizes[p] = os.path.getsize(p)
            except OSError:
                sizes[p] = 0
            total += sizes[p]
        while entries and (len(entries) >= self.max_entries
                           or total + incoming_bytes > self.max_bytes):
            victim = entries.pop(0)  # oldest first: keep recent poisons
            try:
                os.unlink(victim)
            except OSError:
                pass
            total -= sizes.get(victim, 0)
            metrics.count("armor.dlq_evicted")

    def put(self, buf, *, error: str, stage: str,
            tenant: Optional[str] = None,
            ring: Optional[List[str]] = None) -> str:
        """Serialize one poisoned request into the DLQ; returns the file
        path.  The record is the request's own wire encoding with a
        ``_dlq`` meta object attached: ``{error, stage, tenant, t,
        ring}`` — everything a post-mortem replay needs."""
        host = buf.to_host() if hasattr(buf, "to_host") else buf
        rec = host.with_tensors([np.asarray(t) for t in host.tensors])
        rec.meta.pop("_host_post", None)
        rec.meta[META_DLQ] = {
            "error": str(error)[:2000],
            "stage": stage,
            "tenant": tenant,
            "t": time.time(),
            "ring": list(ring or [])[-40:],
        }
        payload = wire.encode_buffer(rec)
        frame = struct.pack(
            "<II", DLQ_MAGIC, zlib.crc32(payload) & 0xFFFFFFFF) + payload
        with self._lock:
            os.makedirs(self.path, exist_ok=True)
            self._evict_locked(len(frame))
            self._n += 1
            name = (f"{_DLQ_PREFIX}{time.time():.6f}-{self._n:06d}"
                    f"{_DLQ_SUFFIX}")
            path = os.path.join(self.path, name)
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(frame)
            os.replace(tmp, path)  # readers never see a half write
        return path


class CircuitBreaker:
    """Sliding-window repeat-offender breaker over per-tenant poisons.

    ``threshold`` poisons from one tenant inside ``window_s`` seconds
    flip that tenant's admission override to ``shed`` through
    ``apply_fn(tenant, engage)`` (the pipeline wires this to every
    query-server core's ``tenant_admission`` map — PR 11's autoscaler
    lever, reused).  The trip latches until :meth:`reset`."""

    _GUARDED_BY = {"_hits": "_lock", "tripped": "_lock"}

    def __init__(self, threshold: int, window_s: float,
                 apply_fn: Callable[[str, bool], None],
                 recorder: Optional[tracing.FlightRecorder] = None):
        self.threshold = max(0, int(threshold))
        self.window_s = float(window_s)
        self.apply_fn = apply_fn
        self.recorder = recorder
        self._hits: Dict[str, collections.deque] = {}
        self.tripped: set = set()
        self._lock = threading.Lock()

    def record_poison(self, tenant: Optional[str]) -> bool:
        """One poison observed for ``tenant``; returns True when this
        poison TRIPS the breaker (edge, not level)."""
        if self.threshold <= 0 or tenant is None:
            return False
        now = time.monotonic()
        with self._lock:
            dq = self._hits.setdefault(
                tenant, collections.deque(maxlen=self.threshold))
            dq.append(now)
            if tenant in self.tripped:
                # self-healing latch: another actor (the autoscaler
                # relax edge shares the tenant_admission map) may have
                # popped or overwritten the override — a poison from a
                # TRIPPED tenant re-asserts it
                try:
                    self.apply_fn(tenant, True)
                except Exception:  # noqa: BLE001
                    log.exception("breaker re-assert failed for "
                                  "tenant %s", tenant)
                return False
            if len(dq) < self.threshold or now - dq[0] > self.window_s:
                return False
            self.tripped.add(tenant)
        try:
            self.apply_fn(tenant, True)
        except Exception:  # noqa: BLE001 - the breaker must never throw
            log.exception("breaker engage failed for tenant %s", tenant)
        metrics.count("armor.breaker_trips", tenant=tenant)
        log.warning(
            "armor: circuit breaker TRIPPED for tenant %s (%d poisons "
            "within %.1fs) — admission override flipped to shed",
            tenant, self.threshold, self.window_s)
        if self.recorder is not None and self.recorder.active:
            self.recorder.record(
                "armor.breaker", "armor", None, time.monotonic_ns(), 0,
                tenant=tenant, threshold=self.threshold,
                window_s=self.window_s, edge="trip")
        return True

    def reset(self, tenant: str) -> bool:
        with self._lock:
            if tenant not in self.tripped:
                return False
            self.tripped.discard(tenant)
            self._hits.pop(tenant, None)
        try:
            self.apply_fn(tenant, False)
        except Exception:  # noqa: BLE001
            log.exception("breaker reset failed for tenant %s", tenant)
        if self.recorder is not None and self.recorder.active:
            self.recorder.record(
                "armor.breaker", "armor", None, time.monotonic_ns(), 0,
                tenant=tenant, edge="reset")
        return True


class Armor:
    """One pipeline's quarantine surface: DLQ + breaker + the nan-guard
    flag, built by ``Pipeline(quarantine=..., nan_guard=...)`` and held
    on ``pipeline._armor`` (runners and the llm serve loop read it
    through the same attach pattern as ``_trace_rec``)."""

    def __init__(self, policy: QuarantinePolicy, *, nan_guard: bool,
                 apply_admission: Callable[[str, bool], None],
                 recorder: Optional[tracing.FlightRecorder] = None):
        self.policy = policy
        self.nan_guard = bool(nan_guard)
        self.recorder = recorder
        self.dlq = DeadLetterQueue(policy.dir, policy.max_entries,
                                   policy.max_bytes)
        self.breaker = CircuitBreaker(
            policy.breaker_threshold, policy.breaker_window_s,
            apply_admission, recorder=recorder)

    def quarantine(self, buf, *, error: BaseException, stage: str) -> str:
        """Quarantine one poisoned request: DLQ record (with the recent
        flight-recorder window attached when tracing is on), per-tenant
        poison counter, ``armor.quarantine`` span, breaker accounting.
        Never raises — the quarantine path runs inside a runner's
        exception handler."""
        tenant = buf.meta.get(tracing.META_TENANT) \
            if hasattr(buf, "meta") else None
        ring: List[str] = []
        rec = self.recorder if self.recorder is not None \
            else (tracing.recorder if tracing.recorder.active else None)
        if rec is not None and rec.active:
            try:
                ring = tracing.format_recent(5.0, rec)
            except Exception:  # noqa: BLE001
                ring = []
        path = ""
        if self.policy.dir:
            # nan_guard-only armor (no quarantine= DLQ dir) still
            # counts/answers/breaker-trips — it just has nowhere to
            # preserve the pill
            try:
                path = self.dlq.put(
                    buf, error=f"{type(error).__name__}: {error}",
                    stage=stage, tenant=tenant, ring=ring)
            except Exception:  # noqa: BLE001 - a full/broken disk must
                log.exception("armor: DLQ write failed")  # not kill us
        metrics.count("armor.quarantined", tenant=tenant)
        log.warning(
            "armor: quarantined poison request at stage %s (tenant=%s): "
            "%r -> %s", stage, tenant, error, path or "<dlq write failed>")
        if rec is not None and rec.active:
            tid = buf.meta.get(tracing.META_TRACE_ID) \
                if hasattr(buf, "meta") else None
            args = {"error": str(error)[:200]}
            if tenant is not None:
                args["tenant"] = tenant
            if path:
                args["dlq"] = os.path.basename(path)
            try:
                rec.record("armor.quarantine", stage, tid,
                           time.monotonic_ns(), 0, **args)
            except Exception:  # noqa: BLE001 - never raise from here
                pass
        self.breaker.record_poison(tenant)
        return path

    # -- nan guard ---------------------------------------------------------
    @staticmethod
    def nonfinite(buf) -> bool:
        """True when any float tensor of ``buf`` holds NaN/Inf.  Forces
        host materialization of device outputs — the cost of turning
        silent numeric corruption into a typed poison, paid only when
        ``nan_guard=True``."""
        for t in getattr(buf, "tensors", []):
            a = np.asarray(t)
            if a.dtype.kind == "f" and a.size \
                    and not np.isfinite(a).all():
                return True
        return False


def poison_terminator(buf, error: BaseException):
    """The typed answer a poisoned request's client receives: an empty
    buffer keeping the request's routing meta (conn/msg/tenant/trace
    ids) with ``abort_reason="poison"``.  Runners forward it without
    invoking stages (:data:`META_POISON`); the serversink routes it like
    any response; streaming consumers see ``stream_aborted`` when the
    request was a token stream."""
    term = buf.with_tensors([])
    term.meta.pop("_host_post", None)
    term.meta[META_POISON] = True
    term.meta[META_ABORT_REASON] = ABORT_REASON_POISON
    term.meta["error"] = f"{type(error).__name__}: {str(error)[:200]}"
    if META_STREAM_INDEX in term.meta:
        term.meta[META_STREAM_LAST] = True
        term.meta[META_STREAM_ABORTED] = True
    return term
