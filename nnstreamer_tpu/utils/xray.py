"""nns-xray: predicted-vs-actual reconciliation for the running pipeline.

The deep lint (docs/ANALYSIS.md "Deep pass") makes *static promises* — a
closed compiled-program census, an HBM high-water estimate, fetch-bound
verdicts — and until now the runtime was *trusted* to honor them: an
unpredicted recompile, an HBM estimate drifting 2x from silicon, or a
stage running at 4% MFU was invisible until a chip sweep regressed.  This
module closes the loop:

* **Program registry / census drift** — every jit entry point (BatchRunner
  bucket programs, FusedElement chains, the jax tensor_filter path, the
  llm 3-program serve loop, the device-aggregator ring) registers its
  compiles with the process-wide :data:`registry` — stage, abstract
  signature, trigger shape, compile wall time — and the registry
  reconciles the live program set against the deep lint's predicted
  census CONTINUOUSLY: the prediction arithmetic is the SAME shared code
  (``pipeline/batching.ladder``, ``plan.adaptive_variant_budget``,
  ``serving_plan()['programs']``, ``tracecheck.AGGREGATOR_PROGRAMS``), so
  an unpredicted signature fires a ``census-drift`` warning carrying the
  field-level signature diff (reusing
  :func:`~nnstreamer_tpu.core.caps.explain_mismatch`) plus a
  flight-recorder ring dump, and ``<stage>.compiles`` /
  ``xray.census_drift`` land in Prometheus.

* **Device-time / MFU attribution** — per-dispatch FLOPs/bytes from the
  compiled program's cost analysis (``jit(fn).lower(...).cost_analysis()``
  — a trace, never an extra backend compile) joined with measured dispatch
  wall time yield per-stage ``mfu`` and ``roofline_fraction`` gauges and
  price the bucket ladder's pad waste in FLOPs
  (``<stage>.pad_waste_flops``), with a ``device:<stage>`` track emitted
  into the Chrome/Perfetto trace beside the host spans.  On async
  backends the measured time is the host-side dispatch window (sinks are
  where the pipeline blocks); on the CPU proxy it is compute.

* **HBM ledger** — a live per-category ledger (params / KV pool /
  aggregator rings / dispatch-window activations; device
  ``memory_stats()`` where the backend provides them, model-side
  accounting elsewhere) reconciled against the deep-lint estimate
  (:meth:`ResourceReport.by_category`), warning past
  ``Config.xray_hbm_tolerance``.

* :func:`explain` / ``python -m nnstreamer_tpu.tools.doctor`` — one
  report joining plan, residency, mesh, census, SLO verdicts, and the
  measured ledger into predicted-vs-actual columns with a
  machine-readable JSON twin for CI.

**Zero overhead when off** (the PR 5 ``record()``-raises discipline):
instrumentation sites hold ``element._xray`` — ``None`` unless
``Pipeline(xray=True)`` / ``NNS_TPU_XRAY=1`` — so the disabled hot path
is ONE pointer check: no wrapper objects, no meta, no cost_analysis
calls.  Pinned structurally by tests/test_xray.py (registry methods
monkeypatched to raise under an xray-off run).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, FrozenSet, List, Optional, Tuple

from ..core.log import logger, metrics
from . import locks

log = logger(__name__)

#: HBM ledger categories — the deep lint's StageResource fields, measured
#: live (docs/OBSERVABILITY.md "Predicted vs actual").  ``train_state``
#: (nns-learn) = trainer optimizer moments + the device-resident
#: streaming sample window, reconciled against
#: ``trainer/subplugin.train_plan``'s eval_shape-abstracted estimate.
HBM_CATEGORIES: Tuple[str, ...] = ("params", "kv_pool", "agg_rings",
                                   "activations", "train_state",
                                   "prng_state")

#: ledger categories below this are never drift-warned: transient
#: windows (activations) legitimately read 0 between dispatches, and
#: byte-level noise on tiny stages is not an estimate failure
HBM_WARN_FLOOR = 1 << 20

#: peak dense-matmul TFLOPs per chip by device_kind substring (bf16
#: where the MXU has one).  ``Config.peak_tflops`` overrides; the CPU
#: fallback makes MFU numbers on the host proxy *indicative only* (the
#: gauge still proves the attribution plumbing end to end).
_PEAK_TFLOPS_BY_KIND: Tuple[Tuple[str, float], ...] = (
    ("v5p", 459.0), ("v5e", 197.0), ("v5", 459.0),
    ("v4", 275.0), ("v3", 123.0), ("v2", 45.0),
    ("cpu", 0.1),
)

_peak_cache: Dict[str, float] = {}


def peak_flops() -> float:
    """Peak FLOP/s of one local device — ``Config.peak_tflops`` when set
    (``NNS_TPU_PEAK_TFLOPS``), else the device_kind table above."""
    from ..core.config import get_config

    cfg = get_config()
    if cfg.peak_tflops > 0:
        return cfg.peak_tflops * 1e12
    got = _peak_cache.get("flops")
    if got is not None:
        return got
    kind = "cpu"
    try:
        import jax

        kind = str(jax.devices()[0].device_kind).lower()
    except Exception:  # noqa: BLE001 - attribution must not crash
        pass
    val = 0.1e12
    for sub, tf in _PEAK_TFLOPS_BY_KIND:
        if sub in kind:
            val = tf * 1e12
            break
    _peak_cache["flops"] = val
    return val


def peak_bw() -> float:
    """Peak HBM bandwidth (bytes/s) — the residency planner's calibrated
    :data:`~nnstreamer_tpu.pipeline.residency.HBM_GBPS` roofline constant,
    so static fetch pricing and live roofline attribution use one number."""
    from ..pipeline.residency import HBM_GBPS

    return HBM_GBPS * 1e9


# ---------------------------------------------------------------------------
# abstract signatures
# ---------------------------------------------------------------------------

def abstract_signature(args, kwargs) -> Tuple:
    """The call's abstract signature: one descriptor per pytree leaf —
    ``("t", shape, dtype, weak)`` for array-likes, ``("py", typename)``
    for raw python scalars (which jit weak-types: the classic
    numpy-scalar-vs-python-int census trap is exactly this difference)."""
    import jax

    sig = []
    for x in jax.tree_util.tree_leaves((args, kwargs)):
        shape = getattr(x, "shape", None)
        dtype = getattr(x, "dtype", None)
        if shape is not None and dtype is not None:
            sig.append(("t", tuple(int(d) for d in shape), str(dtype),
                        bool(getattr(x, "weak_type", False))))
        else:
            sig.append(("py", type(x).__name__))
    return tuple(sig)


def render_leaf(leaf: Tuple) -> str:
    if leaf[0] == "py":
        return f"py:{leaf[1]}"
    _, shape, dtype, weak = leaf
    return f"{list(shape)}{dtype}" + ("~weak" if weak else "")


def render_signature(sig: Tuple) -> str:
    return ", ".join(render_leaf(leaf) for leaf in sig)


def _sig_tensors(sig: Tuple):
    """TensorsSpec view of an all-array signature (None when any leaf is
    a raw python scalar — those have no spec representation)."""
    from ..core.types import TensorSpec, TensorsSpec

    specs = []
    for leaf in sig:
        if leaf[0] != "t":
            return None
        _, shape, dtype, _ = leaf
        try:
            specs.append(TensorSpec.from_shape(tuple(shape) or (1,), dtype))
        except Exception:  # noqa: BLE001 - exotic dtypes fall back
            return None
    return TensorsSpec(tuple(specs))


def explain_signature_drift(actual: Tuple, predicted: Optional[Tuple]) -> str:
    """Field-level diff between a drifted abstract signature and the
    stage's predicted/baseline one — :func:`explain_mismatch` for the
    shape/dtype part, leaf-by-leaf for what caps cannot express (weak
    typing, raw python scalars, arity)."""
    if predicted is None:
        return "no predicted signature to diff against"
    if len(actual) != len(predicted):
        return (f"arity {len(actual)} ⊄ predicted {len(predicted)} "
                f"([{render_signature(actual)}] vs "
                f"[{render_signature(predicted)}])")
    a_spec, p_spec = _sig_tensors(actual), _sig_tensors(predicted)
    if a_spec is not None and p_spec is not None \
            and not a_spec.is_compatible(p_spec):
        from ..core.caps import Caps, explain_mismatch

        return explain_mismatch(Caps.tensors(a_spec), Caps.tensors(p_spec))
    for i, (la, lp) in enumerate(zip(actual, predicted)):
        if la != lp:
            return (f"arg {i}: {render_leaf(la)} ⊄ predicted "
                    f"{render_leaf(lp)}")
    return "same abstract signature recompiled"


def _cache_size(fn) -> int:
    try:
        return int(fn._cache_size())
    except Exception:  # noqa: BLE001 - non-jit callables have no cache
        return -1


def _cost_of(fn, args, kwargs) -> Tuple[float, float]:
    """(flops, bytes accessed) for one signature from the lowered
    program's cost analysis — ``lower()`` TRACES (no backend compile, no
    dispatch, and jit's own cache is untouched, so zero-recompile pins
    keep holding).  Best-effort: attribution must never take a pipeline
    down."""
    try:
        ca = fn.lower(*args, **kwargs).cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        return (float(ca.get("flops", 0.0) or 0.0),
                float(ca.get("bytes accessed", 0.0) or 0.0))
    except Exception:  # noqa: BLE001
        return 0.0, 0.0


# ---------------------------------------------------------------------------
# tracked programs
# ---------------------------------------------------------------------------

class TrackedProgram:
    """A jitted callable with its compiles registered and its dispatches
    attributed.  Cache growth (``jit._cache_size``) is the compile
    detector — it catches signatures the call site never meant to mint
    (the numpy-scalar ``_set_tok`` trap) exactly where a
    wrap-at-build-time scheme would miss them.

    ``rows`` pins the trigger batch dim (bucket programs whose stacking
    happens inside the program); ``rows_from_leading`` derives it from
    the first array leaf (sharded programs, stacked on host).  ``rec``
    may be a FlightRecorder or a zero-arg callable resolving to one (the
    llm serve loop's recorder attaches after construction)."""

    def __init__(self, fn: Callable, reg: "ProgramRegistry", stage: str,
                 kind: str, rec=None, rows: Optional[int] = None,
                 rows_from_leading: bool = False, devices: int = 1):
        self._fn = fn
        self._reg = reg
        self.stage = stage
        self.kind = kind
        self._rec = rec
        self._rows = rows
        self._rows_leading = rows_from_leading
        #: chips this program executes across (a sharded/TP program's
        #: cost analysis covers the GLOBAL work — MFU/roofline divide
        #: the aggregate peak, not one chip's)
        self.devices = max(1, int(devices))
        self._known = _cache_size(fn)
        #: latest compiled signature's cost (per dispatch)
        self.flops = 0.0
        self.bytes_ = 0.0
        #: post-warmup dispatch stats (compile calls excluded: their wall
        #: time is compile, not device work)
        self.disp_ns = 0
        self.disp_n = 0

    def __getattr__(self, name):
        # drop-in transparency: cache-size pins, .lower() cost probes,
        # and anything else callers read off a jitted fn pass through
        # (__dict__ access keeps a half-built instance from recursing)
        fn = self.__dict__.get("_fn")
        if fn is None:
            raise AttributeError(name)
        return getattr(fn, name)

    def __call__(self, *args, **kwargs):
        fn = self._fn
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        dt = time.perf_counter() - t0
        n = _cache_size(fn)
        if n != self._known:
            self._known = n
            sig = abstract_signature(args, kwargs)
            flops, bts = _cost_of(fn, args, kwargs)
            if flops:
                self.flops = flops
            if bts:
                self.bytes_ = bts
            rows = self._rows
            if rows is None and self._rows_leading:
                rows = next((leaf[1][0] for leaf in sig
                             if leaf[0] == "t" and leaf[1]), None)
            self._reg.register(self.stage, self.kind, sig,
                               compile_s=dt, flops=flops, bytes_=bts,
                               rows=rows)
        else:
            self.disp_ns += int(dt * 1e9)
            self.disp_n += 1
            rec = self._rec() if callable(self._rec) else self._rec
            if rec is not None and rec.active:
                # the DEVICE track: one span per dispatch on its own
                # `device:<stage>` Perfetto thread, beside the host spans
                dur = int(dt * 1e9)
                rec.record("device", f"device:{self.stage}", None,
                           time.monotonic_ns() - dur, dur,
                           program=self.kind, flops=self.flops)
        return out


class ProgramRegistry:
    """Process-wide live compiled-program census (one per process, like
    ``core.log.metrics``).  ``expect()`` installs the deep lint's
    predicted budget per ``(stage, kind)``; ``track()`` wraps a jitted
    fn; ``register()`` records one compile and fires ``census-drift``
    when the live set escapes the prediction."""

    #: nns-tsan lock discipline (lint --threads verifies statically,
    #: NNS_TPU_TSAN=1 verifies live — docs/ANALYSIS.md "Threads pass")
    _GUARDED_BY = {"_expected": "_lock", "_live": "_lock",
                   "_trackers": "_lock", "_drifts": "_lock",
                   "_drift_dumped": "_lock"}

    def __init__(self):
        self._lock = locks.make_lock("ProgramRegistry._lock")
        #: (stage, kind) -> (budget, allow-set or None, note)
        self._expected: Dict[Tuple[str, str],
                             Tuple[int, Optional[FrozenSet[int]], str]] = {}
        #: (stage, kind) -> {"compiles": int, "sigs": {sig: info}}
        self._live: Dict[Tuple[str, str], Dict[str, Any]] = {}
        self._trackers: List[TrackedProgram] = []
        self._drifts: List[Dict[str, Any]] = []
        #: (stage, kind) keys whose first drift already warned + dumped
        self._drift_dumped: set = set()

    # -- install -----------------------------------------------------------
    def expect(self, stage: str, kind: str, budget: int = 0,
               allow=None, note: str = "") -> None:
        """Declare the predicted census for ``(stage, kind)``: at most
        ``budget`` compiled programs (0 = unbounded, mirroring the deep
        lint's ``recompile-unbounded`` verdict), optionally constrained
        to trigger batch dims in ``allow`` (the bucket ladder).

        Installing an expectation RESETS the key's live compile count:
        the registry is process-wide (like ``core.log.metrics``), and a
        second pipeline re-using a stage name must be measured against
        its own warmup, not a predecessor's accumulated census.  Within
        one pipeline's lifetime the count only grows — a mid-run
        ``reload_model`` recompile counts toward the budget by design
        (the deep lint does not model reloads; the drift IS the
        signal).  The corollary of the shared registry (exactly the
        metrics registry's semantics): two CONCURRENT pipelines whose
        stages share auto-generated names share census keys too — give
        elements distinct ``name=`` props when running xray pipelines
        side by side, or the later start() re-bases the earlier one's
        counts."""
        with self._lock:
            self._expected[(stage, kind)] = (
                int(budget), frozenset(allow) if allow else None, note)
            self._live.pop((stage, kind), None)
            # a fresh expectation also retires the key's PAST drift
            # verdicts (and re-arms its warn+dump): a new pipeline's
            # explain()/doctor must not inherit a stopped predecessor's
            # findings (the reconciler's gauge twin corrects on its
            # next tick)
            self._drifts = [d for d in self._drifts
                            if (d["stage"], d["kind"]) != (stage, kind)]
            self._drift_dumped.discard((stage, kind))

    def track(self, fn: Callable, stage: str, kind: str, rec=None,
              rows: Optional[int] = None,
              rows_from_leading: bool = False,
              devices: int = 1) -> Callable:
        """Wrap a jitted fn so its compiles register here.  Idempotent —
        re-wrapping a tracked program returns it unchanged (reload paths
        re-run their build hooks).  The registry holds trackers WEAKLY:
        a stopped pipeline's programs (and the params their closures
        capture) release normally; dead refs are pruned at the next
        stats read."""
        if isinstance(fn, TrackedProgram):
            return fn
        tp = TrackedProgram(fn, self, stage, kind, rec=rec, rows=rows,
                            rows_from_leading=rows_from_leading,
                            devices=devices)
        import weakref

        with self._lock:
            self._trackers.append(weakref.ref(tp))
        return tp

    # -- the census --------------------------------------------------------
    def register(self, stage: str, kind: str, sig: Tuple, *,
                 compile_s: float = 0.0, flops: float = 0.0,
                 bytes_: float = 0.0, rows: Optional[int] = None) -> None:
        """Record one compile.  Fires ``census-drift`` when the live
        program set escapes the installed expectation — count past the
        budget, or a trigger batch dim outside the predicted ladder."""
        key = (stage, kind)
        with self._lock:
            ent = self._live.setdefault(key, {"compiles": 0, "sigs": {}})
            ent["compiles"] += 1
            compiles = ent["compiles"]
            baseline = next(iter(ent["sigs"]), None)
            if sig not in ent["sigs"]:
                ent["sigs"][sig] = {
                    "compile_s": compile_s, "flops": flops,
                    "bytes": bytes_, "rows": rows,
                    "ts": time.monotonic(),
                }
            exp = self._expected.get(key)
        metrics.count(f"{stage}.compiles")
        if exp is None:
            return
        budget, allow, _note = exp
        reason = None
        if allow is not None and rows is not None and rows not in allow:
            reason = (f"trigger batch dim {rows} is not in the predicted "
                      f"bucket ladder {sorted(allow)}")
        elif budget and compiles > budget:
            reason = (f"{compiles} compiled program(s) exceed the "
                      f"predicted census of {budget}")
        if reason is not None:
            self._fire_drift(stage, kind, sig, baseline, reason)

    #: recorded drift records are bounded: a recompile STORM (the exact
    #: pathology the census catches) must not grow the process-wide
    #: singleton without limit — past the cap only the counter advances
    MAX_DRIFT_RECORDS = 512

    def _fire_drift(self, stage: str, kind: str, sig: Tuple,
                    baseline: Optional[Tuple], reason: str) -> None:
        diff = explain_signature_drift(sig, baseline)
        drift = {
            "stage": stage, "kind": kind, "reason": reason,
            "signature": render_signature(sig),
            "predicted_signature": (render_signature(baseline)
                                    if baseline is not None else None),
            "diff": diff,
        }
        with self._lock:
            if len(self._drifts) < self.MAX_DRIFT_RECORDS:
                self._drifts.append(drift)
            # warn + ring dump ONCE per key (the watchdog discipline): a
            # storm minting hundreds of programs must not pay a full
            # flight-recorder dump per compile inside the dispatch path
            first = (stage, kind) not in self._drift_dumped
            self._drift_dumped.add((stage, kind))
        # counter, named DISTINCTLY from the reconciler's
        # `xray.census_drift` gauge twin: one raw name rendered as both
        # families would flip type between scrapes once publish() runs
        metrics.count("xray.census_drifts")
        from . import tracing

        if tracing.recorder.active:
            tracing.recorder.record("xray.drift", stage, None,
                                    time.monotonic_ns(), 0,
                                    program=kind, reason=reason)
        if not first:
            log.debug("census-drift (repeat): %s/%s: %s", stage, kind,
                      reason)
            return
        log.warning(
            "census-drift: stage %s (%s): %s — signature [%s]; diff vs "
            "predicted: %s", stage, kind, reason,
            drift["signature"], diff)
        # the post-mortem window rides the FIRST drift per key, like
        # watchdog fires
        tracing.dump_recent_to_log(
            log, reason=f"census-drift at {stage}/{kind}: {reason}")

    # -- accessors ---------------------------------------------------------
    def has_compiles(self) -> bool:
        """True once any tracked program compiled — the 'pipeline has
        actually done device work' signal the ledger's under-prediction
        warn gates on (an idle pipeline's unallocated pool is not
        drift)."""
        with self._lock:
            return bool(self._live)

    def drifts(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(d) for d in self._drifts]

    def drift_count(self) -> int:
        with self._lock:
            return len(self._drifts)

    def census(self) -> Dict[str, Dict[str, Any]]:
        """Predicted-vs-live join, keyed ``"<stage>/<kind>"``: the doctor
        report's census table."""
        with self._lock:
            expected = dict(self._expected)
            live = {k: (v["compiles"],
                        [render_signature(s) for s in v["sigs"]])
                    for k, v in self._live.items()}
        out: Dict[str, Dict[str, Any]] = {}
        for key in sorted(set(expected) | set(live)):
            stage, kind = key
            budget, allow, note = expected.get(key, (0, None, ""))
            compiles, sigs = live.get(key, (0, []))
            out[f"{stage}/{kind}"] = {
                "stage": stage, "kind": kind,
                "predicted": budget or None,
                "allow": sorted(allow) if allow else None,
                "live_compiles": compiles,
                "live_signatures": sigs,
                "within": (not budget) or compiles <= budget,
                "note": note,
            }
        return out

    def stage_stats(self) -> Dict[str, Dict[str, float]]:
        """Per-stage device-time attribution aggregated over trackers:
        dispatch count, summed wall time, FLOPs/bytes throughput, and
        the derived ``mfu`` / ``roofline_fraction``."""
        with self._lock:
            # prune dead weakrefs (stopped pipelines' programs)
            self._trackers = [r for r in self._trackers
                              if r() is not None]
            trackers = [r() for r in self._trackers]
        pk, bw = peak_flops(), peak_bw()
        agg: Dict[str, Dict[str, float]] = {}
        for tp in trackers:
            if tp is None or tp.disp_n == 0:
                continue
            st = agg.setdefault(tp.stage, {
                "dispatches": 0.0, "device_ns": 0.0,
                "flops_total": 0.0, "bytes_total": 0.0,
                "peak_flop_time": 0.0, "ideal_s": 0.0})
            secs = tp.disp_ns / 1e9
            dev = max(1, tp.devices)
            st["dispatches"] += tp.disp_n
            st["device_ns"] += tp.disp_ns
            st["flops_total"] += tp.flops * tp.disp_n
            st["bytes_total"] += tp.bytes_ * tp.disp_n
            # a sharded/TP program's cost analysis covers the GLOBAL
            # work spread over `devices` chips: utilization denominates
            # in the AGGREGATE peak available during the measured time,
            # and the ideal (roofline) time divides both rooflines by
            # the participating chip count
            st["peak_flop_time"] += pk * dev * secs
            st["ideal_s"] += max(
                tp.flops / (pk * dev) if pk else 0.0,
                tp.bytes_ / (bw * dev) if bw else 0.0) * tp.disp_n
        for st in agg.values():
            secs = st["device_ns"] / 1e9
            if secs <= 0:
                st["mfu"] = st["roofline_fraction"] = 0.0
                continue
            st["mfu"] = (st["flops_total"] / st["peak_flop_time"]
                         if st["peak_flop_time"] else 0.0)
            st["roofline_fraction"] = min(1.0, st["ideal_s"] / secs)
        return agg

    def publish(self) -> None:
        """One reconciler tick's gauge export: per-stage ``mfu`` /
        ``roofline_fraction`` plus the census-drift total."""
        for stage, st in self.stage_stats().items():
            metrics.gauge(f"{stage}.mfu", st["mfu"])
            metrics.gauge(f"{stage}.roofline_fraction",
                          st["roofline_fraction"])
        metrics.gauge("xray.census_drift", float(self.drift_count()))

    def reset(self) -> None:
        with self._lock:
            self._expected.clear()
            self._live.clear()
            self._trackers.clear()
            self._drifts.clear()
            self._drift_dumped.clear()


#: THE process-wide registry (``Pipeline(xray=True)`` hands it to every
#: instrumentation site as ``element._xray``; off pipelines hold None)
registry = ProgramRegistry()


# ---------------------------------------------------------------------------
# HBM ledger
# ---------------------------------------------------------------------------

def measure_hbm(pipeline) -> Dict[str, int]:
    """Model-side live accounting per category, plus raw device
    ``memory_stats()`` where the backend provides them (TPU; CPU/PJRT
    hosts return nothing).  Bytes are process-global — under a >1
    ``model`` axis divide params/pool by M to compare per chip."""
    out: Dict[str, int] = {c: 0 for c in HBM_CATEGORIES}
    for el in {id(e): e for e in pipeline.elements.values()}.values():
        # a stopped (or never-started) tensor_filter holds fw=None —
        # param_bytes() would lazily RELOAD the framework (multi-GiB
        # checkpoints, never close()d again) just to read a byte count
        if not (hasattr(el, "fw") and el.fw is None):
            try:
                out["params"] += int(el.param_bytes() or 0)
            except Exception:  # noqa: BLE001 - accounting probe only
                pass
        fw = getattr(el, "fw", None)
        loop = getattr(fw, "_serve", None) if fw is not None else None
        if loop is not None:
            out["kv_pool"] += int(getattr(loop, "_pool_nbytes", 0) or 0)
            # sampler per-slot PRNG key state (temperature > 0 loops;
            # 0 for greedy — serving_plan's prng_state_bytes twin)
            out["prng_state"] += int(
                getattr(loop, "_prng_nbytes", 0) or 0)
        ring = getattr(el, "_ring", None)
        if ring is not None and hasattr(ring, "nbytes"):
            out["agg_rings"] += int(ring.nbytes)
        train_fn = getattr(el, "train_state_bytes", None)
        if train_fn is not None:
            try:
                out["train_state"] += int(train_fn() or 0)
            except Exception:  # noqa: BLE001 - accounting probe only
                pass
    act = 0
    for r in {id(r): r for r in pipeline._runners.values()}.values():
        try:
            # lock-free snapshot of a deque the stage thread mutates:
            # CPython raises RuntimeError if an append lands mid-copy —
            # skip the sample rather than take a lock onto the hot path
            items = list(r._inflight)
        except RuntimeError:
            continue
        for item in items:
            for _pad, o in item[0]:
                tensors = getattr(o, "tensors", None)
                if tensors:
                    act += sum(int(getattr(t, "nbytes", 0) or 0)
                               for t in tensors)
    out["activations"] = act
    try:
        import jax

        stats = [d.memory_stats() for d in jax.local_devices()]
        in_use = sum(int((s or {}).get("bytes_in_use", 0)) for s in stats)
        if in_use:
            out["device_bytes_in_use"] = in_use
    except Exception:  # noqa: BLE001 - stats are a bonus, not a contract
        pass
    return out


def predicted_hbm(pipeline) -> Optional[Dict[str, int]]:
    """The deep lint's per-category estimate for this pipeline's own
    knobs (cached on the pipeline; None when the deep pass cannot run —
    e.g. an unparsable graph mid-refactor)."""
    rep = getattr(pipeline, "_xray_deep", False)
    if rep is False:
        rep = None
        try:
            from ..analysis import analyze

            got = analyze(pipeline.graph, deep=True,
                          batch_max=pipeline.batch_max,
                          batch_buckets=pipeline.batch_buckets,
                          adaptive_buckets=pipeline.adaptive_buckets,
                          data_parallel=pipeline.data_parallel,
                          model_parallel=pipeline.model_parallel,
                          dispatch_depth=pipeline.dispatch_depth)
            rep = getattr(got, "resources", None)
        except Exception:  # noqa: BLE001 - prediction is best-effort
            log.exception("xray: deep-lint prediction failed")
        pipeline._xray_deep = rep
    if rep is None:
        return None
    return rep.by_category()


class XrayReconciler:
    """The continuous predicted-vs-actual loop (0.5 s daemon, the SLO
    engine's cadence): publishes per-stage MFU/roofline gauges, the HBM
    ledger (measured + predicted + ratio per category), and warns ONCE
    per category when the ratio escapes ``Config.xray_hbm_tolerance``.
    ``Pipeline.stop()`` stops AND joins it — the thread-shutdown audit
    counts it like the sampler and the SLO engine."""

    def __init__(self, pipeline, period_s: float = 0.5):
        self.pipeline = pipeline
        self.period_s = period_s
        self._stop = threading.Event()
        self._warned: set = set()
        self._act_peak = 0
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "XrayReconciler":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="nns-xray", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2.0)

    def _run(self) -> None:
        while not self._stop.wait(self.period_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 - must never die loud
                log.exception("xray reconciler tick failed")

    def tick(self) -> None:
        registry.publish()
        measured = measure_hbm(self.pipeline)
        # the window is transient: reconcile its PEAK against the
        # high-water estimate, not whatever instant the tick landed on
        self._act_peak = max(self._act_peak, measured["activations"])
        measured["activations"] = self._act_peak
        predicted = predicted_hbm(self.pipeline)
        from ..core.config import get_config

        tol = float(get_config().xray_hbm_tolerance)
        for cat in HBM_CATEGORIES:
            m = measured.get(cat, 0)
            metrics.gauge(f"xray.hbm.{cat}", float(m))
            p = (predicted or {}).get(cat, 0)
            if not predicted or not p:
                continue
            metrics.gauge(f"xray.hbm_predicted.{cat}", float(p))
            ratio = m / p
            metrics.gauge(f"xray.hbm_drift.{cat}", ratio)
            if cat in self._warned:
                continue
            # either direction, each gated on ITS side's noise floor: an
            # over-use warns when the measurement is real, an
            # over-PREDICTION warns when the estimate was (a dead probe
            # measuring 0 against a 500 MiB estimate is exactly the
            # drift the ledger exists to surface) — but only once the
            # pipeline has compiled something, so an idle serve loop's
            # not-yet-allocated pool is not flagged before first traffic
            if (m > p * tol and m > HBM_WARN_FLOOR) or \
                    (p > m * tol and p > HBM_WARN_FLOOR
                     and registry.has_compiles()):
                self._warned.add(cat)
                log.warning(
                    "hbm-drift: category %s measured %.1f MiB vs deep-lint "
                    "estimate %.1f MiB (%.2fx, tolerance %gx) — the static "
                    "budget no longer describes this pipeline; re-check "
                    "the lint's resource report (docs/ANALYSIS.md)",
                    cat, m / 2**20, p / 2**20, ratio, tol)


# ---------------------------------------------------------------------------
# the doctor report
# ---------------------------------------------------------------------------

def explain(pipeline) -> Dict[str, Any]:
    """One predicted-vs-actual report for a (running or finished)
    pipeline: plan + mesh, residency, census (predicted budgets vs live
    program set + drifts), HBM ledger per category, per-stage device-time
    attribution, and the SLO verdict when an engine is attached.  JSON-
    serializable — the doctor CLI's machine-readable twin."""
    from ..core.config import get_config

    plan = {
        "stages": [{
            "stage": s.element.name,
            "elements": [pipeline.graph.nodes[n].kind for n in s.node_ids],
            "batchable": s.batchable, "shardable": s.shardable,
            "restartable": s.restartable,
        } for s in pipeline.stages],
        "batch_max": pipeline.batch_max,
        "dispatch_depth": pipeline.dispatch_depth,
        "fetch_depth": pipeline.fetch_depth,
        "adaptive_buckets": pipeline.adaptive_buckets,
    }
    mesh = {"data": pipeline.mesh_shape[0], "model": pipeline.mesh_shape[1]}
    res = pipeline.residency
    residency = {
        "resident_edges": res.resident_edges,
        "reduced_outputs": list(res.reduced_outputs),
        "fetch": [{"sink": e.sink, "producer": e.producer,
                   "bytes_per_buffer": e.bytes_per_buffer,
                   "reduced": e.reduced} for e in res.fetch],
    }
    census = {
        "programs": registry.census(),
        "drift": registry.drifts(),
        "drift_total": registry.drift_count(),
    }
    tol = float(get_config().xray_hbm_tolerance)
    measured = measure_hbm(pipeline)
    recon = getattr(pipeline, "_xray_recon", None)
    if recon is not None:
        measured["activations"] = max(measured["activations"],
                                      recon._act_peak)
    predicted = predicted_hbm(pipeline)
    hbm: Dict[str, Any] = {"tolerance": tol, "categories": {}}
    for cat in HBM_CATEGORIES:
        m = measured.get(cat, 0)
        p = (predicted or {}).get(cat) if predicted else None
        hbm["categories"][cat] = {
            "predicted": p, "measured": m,
            "ratio": (m / p) if p else None,
            # over-use is the failure the budget exists to catch;
            # under-use (a transient window that never filled) is fine,
            # and byte-level noise below the reconciler's warn floor
            # never fails a gate (a 0-byte estimate vs a few live KiB)
            "ok": (p is None) or m <= max(p * tol, HBM_WARN_FLOOR),
        }
    if "device_bytes_in_use" in measured:
        hbm["device_bytes_in_use"] = measured["device_bytes_in_use"]
    slo = None
    if pipeline._slo_policy is not None:
        try:
            slo = pipeline.slo_report()
        except Exception:  # noqa: BLE001 - verdict is best-effort here
            pass
    ok = (census["drift_total"] == 0
          and all(c["ok"] for c in hbm["categories"].values()))
    return {
        "xray": pipeline.xray,
        "plan": plan, "mesh": mesh, "residency": residency,
        "census": census, "hbm": hbm,
        "device_time": registry.stage_stats(),
        "slo": slo, "ok": ok,
    }


def _mib(n) -> str:
    return "-" if n is None else f"{n / 2**20:.2f} MiB"


def render_report(rep: Dict[str, Any]) -> str:
    """Human rendering of :func:`explain` — the predicted-vs-actual
    columns the doctor CLI prints."""
    lines = [
        "pipeline doctor — predicted vs actual",
        f"  plan: {len(rep['plan']['stages'])} stage(s), "
        f"batch_max={rep['plan']['batch_max']}, "
        f"dispatch_depth={rep['plan']['dispatch_depth']}, "
        f"mesh (data={rep['mesh']['data']}, model={rep['mesh']['model']})",
        f"  residency: {rep['residency']['resident_edges']} device-"
        f"resident edge(s), {len(rep['residency']['fetch'])} fetch "
        "edge(s)",
        "  census (compiled programs, predicted vs live):",
    ]
    progs = rep["census"]["programs"]
    if not progs:
        lines.append("    (no tracked programs — xray off or nothing "
                     "compiled)")
    for key in sorted(progs):
        e = progs[key]
        pred = e["predicted"] if e["predicted"] else "unbounded"
        mark = "OK" if e["within"] else "DRIFT"
        lines.append(f"    {key}: predicted {pred}, live "
                     f"{e['live_compiles']} [{mark}]")
    for d in rep["census"]["drift"]:
        lines.append(f"    drift: {d['stage']}/{d['kind']}: {d['reason']}"
                     f" — {d['diff']}")
    lines.append(f"  hbm ledger (tolerance {rep['hbm']['tolerance']:g}x):")
    for cat, c in rep["hbm"]["categories"].items():
        ratio = "-" if c["ratio"] is None else f"{c['ratio']:.2f}x"
        mark = "OK" if c["ok"] else "DRIFT"
        lines.append(f"    {cat}: predicted {_mib(c['predicted'])}, "
                     f"measured {_mib(c['measured'])} ({ratio}) [{mark}]")
    if "device_bytes_in_use" in rep["hbm"]:
        lines.append(f"    device bytes_in_use: "
                     f"{_mib(rep['hbm']['device_bytes_in_use'])}")
    if rep["device_time"]:
        lines.append("  device time (measured dispatch attribution):")
        for stage in sorted(rep["device_time"]):
            st = rep["device_time"][stage]
            lines.append(
                f"    {stage}: {int(st['dispatches'])} dispatch(es), "
                f"{st['device_ns'] / 1e6:.1f} ms, mfu {st['mfu']:.4f}, "
                f"roofline {st['roofline_fraction']:.4f}")
    if rep["slo"] is not None:
        ok = rep["slo"].get("ok")
        lines.append(f"  slo: {'green' if ok else 'BREACHING'} "
                     f"(breaches: {rep['slo'].get('breaches')})")
    lines.append(f"  verdict: {'OK' if rep['ok'] else 'DRIFT'} "
                 f"(census drift {rep['census']['drift_total']})")
    return "\n".join(lines)


def verdict_lines(rep: Dict[str, Any]) -> List[str]:
    """The timing-insensitive verdict subset the CI gate pins against
    ``tools/xray_baseline.txt``: expectation keys + per-category HBM
    verdicts + the drift total — deterministic for a fixed pipeline,
    regardless of which bucket programs a given run's occupancies
    happened to compile."""
    lines = [f"census drift {rep['census']['drift_total']}"]
    for key in sorted(rep["census"]["programs"]):
        e = rep["census"]["programs"][key]
        if e["predicted"]:
            lines.append(
                f"{key}: {'within budget' if e['within'] else 'OVER'}")
    for cat in HBM_CATEGORIES:
        c = rep["hbm"]["categories"][cat]
        lines.append(f"hbm {cat}: {'ok' if c['ok'] else 'DRIFT'}")
    lines.append(f"doctor: {'OK' if rep['ok'] else 'DRIFT'}")
    return lines
