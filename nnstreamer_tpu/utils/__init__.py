"""nnstreamer_tpu.utils"""
