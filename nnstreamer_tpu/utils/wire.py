"""Tensor wire format: self-describing serialization of a Buffer.

Reference analog: the flatbuf/protobuf/flexbuf codecs
(``ext/nnstreamer/tensor_decoder/tensordec-flatbuf.cc`` etc., SURVEY
§2.5/2.6) that serialize ``other/tensors`` for IPC — and the framing
nnstreamer-edge puts on the wire (§2.7).  One codec serves all of:
``tensor_decoder mode=flexbuf``, ``tensor_converter mode=flexbuf``, the
tensor_query TCP protocol, and edge pub/sub.

Layout (little-endian):

    u32 magic "NNST" | u32 version | u32 flags | u32 num_tensors
    | i64 pts (-1 = none) | u64 seqno | u32 meta_len | meta (utf-8 JSON)
    per tensor:
      u32 rank | u32 dims[rank] (innermost-first) | u32 name_len
      | dtype_name utf-8 | u64 nbytes | raw bytes (C-order)

JSON meta keeps only JSON-representable entries; numpy scalars/arrays in
meta are converted (arrays to nested lists) — sufficient for detection/query
metadata.  Dropped (non-JSON) meta keys are counted (``wire.meta_dropped``)
and logged once per key at debug so journal/DLQ replays losing meta is
diagnosable, never silent.

Hardening (docs/ROBUSTNESS.md): every field the decoder reads is
attacker-controlled on the public front door.  :func:`decode_buffer` and
:func:`read_frame` therefore enforce strict, configurable
:class:`WireLimits` — max rank/dims/tensor bytes/meta bytes/tensor
count/frame bytes, a dtype-name whitelist, and declared-vs-actual length
cross-checks — and EVERY reject raises the typed :exc:`WireError`
(a ``ValueError`` subclass, so pre-armor ``except ValueError`` handlers
keep working).  A crafted header can no longer surface as a raw
``struct.error`` in a server read loop or trigger a multi-gigabyte
allocation: declared sizes are validated BEFORE any allocation, and
socket reads are chunked (``_RECV_CHUNK``) so ``recv`` never allocates
more than 1 MiB at a time.  CRC framing (``read_frame``/``write_frame``)
is mandatory on every framed transport.
"""

from __future__ import annotations

import dataclasses
import json
import os
import struct
from typing import List, Optional, Tuple

import numpy as np

from ..core.buffer import Buffer
from ..core.log import logger, metrics
from ..core.types import _DTYPE_NAMES, TensorSpec, TensorsSpec, \
    dtype_from_name, dtype_name

log = logger(__name__)

MAGIC = 0x4E4E5354  # "NNST"
VERSION = 1

_HDR_FMT = "<IIIIqQI"
_HDR_SIZE = struct.calcsize(_HDR_FMT)

#: max bytes a single ``recv`` may be asked for (bounds the transient
#: allocation a hostile length prefix can force inside ``_read_exact``)
_RECV_CHUNK = 1 << 20


class WireError(ValueError):
    """Typed reject of a wire frame/payload that violates the format or
    the configured :class:`WireLimits`.

    Subclasses ``ValueError`` so every pre-armor handler (the query
    client rx loop's ``except ValueError``) keeps catching it; new code
    should catch ``WireError`` and answer/count it per tenant instead of
    tearing the connection down (docs/ROBUSTNESS.md)."""


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


@dataclasses.dataclass(frozen=True)
class WireLimits:
    """Bounds enforced by :func:`decode_buffer` / :func:`read_frame`.

    Defaults are deliberately generous for trusted intra-host pipelines
    (a 256 MiB tensor is a 4K video batch, not a query request) and
    env-overridable for hardened front doors:
    ``NNS_TPU_WIRE_MAX_TENSOR_BYTES``, ``NNS_TPU_WIRE_MAX_META_BYTES``,
    ``NNS_TPU_WIRE_MAX_FRAME_BYTES``, ``NNS_TPU_WIRE_MAX_TENSORS``,
    ``NNS_TPU_WIRE_MAX_RANK``.  The dtype whitelist is the codec's own
    name table (core/types) — a wire frame can never name a dtype the
    pipeline would not itself emit."""

    max_tensors: int = 64
    max_rank: int = 16
    max_dim: int = 1 << 28
    max_tensor_bytes: int = 256 << 20
    max_meta_bytes: int = 1 << 20
    max_name_len: int = 64
    max_frame_bytes: int = 512 << 20
    dtype_names: frozenset = frozenset(_DTYPE_NAMES) | {"bool"}

    @classmethod
    def from_env(cls) -> "WireLimits":
        return cls(
            max_tensors=_env_int("NNS_TPU_WIRE_MAX_TENSORS", 64),
            max_rank=_env_int("NNS_TPU_WIRE_MAX_RANK", 16),
            max_tensor_bytes=_env_int(
                "NNS_TPU_WIRE_MAX_TENSOR_BYTES", 256 << 20),
            max_meta_bytes=_env_int(
                "NNS_TPU_WIRE_MAX_META_BYTES", 1 << 20),
            max_frame_bytes=_env_int(
                "NNS_TPU_WIRE_MAX_FRAME_BYTES", 512 << 20),
        )


#: process defaults (env-resolved once at import; tests construct their
#: own tighter WireLimits and pass them explicitly)
DEFAULT_LIMITS = WireLimits.from_env()


#: meta keys already debug-logged as dropped (bounded; once per key)
_warned_meta_keys: set = set()


def _meta_safe(meta: dict) -> dict:
    out = {}
    for k, v in meta.items():
        if isinstance(v, np.ndarray):
            out[k] = v.tolist()
        elif isinstance(v, (np.integer, np.floating)):
            out[k] = v.item()
        else:
            try:
                json.dumps(v)
                out[k] = v
            except (TypeError, ValueError):
                # Non-JSON meta cannot ride the wire (or a journal/DLQ
                # record) — count the drop and say so ONCE per key, so a
                # replay missing meta is diagnosable, never a mystery.
                metrics.count("wire.meta_dropped")
                if k not in _warned_meta_keys:
                    if len(_warned_meta_keys) > 1024:
                        _warned_meta_keys.clear()
                    _warned_meta_keys.add(k)
                    log.debug(
                        "wire: dropping non-JSON meta key %r (%s) from "
                        "encoded buffer; further drops of this key are "
                        "counted in wire.meta_dropped only",
                        k, type(v).__name__)
                continue
    return out


def encode_buffer(buf: Buffer, flags: int = 0) -> bytes:
    meta = json.dumps(_meta_safe(buf.meta)).encode("utf-8")
    parts = [
        struct.pack(
            _HDR_FMT,
            MAGIC,
            VERSION,
            flags,
            len(buf.tensors),
            buf.pts if buf.pts is not None else -1,
            buf.seqno,
            len(meta),
        ),
        meta,
    ]
    for t in buf.tensors:
        a = np.ascontiguousarray(np.asarray(t))
        spec = TensorSpec.of(a)
        name = dtype_name(a.dtype)
        if name.strip().lower() not in DEFAULT_LIMITS.dtype_names:
            # symmetric with the decode whitelist: fail LOUDLY at
            # encode instead of producing bytes (a DLQ record, a
            # journal entry) the decoder can never read back
            raise WireError(
                f"dtype {name!r} is not wire-serializable "
                f"(whitelist: {sorted(DEFAULT_LIMITS.dtype_names)})")
        dname = name.encode()
        parts.append(
            struct.pack(f"<I{a.ndim}II", a.ndim, *[int(d) for d in spec.dims], len(dname))
        )
        parts.append(dname)
        raw = a.tobytes()
        parts.append(struct.pack("<Q", len(raw)))
        parts.append(raw)
    return b"".join(parts)


def _unpack(fmt: str, raw: bytes, off: int, what: str):
    """``struct.unpack_from`` with truncation surfaced as a typed
    :exc:`WireError` instead of an uncaught ``struct.error``."""
    try:
        return struct.unpack_from(fmt, raw, off)
    except struct.error as e:
        raise WireError(f"truncated wire payload ({what}): {e}") from None


def decode_buffer(raw: bytes,
                  limits: WireLimits = None) -> Tuple[Buffer, int]:
    """Decode one buffer; returns (buffer, flags).

    Every malformed/oversized field raises :exc:`WireError` — declared
    sizes are bounds-checked against ``limits`` (default
    :data:`DEFAULT_LIMITS`) and cross-checked against the actual payload
    BEFORE any array is materialized, so a hostile header cannot crash
    the caller with ``struct.error`` or force a giant allocation."""
    lim = limits or DEFAULT_LIMITS
    magic, version, flags, n, pts, seqno, meta_len = _unpack(
        _HDR_FMT, raw, 0, "header")
    if magic != MAGIC:
        raise WireError("bad wire magic")
    if version != VERSION:
        raise WireError(f"unsupported wire version {version}")
    if n > lim.max_tensors:
        raise WireError(
            f"tensor count {n} exceeds limit {lim.max_tensors}")
    if meta_len > lim.max_meta_bytes:
        raise WireError(
            f"meta length {meta_len} exceeds limit {lim.max_meta_bytes}")
    off = _HDR_SIZE
    if off + meta_len > len(raw):
        raise WireError(
            f"declared meta length {meta_len} overruns payload "
            f"({len(raw) - off} bytes left)")
    if meta_len:
        try:
            meta = json.loads(raw[off:off + meta_len].decode("utf-8"))
        except (UnicodeDecodeError, ValueError) as e:
            raise WireError(f"bad wire meta json: {e}") from None
        if not isinstance(meta, dict):
            raise WireError(
                f"wire meta must be a JSON object, got "
                f"{type(meta).__name__}")
    else:
        meta = {}
    off += meta_len
    tensors: List[np.ndarray] = []
    for ti in range(n):
        (rank,) = _unpack("<I", raw, off, f"tensor {ti} rank")
        off += 4
        if rank > lim.max_rank:
            raise WireError(
                f"tensor {ti} rank {rank} exceeds limit {lim.max_rank}")
        dims = _unpack(f"<{rank}I", raw, off, f"tensor {ti} dims")
        off += 4 * rank
        (name_len,) = _unpack("<I", raw, off, f"tensor {ti} name_len")
        off += 4
        if name_len > lim.max_name_len:
            raise WireError(
                f"tensor {ti} dtype name length {name_len} exceeds "
                f"limit {lim.max_name_len}")
        if off + name_len > len(raw):
            raise WireError(f"tensor {ti} dtype name overruns payload")
        try:
            name = raw[off:off + name_len].decode("utf-8")
        except UnicodeDecodeError:
            raise WireError(
                f"tensor {ti} dtype name is not utf-8") from None
        key = name.strip().lower()
        if key not in lim.dtype_names:
            # whitelist BEFORE dtype_from_name's permissive numpy
            # fallback: the wire may only name dtypes the codec emits
            raise WireError(
                f"tensor {ti} dtype {name!r} not in the wire whitelist")
        dtype = dtype_from_name(key)
        off += name_len
        (nbytes,) = _unpack("<Q", raw, off, f"tensor {ti} nbytes")
        off += 8
        if nbytes > lim.max_tensor_bytes:
            raise WireError(
                f"tensor {ti} declares {nbytes} bytes, limit "
                f"{lim.max_tensor_bytes}")
        expect = int(dtype.itemsize)
        for d in dims:
            if d > lim.max_dim:
                raise WireError(
                    f"tensor {ti} dim {d} exceeds limit {lim.max_dim}")
            expect *= int(d)
        if expect != nbytes:
            # the declared-vs-derived cross-check: dims x itemsize IS
            # the byte count; any mismatch is a forged header
            raise WireError(
                f"tensor {ti} declares {nbytes} bytes but dims "
                f"{tuple(int(d) for d in dims)} x {dtype} = {expect}")
        if off + nbytes > len(raw):
            raise WireError(
                f"tensor {ti} payload ({nbytes} bytes) overruns frame "
                f"({len(raw) - off} bytes left)")
        shape = tuple(reversed(dims))
        arr = np.frombuffer(raw, dtype, count=nbytes // dtype.itemsize, offset=off)
        tensors.append(arr.reshape(shape))
        off += nbytes
    if off != len(raw):
        raise WireError(
            f"{len(raw) - off} trailing bytes after the last declared "
            "tensor")
    buf = Buffer(tensors, pts=None if pts < 0 else pts, meta=meta)
    buf.seqno = seqno
    return buf, flags


def salvage_meta(raw: bytes,
                 limits: WireLimits = None) -> Optional[dict]:
    """Best-effort recovery of just the header meta of a payload
    :func:`decode_buffer` rejected — so a server can answer a malformed
    request's ``_query_msg`` with a TYPED reject instead of leaving the
    client to wait out its timeout.  Returns the meta dict when the
    header + meta section parse within limits, else None.  Never
    raises (it runs inside reject handlers)."""
    lim = limits or DEFAULT_LIMITS
    try:
        magic, version, _flags, _n, _pts, _seq, meta_len = \
            struct.unpack_from(_HDR_FMT, raw, 0)
        if magic != MAGIC or version != VERSION \
                or meta_len > lim.max_meta_bytes \
                or _HDR_SIZE + meta_len > len(raw):
            return None
        if not meta_len:
            return {}
        meta = json.loads(
            raw[_HDR_SIZE:_HDR_SIZE + meta_len].decode("utf-8"))
        return meta if isinstance(meta, dict) else None
    except Exception:  # noqa: BLE001 - salvage is best-effort by contract
        return None


def read_frame(sock, limits: WireLimits = None) -> Optional[bytes]:
    """Read one crc-protected, length-prefixed frame from a socket-like
    object (``u64 len | payload | u32 crc32``).

    With a socket timeout set, ``socket.timeout`` propagates ONLY while the
    stream is idle (no header byte read yet) — callers use that to poll
    their stop flags.  Once a frame has started, timeouts are swallowed and
    the read continues: dropping partially-read bytes would desync the
    length-prefixed stream for good.

    A declared length above ``limits.max_frame_bytes`` and a CRC mismatch
    both raise :exc:`WireError` — framing-level violations, after which
    the stream cannot be trusted to resync (callers drop the
    connection); per-frame payload problems surface later, from
    :func:`decode_buffer`, and are recoverable per frame."""
    from ..native import wire_check

    lim = limits or DEFAULT_LIMITS
    hdr = _read_exact(sock, 8, idle_timeout=True)
    if hdr is None:
        return None
    (length,) = struct.unpack("<Q", hdr)
    if length > lim.max_frame_bytes:
        # reject BEFORE reading (or allocating for) the body: a forged
        # u64 length is the cheapest memory bomb there is
        raise WireError(
            f"frame declares {length} bytes, limit {lim.max_frame_bytes}")
    payload = _read_exact(sock, length)
    if payload is None:
        return None
    tail = _read_exact(sock, 4)
    if tail is None:
        return None
    (crc,) = struct.unpack("<I", tail)
    if not wire_check(payload, crc):
        raise WireError("wire frame crc mismatch (corrupt stream)")
    return payload


def write_frame(sock, payload: bytes) -> None:
    """Send one frame with length prefix + trailing crc32 (native-assembled
    single-copy gather when the C++ library is available)."""
    from ..native import wire_gather

    sock.sendall(wire_gather([payload]))


def frame_bytes(payload: bytes) -> bytes:
    """The exact bytes :func:`write_frame` would put on a socket
    (``u64 len | payload | u32 crc32``) — the file framing flight-recorder
    ring dumps use (utils/tracing.dump_ring)."""
    from ..native import wire_gather

    return wire_gather([payload])


def unframe_bytes(raw: bytes, limits: WireLimits = None) -> bytes:
    """Validate and strip the :func:`frame_bytes` framing from an
    in-memory frame (a ring-dump file read whole).  Exactly one frame
    must span the input; length and crc violations raise
    :exc:`WireError` like the socket reader's."""
    from ..native import wire_check

    lim = limits or DEFAULT_LIMITS
    if len(raw) < 12:
        raise WireError(f"framed blob too short ({len(raw)} bytes)")
    (length,) = struct.unpack_from("<Q", raw, 0)
    if length > lim.max_frame_bytes:
        raise WireError(
            f"frame declares {length} bytes, limit {lim.max_frame_bytes}")
    if len(raw) != 8 + length + 4:
        raise WireError(
            f"framed blob is {len(raw)} bytes, expected "
            f"{8 + length + 4} for the declared payload")
    payload = raw[8:8 + length]
    (crc,) = struct.unpack_from("<I", raw, 8 + length)
    if not wire_check(payload, crc):
        raise WireError("wire frame crc mismatch (corrupt dump)")
    return payload


def _read_exact(sock, n: int, idle_timeout: bool = False) -> Optional[bytes]:
    import socket as _socket

    chunks = []
    got = 0
    while got < n:
        try:
            # chunked: recv(k) may allocate k bytes up front, so a huge
            # remaining count must never reach it in one call
            chunk = sock.recv(min(n - got, _RECV_CHUNK))
        except _socket.timeout:
            if idle_timeout and got == 0:
                raise
            continue  # mid-frame stall: keep the partial bytes, keep reading
        if not chunk:
            return None
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)
