"""Tensor wire format: self-describing serialization of a Buffer.

Reference analog: the flatbuf/protobuf/flexbuf codecs
(``ext/nnstreamer/tensor_decoder/tensordec-flatbuf.cc`` etc., SURVEY
§2.5/2.6) that serialize ``other/tensors`` for IPC — and the framing
nnstreamer-edge puts on the wire (§2.7).  One codec serves all of:
``tensor_decoder mode=flexbuf``, ``tensor_converter mode=flexbuf``, the
tensor_query TCP protocol, and edge pub/sub.

Layout (little-endian):

    u32 magic "NNST" | u32 version | u32 flags | u32 num_tensors
    | i64 pts (-1 = none) | u64 seqno | u32 meta_len | meta (utf-8 JSON)
    per tensor:
      u32 rank | u32 dims[rank] (innermost-first) | u32 name_len
      | dtype_name utf-8 | u64 nbytes | raw bytes (C-order)

JSON meta keeps only JSON-representable entries; numpy scalars/arrays in
meta are converted (arrays to nested lists) — sufficient for detection/query
metadata.
"""

from __future__ import annotations

import json
import struct
from typing import List, Optional, Tuple

import numpy as np

from ..core.buffer import Buffer
from ..core.types import TensorSpec, TensorsSpec, dtype_from_name, dtype_name

MAGIC = 0x4E4E5354  # "NNST"
VERSION = 1


def _meta_safe(meta: dict) -> dict:
    out = {}
    for k, v in meta.items():
        if isinstance(v, np.ndarray):
            out[k] = v.tolist()
        elif isinstance(v, (np.integer, np.floating)):
            out[k] = v.item()
        else:
            try:
                json.dumps(v)
                out[k] = v
            except (TypeError, ValueError):
                continue
    return out


def encode_buffer(buf: Buffer, flags: int = 0) -> bytes:
    meta = json.dumps(_meta_safe(buf.meta)).encode("utf-8")
    parts = [
        struct.pack(
            "<IIIIqQI",
            MAGIC,
            VERSION,
            flags,
            len(buf.tensors),
            buf.pts if buf.pts is not None else -1,
            buf.seqno,
            len(meta),
        ),
        meta,
    ]
    for t in buf.tensors:
        a = np.ascontiguousarray(np.asarray(t))
        spec = TensorSpec.of(a)
        dname = dtype_name(a.dtype).encode()
        parts.append(
            struct.pack(f"<I{a.ndim}II", a.ndim, *[int(d) for d in spec.dims], len(dname))
        )
        parts.append(dname)
        raw = a.tobytes()
        parts.append(struct.pack("<Q", len(raw)))
        parts.append(raw)
    return b"".join(parts)


def decode_buffer(raw: bytes) -> Tuple[Buffer, int]:
    """Decode one buffer; returns (buffer, flags)."""
    magic, version, flags, n, pts, seqno, meta_len = struct.unpack_from("<IIIIqQI", raw, 0)
    if magic != MAGIC:
        raise ValueError("bad wire magic")
    if version != VERSION:
        raise ValueError(f"unsupported wire version {version}")
    off = struct.calcsize("<IIIIqQI")
    meta = json.loads(raw[off : off + meta_len].decode("utf-8")) if meta_len else {}
    off += meta_len
    tensors: List[np.ndarray] = []
    for _ in range(n):
        (rank,) = struct.unpack_from("<I", raw, off)
        off += 4
        dims = struct.unpack_from(f"<{rank}I", raw, off)
        off += 4 * rank
        (name_len,) = struct.unpack_from("<I", raw, off)
        off += 4
        dtype = dtype_from_name(raw[off : off + name_len].decode())
        off += name_len
        (nbytes,) = struct.unpack_from("<Q", raw, off)
        off += 8
        shape = tuple(reversed(dims))
        arr = np.frombuffer(raw, dtype, count=nbytes // dtype.itemsize, offset=off)
        tensors.append(arr.reshape(shape))
        off += nbytes
    buf = Buffer(tensors, pts=None if pts < 0 else pts, meta=meta)
    buf.seqno = seqno
    return buf, flags


def read_frame(sock) -> Optional[bytes]:
    """Read one crc-protected, length-prefixed frame from a socket-like
    object (``u64 len | payload | u32 crc32``).

    With a socket timeout set, ``socket.timeout`` propagates ONLY while the
    stream is idle (no header byte read yet) — callers use that to poll
    their stop flags.  Once a frame has started, timeouts are swallowed and
    the read continues: dropping partially-read bytes would desync the
    length-prefixed stream for good.
    """
    from ..native import wire_check

    hdr = _read_exact(sock, 8, idle_timeout=True)
    if hdr is None:
        return None
    (length,) = struct.unpack("<Q", hdr)
    payload = _read_exact(sock, length)
    if payload is None:
        return None
    tail = _read_exact(sock, 4)
    if tail is None:
        return None
    (crc,) = struct.unpack("<I", tail)
    if not wire_check(payload, crc):
        raise ValueError("wire frame crc mismatch (corrupt stream)")
    return payload


def write_frame(sock, payload: bytes) -> None:
    """Send one frame with length prefix + trailing crc32 (native-assembled
    single-copy gather when the C++ library is available)."""
    from ..native import wire_gather

    sock.sendall(wire_gather([payload]))


def _read_exact(sock, n: int, idle_timeout: bool = False) -> Optional[bytes]:
    import socket as _socket

    chunks = []
    got = 0
    while got < n:
        try:
            chunk = sock.recv(n - got)
        except _socket.timeout:
            if idle_timeout and got == 0:
                raise
            continue  # mid-frame stall: keep the partial bytes, keep reading
        if not chunk:
            return None
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)
