"""nns-tsan dynamic side: opt-in tracked lock primitives (ISSUE 17).

The threaded runtime's lock discipline is checked twice, from two
directions that meet in the middle:

* **statically** — :mod:`nnstreamer_tpu.analysis.concurrency` reads the
  package source and verifies the ``_GUARDED_BY`` contract, the nested
  ``with`` lock-order graph, and thread join lifecycles (``lint
  --threads``);
* **dynamically** — this module's :class:`TrackedLock` /
  :class:`TrackedRLock` / :class:`TrackedCondition` record every
  *actual* per-thread acquisition into a process-wide order graph
  (:data:`graph`) and detect, live: lock-order inversions (an A→B edge
  observed after B→A), same-thread re-entry of a non-reentrant lock
  (certain self-deadlock — reported *before* blocking forever), and
  guarded-field access without the declared lock
  (:func:`assert_guarded`).

Opt-in and zero-overhead off.  The hot lock owners construct their
primitives through :func:`make_lock` / :func:`make_rlock` /
:func:`make_condition`; with ``NNS_TPU_TSAN`` unset those factories
return **plain** ``threading`` primitives, so the off path is the
untracked code path — there is no "tracking that discards", exactly the
trace-off structural pin (tools/tracing_gate.py).  CI pins this by
monkeypatching :meth:`LockOrderGraph.acquired` to raise and running the
suite with the env unset.  With ``NNS_TPU_TSAN=1`` a detected inversion
always counts ``tsan.inversions``, fires a ``tsan.inversion`` span and a
flight-ring dump; it additionally **raises** :class:`LockOrderError`
when ``NNS_TPU_TSAN_RAISE=1`` (tests) — soak chaos runs record-only and
assert zero after the fact via :func:`report`.

Lock *names* are class-level identities (``"StageQueue._lock"``): the
order graph deliberately keys edges by name, not instance, so an
inversion between any two instances of the same two lock classes is the
same finding the static pass would report.  Same-name edges are ignored
(two _StageQueue instances nest by pipeline topology, a hierarchy the
name key cannot order), except same-*instance* re-entry, which is a
hard error for non-reentrant locks.
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Dict, List, Optional, Tuple

ENV_FLAG = "NNS_TPU_TSAN"
ENV_RAISE = "NNS_TPU_TSAN_RAISE"

#: flips True the first time a factory vends a tracked primitive; the
#: cheap early-out for assert_guarded() call sites in untracked runs
_active = False


def enabled() -> bool:
    """True when ``NNS_TPU_TSAN=1`` — read at *factory call* time, so a
    test can flip the env and construct a fresh tracked owner without
    re-importing anything."""
    return os.environ.get(ENV_FLAG, "") == "1"


class LockOrderError(RuntimeError):
    """A live lock-order inversion or non-reentrant self-deadlock."""


class GuardViolation(RuntimeError):
    """A guarded field touched without its declared lock held."""


def _site() -> str:
    """``file:line`` of the acquiring *user* frame: nearest caller that
    is neither this module nor threading.py (Condition wait()/notify()
    route re-acquires through stdlib frames).  Cheap enough in tsan
    mode; never runs when tracking is off."""
    try:
        skip = (__file__, threading.__file__)
        f = sys._getframe(2)
        for _ in range(12):
            if f is None:
                break
            if f.f_code.co_filename not in skip:
                return (f"{os.path.basename(f.f_code.co_filename)}"
                        f":{f.f_lineno}")
            f = f.f_back
    except Exception:  # noqa: BLE001 - bookkeeping must never break locks
        pass
    return "?"


class LockOrderGraph:
    """Process-wide acquisition-order graph + per-thread held stacks.

    Edges are ``(outer name, inner name) -> first site`` observed; a new
    edge whose reverse path already exists is an inversion.  All graph
    state is guarded by its own private mutex (``_mu``), which is always
    innermost and therefore can never participate in an inversion."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        #: (outer, inner) -> "file:line (thread)" of first observation
        self._edges: Dict[Tuple[str, str], str] = {}
        self._tls = threading.local()
        self._inversions: List[dict] = []
        self._guard_violations: List[dict] = []
        self._seen: set = set()  # dedup key per reported cycle
        #: total first-entry acquisitions — the "tsan actually engaged"
        #: liveness signal (edges stay 0 when no two tracked locks nest)
        self._acquisitions = 0

    # -- per-thread stack --------------------------------------------------
    def _stack(self) -> List[list]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st  # entries: [name, lock_obj, count]

    def held_names(self) -> List[str]:
        return [e[0] for e in self._stack()]

    def holds(self, lock: object) -> bool:
        return any(e[1] is lock for e in self._stack())

    # -- acquisition hooks -------------------------------------------------
    def before_acquire(self, name: str, lock: object, reentrant: bool,
                       blocking: bool) -> None:
        """Called BEFORE blocking: same-instance re-entry of a plain
        Lock would deadlock this thread forever, so it must be caught
        while we can still raise.  Non-blocking probes are exempt —
        Condition's ``_is_owned`` fallback deliberately try-acquires
        the lock its owner already holds."""
        if blocking and not reentrant and self.holds(lock):
            raise LockOrderError(
                f"self-deadlock: thread {threading.current_thread().name!r}"
                f" re-acquiring non-reentrant lock {name!r} it already"
                f" holds (at {_site()})")

    def acquired(self, name: str, lock: object) -> None:
        st = self._stack()
        for e in st:
            if e[1] is lock:  # reentrant re-acquire: count, no new edges
                e[2] += 1
                return
        site = (f"{_site()} "
                f"(thread {threading.current_thread().name!r})")
        new_edges = [(e[0], name) for e in st if e[0] != name]
        st.append([name, lock, 1])
        if not new_edges:
            with self._mu:
                self._acquisitions += 1
            return
        with self._mu:
            self._acquisitions += 1
            for a, b in new_edges:
                self._edges.setdefault((a, b), site)
            cycles = [self._find_cycle(a, b) for a, b in new_edges]
        for (a, b), cyc in zip(new_edges, cycles):
            if cyc:
                self._report_inversion(a, b, site, cyc)

    def released(self, name: str, lock: object) -> None:
        st = self._stack()
        for i in range(len(st) - 1, -1, -1):
            if st[i][1] is lock:
                st[i][2] -= 1
                if st[i][2] <= 0:
                    del st[i]
                return

    # -- cycle detection (caller holds _mu) --------------------------------
    def _find_cycle(self, a: str, b: str) -> Optional[List[str]]:
        """Path b →* a in the edge set means edge (a, b) closed a cycle;
        returns the node chain ``[b, ..., a]`` or None."""
        stack, parent = [b], {b: None}
        while stack:
            cur = stack.pop()
            if cur == a:
                chain = [cur]
                while parent[chain[-1]] is not None:
                    chain.append(parent[chain[-1]])
                return chain[::-1]
            for (x, y) in self._edges:
                if x == cur and y not in parent:
                    parent[y] = cur
                    stack.append(y)
        return None

    # -- reporting ---------------------------------------------------------
    def _report_inversion(self, a: str, b: str, site: str,
                          chain: List[str]) -> None:
        key = frozenset(chain) | {a}
        with self._mu:
            if key in self._seen:
                return
            self._seen.add(key)
            back = " -> ".join(chain + [b])
            back_site = self._edges.get((chain[0], chain[1]), "?") \
                if len(chain) > 1 else self._edges.get((b, a), "?")
            rec = {"edge": f"{a} -> {b}", "at": site,
                   "reverse": back, "reverse_at": back_site}
            self._inversions.append(rec)
        msg = (f"lock-order inversion: {a} -> {b} at {site}, but the"
               f" reverse path {back} was first taken at {back_site}")
        self._emit("tsan.inversion", "tsan.inversions", msg)
        if os.environ.get(ENV_RAISE, "") == "1":
            raise LockOrderError(msg)

    def report_guard(self, owner: str, attr: str, lock_name: str) -> None:
        msg = (f"guarded field {owner}.{attr} accessed without"
               f" {lock_name} held (at {_site()}, thread"
               f" {threading.current_thread().name!r})")
        with self._mu:
            self._guard_violations.append({"field": f"{owner}.{attr}",
                                           "lock": lock_name,
                                           "at": _site()})
        self._emit("tsan.inversion", "tsan.guard_violations", msg)
        if os.environ.get(ENV_RAISE, "") == "1":
            raise GuardViolation(msg)

    def _emit(self, span_kind: str, metric: str, msg: str) -> None:
        """Cold path: metric + span + ring dump.  Imports are lazy so
        this module stays stdlib-only at import time (core.log imports
        us for Metrics' own lock)."""
        try:
            from ..core.log import logger, metrics
            metrics.count(metric)
            logger(__name__).error(msg)
        except Exception:  # noqa: BLE001
            pass
        try:
            import time

            from ..core.log import logger
            from . import tracing
            if tracing.recorder.active:
                tracing.recorder.record(span_kind, "tsan", None,
                                        time.time_ns(), 0,
                                        reason=msg[:400])
                tracing.dump_recent_to_log(
                    logger(__name__), reason="tsan inversion")
        except Exception:  # noqa: BLE001
            pass

    # -- introspection -----------------------------------------------------
    def snapshot(self) -> dict:
        with self._mu:
            return {
                "edges": len(self._edges),
                "acquisitions": self._acquisitions,
                "inversions": list(self._inversions),
                "guard_violations": list(self._guard_violations),
            }

    def reset(self) -> None:
        with self._mu:
            self._edges.clear()
            self._inversions.clear()
            self._guard_violations.clear()
            self._seen.clear()
            self._acquisitions = 0


#: the process-wide order graph (one per process, like core.log.metrics)
graph = LockOrderGraph()


class TrackedLock:
    """``threading.Lock`` with acquisition-order bookkeeping.  Exposes
    acquire/release/__enter__/__exit__/locked, which is exactly the
    surface ``threading.Condition`` needs — a Condition built over a
    TrackedLock routes its wait()-time release/re-acquire through the
    wrapper, so the held stack stays truthful across waits."""

    __slots__ = ("_raw", "name")
    _reentrant = False

    def __init__(self, name: str = "lock") -> None:
        self._raw = threading.Lock()
        self.name = name

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        graph.before_acquire(self.name, self, self._reentrant, blocking)
        got = self._raw.acquire(blocking, timeout)
        if got:
            try:
                graph.acquired(self.name, self)
            except BaseException:
                # raise-mode inversion: leave no half-held state behind
                graph.released(self.name, self)
                self._raw.release()
                raise
        return got

    def release(self) -> None:
        graph.released(self.name, self)
        self._raw.release()

    def __enter__(self) -> "TrackedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._raw.locked()

    def held_by_me(self) -> bool:
        return graph.holds(self)

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"<TrackedLock {self.name} locked={self.locked()}>"


class TrackedRLock(TrackedLock):
    """``threading.RLock`` twin: re-entry by the owner is legal and
    counted, only the first acquisition records order edges."""

    __slots__ = ()
    _reentrant = True

    def __init__(self, name: str = "rlock") -> None:
        super().__init__(name)
        self._raw = threading.RLock()

    def locked(self) -> bool:  # RLock has no .locked() before 3.12
        if self._raw.acquire(blocking=False):
            self._raw.release()
            return False
        return True


class TrackedCondition:
    """``threading.Condition`` over a (shared) :class:`TrackedLock`.

    CPython's Condition detects that the wrapper is not one of its
    known lock types and falls back to plain ``release()`` /
    ``acquire()`` for the wait()-time handoff — both of which are the
    wrapper's tracked methods, so a thread blocked in ``wait()``
    correctly shows as NOT holding the lock."""

    def __init__(self, lock=None, name: str = "cond") -> None:
        if lock is None:
            lock = TrackedLock(f"{name}.lock")
        self.name = name
        self._lock = lock
        self._cond = threading.Condition(lock)

    def __enter__(self):
        return self._cond.__enter__()

    def __exit__(self, *exc):
        return self._cond.__exit__(*exc)

    def acquire(self, *a, **k):
        return self._lock.acquire(*a, **k)

    def release(self):
        return self._lock.release()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._cond.wait(timeout)

    def wait_for(self, predicate, timeout: Optional[float] = None):
        return self._cond.wait_for(predicate, timeout)

    def notify(self, n: int = 1) -> None:
        self._cond.notify(n)

    def notify_all(self) -> None:
        self._cond.notify_all()


# -- factories (the adoption surface) --------------------------------------

def make_lock(name: str):
    """A mutex: :class:`TrackedLock` under ``NNS_TPU_TSAN=1``, else a
    plain ``threading.Lock`` (the structurally-untracked off path)."""
    global _active
    if enabled():
        _active = True
        return TrackedLock(name)
    return threading.Lock()


def make_rlock(name: str):
    global _active
    if enabled():
        _active = True
        return TrackedRLock(name)
    return threading.RLock()


def make_condition(lock=None, name: str = "cond"):
    """A condition variable over ``lock`` (which may be shared by
    several conditions, the _StageQueue shape).  Tracked iff the lock
    is tracked — callers build the lock with :func:`make_lock`, so one
    env read decides the whole owner."""
    global _active
    if isinstance(lock, (TrackedLock, TrackedRLock)) or \
            (lock is None and enabled()):
        _active = True
        return TrackedCondition(lock, name)
    return threading.Condition(lock)


def assert_guarded(obj, attr: str) -> None:
    """Live twin of the static ``unguarded-write`` check: verify the
    calling thread holds the lock that ``type(obj)._GUARDED_BY``
    declares for ``attr``.  No-op unless a tracked primitive exists in
    the process (i.e. free in untracked runs), and only enforceable
    when the owner's lock came from :func:`make_lock`."""
    if not _active:
        return
    gb = getattr(type(obj), "_GUARDED_BY", None)
    if not gb or attr not in gb:
        return
    lock = getattr(obj, gb[attr], None)
    if isinstance(lock, TrackedLock) and not graph.holds(lock):
        graph.report_guard(type(obj).__name__, attr, gb[attr])


def report() -> dict:
    """Process-wide tsan summary (the soak row surface)."""
    snap = graph.snapshot()
    snap["enabled"] = enabled()
    return snap


def reset() -> None:
    graph.reset()
