"""Profiling hooks: XLA device traces + pipeline metrics export.

Reference analog (SURVEY §5.1): the reference's per-filter latency
properties plus GStreamer tracers / gst-shark for deeper dives.  TPU
equivalents:

* :func:`trace` — context manager around ``jax.profiler`` producing an
  xplane trace viewable in TensorBoard/XProf (device timelines, HBM);
* :func:`metrics_text` — the process metrics in Prometheus text format
  (frames in/out, queue depths via gauges, per-stage latency quantiles,
  and the adaptive micro-batching series: ``<stage>.batch_occupancy``
  distributions and ``<stage>.batch_pad_waste`` counters — docs/BATCHING.md);
* :func:`start_metrics_server` — a ``/metrics`` HTTP endpoint (SURVEY
  §5.5 "a /metrics-style counter set").
"""

from __future__ import annotations

import contextlib
import http.server
import re
import threading
from typing import Optional

from ..core.log import logger, metrics

log = logger(__name__)


@contextlib.contextmanager
def trace(logdir: str):
    """Capture a device trace for the enclosed block (no-op if the jax
    profiler is unavailable on this backend)."""
    import jax

    try:
        jax.profiler.start_trace(logdir)
        started = True
    except (RuntimeError, NotImplementedError) as e:  # pragma: no cover
        log.warning("jax profiler unavailable: %s", e)
        started = False
    try:
        yield
    finally:
        if started:
            jax.profiler.stop_trace()


_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    return _NAME_RE.sub("_", name)


def metrics_text() -> str:
    """Render the global metrics registry in Prometheus text format."""
    lines = []
    for name, value in sorted(metrics.snapshot().items()):
        lines.append(f"nnstpu_{_prom_name(name)} {value:.9g}")
    return "\n".join(lines) + "\n"


class _MetricsHandler(http.server.BaseHTTPRequestHandler):
    def do_GET(self):  # noqa: N802 - http.server API
        if self.path.rstrip("/") not in ("", "/metrics"):
            self.send_response(404)
            self.end_headers()
            return
        body = metrics_text().encode()
        self.send_response(200)
        self.send_header("Content-Type", "text/plain; version=0.0.4")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):  # silence per-request stderr noise
        pass


def start_metrics_server(port: int = 0, host: str = "127.0.0.1"):
    """Serve ``/metrics`` on a daemon thread; returns the HTTPServer (its
    ``server_port`` reports the bound port; call ``shutdown()`` to stop)."""
    srv = http.server.ThreadingHTTPServer((host, port), _MetricsHandler)
    threading.Thread(target=srv.serve_forever, daemon=True,
                     name=f"metrics:{srv.server_port}").start()
    return srv
