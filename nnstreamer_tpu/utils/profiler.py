"""Profiling hooks: XLA device traces + pipeline metrics export.

Reference analog (SURVEY §5.1): the reference's per-filter latency
properties plus GStreamer tracers / gst-shark for deeper dives.  TPU
equivalents:

* :func:`trace` — context manager around ``jax.profiler`` producing an
  xplane trace viewable in TensorBoard/XProf (device timelines, HBM);
* :func:`metrics_text` — the process metrics in Prometheus text format
  (frames in/out, queue depths via gauges, per-stage latency quantiles,
  and the adaptive micro-batching series: ``<stage>.batch_occupancy``
  distributions and ``<stage>.batch_pad_waste`` counters — docs/BATCHING.md);
* :func:`start_metrics_server` — a ``/metrics`` HTTP endpoint (SURVEY
  §5.5 "a /metrics-style counter set").
"""

from __future__ import annotations

import contextlib
import http.server
import re
import threading
from typing import Optional

from ..core.log import logger, metrics

log = logger(__name__)


@contextlib.contextmanager
def trace(logdir: str):
    """Capture a device trace for the enclosed block (no-op if the jax
    profiler is unavailable on this backend)."""
    import jax

    try:
        jax.profiler.start_trace(logdir)
        started = True
    except (RuntimeError, NotImplementedError) as e:  # pragma: no cover
        log.warning("jax profiler unavailable: %s", e)
        started = False
    try:
        yield
    finally:
        if started:
            jax.profiler.stop_trace()


_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")


def _prom_name(name: str) -> str:
    return _NAME_RE.sub("_", name)


#: HELP/TYPE metadata for the batching/sharding series (docs/BATCHING.md)
#: so Prometheus scrapes are well-formed self-describing exposition, keyed
#: by the raw series suffix the runtime emits per stage.
_SERIES_META = {
    "batch_occupancy": ("buffers drained per micro-batch dispatch "
                        "(distribution)", "gauge"),
    "batch_pad_waste": ("pad rows appended to reach the bucket size",
                        "counter"),
    "shard_rows": ("rows placed on each mesh device by sharded dispatches",
                   "counter"),
    "shard_dispatch": ("sharded micro-batch dispatches", "counter"),
    "param_replications": ("one-time stage parameter replications onto "
                           "the mesh", "counter"),
}


def _series_meta(raw: str):
    """(help, type) when ``raw`` belongs to a documented batching/sharding
    series (including derived ``.p50``/``.mean`` quantile samples and
    per-device ``.dN`` placement counters), else None."""
    for key, (help_, typ) in _SERIES_META.items():
        if raw.endswith("." + key) or f".{key}." in raw or raw == key \
                or raw.startswith(key + "."):
            if raw.endswith((".p50", ".p99", ".mean", ".n")):
                return help_, "gauge"  # derived summary samples
            return help_, typ
    return None


def metrics_text() -> str:
    """Render the global metrics registry in Prometheus text format.

    Sanitized names that COLLIDE (``a.b:c`` and ``a.b/c`` both sanitize to
    ``a_b_c``) are disambiguated deterministically: every colliding raw
    name gets a short hash of itself appended, so no sample silently
    shadows another and the same registry always renders the same text.
    Batching/sharding series carry ``# HELP``/``# TYPE`` headers.
    """
    import hashlib

    snap = metrics.snapshot()
    by_prom: dict = {}
    for raw in snap:
        by_prom.setdefault(_prom_name(raw), []).append(raw)
    lines = []
    for prom in sorted(by_prom):
        raws = sorted(by_prom[prom])
        for raw in raws:
            name = prom if len(raws) == 1 else \
                f"{prom}_{hashlib.sha1(raw.encode()).hexdigest()[:6]}"
            meta = _series_meta(raw)
            if meta is not None:
                lines.append(f"# HELP nnstpu_{name} {meta[0]}")
                lines.append(f"# TYPE nnstpu_{name} {meta[1]}")
            lines.append(f"nnstpu_{name} {snap[raw]:.9g}")
    return "\n".join(lines) + "\n"


class _MetricsHandler(http.server.BaseHTTPRequestHandler):
    def do_GET(self):  # noqa: N802 - http.server API
        if self.path.rstrip("/") not in ("", "/metrics"):
            self.send_response(404)
            self.end_headers()
            return
        body = metrics_text().encode()
        self.send_response(200)
        self.send_header("Content-Type", "text/plain; version=0.0.4")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):  # silence per-request stderr noise
        pass


def start_metrics_server(port: int = 0, host: str = "127.0.0.1"):
    """Serve ``/metrics`` on a daemon thread; returns the HTTPServer (its
    ``server_port`` reports the bound port; call ``shutdown()`` to stop)."""
    srv = http.server.ThreadingHTTPServer((host, port), _MetricsHandler)
    threading.Thread(target=srv.serve_forever, daemon=True,
                     name=f"metrics:{srv.server_port}").start()
    return srv
