"""Profiling hooks: XLA device traces + pipeline metrics export.

Reference analog (SURVEY §5.1): the reference's per-filter latency
properties plus GStreamer tracers / gst-shark for deeper dives.  TPU
equivalents:

* :func:`trace` — context manager around ``jax.profiler`` producing an
  xplane trace viewable in TensorBoard/XProf (device timelines, HBM);
  the per-buffer flight recorder (``utils/tracing.py``, Chrome
  trace-event JSON for Perfetto) covers the pipeline layer —
  docs/OBSERVABILITY.md;
* :func:`metrics_text` — the process metrics in Prometheus text format:
  counters, sampler-fed gauges (queue depth, staleness watermark), REAL
  cumulative histograms with explicit buckets for every
  ``observe_latency`` series (stage latency, queue wait, end-to-end
  pipeline latency), and the batching/sharding series
  (``<stage>.batch_occupancy`` / ``<stage>.batch_pad_waste`` —
  docs/BATCHING.md);
* :func:`start_metrics_server` / :func:`stop_metrics_server` /
  :func:`metrics_server` — a ``/metrics`` HTTP endpoint with clean
  shutdown (SURVEY §5.5 "a /metrics-style counter set").
"""

from __future__ import annotations

import contextlib
import http.server
import re
import threading
from typing import Optional

from ..core.log import LATENCY_BUCKETS, logger, metrics

log = logger(__name__)


@contextlib.contextmanager
def trace(logdir: str):
    """Capture a device trace for the enclosed block (no-op if the jax
    profiler is unavailable on this backend)."""
    import jax

    try:
        jax.profiler.start_trace(logdir)
        started = True
    except (RuntimeError, NotImplementedError) as e:  # pragma: no cover
        log.warning("jax profiler unavailable: %s", e)
        started = False
    try:
        yield
    finally:
        if started:
            jax.profiler.stop_trace()


_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")


def _prom_name(name: str) -> str:
    return _NAME_RE.sub("_", name)


#: HELP/TYPE metadata keyed by the raw series suffix the runtime emits per
#: stage, so Prometheus scrapes are well-formed self-describing exposition
#: (docs/BATCHING.md, docs/OBSERVABILITY.md).
_SERIES_META = {
    "batch_occupancy": ("buffers drained per micro-batch dispatch "
                        "(distribution)", "gauge"),
    "batch_pad_waste": ("pad rows appended to reach the bucket size",
                        "counter"),
    "shard_rows": ("rows placed on each mesh device by sharded dispatches",
                   "counter"),
    "shard_dispatch": ("sharded micro-batch dispatches", "counter"),
    "param_replications": ("one-time stage parameter placements onto "
                           "the mesh", "counter"),
    "param_shards": ("param leaves SHARDED over the mesh's `model` axis "
                     "at placement (2-D placement, docs/BATCHING.md)",
                     "counter"),
    "param_replicas": ("param leaves replicated (no `model`-axis pspec) "
                       "at placement", "counter"),
    "queue_depth": ("stage input queue depth (sampler gauge)", "gauge"),
    "inflight_window": ("dispatched-but-unemitted micro-batches held in "
                        "the dispatch window (sampler gauge)", "gauge"),
    "staleness_s": ("seconds since this sink last delivered a buffer "
                    "(pipeline staleness watermark, sampler gauge)",
                    "gauge"),
    "watermark_pts": ("highest presentation timestamp delivered at this "
                      "sink (ns)", "gauge"),
    # front-door series (docs/SERVING.md "Front door")
    "shed": ("requests shed at query-server admission under backlog "
             "(per-tenant labels when the request carried a tenant)",
             "counter"),
    "downgraded": ("requests moved to the low-priority lane under backlog "
                   "(admission=downgrade)", "counter"),
    "sheds": ("shed notices received by this query client", "counter"),
    "backlog": ("query-server inbound backlog depth (gauge)", "gauge"),
    "burn_rate": ("SLO error-budget burn rate: 1.0 = consuming exactly "
                  "the budget (utils/slo.py)", "gauge"),
    "breach": ("SLO breach flag: 1 = tenant currently out of SLO",
               "gauge"),
    # nns-xray predicted-vs-actual series (utils/xray.py,
    # docs/OBSERVABILITY.md "Predicted vs actual")
    "compiles": ("XLA programs compiled by this stage's tracked jit "
                 "entry points (nns-xray program registry)", "counter"),
    "census_drifts": ("compiled programs that escaped the deep lint's "
                      "predicted census (counter; fired at register "
                      "time)", "counter"),
    "census_drift": ("census-drift total, republished every reconciler "
                     "tick (gauge twin of the xray.census_drifts "
                     "counter — distinct names so neither family ever "
                     "changes type between scrapes)", "gauge"),
    "mfu": ("model FLOPs utilization: tracked-program FLOPs per second "
            "of measured dispatch time over the device's peak "
            "(Config.peak_tflops / device-kind table)", "gauge"),
    "roofline_fraction": ("fraction of the compute/HBM roofline this "
                          "stage's dispatches achieve (ideal time from "
                          "cost analysis vs measured)", "gauge"),
    "pad_waste_flops": ("FLOPs spent computing bucket-ladder pad rows "
                        "(the adaptive ladder's pad waste priced in "
                        "FLOPs, not rows)", "counter"),
    "hbm": ("nns-xray HBM ledger: live measured bytes per category "
            "(params / kv_pool / agg_rings / activations)", "gauge"),
    "hbm_predicted": ("nns-xray HBM ledger: the deep-lint estimate per "
                      "category", "gauge"),
    "hbm_drift": ("nns-xray HBM ledger: measured / predicted ratio per "
                  "category (warns past Config.xray_hbm_tolerance)",
                  "gauge"),
}

#: HELP text for histogram series, by raw-name suffix (fallback generic)
_HIST_HELP = {
    "batch_occupancy": "buffers drained per micro-batch dispatch "
                       "(cumulative histogram; bucket bounds mirror the "
                       "static ladder — the adaptive ladder refines from "
                       "this same occupancy stream, docs/BATCHING.md)",
    "proc": "per-buffer stage process latency, seconds (histogram)",
    "invoke": "model invocation latency, seconds (histogram)",
    "push": "source push latency, seconds (histogram)",
    "queue_wait": "seconds a buffer waited in the stage input queue "
                  "(histogram; trace_mode != off)",
    "e2e_latency": "source-ingress-to-sink-delivery pipeline latency, "
                   "seconds (histogram; trace_mode != off)",
}


def _series_meta(raw: str):
    """(help, type) when ``raw`` belongs to a documented series (including
    derived ``.p50``/``.mean`` quantile samples and per-device ``.dN``
    placement counters), else None."""
    for key, (help_, typ) in _SERIES_META.items():
        if raw.endswith("." + key) or f".{key}." in raw or raw == key \
                or raw.startswith(key + "."):
            if raw.endswith((".p50", ".p99", ".mean", ".n")):
                return help_, "gauge"  # derived summary samples
            return help_, typ
    return None


def _hist_help(raw: str) -> str:
    for key, help_ in _HIST_HELP.items():
        if raw.endswith("." + key) or raw == key:
            return help_
    return "latency seconds (histogram)"


def _dedup_prom_names(raws) -> dict:
    """raw -> exposition name: sanitized, with colliding sanitizations
    (``a.b:c`` and ``a.b/c`` both -> ``a_b_c``) disambiguated by a short
    deterministic hash of the raw name — the SAME rule for every sample
    family, so no series silently shadows another and the same registry
    always renders the same text."""
    import hashlib

    by_prom: dict = {}
    for raw in raws:
        by_prom.setdefault(_prom_name(raw), []).append(raw)
    out = {}
    for prom, group in by_prom.items():
        for raw in group:
            out[raw] = prom if len(group) == 1 else \
                f"{prom}_{hashlib.sha1(raw.encode()).hexdigest()[:6]}"
    return out


def _tenant_label_values(raws) -> dict:
    """raw tenant value -> exposition label value.  Tenant label values go
    through the SAME sanitization + deterministic sha1 collision
    disambiguation as series names (``a:b`` and ``a/b`` must not merge
    into one ``a_b`` tenant), so the same registry always renders the
    same labels — scraping twice yields identical series."""
    return _dedup_prom_names(raws)


def _hist_series(lines: list, name: str, counts, total, n,
                 label: str = "", bounds=LATENCY_BUCKETS) -> None:
    """One histogram's sample lines; ``label`` is a pre-rendered
    ``tenant="x",`` prefix for labeled twins (empty for the base).
    ``bounds`` defaults to the latency family's; bucketed value series
    (occupancy) carry their own."""
    cum = 0
    for bound, c in zip(bounds, counts):
        cum += c
        lines.append(f'{name}_bucket{{{label}le="{bound:g}"}} {cum}')
    cum += counts[-1]
    lines.append(f'{name}_bucket{{{label}le="+Inf"}} {cum}')
    suffix = f"{{{label[:-1]}}}" if label else ""
    lines.append(f"{name}_sum{suffix} {total:.9g}")
    lines.append(f"{name}_count{suffix} {n}")


def _render_histograms(lines: list) -> None:
    """Cumulative ``_bucket``/``_sum``/``_count`` exposition for every
    observe_latency series (real Prometheus histograms — aggregatable
    across scrapes, unlike the point-in-time quantile gauges).  Labeled
    (per-tenant) twins render under the SAME family — one
    ``# HELP``/``# TYPE`` header, base sample first, then one sample set
    per tenant."""
    hists = metrics.histograms()
    vhists = metrics.value_histograms()
    labeled = metrics.labeled_histograms()
    by_name: dict = {}
    for (raw, ten), h in labeled.items():
        by_name.setdefault(raw, {})[ten] = h
    names = _dedup_prom_names(set(hists) | set(by_name) | set(vhists))
    tlabels = _tenant_label_values({t for (_, t) in labeled})
    for raw in sorted(names):
        name = f"nnstpu_{names[raw]}"
        lines.append(f"# HELP {name} {_hist_help(raw)}")
        lines.append(f"# TYPE {name} histogram")
        if raw in hists:
            counts, total, n = hists[raw]
            _hist_series(lines, name, counts, total, n)
        if raw in vhists:
            # bucketed value series (occupancy): own bounds, same
            # cumulative _bucket/_sum/_count exposition family
            bounds, counts, total, n = vhists[raw]
            _hist_series(lines, name, counts, total, n, bounds=bounds)
        for ten in sorted(by_name.get(raw, ()),
                          key=lambda t: tlabels[t]):
            counts, total, n = by_name[raw][ten]
            _hist_series(lines, name, counts, total, n,
                         label=f'tenant="{tlabels[ten]}",')


def metrics_text(openmetrics: bool = False) -> str:
    """Render the global metrics registry in Prometheus text format.

    Histograms first (``observe_latency`` series), then gauges, then
    counters + derived quantile samples.  Sanitized names that COLLIDE
    (``a.b:c`` and ``a.b/c`` both sanitize to ``a_b_c``) are
    disambiguated deterministically: every colliding raw name gets a
    short hash of itself appended, so no sample silently shadows another
    and the same registry always renders the same text (scraping twice
    yields identical series names).  Per-tenant labeled twins render
    under the same family as ``{tenant="..."}`` samples, with tenant
    label values passed through the SAME sanitize+hash rule.

    ``openmetrics=True`` appends the mandatory ``# EOF`` trailer — the
    OpenMetrics framing a negotiating scraper (``Accept:
    application/openmetrics-text``) uses to detect truncated bodies; the
    ``/metrics`` handler selects it via content negotiation.
    """
    lines: list = []
    _render_histograms(lines)
    gauges = metrics.gauges()
    lgauges = metrics.labeled_gauges()
    lg_by_name: dict = {}
    for (raw, ten), v in lgauges.items():
        lg_by_name.setdefault(raw, {})[ten] = v
    gnames = _dedup_prom_names(set(gauges) | set(lg_by_name))
    gtlabels = _tenant_label_values({t for (_, t) in lgauges})
    for raw in sorted(gnames):
        name = f"nnstpu_{gnames[raw]}"
        meta = _series_meta(raw)
        lines.append(f"# HELP {name} "
                     f"{meta[0] if meta else 'instantaneous gauge'}")
        lines.append(f"# TYPE {name} gauge")
        if raw in gauges:
            lines.append(f"{name} {gauges[raw]:.9g}")
        for ten in sorted(lg_by_name.get(raw, ()),
                          key=lambda t: gtlabels[t]):
            lines.append(f'{name}{{tenant="{gtlabels[ten]}"}} '
                         f"{lg_by_name[raw][ten]:.9g}")
    snap = metrics.snapshot()
    lcounters = metrics.labeled_counters()
    lc_by_name: dict = {}
    for (raw, ten), v in lcounters.items():
        lc_by_name.setdefault(raw, {})[ten] = v
    counters = [raw for raw in set(snap) | set(lc_by_name)
                if raw not in gauges and raw not in lg_by_name]
    cnames = _dedup_prom_names(counters)
    ctlabels = _tenant_label_values({t for (_, t) in lcounters})
    for raw in sorted(counters):
        name = cnames[raw]
        meta = _series_meta(raw)
        # OpenMetrics: counter SAMPLES are named `<family>_total` (the
        # parser rejects a typed counter sample without the suffix);
        # untyped series stay "unknown" and keep the bare name
        sample = name
        if meta is not None:
            lines.append(f"# HELP nnstpu_{name} {meta[0]}")
            lines.append(f"# TYPE nnstpu_{name} {meta[1]}")
            if openmetrics and meta[1] == "counter":
                sample = f"{name}_total"
        if raw in snap:
            lines.append(f"nnstpu_{sample} {snap[raw]:.9g}")
        for ten in sorted(lc_by_name.get(raw, ()),
                          key=lambda t: ctlabels[t]):
            lines.append(f'nnstpu_{sample}{{tenant="{ctlabels[ten]}"}} '
                         f"{lc_by_name[raw][ten]:.9g}")
    if openmetrics:
        lines.append("# EOF")
    return "\n".join(lines) + "\n"


#: OpenMetrics media type (negotiated via the Accept header); the
#: classic Prometheus text exposition stays the default
OPENMETRICS_CONTENT_TYPE = \
    "application/openmetrics-text; version=1.0.0; charset=utf-8"
_PROM_CONTENT_TYPE = "text/plain; version=0.0.4"


class _MetricsHandler(http.server.BaseHTTPRequestHandler):
    def do_GET(self):  # noqa: N802 - http.server API
        if self.path.rstrip("/") not in ("", "/metrics"):
            self.send_response(404)
            self.end_headers()
            return
        # Content negotiation: a scraper that asks for OpenMetrics gets
        # the matching Content-Type AND the `# EOF` trailer (its
        # truncation detector); everyone else keeps the classic text
        # exposition byte-for-byte.
        accept = self.headers.get("Accept", "") or ""
        om = "application/openmetrics-text" in accept
        body = metrics_text(openmetrics=om).encode()
        self.send_response(200)
        self.send_header("Content-Type",
                         OPENMETRICS_CONTENT_TYPE if om
                         else _PROM_CONTENT_TYPE)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):  # silence per-request stderr noise
        pass


class _MetricsServer(http.server.ThreadingHTTPServer):
    # SO_REUSEADDR: a restart must rebind the port without waiting out
    # TIME_WAIT (http.server sets it too — pinned explicitly here so the
    # contract survives a base-class change)
    allow_reuse_address = True
    daemon_threads = True


def start_metrics_server(port: int = 0, host: str = "127.0.0.1"):
    """Serve ``/metrics`` on a daemon thread; returns the HTTPServer (its
    ``server_port`` reports the bound port).  Stop cleanly with
    :func:`stop_metrics_server` (or use the :func:`metrics_server`
    context manager)."""
    srv = _MetricsServer((host, port), _MetricsHandler)
    t = threading.Thread(target=srv.serve_forever, daemon=True,
                         name=f"metrics:{srv.server_port}")
    srv._nns_thread = t  # joined by stop_metrics_server
    t.start()
    return srv


def stop_metrics_server(srv, timeout: float = 5.0) -> None:
    """Shut the ``/metrics`` endpoint down and release its port: stops the
    serve loop, joins the server thread, closes the listening socket.
    Safe to call twice."""
    srv.shutdown()
    t = getattr(srv, "_nns_thread", None)
    if t is not None and t.is_alive():
        t.join(timeout=timeout)
    srv.server_close()


@contextlib.contextmanager
def metrics_server(port: int = 0, host: str = "127.0.0.1"):
    """``with metrics_server() as srv:`` — endpoint for the block's
    lifetime, cleanly stopped on exit."""
    srv = start_metrics_server(port, host)
    try:
        yield srv
    finally:
        stop_metrics_server(srv)
