"""Shared TCP listener + handshake scaffolding for the distribution
elements (tensor_query server, edgesink publisher).

Reference analog: the connection handshake / capability exchange inside
nnstreamer-edge (SURVEY §2.7) — one implementation serving both the
request/response (query) and pub/sub (edge) transports.
"""

from __future__ import annotations

import json
import socket
import threading
from typing import Callable, Optional

from ..core.log import logger
from . import wire

log = logger(__name__)


class TcpListener:
    """Bind + accept loop; one daemon thread per connection.

    ``session_cb(conn)`` runs on the connection's own thread and owns the
    socket's lifetime (the listener closes it after the callback returns).
    """

    def __init__(self, host: str, port: int,
                 session_cb: Callable[[socket.socket], None],
                 name: str = "tcp"):
        self._session_cb = session_cb
        self._name = name
        self._stopping = threading.Event()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(16)
        self._sock.settimeout(0.2)
        self.port = self._sock.getsockname()[1]
        threading.Thread(
            target=self._accept_loop, name=f"{name}-accept:{self.port}",
            daemon=True,
        ).start()

    @property
    def stopping(self) -> threading.Event:
        return self._stopping

    def _accept_loop(self) -> None:
        while not self._stopping.is_set():
            try:
                conn, _addr = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(
                target=self._session, args=(conn,), daemon=True,
                name=f"{self._name}-conn",
            ).start()

    def _session(self, conn: socket.socket) -> None:
        try:
            self._session_cb(conn)
        except (OSError, ValueError) as e:
            log.debug("%s: session ended: %s", self._name, e)
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def close(self) -> None:
        self._stopping.set()
        try:
            self._sock.close()
        except OSError:
            pass


def parse_control(raw: Optional[bytes]) -> Optional[dict]:
    """Control frames are JSON objects; tensor frames start with the wire
    magic.  Returns None for non-control frames."""
    if not raw:
        return None
    if len(raw) >= 4 and int.from_bytes(raw[:4], "little") == wire.MAGIC:
        return None
    try:
        msg = json.loads(raw.decode("utf-8"))
    except (UnicodeDecodeError, ValueError):
        return None
    return msg if isinstance(msg, dict) else None


PROTOCOL_VERSION = 2  # v2: crc32-trailed wire frames


def finish_server_handshake(conn: socket.socket, hello: Optional[dict],
                            expect_types, topic: str = "") -> Optional[dict]:
    """Validate an already-read hello and reply ack/nack (the shared half of
    every server-side handshake: version gate, topic filter, TCP_NODELAY).

    ``expect_types`` is one type string or a tuple of acceptable ones.
    Returns the hello dict on success, None on rejection."""
    if isinstance(expect_types, str):
        expect_types = (expect_types,)
    if not hello or hello.get("type") not in expect_types:
        return None
    if hello.get("proto", 0) != PROTOCOL_VERSION:
        # Frame layout differs across versions: reject at connect time
        # instead of desyncing mid-stream.
        wire.write_frame(conn, json.dumps(
            {"type": "nack",
             "reason": f"protocol version {hello.get('proto')} != "
                       f"{PROTOCOL_VERSION}"}).encode())
        return None
    if topic and hello.get("topic", "") not in ("", topic):
        wire.write_frame(conn, json.dumps(
            {"type": "nack", "reason": "topic mismatch"}).encode())
        return None
    wire.write_frame(conn, json.dumps(
        {"type": "ack", "topic": topic, "proto": PROTOCOL_VERSION}).encode())
    conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return hello


def server_handshake(conn: socket.socket, expect_type: str,
                     topic: str = "") -> Optional[dict]:
    """Read a hello frame, enforce version + topic, reply ack/nack.

    Returns the hello dict on success, None on rejection (nack sent)."""
    conn.settimeout(5.0)
    hello = parse_control(wire.read_frame(conn))
    return finish_server_handshake(conn, hello, expect_type, topic)


def client_handshake(conn: socket.socket, hello_type: str, **fields) -> dict:
    """Send hello, await ack; raises ConnectionError on rejection."""
    wire.write_frame(conn, json.dumps(
        {"type": hello_type, "proto": PROTOCOL_VERSION, **fields}).encode("utf-8"))
    ack = parse_control(wire.read_frame(conn))
    if ack and ack.get("type") == "nack":
        # the server's typed refusal carries the reason (version/topic
        # mismatch) — surface it instead of the raw frame
        raise ConnectionError(
            f"server rejected handshake: {ack.get('reason', 'unspecified')}")
    if not ack or ack.get("type") != "ack":
        raise ConnectionError(f"server rejected connection: {ack}")
    conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return ack
