"""Shared TCP listener + handshake scaffolding for the distribution
elements (tensor_query server, edgesink publisher).

Reference analog: the connection handshake / capability exchange inside
nnstreamer-edge (SURVEY §2.7) — one implementation serving both the
request/response (query) and pub/sub (edge) transports.
"""

from __future__ import annotations

import json
import socket
import threading
import time
from typing import Callable, Optional

from ..core.log import logger
from . import tracing, wire

log = logger(__name__)


class TcpListener:
    """Bind + accept loop; one daemon thread per connection.

    ``session_cb(conn)`` runs on the connection's own thread and owns the
    socket's lifetime (the listener closes it after the callback returns).
    """

    def __init__(self, host: str, port: int,
                 session_cb: Callable[[socket.socket], None],
                 name: str = "tcp"):
        self._session_cb = session_cb
        self._name = name
        self._stopping = threading.Event()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(16)
        self._sock.settimeout(0.2)
        self.port = self._sock.getsockname()[1]
        threading.Thread(
            target=self._accept_loop, name=f"{name}-accept:{self.port}",
            daemon=True,
        ).start()

    @property
    def stopping(self) -> threading.Event:
        return self._stopping

    def _accept_loop(self) -> None:
        while not self._stopping.is_set():
            try:
                conn, _addr = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(
                target=self._session, args=(conn,), daemon=True,
                name=f"{self._name}-conn",
            ).start()

    def _session(self, conn: socket.socket) -> None:
        try:
            self._session_cb(conn)
        except (OSError, ValueError) as e:
            log.debug("%s: session ended: %s", self._name, e)
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def close(self) -> None:
        self._stopping.set()
        try:
            self._sock.close()
        except OSError:
            pass


def parse_control(raw: Optional[bytes]) -> Optional[dict]:
    """Control frames are JSON objects; tensor frames start with the wire
    magic.  Returns None for non-control frames."""
    if not raw:
        return None
    if len(raw) >= 4 and int.from_bytes(raw[:4], "little") == wire.MAGIC:
        return None
    try:
        msg = json.loads(raw.decode("utf-8"))
    except (UnicodeDecodeError, ValueError):
        return None
    return msg if isinstance(msg, dict) else None


PROTOCOL_VERSION = 2  # v2: crc32-trailed wire frames


def finish_server_handshake(conn: socket.socket, hello: Optional[dict],
                            expect_types, topic: str = "") -> Optional[dict]:
    """Validate an already-read hello and reply ack/nack (the shared half of
    every server-side handshake: version gate, topic filter, TCP_NODELAY).

    ``expect_types`` is one type string or a tuple of acceptable ones.
    Returns the hello dict on success, None on rejection."""
    if isinstance(expect_types, str):
        expect_types = (expect_types,)
    if not hello or hello.get("type") not in expect_types:
        return None
    if hello.get("proto", 0) != PROTOCOL_VERSION:
        # Frame layout differs across versions: reject at connect time
        # instead of desyncing mid-stream.
        wire.write_frame(conn, json.dumps(
            {"type": "nack",
             "reason": f"protocol version {hello.get('proto')} != "
                       f"{PROTOCOL_VERSION}"}).encode())
        return None
    if topic and hello.get("topic", "") not in ("", topic):
        wire.write_frame(conn, json.dumps(
            {"type": "nack", "reason": "topic mismatch"}).encode())
        return None
    ack = {"type": "ack", "topic": topic, "proto": PROTOCOL_VERSION}
    if isinstance(hello.get("t0"), int):
        # nns-weave clock echo piggybacked on the handshake
        # (docs/OBSERVABILITY.md "Distributed tracing"): echo the
        # client's send stamp with our receive/send stamps + trace epoch
        # so the client can derive offset ± uncertainty between the two
        # monotonic bases.  t1 ideally marks hello arrival; stamping it
        # here (validation later than read) only widens the bound.
        ack.update(t0=hello["t0"], t1=time.monotonic_ns(),
                   epoch=tracing.trace_epoch(), t2=time.monotonic_ns())
    wire.write_frame(conn, json.dumps(ack).encode())
    conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return hello


def server_handshake(conn: socket.socket, expect_type: str,
                     topic: str = "") -> Optional[dict]:
    """Read a hello frame, enforce version + topic, reply ack/nack.

    Returns the hello dict on success, None on rejection (nack sent)."""
    conn.settimeout(5.0)
    hello = parse_control(wire.read_frame(conn))
    return finish_server_handshake(conn, hello, expect_type, topic)


def client_handshake(conn: socket.socket, hello_type: str, **fields) -> dict:
    """Send hello, await ack; raises ConnectionError on rejection.

    The hello carries a clock-echo stamp (``t0`` + this process's trace
    epoch); a weave-aware server echoes ``t0/t1/t2`` + its epoch in the
    ack, and the returned dict then gains a synthesized ``clock`` entry
    ``{"epoch", "offset_ns", "uncertainty_ns"}`` (offset = peer − local
    monotonic base) for the caller to feed into
    ``tracing.recorder.note_clock``.  Older servers ignore the stamp."""
    t0 = time.monotonic_ns()
    wire.write_frame(conn, json.dumps(
        {"type": hello_type, "proto": PROTOCOL_VERSION, "t0": t0,
         "epoch": tracing.trace_epoch(), **fields}).encode("utf-8"))
    ack = parse_control(wire.read_frame(conn))
    t3 = time.monotonic_ns()
    if ack and ack.get("type") == "nack":
        # the server's typed refusal carries the reason (version/topic
        # mismatch) — surface it instead of the raw frame
        raise ConnectionError(
            f"server rejected handshake: {ack.get('reason', 'unspecified')}")
    if not ack or ack.get("type") != "ack":
        raise ConnectionError(f"server rejected connection: {ack}")
    conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    if ack.get("t0") == t0 and isinstance(ack.get("t1"), int) \
            and isinstance(ack.get("t2"), int) \
            and isinstance(ack.get("epoch"), int):
        off, unc = tracing.clock_offset(t0, ack["t1"], ack["t2"], t3)
        ack["clock"] = {"epoch": ack["epoch"], "offset_ns": off,
                        "uncertainty_ns": unc}
    return ack
