"""nns-armor: durable request journal (write-ahead log) for the query
front door (ISSUE 12, docs/ROBUSTNESS.md).

PR 11 made the serving substrate elastic — reconnect, drain/adopt,
autoscale — but an ACCEPTED request still died silently with the
process.  This module closes that hole: the serversrc appends every
accepted request's wire payload to a segment-rotated, CRC'd journal
BEFORE the pipeline sees it, the serversink acknowledges the entry when
the answer leaves (the answered-offset watermark), and a restarted
pipeline (``Pipeline(journal_replay=True)``) re-admits exactly the
unanswered entries — seqno-deduped, so a double restart never
double-processes an already-answered request.

Record layout (little-endian, one stream of records per segment file):

    u32 magic ("JREQ" requests / "JACK" acks) | u64 seqno
    | u32 payload_len | u32 crc32(payload) | payload

Ack records carry no payload (len 0, crc of ``b""``).  Segments rotate
at ``segment_bytes``; fully-acknowledged segments are deleted at
rotation (the GC), so steady-state disk usage is bounded by the
unanswered window plus one segment.

Torn-tail policy (the crash-consistency contract the property test
pins): a record that fails its magic/length/CRC check ends the segment —
everything before it is recovered, everything from it on is dropped.  A
SIGKILL mid-append can only tear the LAST record of the LAST segment,
so no fully-CRC'd entry is ever lost and no torn bytes are ever
replayed.

fsync policy (``fsync=off|batch|always``):

* ``off``    — never fsync; durability = the OS page cache (survives a
  process kill, not a host power cut).
* ``batch``  — appends/acks are buffered writes; a background flusher
  thread fsyncs every ``batch_interval_s`` (with an inline
  ``batch_every`` backstop so a burst can never grow the loss window
  unboundedly).  The bounded-loss default: the fsync is OFF the
  request path, which is what keeps the journal-overhead A/B's p50
  delta under its 3% target.
* ``always`` — fsync every append before returning (survives power
  loss; pays one fsync per request).

Everything here is host-side file I/O — no jax import, no device work.
"""

from __future__ import annotations

import os
import struct
import threading
import time
import zlib
from typing import Dict, List, Optional, Tuple

from ..core.log import logger, metrics
from . import locks

log = logger(__name__)

MAGIC_REQ = 0x4A524551  # "JREQ"
MAGIC_ACK = 0x4A41434B  # "JACK"

_REC_FMT = "<IQII"
_REC_SIZE = struct.calcsize(_REC_FMT)

FSYNC_MODES = ("off", "batch", "always")

_SEG_PREFIX = "wal-"
_SEG_SUFFIX = ".log"


def _crc(payload: bytes) -> int:
    return zlib.crc32(payload) & 0xFFFFFFFF


def pack_record(magic: int, seqno: int, payload: bytes = b"") -> bytes:
    return struct.pack(_REC_FMT, magic, seqno, len(payload),
                       _crc(payload)) + payload


def _segments(path: str) -> List[str]:
    try:
        names = os.listdir(path)
    except FileNotFoundError:
        return []
    segs = [n for n in names
            if n.startswith(_SEG_PREFIX) and n.endswith(_SEG_SUFFIX)]
    return [os.path.join(path, n) for n in sorted(segs)]


def _scan_segment(path: str) -> Tuple[List[Tuple[int, int, bytes]], int]:
    """Parse one segment file.  Returns ``(records, torn_bytes)`` where
    each record is ``(magic, seqno, payload)``; parsing stops at the
    first record whose header/length/CRC does not check out (the torn
    tail), with ``torn_bytes`` the dropped byte count."""
    with open(path, "rb") as f:
        raw = f.read()
    out: List[Tuple[int, int, bytes]] = []
    off = 0
    n = len(raw)
    while off + _REC_SIZE <= n:
        magic, seqno, plen, crc = struct.unpack_from(_REC_FMT, raw, off)
        if magic not in (MAGIC_REQ, MAGIC_ACK):
            break
        body_off = off + _REC_SIZE
        if body_off + plen > n:
            break  # truncated payload: torn tail
        payload = raw[body_off:body_off + plen]
        if _crc(payload) != crc:
            break  # corrupt payload: torn tail
        out.append((magic, seqno, payload))
        off = body_off + plen
    return out, n - off


class Journal:
    """Append-only request journal over a directory of rotated segments.

    One writer (the serversrc reader threads serialize on the lock), any
    number of out-of-band readers (:func:`replay_unanswered` reads the
    files directly — the yank_process soak inspects a killed server's
    journal this way)."""

    #: nns-tsan lock discipline (lint --threads verifies statically,
    #: NNS_TPU_TSAN=1 verifies live — docs/ANALYSIS.md "Threads pass")
    _GUARDED_BY = {
        "_file": "_lock", "_file_bytes": "_lock", "_seg_index": "_lock",
        "_unsynced": "_lock", "_live_unacked": "_lock",
        "_seg_seqnos": "_lock", "_cur_seqnos": "_lock",
        "_next_seq": "_lock",
    }

    def __init__(self, path: str, *, fsync: str = "batch",
                 segment_bytes: int = 8 << 20, batch_every: int = 256,
                 batch_interval_s: float = 0.05):
        if fsync not in FSYNC_MODES:
            raise ValueError(
                f"journal fsync must be one of {FSYNC_MODES}, got "
                f"{fsync!r}")
        self.path = path
        self.fsync = fsync
        self.segment_bytes = max(1 << 12, int(segment_bytes))
        self.batch_every = max(1, int(batch_every))
        self.batch_interval_s = max(0.001, float(batch_interval_s))
        os.makedirs(path, exist_ok=True)
        self._lock = locks.make_lock("Journal._lock")
        self._stop_flush = threading.Event()
        self._kick = threading.Event()  # batch_every backstop wakeup
        self._flusher: Optional[threading.Thread] = None
        self._file = None
        self._file_bytes = 0
        self._seg_index = 0
        self._unsynced = 0
        #: seqnos appended (REQ) into the CURRENT process's segments and
        #: not yet acked — the live watermark mirror (replay rebuilds
        #: the on-disk truth; this set only drives GC decisions)
        self._live_unacked: set = set()
        #: per-segment seqnos, for GC at rotation
        self._seg_seqnos: Dict[str, set] = {}
        # resume appending AFTER any existing segments (a replayed
        # journal keeps its history until acked + GC'd)
        #: the recovery SNAPSHOT: ``(seqno, payload)`` of every entry
        #: that was accepted-but-unanswered when this Journal opened.
        #: Replay consumers read THIS, not a later directory re-scan —
        #: anything accepted after open (e.g. a reconnected client's
        #: resend, once the server is listening again) is a new entry
        #: and must never be replayed on top of its own admission.
        #: Consumers should clear it once staged (the serversrc does):
        #: a large unanswered window's payload bytes must not stay
        #: pinned for the journal's whole lifetime.
        self.recovered_unanswered: List[Tuple[int, bytes]] = []
        existing = _segments(path)
        if existing:
            last = os.path.basename(existing[-1])
            self._seg_index = int(
                last[len(_SEG_PREFIX):-len(_SEG_SUFFIX)]) + 1
            state = scan(path)
            self._next_seq = state.max_seqno + 1
            self._live_unacked = set(state.unanswered)
            self.recovered_unanswered = [
                (s, state.requests[s]) for s in state.unanswered]
        else:
            self._next_seq = 1
        self._open_segment()
        if self.fsync == "batch":
            # the fsync lives on THIS thread, off the request path: an
            # append is a buffered write, durability follows within
            # batch_interval_s (the bounded-loss contract)
            self._flusher = threading.Thread(
                target=self._flush_loop, name="nns-journal-flush",
                daemon=True)
            self._flusher.start()

    def _flush_loop(self) -> None:
        while True:
            self._kick.wait(self.batch_interval_s)
            self._kick.clear()
            if self._stop_flush.is_set():
                return
            with self._lock:
                if self._file is None or not self._unsynced:
                    continue
                # flush (userspace) under the lock, fsync OUTSIDE it: a
                # multi-ms fsync holding the lock would stall every
                # append colliding with it — exactly the latency the
                # batch mode exists to keep off the request path
                self._file.flush()
                self._unsynced = 0
                fd = self._file.fileno()
            try:
                os.fsync(fd)
            except OSError:
                pass  # racing a rotation: the next tick covers it

    # -- write path --------------------------------------------------------
    def _seg_path(self, index: int) -> str:
        return os.path.join(self.path,
                            f"{_SEG_PREFIX}{index:08d}{_SEG_SUFFIX}")

    def _open_segment(self) -> None:
        p = self._seg_path(self._seg_index)
        self._file = open(p, "ab")
        self._file_bytes = self._file.tell()
        # the CURRENT segment's seqno set, cached: append() is the hot
        # path and must not rebuild the path string per record
        self._cur_seqnos = self._seg_seqnos[p] = set()

    def _rotate_locked(self) -> None:
        self._sync_locked(force=True)
        self._file.close()
        # GC: delete the longest PREFIX of segments (oldest first)
        # whose every REQ seqno is acked, stopping at the first segment
        # holding an unacked request — bounded steady-state disk usage.
        # Strictly a prefix: an ACK record always lands at or after its
        # REQ, so a deleted old segment's acks can only reference
        # requests deleted with it, while a req whose ack lives in a
        # LATER segment leaves (at worst) a dangling ack the scanner
        # ignores.  Deleting an arbitrary fully-acked MIDDLE segment
        # would instead destroy acks for older retained requests and
        # resurrect answered work at the next replay.
        for p in _segments(self.path)[:-1]:
            seqs = self._seg_seqnos.get(p)
            if seqs is None:
                # pre-restart segment: scan it once for its REQ seqnos
                recs, _ = _scan_segment(p)
                seqs = {s for m, s, _pl in recs if m == MAGIC_REQ}
                self._seg_seqnos[p] = seqs
            if seqs & self._live_unacked:
                break  # prefix ends here
            try:
                os.unlink(p)
            except OSError:
                break
            self._seg_seqnos.pop(p, None)
            metrics.count("journal.segments_gcd")
        self._seg_index += 1
        self._open_segment()

    def _write_locked(self, rec: bytes) -> None:
        if self._file_bytes + len(rec) > self.segment_bytes \
                and self._file_bytes > 0:
            self._rotate_locked()
        self._file.write(rec)
        self._file_bytes += len(rec)

    def _sync_locked(self, force: bool = False) -> None:
        if self._unsynced == 0:
            return
        self._file.flush()
        if self.fsync != "off" or force:
            try:
                os.fsync(self._file.fileno())
            except OSError:
                pass
        self._unsynced = 0

    def _after_write_locked(self) -> None:
        """Per-record durability step: ``always`` fsyncs inline,
        ``off`` flushes to the page cache (a SIGKILL must not lose
        python-buffered bytes), ``batch`` leaves the write buffered and
        at most KICKS the flusher (the request path never fsyncs)."""
        if self.fsync == "always":
            self._sync_locked(force=True)
        elif self.fsync == "off":
            self._file.flush()
            self._unsynced = 0
        elif self._unsynced >= self.batch_every:
            self._kick.set()

    def append(self, payload: bytes, tenant: Optional[str] = None) -> int:
        """Append one accepted request payload; returns its journal
        seqno (the dedup key the ack + replay paths use), or 0 when
        the journal is already closed (a reader thread racing
        shutdown: the request is simply not journaled)."""
        with self._lock:
            if self._file is None:
                return 0
            seq = self._next_seq
            self._next_seq += 1
            self._write_locked(pack_record(MAGIC_REQ, seq, payload))
            self._live_unacked.add(seq)
            self._cur_seqnos.add(seq)
            self._unsynced += 1
            self._after_write_locked()
        metrics.count("journal.appends", tenant=tenant)
        return seq

    def ack(self, seqno: int) -> bool:
        """Record that entry ``seqno`` was answered (the watermark); an
        acked entry is never replayed.  IDEMPOTENT: only the first ack
        of a live unacked seqno writes a record (multiplicity stays 1
        even when several failure paths race to retire one entry), and
        a closed journal no-ops.  Returns True when the ack was
        recorded."""
        seqno = int(seqno)
        with self._lock:
            if self._file is None or seqno not in self._live_unacked:
                return False
            self._write_locked(pack_record(MAGIC_ACK, seqno))
            self._live_unacked.discard(seqno)
            self._unsynced += 1
            self._after_write_locked()
        metrics.count("journal.acks")
        return True

    def flush(self) -> None:
        with self._lock:
            if self._file is not None:
                self._sync_locked(force=True)

    def close(self) -> None:
        self._stop_flush.set()
        self._kick.set()
        if self._flusher is not None:
            self._flusher.join(timeout=2.0)
            self._flusher = None
        with self._lock:
            if self._file is not None:
                self._sync_locked(force=True)
                self._file.close()
                self._file = None

    # -- stats -------------------------------------------------------------
    def unacked_count(self) -> int:
        with self._lock:
            return len(self._live_unacked)


class JournalState:
    """Result of :func:`scan`: what a journal directory durably holds."""

    def __init__(self):
        self.requests: Dict[int, bytes] = {}
        self.acked: set = set()
        self.torn_bytes = 0
        self.max_seqno = 0
        self.duplicate_seqnos = 0
        self.ack_multiplicity: Dict[int, int] = {}

    @property
    def unanswered(self) -> List[int]:
        return sorted(s for s in self.requests if s not in self.acked)


def scan(path: str) -> JournalState:
    """Read every segment in order, CRC-verifying each record; torn
    tails are dropped per segment (see module docstring)."""
    st = JournalState()
    segs = _segments(path)
    for i, p in enumerate(segs):
        recs, torn = _scan_segment(p)
        if torn:
            st.torn_bytes += torn
            if i != len(segs) - 1:
                # mid-history corruption (not a crash artifact): recover
                # what checks out, but say so loudly
                log.warning(
                    "journal %s: %d torn bytes in NON-final segment %s "
                    "(disk corruption?); recovered %d records before it",
                    path, torn, os.path.basename(p), len(recs))
        for magic, seqno, payload in recs:
            if magic == MAGIC_REQ:
                if seqno in st.requests:
                    st.duplicate_seqnos += 1
                    continue  # seqno dedup: first durable copy wins
                st.requests[seqno] = payload
            else:
                st.ack_multiplicity[seqno] = \
                    st.ack_multiplicity.get(seqno, 0) + 1
                st.acked.add(seqno)
            if seqno > st.max_seqno:
                st.max_seqno = seqno
    return st


def replay_unanswered(path: str) -> List[Tuple[int, bytes]]:
    """``(seqno, payload)`` for every fully-CRC'd accepted-but-unanswered
    entry, in append order — the ``Pipeline(journal_replay=True)``
    re-admission source.  Exactly-once composition: re-admitted entries
    keep their seqno, are acked when answered, and a further restart
    replays only what is STILL unanswered."""
    st = scan(path)
    return [(s, st.requests[s]) for s in st.unanswered]
