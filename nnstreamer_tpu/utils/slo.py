"""nns-slo: per-tenant SLO accounting over the live metrics registry.

The production question PR 5's histograms could not yet answer — "is
tenant X inside its p99 budget right now, and if not, which stage is
burning it?" — becomes first-class here (docs/SERVING.md "Front door"):

* **Policy** (:class:`SLOPolicy` / :class:`TenantSLO`): declarative
  per-tenant objectives — p50/p99 end-to-end latency, minimum
  throughput, and an error budget (the fraction of requests allowed to
  violate latency or be shed before the tenant counts as breaching).
  Loaded from a dict, a JSON file, or built in code; validated by
  :func:`validate_policy` (the schema the CI soak gate asserts).
* **Engine** (:class:`SLOEngine`): evaluates the policy continuously off
  the live per-tenant labeled histograms (``<sink>.e2e_latency`` — fed
  by the runtime when ``trace_mode != off``) and shed counters,
  publishing ``slo.burn_rate`` / ``slo.breach`` gauges per tenant into
  the same registry Prometheus scrapes.  ``Pipeline(slo=...)`` starts
  one; ``Pipeline.slo_report()`` is the on-demand verdict.
* **Attribution** (:func:`dominant_span`): for a breaching tenant, the
  span kind (queue/stage/batch/inflight/shard/fetch) that accounts for
  the most recorded time in the flight-recorder ring — the "which stage
  is burning it" half of the question, answered from the same ring the
  watchdog dumps.

Burn rate follows the classic error-budget formulation: with budget
``b`` (default 1%), ``burn = bad_fraction / b`` where a request is bad
if its e2e latency exceeded the p99 objective OR it was shed at
admission.  ``burn == 1.0`` means the tenant is consuming exactly its
budget; sustained ``> 1`` means the budget exhausts early — the engine
flags it alongside hard p50/p99/fps violations.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import math
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from ..core.log import Metrics, logger
from ..core.log import metrics as _global_metrics
from . import tracing

log = logger(__name__)

#: span kinds that count toward dominant-span attribution: the
#: per-stage WORK/WAIT decomposition of an e2e latency (e2e itself,
#: ingress instants, and admission instants are excluded — they either
#: cover everything or have no duration)
ATTRIBUTABLE_KINDS = ("queue", "stage", "batch", "inflight", "shard",
                      "fetch")


@dataclasses.dataclass
class TenantSLO:
    """One tenant's objectives.  A zero objective means "not set" —
    only explicit objectives are enforced."""

    tenant: str
    p50_ms: float = 0.0
    p99_ms: float = 0.0
    min_fps: float = 0.0
    #: serving-timeline objective (docs/OBSERVABILITY.md "Distributed
    #: tracing"): p99 time-to-first-token, evaluated off the tenant's
    #: ``llm.serve.ttft_ms`` reservoir (millisecond-valued)
    ttft_p99_ms: float = 0.0
    #: fraction of requests allowed to violate p99 latency or be shed
    #: before burn_rate reads 1.0
    error_budget: float = 0.01

    @classmethod
    def from_dict(cls, d: dict) -> "TenantSLO":
        return cls(tenant=str(d["tenant"]),
                   p50_ms=float(d.get("p50_ms", 0.0)),
                   p99_ms=float(d.get("p99_ms", 0.0)),
                   min_fps=float(d.get("min_fps", 0.0)),
                   ttft_p99_ms=float(d.get("ttft_p99_ms", 0.0)),
                   error_budget=float(d.get("error_budget", 0.01)))

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class SLOPolicy:
    """The declarative config: per-tenant objectives + the series the
    engine reads.  ``sinks`` defaults to whatever the owning Pipeline
    reports; ``shed_series`` is the admission-control counter family
    (docs/SERVING.md)."""

    tenants: List[TenantSLO] = dataclasses.field(default_factory=list)
    sinks: List[str] = dataclasses.field(default_factory=list)
    shed_series: str = "query_server.shed"

    def for_tenant(self, tenant: str) -> Optional[TenantSLO]:
        for t in self.tenants:
            if t.tenant == tenant:
                return t
        return None

    def to_dict(self) -> dict:
        return {"tenants": [t.to_dict() for t in self.tenants],
                "sinks": list(self.sinks),
                "shed_series": self.shed_series}


def validate_policy(d: dict) -> List[str]:
    """Schema problems of a policy dict (empty list = valid).  The shape
    ``python -m nnstreamer_tpu.tools.slo validate`` and the CI soak gate
    check."""
    problems: List[str] = []
    if not isinstance(d, dict):
        return ["policy must be a JSON object"]
    tenants = d.get("tenants")
    if not isinstance(tenants, list) or not tenants:
        problems.append("'tenants' must be a non-empty list")
        tenants = []
    seen = set()
    for i, t in enumerate(tenants):
        if not isinstance(t, dict):
            problems.append(f"tenants[{i}]: must be an object")
            continue
        name = t.get("tenant")
        if not name or not isinstance(name, str):
            problems.append(f"tenants[{i}]: 'tenant' (non-empty string) "
                            "required")
        elif name in seen:
            problems.append(f"tenants[{i}]: duplicate tenant {name!r}")
        else:
            seen.add(name)
        for key in ("p50_ms", "p99_ms", "min_fps", "ttft_p99_ms",
                    "error_budget"):
            v = t.get(key, 0)
            if not isinstance(v, (int, float)) or v < 0:
                problems.append(
                    f"tenants[{i}].{key}: must be a number >= 0")
        eb = t.get("error_budget", 0.01)
        if isinstance(eb, (int, float)) and eb > 1:
            problems.append(
                f"tenants[{i}].error_budget: a fraction in [0, 1], "
                f"got {eb}")
        unknown = set(t) - {"tenant", "p50_ms", "p99_ms", "min_fps",
                            "ttft_p99_ms", "error_budget"}
        if unknown:
            problems.append(
                f"tenants[{i}]: unknown keys {sorted(unknown)}")
    if "sinks" in d and not (isinstance(d["sinks"], list) and all(
            isinstance(s, str) for s in d["sinks"])):
        problems.append("'sinks' must be a list of sink element names")
    if "shed_series" in d and not isinstance(d["shed_series"], str):
        problems.append("'shed_series' must be a string")
    unknown = set(d) - {"tenants", "sinks", "shed_series"}
    if unknown:
        problems.append(f"unknown top-level keys {sorted(unknown)}")
    return problems


def load_policy(obj) -> SLOPolicy:
    """Accepts an :class:`SLOPolicy`, a policy dict, or a JSON file path;
    ``None`` yields an empty policy (every tenant informational-only).
    Raises ``ValueError`` naming every schema problem at once."""
    if obj is None:
        return SLOPolicy()
    if isinstance(obj, SLOPolicy):
        return obj
    if isinstance(obj, str):
        with open(obj) as f:
            obj = json.load(f)
    if not isinstance(obj, dict):
        raise ValueError(
            f"slo policy must be SLOPolicy | dict | path, got {type(obj)}")
    problems = validate_policy(obj)
    if problems:
        raise ValueError("invalid SLO policy: " + "; ".join(problems))
    return SLOPolicy(
        tenants=[TenantSLO.from_dict(t) for t in obj["tenants"]],
        sinks=list(obj.get("sinks", [])),
        shed_series=str(obj.get("shed_series", "query_server.shed")))


def dominant_span(tenant: str,
                  rec: Optional[tracing.FlightRecorder] = None
                  ) -> Optional[Tuple[str, float]]:
    """(span kind, total milliseconds) of the kind that accounts for the
    most recorded time for ``tenant`` in the flight-recorder ring, or
    None when the ring holds nothing attributable.  This is the "which
    stage is burning the budget" answer — the same spans a watchdog/
    error ring dump shows.

    Single-buffer spans carry a ``tenant`` arg and credit their full
    duration; batched spans carry a row-aligned ``tenants`` list and
    credit the tenant its ROW SHARE of the amortized duration."""
    evs = (rec or tracing.recorder).events()
    sums: Dict[str, float] = {}
    for e in evs:
        if not e.args or e.kind not in ATTRIBUTABLE_KINDS or e.dur <= 0:
            continue
        if e.args.get("tenant") == tenant:
            sums[e.kind] = sums.get(e.kind, 0.0) + e.dur
        else:
            rows = e.args.get("tenants")
            if rows and tenant in rows:
                share = e.dur * rows.count(tenant) / len(rows)
                sums[e.kind] = sums.get(e.kind, 0.0) + share
    if not sums:
        return None
    kind = max(sums, key=sums.get)
    return kind, sums[kind] / 1e6


class SLOEngine:
    """Continuous per-tenant SLO evaluation off the live registry.

    ``evaluate()`` computes one verdict dict per tenant (the union of
    policy tenants and tenants observed on the sinks' labeled e2e
    histograms) and publishes ``slo.burn_rate`` / ``slo.breach`` gauges;
    ``report()`` additionally attributes each breaching tenant's
    dominant span kind from the ring.  ``start(period_s)`` runs
    ``evaluate`` on a daemon thread (what ``Pipeline(slo=...)`` uses).

    Throughput is a RATE over a sliding window: every evaluation
    snapshots per-tenant request counts into a bounded history, and
    ``fps`` derives against the newest snapshot at least
    :data:`MIN_RATE_WINDOW_S` old (the run start until that much history
    exists) — an on-demand ``report()`` landing milliseconds after a
    daemon tick never computes a rate over a near-zero window and
    spuriously flags ``min_fps``.  Evaluation state is lock-guarded, so
    the daemon loop and ad-hoc callers interleave safely."""

    #: minimum seconds a throughput window must span
    MIN_RATE_WINDOW_S = 2.0

    _GUARDED_BY = {"_history": "_eval_lock"}

    def __init__(self, policy: SLOPolicy, sinks: Sequence[str] = (),
                 metrics: Optional[Metrics] = None,
                 recorder: Optional[tracing.FlightRecorder] = None):
        self.policy = policy
        self.sinks = list(policy.sinks or sinks)
        self.metrics = metrics if metrics is not None else _global_metrics
        self.recorder = recorder
        self._t0 = time.monotonic()
        #: (t, {tenant: requests}) snapshots, oldest first (~32 s of
        #: history at the daemon cadence)
        self._history: collections.deque = collections.deque(maxlen=64)
        self._eval_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- data sources ------------------------------------------------------
    def _e2e_series(self) -> List[str]:
        return [f"{s}.e2e_latency" for s in self.sinks]

    def _observed_tenants(self) -> List[str]:
        seen = set()
        for series in self._e2e_series():
            seen.update(self.metrics.tenants(series))
        seen.update(self.metrics.tenants(self.policy.shed_series))
        return sorted(seen)

    def _tenant_latency(self, tenant: str, q: float) -> Optional[float]:
        """q-th percentile (ms) over the tenant's e2e reservoirs, merged
        across sinks."""
        samples: List[float] = []
        for series in self._e2e_series():
            samples.extend(self.metrics.reservoir(series, tenant=tenant))
        if not samples:
            return None
        samples.sort()
        idx = min(len(samples) - 1,
                  max(0, math.ceil(q / 100.0 * len(samples)) - 1))
        return samples[idx] * 1e3

    def _tenant_ttft(self, tenant: str, q: float) -> Optional[float]:
        """q-th percentile (ms) of the tenant's time-to-first-token off
        the ``llm.serve.ttft_ms`` labeled reservoir (already
        millisecond-valued — no unit conversion)."""
        samples = list(self.metrics.reservoir("llm.serve.ttft_ms",
                                              tenant=tenant))
        if not samples:
            return None
        samples.sort()
        idx = min(len(samples) - 1,
                  max(0, math.ceil(q / 100.0 * len(samples)) - 1))
        return samples[idx]

    def _tenant_counts(self, tenant: str, threshold_ms: float
                       ) -> Tuple[int, int]:
        """(requests, requests over threshold) summed across sinks from
        the labeled histograms.  threshold 0 = nothing counted over."""
        total = over = 0
        for series in self._e2e_series():
            frac, n = self.metrics.fraction_over(
                series, threshold_ms / 1e3, tenant=tenant)
            total += n
            over += round(frac * n)
        return total, (over if threshold_ms > 0 else 0)

    def _rate_base(self, now: float) -> Tuple[float, Dict[str, int]]:
        """The newest history snapshot at least MIN_RATE_WINDOW_S old —
        or the run start when no snapshot is old enough yet.  Call with
        ``_eval_lock`` held."""
        base_t, base_n = self._t0, {}
        for t, n in self._history:
            if now - t >= self.MIN_RATE_WINDOW_S:
                base_t, base_n = t, n
            else:
                break
        return base_t, base_n

    # -- evaluation --------------------------------------------------------
    def evaluate(self) -> dict:
        with self._eval_lock:
            return self._evaluate_locked()

    def _evaluate_locked(self) -> dict:
        now = time.monotonic()
        base_t, base_n = self._rate_base(now)
        window = max(1e-9, now - base_t)
        sheds = self.metrics.labeled_counters()
        verdicts: Dict[str, dict] = {}
        tenants = sorted({t.tenant for t in self.policy.tenants}
                         | set(self._observed_tenants()))
        new_last: Dict[str, int] = {}
        for tenant in tenants:
            slo = self.policy.for_tenant(tenant)
            p99_target = slo.p99_ms if slo else 0.0
            requests, lat_bad = self._tenant_counts(tenant, p99_target)
            shed_n = int(sheds.get((self.policy.shed_series, tenant), 0))
            new_last[tenant] = requests
            fps = (requests - base_n.get(tenant, 0)) / window
            p50 = self._tenant_latency(tenant, 50.0)
            p99 = self._tenant_latency(tenant, 99.0)
            ttft_p99 = (self._tenant_ttft(tenant, 99.0)
                        if slo is not None and slo.ttft_p99_ms > 0
                        else None)
            budget = slo.error_budget if slo else 0.01
            attempts = requests + shed_n
            bad = lat_bad + shed_n
            burn = ((bad / attempts) / budget
                    if attempts and budget > 0 else 0.0)
            violations: List[str] = []
            if slo is not None:
                if slo.p50_ms > 0 and p50 is not None and p50 > slo.p50_ms:
                    violations.append(
                        f"p50 {p50:.1f}ms > {slo.p50_ms:g}ms")
                if slo.p99_ms > 0 and p99 is not None and p99 > slo.p99_ms:
                    violations.append(
                        f"p99 {p99:.1f}ms > {slo.p99_ms:g}ms")
                if slo.min_fps > 0 and fps < slo.min_fps:
                    violations.append(
                        f"throughput {fps:.1f}fps < {slo.min_fps:g}fps")
                if slo.ttft_p99_ms > 0 and ttft_p99 is not None \
                        and ttft_p99 > slo.ttft_p99_ms:
                    violations.append(
                        f"ttft p99 {ttft_p99:.1f}ms > "
                        f"{slo.ttft_p99_ms:g}ms")
                if burn > 1.0:
                    violations.append(
                        f"error budget burning at {burn:.2f}x "
                        f"({bad}/{attempts} bad vs budget {budget:g})")
            ok = not violations
            self.metrics.gauge("slo.burn_rate", burn, tenant=tenant)
            self.metrics.gauge("slo.breach", 0.0 if ok else 1.0,
                               tenant=tenant)
            verdicts[tenant] = {
                "tenant": tenant,
                "ok": ok,
                "violations": violations,
                "p50_ms": p50,
                "p99_ms": p99,
                "ttft_p99_ms": ttft_p99,
                "fps": fps,
                "requests": requests,
                "sheds": shed_n,
                "burn_rate": burn,
                "objectives": slo.to_dict() if slo else None,
            }
        self._history.append((now, new_last))
        breaches = [t for t, v in verdicts.items() if not v["ok"]]
        return {"window_s": window, "ok": not breaches,
                "breaches": breaches, "tenants": verdicts}

    def report(self) -> dict:
        """``evaluate()`` + dominant-span attribution for every breaching
        tenant (the :meth:`Pipeline.slo_report` payload)."""
        rep = self.evaluate()
        for tenant in rep["breaches"]:
            dom = dominant_span(tenant, self.recorder)
            v = rep["tenants"][tenant]
            v["dominant_span_kind"] = dom[0] if dom else None
            v["dominant_span_ms"] = dom[1] if dom else None
        return rep

    # -- continuous mode ---------------------------------------------------
    def start(self, period_s: float = 0.5) -> "SLOEngine":
        if self._thread is not None:
            return self
        self._stop.clear()

        def loop():
            while not self._stop.wait(period_s):
                try:
                    self.evaluate()
                except Exception:  # noqa: BLE001 - must never die loud
                    log.exception("slo evaluation tick failed")

        self._thread = threading.Thread(target=loop, name="nns-slo",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2.0)
