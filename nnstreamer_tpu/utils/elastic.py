"""nns-elastic: act on the SLO signal — stream registry, autoscaler,
chaos hooks (ISSUE 11, docs/SERVING.md "Elastic serving").

PR 8 gave the front door per-tenant SLO *measurement* (utils/slo.py:
burn rate, breach attribution, shed/downgrade) and PR 6/9 gave it
valuable per-stream state (paged KV block tables, slot state).  This
module is the *reaction* half:

* **Stream registry** — every continuous-serving stream
  (``filters/llm.py _ContinuousLoop``) registers a process-unique
  ``stream_id`` here at submit; the id rides every emitted token's meta
  (:data:`META_STREAM_ID`) all the way to the query wire.  Downstream
  failure detectors (``tensor_query_serversink`` on a dead connection)
  call :func:`cancel_stream` — a host-value backchannel that lets the
  serve loop release the orphaned stream's KV blocks and slot after a
  ``stream_idle_timeout`` grace instead of leaking pool capacity until
  ``max_new`` runs out.  The grace window exists so a drain/handover
  can still pick the stream up (:meth:`Pipeline.drain_stream`).
* **Autoscaler** — a 0.5 s daemon loop (the same shape as the SLO
  engine's) that reads the live ``slo.burn_rate{tenant=}`` gauges and
  reacts through a small declarative policy table: flip a tenant class
  from ``block`` to ``shed`` admission on the query front door, raise/
  lower per-tenant ``kv_blocks`` reservation quotas on the continuous
  serve loop, or spill a tenant's live stream to a second pipeline via
  drain/adopt.  Every action is span-stamped (``elastic.scale``;
  drain/adopt stamp their own ``elastic.drain``/``elastic.adopt``) and
  rate-limited with hysteresis (``burn_above``/``burn_below`` bands +
  a per-rule cooldown) so the loop cannot flap.
* **Chaos hooks** — test-only injection points the soak harness's
  ``ChaosController`` (tools/soak.py) uses: :func:`chaos_slow_stage`
  adds latency to a named stage's work function (the ``slow_stage``
  profile) without touching any production code path.
* **Reconfig knob table** — :data:`SERVE_KNOB_SIGNATURE` documents, for
  every continuous-serving knob, whether changing it at runtime is a
  host-value move (quotas, budgets, timeouts) or would change a
  COMPILED program signature (slots, block_size, …).  The deep lint's
  ``recompile-on-reconfig`` diagnostic reads this table and suggests
  the drain → versioned-config restart → adopt path as remediation.

Everything here is host-side value movement: no jax import, no device
dispatch, and the serve loop's closed 3-program census is untouched by
any action this module can take.
"""

from __future__ import annotations

import dataclasses
import itertools
import json
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from ..core.log import logger
from ..core.log import metrics as _global_metrics
#: buffer-meta key carrying the continuous-serving stream id.  App data
#: (JSON-safe int), stamped at submit regardless of trace mode: the
#: dead-connection backchannel must work in untraced deployments too.
#: Declared in the shared protocol registry (core/meta_keys.py).
from ..core.meta_keys import META_STREAM_ID  # noqa: F401  (re-export)
from . import tracing

log = logger(__name__)


# ---------------------------------------------------------------------------
# stream registry: the cancel/orphan backchannel
# ---------------------------------------------------------------------------

_stream_ids = itertools.count(1)
_streams: Dict[int, Callable[[str, bool], None]] = {}
_streams_lock = threading.Lock()


def next_stream_id() -> int:
    """GLOBALLY-unique continuous-serving stream id (minted at submit):
    epoch-prefixed like trace ids (docs/OBSERVABILITY.md "Distributed
    tracing"), so a drained stream adopted by another process never
    collides with the adopter's own ids.  Sampler keys are a function of
    the admission number, not this id, so determinism is unaffected."""
    return (tracing.trace_epoch() << 32) | (next(_stream_ids) & 0xFFFFFFFF)


def register_stream(stream_id: int,
                    cancel_cb: Callable[[str, bool], None]) -> None:
    """Register a live/queued serve stream.  ``cancel_cb(reason, force)``
    must be safe to call from any thread (the serve loop consumes the
    mark at its next chunk boundary)."""
    with _streams_lock:
        _streams[stream_id] = cancel_cb


def unregister_stream(stream_id: int) -> None:
    with _streams_lock:
        _streams.pop(stream_id, None)


def cancel_stream(stream_id, reason: str = "cancelled",
                  force: bool = False) -> bool:
    """Mark one serve stream dead.  ``force=False`` (the dead-connection
    default) gives the stream its loop's ``stream_idle_timeout`` grace
    before its blocks/slot are reaped — a drain/handover can still pick
    it up; ``force=True`` reaps at the next chunk boundary.  Returns
    False for an unknown/already-finished id (idempotent: a serversink
    retrying failed sends may call this once per failed token)."""
    if stream_id is None:
        return False
    try:
        stream_id = int(stream_id)
    except (TypeError, ValueError):
        return False  # not a server-minted id: nothing to cancel
    with _streams_lock:
        cb = _streams.get(stream_id)
    if cb is None:
        return False
    try:
        cb(reason, force)
    except Exception:  # noqa: BLE001 - backchannel must never throw upward
        log.exception("cancel_stream(%s) callback failed", stream_id)
        return False
    return True


def live_stream_ids() -> List[int]:
    """Registered (queued or live) serve stream ids, for tests/tools."""
    with _streams_lock:
        return sorted(_streams)


# ---------------------------------------------------------------------------
# chaos hooks (test-only)
# ---------------------------------------------------------------------------

_slow_stages: Dict[str, float] = {}
_slow_lock = threading.Lock()


def chaos_slow_stage(name: str, extra_s: float) -> None:
    """TEST-ONLY fault injection: add ``extra_s`` seconds of latency to
    the named stage's work function.  Consulted by soak work functions
    (tools/soak.py ``slow_stage`` profile) — no production element reads
    this.  ``extra_s <= 0`` clears the injection."""
    with _slow_lock:
        if extra_s > 0:
            _slow_stages[name] = float(extra_s)
        else:
            _slow_stages.pop(name, None)


def chaos_slow_delay(name: str) -> float:
    """Injected extra latency for ``name`` (0.0 = none)."""
    with _slow_lock:
        return _slow_stages.get(name, 0.0)


def chaos_clear() -> None:
    with _slow_lock:
        _slow_stages.clear()


# ---------------------------------------------------------------------------
# reconfig knob table (read by the deep lint's recompile-on-reconfig)
# ---------------------------------------------------------------------------

#: continuous-serving knobs (``custom=`` options, docs/SERVING.md §4/§7)
#: mapped to whether changing them changes a COMPILED program signature
#: (True — requires the drain → versioned-config restart → adopt path)
#: or only host values (False — safe to mutate on a running loop).
#: ``temperature``/``top_k``/``top_p`` are compiled into the decode
#: closure; ``kv_blocks`` is the pool's static shape; ``slots`` is the
#: decode program's row count; ``stream_chunk`` is the static scan
#: length.  The deep lint (analysis/tracecheck.py) warns
#: ``recompile-on-reconfig`` for any requested change of a True knob.
SERVE_KNOB_SIGNATURE: Dict[str, bool] = {
    "slots": True,
    "block_size": True,
    "kv_blocks": True,
    "prefill_chunk": True,
    "stream_chunk": True,
    "temperature": True,
    "top_k": True,
    "top_p": True,
    "dtype": True,
    # speculative decoding: the draft model's geometry and the verify
    # step's k+1 width are compiled program structure
    "draft": True,
    "spec_k": True,
    "draft_seed": True,
    "max_new": False,
    "prefill_budget": False,
    "admit_timeout": False,
    "stream_idle_timeout": False,
    "seed": False,
    # prefix sharing is host-only state (refcounts + the hash index):
    # flipping it changes admission behavior, never a compiled signature
    "prefix_cache": False,
}


#: defaults of the serving knobs (mirrors LLMFramework.open's opts.pop
#: defaults): a reconfig of an UNSET knob compares against these, so
#: proposing the value a loop already runs with is a no-op, not a
#: spurious recompile warning.  ``prefill_budget`` has no static
#: default (it tracks prefill_chunk) — omitted; it is a host-value knob
#: anyway.
SERVE_KNOB_DEFAULTS: Dict[str, object] = {
    "slots": 4, "block_size": 16, "kv_blocks": 0, "prefill_chunk": 32,
    "stream_chunk": 8, "temperature": 0.0, "top_k": 0, "top_p": 1.0,
    "dtype": "bfloat16", "max_new": 32, "admit_timeout": 30.0,
    "stream_idle_timeout": 5.0, "seed": 0,
    "draft": "", "spec_k": 4, "draft_seed": 0, "prefix_cache": 1,
}


def _knob_equal(a, b) -> bool:
    try:
        return float(a) == float(b)
    except (TypeError, ValueError):
        return str(a) == str(b)


def signature_changes(current: Dict[str, object],
                      reconfig: Dict[str, object]
                      ) -> List[Tuple[str, object, object]]:
    """``(knob, old, new)`` for every requested reconfig knob that is
    documented runtime-mutable-LOOKING but actually changes a compiled
    signature.  ``current`` holds the parsed ``custom=`` options;
    missing keys compare against :data:`SERVE_KNOB_DEFAULTS` (numeric
    comparison where possible, so ``0`` == ``0.0``)."""
    out: List[Tuple[str, object, object]] = []
    for knob, new in (reconfig or {}).items():
        if not SERVE_KNOB_SIGNATURE.get(knob, False):
            continue
        old = current.get(knob, SERVE_KNOB_DEFAULTS.get(knob))
        if old is None or not _knob_equal(old, new):
            out.append((knob, current.get(knob), new))
    return out


# ---------------------------------------------------------------------------
# autoscaler
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class ScaleRule:
    """One policy-table row: when ``tenant``'s burn rate crosses
    ``burn_above``, ENGAGE ``action``; when it falls back under
    ``burn_below``, RELAX it.  ``cooldown_s`` rate-limits both edges, and
    the two bands are the hysteresis that keeps the loop from flapping.

    Actions:

    * ``admission:shed`` / ``admission:downgrade`` — override the query
      front door's admission policy for this tenant (the
      ``_ServerCore.tenant_admission`` map); relax removes the override
      (back to the element's configured policy, typically ``block``).
    * ``kv_quota:N`` — cap the tenant's paged-KV block reservations on
      every continuous serve loop at N blocks (a host-value quota the
      admission step enforces); relax clears the quota.
    * ``spill`` — drain ONE of the tenant's live serve streams and adopt
      it on the autoscaler's ``spill_to`` pipeline.  Re-fires once per
      cooldown while the burn stays above the band (no relax edge —
      adopted streams stay where they landed).
    """

    tenant: str = "*"
    burn_above: float = 1.5
    burn_below: float = 0.5
    action: str = "admission:shed"
    cooldown_s: float = 2.0

    @classmethod
    def from_dict(cls, d: dict) -> "ScaleRule":
        return cls(tenant=str(d.get("tenant", "*")),
                   burn_above=float(d.get("burn_above", 1.5)),
                   burn_below=float(d.get("burn_below", 0.5)),
                   action=str(d.get("action", "admission:shed")),
                   cooldown_s=float(d.get("cooldown_s", 2.0)))

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


_ACTION_KINDS = ("admission", "kv_quota", "spill")


def validate_autoscale_policy(d: dict) -> List[str]:
    """Schema problems of an autoscale policy dict (empty = valid)."""
    problems: List[str] = []
    if not isinstance(d, dict):
        return ["policy must be a JSON object"]
    rules = d.get("rules")
    if not isinstance(rules, list) or not rules:
        problems.append("'rules' must be a non-empty list")
        rules = []
    for i, r in enumerate(rules):
        if not isinstance(r, dict):
            problems.append(f"rules[{i}]: must be an object")
            continue
        action = str(r.get("action", "admission:shed"))
        kind = action.split(":", 1)[0]
        if kind not in _ACTION_KINDS:
            problems.append(
                f"rules[{i}].action: {action!r} (expected one of "
                f"admission:shed|admission:downgrade|kv_quota:N|spill)")
        elif kind == "admission" and action.split(":", 1)[1] not in (
                "shed", "downgrade"):
            problems.append(
                f"rules[{i}].action: admission override must be "
                f"shed|downgrade, got {action!r}")
        elif kind == "kv_quota":
            try:
                if int(action.split(":", 1)[1]) < 0:
                    raise ValueError
            except (IndexError, ValueError):
                problems.append(
                    f"rules[{i}].action: kv_quota needs a block count "
                    f">= 0, got {action!r}")
        for key in ("burn_above", "burn_below", "cooldown_s"):
            v = r.get(key, 1.0)
            if not isinstance(v, (int, float)) or v < 0:
                problems.append(f"rules[{i}].{key}: must be a number >= 0")
        ab, bb = r.get("burn_above", 1.5), r.get("burn_below", 0.5)
        if isinstance(ab, (int, float)) and isinstance(bb, (int, float)) \
                and bb >= ab:
            problems.append(
                f"rules[{i}]: burn_below ({bb}) must be < burn_above "
                f"({ab}) — the hysteresis band must have width")
        unknown = set(r) - {"tenant", "burn_above", "burn_below",
                            "action", "cooldown_s"}
        if unknown:
            problems.append(f"rules[{i}]: unknown keys {sorted(unknown)}")
    unknown = set(d) - {"rules"}
    if unknown:
        problems.append(f"unknown top-level keys {sorted(unknown)}")
    return problems


def load_autoscale_policy(obj) -> List[ScaleRule]:
    """Accepts a list of :class:`ScaleRule`, a ``{"rules": [...]}`` dict,
    or a JSON file path.  Raises ``ValueError`` naming every schema
    problem at once (the ``Pipeline(slo=)`` construction-time contract)."""
    if obj is None:
        return []
    if isinstance(obj, list) and all(isinstance(r, ScaleRule) for r in obj):
        return list(obj)
    if isinstance(obj, str):
        with open(obj) as f:
            obj = json.load(f)
    if not isinstance(obj, dict):
        raise ValueError(
            f"autoscale policy must be rules | dict | path, got {type(obj)}")
    problems = validate_autoscale_policy(obj)
    if problems:
        raise ValueError("invalid autoscale policy: " + "; ".join(problems))
    return [ScaleRule.from_dict(r) for r in obj["rules"]]


class Autoscaler:
    """Burn-rate-driven control loop over one pipeline's front door.

    Reads ``slo.burn_rate{tenant=}`` from the live registry (published by
    the SLO engine's own 0.5 s loop — ``Pipeline(slo=...)`` must be
    active) and applies the policy table with hysteresis.  Every action
    is recorded in :attr:`actions` (the soak row's audit trail) and
    span-stamped ``elastic.scale`` on the flight recorder; spill rides
    the pipeline's own ``elastic.drain``/``elastic.adopt`` spans.

    >>> scaler = Autoscaler(srv, {"rules": [
    ...     {"tenant": "*", "burn_above": 1.5, "action": "admission:shed"},
    ... ]})
    >>> scaler.start()   # 0.5 s daemon, like the SLO engine
    """

    _GUARDED_BY = {"_state": "_lock", "actions": "_lock"}

    def __init__(self, pipeline, policy, *, spill_to=None,
                 metrics=None, recorder: Optional[tracing.FlightRecorder]
                 = None):
        self.pipeline = pipeline
        self.rules = load_autoscale_policy(policy)
        self.spill_to = spill_to
        self.metrics = metrics if metrics is not None else _global_metrics
        self.recorder = recorder
        #: audit trail: dicts {t, tenant, action, edge, burn}
        self.actions: List[dict] = []
        #: per-(rule index, tenant) state: {"engaged": bool, "last": t}
        self._state: Dict[Tuple[int, str], dict] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- target discovery --------------------------------------------------
    def _server_cores(self) -> list:
        cores = []
        for el in getattr(self.pipeline, "elements", {}).values():
            core = getattr(el, "_core", None)
            if core is not None and hasattr(core, "tenant_admission"):
                cores.append(core)
        return cores

    def _serve_loops(self, pipeline=None) -> list:
        loops = []
        for el in getattr(pipeline or self.pipeline,
                          "elements", {}).values():
            fw = getattr(el, "fw", None)
            if fw is not None and getattr(fw, "continuous", False):
                loop = getattr(fw, "_serve", None)
                if loop is not None:
                    loops.append(loop)
        return loops

    # -- evaluation --------------------------------------------------------
    def _burns(self) -> Dict[str, float]:
        return {tenant: v for (name, tenant), v
                in self.metrics.labeled_gauges().items()
                if name == "slo.burn_rate"}

    def _span(self, action: str, tenant: str, burn: float,
              edge: str) -> None:
        rec = self.recorder if self.recorder is not None \
            else (tracing.recorder if tracing.recorder.active else None)
        if rec is not None:
            rec.record("elastic.scale", "elastic", None,
                       time.monotonic_ns(), 0, action=action,
                       tenant=tenant, burn=round(burn, 3), edge=edge)

    def _record(self, action: str, tenant: str, burn: float,
                edge: str) -> None:
        self.actions.append({"t": time.monotonic(), "tenant": tenant,
                             "action": action, "edge": edge,
                             "burn": burn})
        self._span(action, tenant, burn, edge)
        log.info("autoscaler: %s %s for tenant %s (burn %.2f)",
                 edge, action, tenant, burn)

    def _apply(self, rule: ScaleRule, tenant: str, burn: float,
               engage: bool) -> bool:
        """One action edge; returns True when it took effect."""
        kind, _, arg = rule.action.partition(":")
        if kind == "admission":
            cores = self._server_cores()
            if not cores:
                return False
            for core in cores:
                if engage:
                    core.tenant_admission[tenant] = arg
                else:
                    core.tenant_admission.pop(tenant, None)
            return True
        if kind == "kv_quota":
            loops = self._serve_loops()
            if not loops:
                return False
            quota = int(arg) if engage else None
            for loop in loops:
                loop.set_tenant_quota(tenant, quota)
            return True
        if kind == "spill":
            if not engage or self.spill_to is None:
                return False
            return self._spill_one(tenant)
        return False

    def _spill_one(self, tenant: str) -> bool:
        """Drain one of ``tenant``'s live serve streams from the primary
        pipeline and adopt it on ``spill_to``."""
        try:
            streams = self.pipeline.serve_streams()
        except Exception:  # noqa: BLE001 - no serve surface: nothing to do
            return False
        for sid, info in sorted(streams.items()):
            if info.get("state") != "live":
                continue
            if tenant not in ("*", info.get("tenant")):
                continue
            try:
                snap = self.pipeline.drain_stream(sid, timeout=10.0)
            except Exception:  # noqa: BLE001 - next candidate
                log.exception("autoscaler: drain of stream %s failed", sid)
                continue
            try:
                self.spill_to.adopt_stream(snap, timeout=10.0)
                return True
            except Exception:  # noqa: BLE001 - spill target refused
                # the snapshot is the ONLY copy of the stream now: put
                # it back where it came from (its slot was just freed,
                # so the home pipeline can re-admit it) rather than
                # letting a full spill target silently kill the client
                log.exception(
                    "autoscaler: spill target refused stream %s; "
                    "re-adopting at home", sid)
                try:
                    self.pipeline.adopt_stream(snap, timeout=10.0)
                except Exception:  # noqa: BLE001 - truly lost
                    log.critical(
                        "autoscaler: stream %s lost in spill (drain "
                        "succeeded, both adopts failed)", sid)
                # a refusing target is almost certainly FULL: back off
                # until the next cooldown instead of bouncing every
                # remaining stream through a drain/re-adopt hiccup
                return False
        return False

    def evaluate(self) -> int:
        """One control tick; returns the number of action edges taken."""
        burns = self._burns()
        now = time.monotonic()
        edges = 0
        with self._lock:
            for i, rule in enumerate(self.rules):
                tenants = (sorted(burns) if rule.tenant == "*"
                           else [rule.tenant])
                for tenant in tenants:
                    burn = burns.get(tenant, 0.0)
                    st = self._state.setdefault(
                        (i, tenant), {"engaged": False, "last": 0.0})
                    if now - st["last"] < rule.cooldown_s:
                        continue
                    if burn >= rule.burn_above and (
                            not st["engaged"]
                            or rule.action == "spill"):
                        if self._apply(rule, tenant, burn, engage=True):
                            st.update(engaged=True, last=now)
                            self._record(rule.action, tenant, burn,
                                         "engage")
                            edges += 1
                    elif st["engaged"] and burn <= rule.burn_below:
                        if self._apply(rule, tenant, burn, engage=False):
                            st.update(engaged=False, last=now)
                            self._record(rule.action, tenant, burn,
                                         "relax")
                            edges += 1
        return edges

    # -- continuous mode ---------------------------------------------------
    def start(self, period_s: float = 0.5) -> "Autoscaler":
        if self._thread is not None:
            return self
        self._stop.clear()

        def loop():
            while not self._stop.wait(period_s):
                try:
                    self.evaluate()
                except Exception:  # noqa: BLE001 - must never die loud
                    log.exception("autoscaler tick failed")

        self._thread = threading.Thread(target=loop, name="nns-elastic",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2.0)
