"""MQTT-lite broker: standalone topic-routed pub/sub over TCP.

Reference analog (SURVEY §2.7): the reference's ``mqttsrc``/``mqttsink``
publish GstBuffers through an external paho-mqtt broker; nnstreamer-edge's
MQTT-hybrid mode uses a broker for discovery.  No MQTT stack exists in this
environment, so the TPU build ships its own minimal broker speaking the
framework wire protocol — same role, same topology (N publishers, M
subscribers, a broker in between), none of the protocol baggage.

Semantics kept from MQTT:

* topic filters with ``#`` (multi-level, suffix) and ``+`` (single level);
* retained messages: a subscriber immediately receives the last retained
  message of every matching topic;
* QoS 0 only — fire-and-forget, slow subscribers drop oldest.

Control frames are JSON (type=hello/ack/sub/pub); payload frames carry
``topic`` in the buffer meta.
"""

from __future__ import annotations

import queue as _queue
import socket
import threading
from typing import Dict, List, Optional, Tuple

from ..core.log import logger
from . import wire
from .net import TcpListener, parse_control

log = logger(__name__)


def topic_matches(pattern: str, topic: str) -> bool:
    """MQTT-style matching: ``a/+/c`` one level, ``a/#`` any suffix."""
    if pattern in ("", "#"):
        return True
    pp = pattern.split("/")
    tp = topic.split("/")
    for i, seg in enumerate(pp):
        if seg == "#":
            return True
        if i >= len(tp):
            return False
        if seg == "+":
            continue
        if seg != tp[i]:
            return False
    return len(pp) == len(tp)


class MqttLiteBroker:
    """Threaded broker; one instance per process, many topics."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 max_queue: int = 64, retain: bool = True):
        self.host = host
        self.max_queue = max_queue
        self.retain_enabled = retain
        self._subs: Dict[int, Tuple[str, _queue.Queue]] = {}
        self._retained: Dict[str, bytes] = {}
        self._lock = threading.Lock()
        self._next_id = 0
        self._port = port
        self._listener: Optional[TcpListener] = None

    @property
    def port(self) -> int:
        return self._listener.port if self._listener else self._port

    def start(self) -> "MqttLiteBroker":
        if self._listener is None:
            self._listener = TcpListener(
                self.host, self._port, self._session, name="mqtt-broker"
            )
        return self

    def stop(self) -> None:
        if self._listener is not None:
            self._listener.close()
            self._listener = None  # lets start() rebind; also the signal
            # session threads poll via _stopping()
        with self._lock:
            for _, q in self._subs.values():
                self._offer(q, None)
            self._subs.clear()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- client session ----------------------------------------------------
    def _session(self, conn: socket.socket) -> None:
        from .net import finish_server_handshake

        conn.settimeout(0.2)
        hello = parse_control(self._read_idle(conn))
        hello = finish_server_handshake(conn, hello, ("pub", "sub"))
        if hello is None:
            conn.close()
            return
        if hello["type"] == "pub":
            self._pub_loop(conn, str(hello.get("topic", "")))
        else:
            self._sub_loop(conn, str(hello.get("topic", "#")))

    def _stopping(self) -> bool:
        # session threads may observe stop() clearing _listener mid-read:
        # a vanished listener means "stopping", never an AttributeError
        listener = self._listener
        return listener is None or listener.stopping.is_set()

    def _read_idle(self, conn) -> Optional[bytes]:
        while not self._stopping():
            try:
                return wire.read_frame(conn)
            except socket.timeout:
                continue
            except (OSError, ValueError):
                return None
        return None

    def _pub_loop(self, conn: socket.socket, default_topic: str) -> None:
        while not self._stopping():
            try:
                frame = wire.read_frame(conn)
            except socket.timeout:
                continue
            except (OSError, ValueError):
                break
            if frame is None:
                break
            self.publish_raw(frame, default_topic)
        conn.close()

    def subscriber_count(self, topic: Optional[str] = None) -> int:
        """Live subscriptions (optionally: those whose pattern matches
        ``topic``).  Lets publishers/tests wait for a subscriber to be
        registered instead of racing the SUBSCRIBE against the first
        QoS-0 publish (which is simply lost if it wins the race)."""
        with self._lock:
            if topic is None:
                return len(self._subs)
            return sum(1 for pat, _ in self._subs.values()
                       if topic_matches(pat, topic))

    def publish_raw(self, frame: bytes, default_topic: str = "") -> None:
        """Route one encoded-buffer frame to matching subscribers."""
        topic = default_topic
        try:  # topic override rides in buffer meta
            buf, _ = wire.decode_buffer(frame)
            topic = str(buf.meta.get("topic", default_topic))
        except ValueError:
            pass
        with self._lock:
            if self.retain_enabled:
                self._retained[topic] = frame
            targets = [q for (pat, q) in self._subs.values() if topic_matches(pat, topic)]
        for q in targets:
            self._offer(q, frame)

    def _sub_loop(self, conn: socket.socket, pattern: str) -> None:
        q: _queue.Queue = _queue.Queue(maxsize=self.max_queue)
        with self._lock:
            sid = self._next_id
            self._next_id += 1
            self._subs[sid] = (pattern, q)
            backlog = [
                f for t, f in self._retained.items() if topic_matches(pattern, t)
            ] if self.retain_enabled else []
        for f in backlog:
            self._offer(q, f)
        try:
            while not self._stopping():
                try:
                    item = q.get(timeout=0.2)
                except _queue.Empty:
                    continue
                if item is None:
                    break
                try:
                    wire.write_frame(conn, item)
                except OSError:
                    break
        finally:
            with self._lock:
                self._subs.pop(sid, None)
            conn.close()

    def _offer(self, q: _queue.Queue, item) -> None:
        while True:
            try:
                q.put_nowait(item)
                return
            except _queue.Full:
                try:
                    q.get_nowait()  # QoS 0: drop oldest
                except _queue.Empty:
                    pass
