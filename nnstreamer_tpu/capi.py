"""Embedding bridge for the native C API (native/src/nnstpu_capi.cpp).

Reference analog: the external ML C-API's single-shot surface —
``ml_single_open`` / ``ml_single_invoke`` / ``ml_single_close`` — which
wraps ``gsttensor_filter_single.c`` (SURVEY §3.5).  Here the C library
embeds CPython and calls THIS module; tensors cross the boundary as raw
little-endian bytes and are shaped/typed from the model's negotiated
specs, exactly like the reference's ``ml_tensors_data`` payloads.

The functions use integer handles (not PyObject pointers) so the C side
never manages Python object lifetimes.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Tuple

import numpy as np

from .core.types import TensorsSpec, dtype_name, dims_to_string

_handles: Dict[int, object] = {}
_next_id = [1]
_lock = threading.Lock()


def _on_fresh_embed() -> None:
    """Called by the C library ONLY when it created the interpreter: the
    process env is the sole configuration channel there, so JAX_PLATFORMS
    is honored.  When loaded into an existing Python process this never
    runs — a host app's programmatic jax.config pin wins (the library
    invariant from core/platform.py)."""
    from .core.platform import honor_jax_platforms

    honor_jax_platforms()


def _spec_str(spec: TensorsSpec) -> str:
    """``dims,dtype`` per tensor, ';'-joined: "3:8:8:1,float32;..." """
    if spec is None:
        return ""
    return ";".join(t.to_string() for t in spec.specs)


def single_open(model: str, framework: str = "auto",
                custom: str = "") -> int:
    """Returns a handle id; raises with a clear message on failure."""
    from .elements.filter import SingleShot

    props = {}
    if custom:
        props["custom"] = custom
    s = SingleShot(framework=framework or "auto", model=model, **props)
    with _lock:
        hid = _next_id[0]
        _next_id[0] += 1
        _handles[hid] = s
    return hid


def _get(hid: int):
    s = _handles.get(int(hid))
    if s is None:
        raise KeyError(f"invalid single-shot handle {hid}")
    return s


def single_info(hid: int) -> Tuple[str, str]:
    s = _get(hid)
    return _spec_str(s.in_spec), _spec_str(s.out_spec)


def single_invoke_bytes(hid: int, blobs: List[bytes]) -> List[bytes]:
    s = _get(hid)
    specs = s.in_spec.specs if s.in_spec is not None else None
    if specs is None:
        raise ValueError(
            "model has no static input spec; the C API needs one to type "
            "raw byte payloads")
    if len(blobs) != len(specs):
        raise ValueError(
            f"model takes {len(specs)} input tensor(s), got {len(blobs)}")
    arrays = []
    for i, (blob, spec) in enumerate(zip(blobs, specs)):
        if len(blob) != spec.nbytes:
            raise ValueError(
                f"input {i}: {len(blob)} bytes, spec "
                f"{dims_to_string(spec.dims)},{dtype_name(spec.dtype)} "
                f"needs {spec.nbytes}")
        arrays.append(
            np.frombuffer(blob, dtype=spec.dtype).reshape(spec.shape))
    outs = s.invoke(arrays)
    return [np.ascontiguousarray(o).tobytes() for o in outs]


def single_close(hid: int) -> None:
    with _lock:
        s = _handles.pop(int(hid), None)
    if s is not None:
        s.close()
