"""Embedding bridge for the native C API (native/src/nnstpu_capi.cpp).

Reference analog: the external ML C-API's single-shot surface —
``ml_single_open`` / ``ml_single_invoke`` / ``ml_single_close`` — which
wraps ``gsttensor_filter_single.c`` (SURVEY §3.5).  Here the C library
embeds CPython and calls THIS module; tensors cross the boundary as raw
little-endian bytes and are shaped/typed from the model's negotiated
specs, exactly like the reference's ``ml_tensors_data`` payloads.

The functions use integer handles (not PyObject pointers) so the C side
never manages Python object lifetimes.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Tuple

import numpy as np

from .core.types import TensorsSpec, dtype_name, dims_to_string

_handles: Dict[int, object] = {}
_next_id = [1]
_lock = threading.Lock()


def _on_fresh_embed() -> None:
    """Called by the C library ONLY when it created the interpreter: the
    process env is the sole configuration channel there, so JAX_PLATFORMS
    is honored.  When loaded into an existing Python process this never
    runs — a host app's programmatic jax.config pin wins (the library
    invariant from core/platform.py)."""
    from .core.platform import honor_jax_platforms

    honor_jax_platforms()


def _spec_str(spec: TensorsSpec) -> str:
    """``dims,dtype`` per tensor, ';'-joined: "3:8:8:1,float32;..." """
    if spec is None:
        return ""
    return ";".join(t.to_string() for t in spec.specs)


def _register(kind: str, obj) -> int:
    with _lock:
        hid = _next_id[0]
        _next_id[0] += 1
        _handles[hid] = (kind, obj)
    return hid


def _get(hid: int, kind: str):
    entry = _handles.get(int(hid))
    if entry is None:
        raise KeyError(f"invalid {kind} handle {hid}")
    if entry[0] != kind:
        # nnstpu_single_h and nnstpu_pipeline_h are both long long in C —
        # a cross-surface mixup must fail loudly, not corrupt state
        raise TypeError(
            f"handle {hid} is a {entry[0]} handle, not {kind}")
    return entry[1]


def single_open(model: str, framework: str = "auto",
                custom: str = "") -> int:
    """Returns a handle id; raises with a clear message on failure."""
    from .elements.filter import SingleShot

    props = {}
    if custom:
        props["custom"] = custom
    s = SingleShot(framework=framework or "auto", model=model, **props)
    return _register("single", s)


def single_info(hid: int) -> Tuple[str, str]:
    s = _get(hid, "single")
    return _spec_str(s.in_spec), _spec_str(s.out_spec)


def single_invoke_bytes(hid: int, blobs: List[bytes]) -> List[bytes]:
    s = _get(hid, "single")
    specs = s.in_spec.specs if s.in_spec is not None else None
    if specs is None:
        raise ValueError(
            "model has no static input spec; the C API needs one to type "
            "raw byte payloads")
    if len(blobs) != len(specs):
        raise ValueError(
            f"model takes {len(specs)} input tensor(s), got {len(blobs)}")
    arrays = []
    for i, (blob, spec) in enumerate(zip(blobs, specs)):
        if len(blob) != spec.nbytes:
            raise ValueError(
                f"input {i}: {len(blob)} bytes, spec "
                f"{dims_to_string(spec.dims)},{dtype_name(spec.dtype)} "
                f"needs {spec.nbytes}")
        arrays.append(
            np.frombuffer(blob, dtype=spec.dtype).reshape(spec.shape))
    outs = s.invoke(arrays)
    return [np.ascontiguousarray(o).tobytes() for o in outs]


def single_close(hid: int) -> None:
    _get(hid, "single")  # loud type/validity check BEFORE unregistering
    with _lock:
        entry = _handles.pop(int(hid), None)
    if entry is not None:
        entry[1].close()


# -- pipeline surface (reference: ml_pipeline_construct / src_input_data /
#    sink callbacks / destroy over the gst-launch DSL, SURVEY §3.1-3.3) ----

def pipeline_open(desc: str) -> int:
    """Construct AND start a pipeline from the gst-launch-style string."""
    from . import Pipeline

    p = Pipeline(desc)
    p.start()
    return _register("pipeline", p)


def pipeline_push(hid: int, name: str, blobs: List[bytes]) -> None:
    """Feed one buffer (one blob per tensor) into appsrc ``name``; bytes
    are typed/shaped from the source's negotiated caps spec, or ride as
    raw uint8 when the caps carry none (the reference's flexible path)."""
    p = _get(hid, "pipeline")
    el = p.element(name)
    spec = getattr(el, "_caps", None)
    spec = spec.spec if spec is not None else None
    if spec is not None and spec.specs and not spec.is_flexible:
        if len(blobs) != len(spec.specs):
            raise ValueError(
                f"appsrc {name!r} caps carry {len(spec.specs)} tensor(s), "
                f"got {len(blobs)}")
        arrays = []
        for i, (blob, t) in enumerate(zip(blobs, spec.specs)):
            if len(blob) != t.nbytes:
                raise ValueError(
                    f"tensor {i}: {len(blob)} bytes, spec {t.to_string()} "
                    f"needs {t.nbytes}")
            arrays.append(np.frombuffer(blob, t.dtype).reshape(t.shape))
        p.push(name, arrays)
    elif spec is not None and spec.specs:
        # FLEXIBLE stream: per-buffer sizes legally vary — type each blob
        # from the caps dtype and ride rank-1 (per-buffer shape is the
        # producer's business, exactly like Pipeline.push of a raw array)
        p.push(name, [np.frombuffer(b, spec.specs[min(i, len(spec.specs) - 1)].dtype)
                      for i, b in enumerate(blobs)])
    else:
        p.push(name, [np.frombuffer(b, np.uint8) for b in blobs])


def pipeline_pull(hid: int, name: str,
                  timeout: float = 30.0) -> Tuple[List[bytes], str]:
    """Pop one buffer from sink ``name``: (per-tensor bytes, spec desc)."""
    p = _get(hid, "pipeline")
    buf = p.pull(name, timeout=timeout)
    arrays = [np.ascontiguousarray(np.asarray(t)) for t in buf.tensors]
    desc = ";".join(
        f"{dims_to_string(tuple(reversed(a.shape)))},{dtype_name(a.dtype)}"
        for a in arrays)
    return [a.tobytes() for a in arrays], desc


def pipeline_eos(hid: int, name: str = "") -> None:
    p = _get(hid, "pipeline")
    if name:
        p.eos(name)
    else:
        p.eos()


def pipeline_close(hid: int) -> None:
    _get(hid, "pipeline")  # loud type/validity check BEFORE unregistering
    with _lock:
        entry = _handles.pop(int(hid), None)
    if entry is not None:
        entry[1].stop()
