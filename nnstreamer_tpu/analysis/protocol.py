"""nns-proto: message-alphabet lint + model drift gate for the
distributed serving protocols.

The wire/handshake surface (elements/query.py, utils/net.py,
utils/wire.py, utils/journal.py, utils/elastic.py, utils/armor.py,
filters/llm.py streaming terminators) speaks a closed vocabulary:
protocol meta keys (core/meta_keys.py — the registry this lint treats
as ground truth), JSON control-frame types (hello/ack/nack), typed
``abort_reason`` values, journal/DLQ record magics and snapshot version
tags.  This pass extracts that vocabulary from the AST — every kind the
code CONSTRUCTS or SENDS and every kind it DISPATCHES on or HANDLES —
and reports:

``meta-key-drift`` (error)
    a protocol meta literal (or control kind / abort reason) used in a
    meta context that is not declared in the core/meta_keys.py registry.
``unhandled-message`` (error)
    a registered kind the linted set sends/stamps but never reads —
    a message nobody is listening for.
``dead-handler`` (warning)
    a registered kind the linted set reads/dispatches on but never
    sends — handler code for a message that cannot arrive.
``unanswered-path`` (error)
    reusing the nns-tsan fixpoint call-proof: a server-side handler
    path that can exit — return, fall through, or raise — after it has
    touched a request's routing meta, without answering, shedding,
    aborting (typed), quarantining, or at least accounting the drop.
    Each such path is a client timeout waiting to happen.
``model-alphabet-drift`` (error) / ``model-alphabet-surplus`` (warning)
    the model-vs-code gate: the union of the shipped protocol models'
    declared alphabets (analysis/statemachine.py) must equal the
    AST-extracted one, so a new message kind (e.g. future kv-transfer
    frames) without a model update is a CI failure, not a latent gap.

Conventions the proof understands (mirrors how the runtime answers):

* answering calls: a method named ``send``, ``quarantine``,
  ``cancel_stream`` or ``poison_terminator``, or containing ``answer``,
  ``reply``, ``abort``, ``shed``, ``reject``, ``ack_journal`` or
  ``send_failed`` — or any local function PROVEN all-paths-answering by
  the fixpoint;
* accounted drops: ``metrics.count(...)`` whose metric name contains
  ``dropped`` or ``shed`` (the path is visible on a dashboard, which is
  the lint's bar for "not a silent strand");
* the obligation ARMS at the first read of a routing meta key
  (``_query_msg`` / ``_query_conn`` / ``_query_batch``): exits before
  the handler has a message in hand (config guards, pre-admission
  rejects) are exempt;
* a loop whose body answers on every path satisfies the obligation for
  the code after it (per-row batch fan-out: each message is answered
  inside its iteration).

Handlers are methods named ``process`` on classes whose name contains
``ServerSink``, plus any function named ``handle_*`` (the explicit
convention for fixtures and future protocol servers).

This module is jax-free at import (pure ``ast``), like concurrency.py:
it runs inside CI on machines with no accelerator stack.  See
docs/ANALYSIS.md "Protocol pass".
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from .diagnostics import ERROR, WARNING, Diagnostic, Report

__all__ = [
    "CODES", "PROTOCOL_MODULES", "Registry", "load_registry",
    "lint_paths", "lint_package", "package_root", "baseline_key",
    "extracted_alphabet",
]

CODES = {
    "meta-key-drift": ERROR,
    "unhandled-message": ERROR,
    "dead-handler": WARNING,
    "unanswered-path": ERROR,
    "model-alphabet-drift": ERROR,
    "model-alphabet-surplus": WARNING,
}

#: the protocol surface, relative to the package root — the dogfood set
PROTOCOL_MODULES = (
    "elements/query.py",
    "utils/net.py",
    "utils/wire.py",
    "utils/journal.py",
    "utils/elastic.py",
    "utils/armor.py",
    "filters/llm.py",
)

#: reading one of these arms the unanswered-path obligation: the
#: handler now holds a routed message it owes a verdict
_ROUTING_KEYS = ("_query_msg", "_query_conn", "_query_batch")

_ANSWER_EXACT = frozenset({"send", "quarantine", "cancel_stream",
                           "poison_terminator"})
_ANSWER_SUBSTR = ("answer", "reply", "abort", "shed", "reject",
                  "ack_journal", "send_failed")
_DROP_METRIC = re.compile(r"dropped|shed")

_META_NAME = re.compile(r"^(meta|metas|m|out_meta|in_meta|resp_meta"
                        r"|meta_\w+|\w+_meta)$")
_MAGIC_NAME = re.compile(r"^(MAGIC_(?P<suf>\w+)|(?P<pre>\w+)_MAGIC|MAGIC)$")


def _pos(line_starts: List[int], node: ast.AST) -> int:
    """Global char offset of ``node`` (the Report caret contract)."""
    return line_starts[node.lineno - 1] + node.col_offset


def _line_starts(source: str) -> List[int]:
    starts, n = [0], 0
    for ln in source.splitlines(keepends=True):
        n += len(ln)
        starts.append(n)
    return starts


def package_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# the registry (core/meta_keys.py), loaded by AST so fixtures can ship
# their own and the lint never imports runtime code
# ---------------------------------------------------------------------------

class Registry:
    def __init__(self):
        self.names: Dict[str, str] = {}      # constant name -> value
        self.meta_keys: Set[str] = set()     # PROTOCOL_META_KEYS values
        self.control: Set[str] = set()       # CONTROL_TYPES values
        self.abort: Set[str] = set()         # ABORT_REASONS values
        self.external: Set[str] = set()      # EXTERNAL_META_KEYS values


def load_registry(root: Optional[str] = None) -> Registry:
    """Parse ``<root>/core/meta_keys.py`` (falling back to the real
    package's) into a :class:`Registry`.  Only simple forms are
    understood — ``NAME = "literal"`` and ``NAME = frozenset({...})`` —
    which is exactly what the registry module restricts itself to."""
    path = os.path.join(root or package_root(), "core", "meta_keys.py")
    if not os.path.exists(path):
        path = os.path.join(package_root(), "core", "meta_keys.py")
    reg = Registry()
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    sets = {"PROTOCOL_META_KEYS": reg.meta_keys,
            "CONTROL_TYPES": reg.control,
            "ABORT_REASONS": reg.abort,
            "EXTERNAL_META_KEYS": reg.external}
    for node in tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            continue
        name, val = node.targets[0].id, node.value
        if isinstance(val, ast.Constant) and isinstance(val.value, str):
            reg.names[name] = val.value
        elif isinstance(val, ast.Call) and isinstance(val.func, ast.Name) \
                and val.func.id == "frozenset" and val.args \
                and isinstance(val.args[0], ast.Set) and name in sets:
            for el in val.args[0].elts:
                if isinstance(el, ast.Constant) and isinstance(el.value, str):
                    sets[name].add(el.value)
                elif isinstance(el, ast.Name) and el.id in reg.names:
                    sets[name].add(reg.names[el.id])
    return reg


# ---------------------------------------------------------------------------
# per-file extraction
# ---------------------------------------------------------------------------

def _is_meta_expr(node: ast.AST) -> bool:
    if isinstance(node, ast.Attribute) and node.attr == "meta":
        return True
    if isinstance(node, ast.Name) and _META_NAME.match(node.id):
        return True
    if isinstance(node, ast.Subscript) \
            and isinstance(node.slice, ast.Constant) \
            and node.slice.value == "meta":
        return True
    return False


class _Use:
    __slots__ = ("kind", "value", "pos", "func")

    def __init__(self, kind: str, value: str, pos: int, func: str):
        self.kind = kind    # meta-write|meta-read|ctrl-send|ctrl-handle|
        self.value = value  # abort-send|abort-handle
        self.pos = pos
        self.func = func


class _FileFacts(ast.NodeVisitor):
    """One linted file: symbol table, every alphabet use site, every
    function body (for the unanswered-path proof)."""

    def __init__(self, path: str, rel: str, source: str, tree: ast.Module,
                 reg: Registry):
        self.path, self.rel, self.source = path, rel, source
        self.reg = reg
        self.line_starts = _line_starts(source)
        self.syms: Dict[str, str] = {}          # local alias -> key value
        self.uses: List[_Use] = []
        self.records: Set[str] = set()          # record:<NAME> kinds
        self.snapshots: Set[str] = set()        # snapshot:v<N> tags
        #: qualname -> (FunctionDef, class name or "")
        self.funcs: Dict[str, Tuple[ast.AST, str]] = {}
        self._stack: List[str] = []
        self._class: List[str] = []
        self._module_consts(tree)
        self.visit(tree)

    # -- symbol table -----------------------------------------------------
    def _module_consts(self, tree: ast.Module) -> None:
        for node in tree.body:
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                for alias in getattr(node, "names", []):
                    tgt = alias.asname or alias.name
                    if alias.name in self.reg.names:
                        self.syms[tgt] = self.reg.names[alias.name]
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                name = node.targets[0].id
                val = self._resolve(node.value)
                if val is not None:
                    self.syms[name] = val
                m = _MAGIC_NAME.match(name)
                if m and isinstance(node.value, ast.Constant) \
                        and isinstance(node.value.value, int):
                    suf = m.group("suf") or m.group("pre") or "FRAME"
                    self.records.add(f"record:{suf}")

    def _resolve(self, node: ast.AST) -> Optional[str]:
        """Resolve an expression to a protocol string: literal, local
        alias, or an attribute of the registry (``meta_keys.META_X`` —
        or any module re-exporting a registry name)."""
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value
        if isinstance(node, ast.Name):
            return self.syms.get(node.id)
        if isinstance(node, ast.Attribute):
            return self.reg.names.get(node.attr)
        return None

    # -- use collection ---------------------------------------------------
    def _fn(self) -> str:
        return ".".join(self._stack) if self._stack else "<module>"

    def _use(self, kind: str, node: ast.AST, key: ast.AST) -> None:
        val = self._resolve(key)
        if val is not None:
            self.uses.append(_Use(kind, val,
                                  _pos(self.line_starts, key), self._fn()))

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._class.append(node.name)
        self._stack.append(node.name)
        self.generic_visit(node)
        self._stack.pop()
        self._class.pop()

    def _visit_func(self, node) -> None:
        self._stack.append(node.name)
        qual = self._fn()
        self.funcs[qual] = (node, self._class[-1] if self._class else "")
        self.generic_visit(node)
        self._stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def visit_Subscript(self, node: ast.Subscript) -> None:
        if _is_meta_expr(node.value):
            kind = "meta-write" if isinstance(node.ctx, ast.Store) \
                else "meta-read"
            self._use(kind, node, node.slice)
        self.generic_visit(node)

    def visit_Compare(self, node: ast.Compare) -> None:
        # K in <meta>  /  K not in <meta>
        if len(node.ops) == 1 and isinstance(node.ops[0],
                                             (ast.In, ast.NotIn)) \
                and _is_meta_expr(node.comparators[0]):
            self._use("meta-read", node, node.left)
        # <x>.get("type") == "kind"  (control dispatch)
        if len(node.ops) == 1 and isinstance(node.ops[0],
                                             (ast.Eq, ast.NotEq, ast.In,
                                              ast.NotIn)):
            if self._is_type_get(node.left):
                comp = node.comparators[0]
                elts = comp.elts if isinstance(comp, (ast.Tuple, ast.List,
                                                      ast.Set)) else [comp]
                for el in elts:
                    self._use("ctrl-handle", node, el)
            # abort-reason dispatch: meta.get("abort_reason") == "wire"
            if self._is_abort_get(node.left):
                self._use("abort-handle", node, node.comparators[0])
        self.generic_visit(node)

    def _is_type_get(self, node: ast.AST) -> bool:
        return (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "get" and node.args
                and self._resolve(node.args[0]) == "type")

    def _is_abort_get(self, node: ast.AST) -> bool:
        return (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "get" and node.args
                and self._resolve(node.args[0]) == "abort_reason")

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        if isinstance(fn, ast.Attribute) and _is_meta_expr(fn.value):
            if fn.attr in ("get", "pop", "setdefault") and node.args:
                self._use("meta-read", node, node.args[0])
                if fn.attr == "setdefault":
                    self._use("meta-write", node, node.args[0])
            elif fn.attr == "update":
                for arg in node.args:
                    if isinstance(arg, ast.Dict):
                        self._dict_keys("meta-write", arg)
                for kw in node.keywords:
                    if kw.arg is not None:
                        self.uses.append(_Use(
                            "meta-write", kw.arg,
                            _pos(self.line_starts, kw.value), self._fn()))
        name = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else "")
        if name == "client_handshake" and len(node.args) >= 2:
            self._use("ctrl-send", node, node.args[1])
        elif name == "server_handshake" and len(node.args) >= 2:
            self._ctrl_expect(node.args[1])
        elif name == "finish_server_handshake" and len(node.args) >= 3:
            self._ctrl_expect(node.args[2])
        for kw in node.keywords:
            if kw.arg == "meta" and isinstance(kw.value, ast.Dict):
                self._dict_keys("meta-write", kw.value)
        self.generic_visit(node)

    def _ctrl_expect(self, arg: ast.AST) -> None:
        elts = arg.elts if isinstance(arg, (ast.Tuple, ast.List)) else [arg]
        for el in elts:
            self._use("ctrl-handle", el, el)

    def visit_Dict(self, node: ast.Dict) -> None:
        # {"type": "kind", ...} constructs a control message
        for k, v in zip(node.keys, node.values):
            if k is None:
                continue
            kv = self._resolve(k)
            if kv == "type" and self._resolve(v) is not None:
                self._use("ctrl-send", node, v)
            if kv == "version" and isinstance(v, ast.Constant) \
                    and isinstance(v.value, int):
                self.snapshots.add(f"snapshot:v{v.value}")
            if kv == "abort_reason" and self._resolve(v) is not None:
                self._use("abort-send", node, v)
        # {**meta, "k": v}: an updated meta dict rides on
        if any(k is None and _is_meta_expr(v)
               for k, v in zip(node.keys, node.values)):
            self._dict_keys("meta-write", node)
        self.generic_visit(node)

    def _dict_keys(self, kind: str, node: ast.Dict) -> None:
        for k, v in zip(node.keys, node.values):
            if k is None:
                continue
            self._use(kind, node, k)
            if self._resolve(k) == "abort_reason" \
                    and self._resolve(v) is not None:
                self._use("abort-send", node, v)

    def visit_Assign(self, node: ast.Assign) -> None:
        # meta[K] = <abort reason constant>?
        for tgt in node.targets:
            if isinstance(tgt, ast.Subscript) and _is_meta_expr(tgt.value) \
                    and self._resolve(tgt.slice) == "abort_reason" \
                    and self._resolve(node.value) is not None:
                self._use("abort-send", node, node.value)
        self.generic_visit(node)


# ---------------------------------------------------------------------------
# unanswered-path: fixpoint call-proof over explicit exits
# ---------------------------------------------------------------------------

class _Exit:
    __slots__ = ("kind", "pos", "answered", "armed")

    def __init__(self, kind, pos, answered, armed):
        self.kind, self.pos = kind, pos
        self.answered, self.armed = answered, armed


class _PathState:
    __slots__ = ("answered", "armed")

    def __init__(self, answered=False, armed=False):
        self.answered, self.armed = answered, armed

    def copy(self):
        return _PathState(self.answered, self.armed)


def _is_answering_call(node: ast.Call, proven: Set[str]) -> bool:
    fn = node.func
    name = fn.attr if isinstance(fn, ast.Attribute) else (
        fn.id if isinstance(fn, ast.Name) else "")
    if name in _ANSWER_EXACT or name in proven:
        return True
    if any(s in name for s in _ANSWER_SUBSTR):
        return True
    if name == "count":
        # metrics.count("...dropped"): an accounted drop
        for arg in node.args:
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str) \
                    and _DROP_METRIC.search(arg.value):
                return True
            if isinstance(arg, ast.JoinedStr):
                for part in arg.values:
                    if isinstance(part, ast.Constant) \
                            and _DROP_METRIC.search(str(part.value)):
                        return True
    return False


class _FuncProof:
    """Walk one function's statements tracking, per path, whether the
    obligation is armed (a routing meta key was read) and answered (an
    answering call happened).  Explicit exits — return / raise / falling
    off the end — while armed and unanswered are the findings."""

    def __init__(self, facts: _FileFacts, fndef, proven: Set[str]):
        self.facts = facts
        self.fndef = fndef
        self.proven = proven
        self.exits: List[_Exit] = []

    def run(self) -> List[_Exit]:
        st = _PathState()
        fall = self._block(self.fndef.body, st)
        if fall is not None:
            self.exits.append(_Exit("fall-through",
                                    _pos(self.facts.line_starts,
                                         self.fndef.body[-1]),
                                    fall.answered, fall.armed))
        return self.exits

    # -- expression effects ----------------------------------------------
    def _expr_effects(self, node: ast.AST, st: _PathState) -> None:
        if node is None:
            return
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call) \
                    and _is_answering_call(sub, self.proven):
                st.answered = True
            key = None
            if isinstance(sub, ast.Subscript) \
                    and _is_meta_expr(sub.value):
                key = self.facts._resolve(sub.slice)
            elif isinstance(sub, ast.Call) \
                    and isinstance(sub.func, ast.Attribute) \
                    and _is_meta_expr(sub.func.value) \
                    and sub.func.attr in ("get", "pop") and sub.args:
                key = self.facts._resolve(sub.args[0])
            elif isinstance(sub, ast.Compare) and len(sub.ops) == 1 \
                    and isinstance(sub.ops[0], (ast.In, ast.NotIn)) \
                    and _is_meta_expr(sub.comparators[0]):
                key = self.facts._resolve(sub.left)
            if key in _ROUTING_KEYS:
                st.armed = True

    # -- statement walk ---------------------------------------------------
    def _block(self, stmts, st: _PathState) -> Optional[_PathState]:
        """Returns the fall-through state, or None if every path in the
        block diverged (return/raise/continue/break)."""
        cur: Optional[_PathState] = st
        for stmt in stmts:
            if cur is None:
                break  # unreachable tail
            cur = self._stmt(stmt, cur)
        return cur

    def _merge(self, states) -> Optional[_PathState]:
        live = [s for s in states if s is not None]
        if not live:
            return None
        return _PathState(all(s.answered for s in live),
                          any(s.armed for s in live))

    def _stmt(self, stmt, st: _PathState) -> Optional[_PathState]:
        ls = self.facts.line_starts
        if isinstance(stmt, ast.Return):
            self._expr_effects(stmt.value, st)
            self.exits.append(_Exit("return", _pos(ls, stmt),
                                    st.answered, st.armed))
            return None
        if isinstance(stmt, ast.Raise):
            self._expr_effects(stmt.exc, st)
            self.exits.append(_Exit("raise", _pos(ls, stmt),
                                    st.answered, st.armed))
            return None
        if isinstance(stmt, (ast.Continue, ast.Break)):
            self.exits.append(_Exit("loop-exit", _pos(ls, stmt),
                                    st.answered, st.armed))
            return None
        if isinstance(stmt, ast.If):
            self._expr_effects(stmt.test, st)
            a = self._block(stmt.body, st.copy())
            b = self._block(stmt.orelse, st.copy()) if stmt.orelse \
                else st.copy()
            return self._merge([a, b])
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._expr_effects(stmt.iter, st)
            # per-iteration obligation: a body that answers on every
            # path covers the items it consumed; the post-loop state
            # keeps the pre-loop answered unless the body is total
            body_exits_before = len(self.exits)
            bst = self._block(stmt.body, st.copy())
            body_exits = self.exits[body_exits_before:]
            loop_total = all(
                e.answered for e in body_exits if e.kind == "loop-exit")
            if bst is not None:
                loop_total = loop_total and bst.answered
            # loop-exit records inside this loop are resolved here, not
            # at function level
            del self.exits[body_exits_before:]
            self.exits.extend(e for e in body_exits
                              if e.kind != "loop-exit")
            out = st.copy()
            if loop_total and (bst is not None or body_exits):
                out.answered = True
            if bst is not None:
                out.armed = out.armed or bst.armed
            if stmt.orelse:
                return self._block(stmt.orelse, out)
            return out
        if isinstance(stmt, ast.While):
            self._expr_effects(stmt.test, st)
            body_exits_before = len(self.exits)
            bst = self._block(stmt.body, st.copy())
            body_exits = self.exits[body_exits_before:]
            del self.exits[body_exits_before:]
            self.exits.extend(e for e in body_exits
                              if e.kind != "loop-exit")
            out = st.copy()
            if bst is not None:
                out.armed = out.armed or bst.armed
                out.answered = out.answered or bst.answered is True \
                    and st.answered
            return out
        if isinstance(stmt, ast.Try):
            before = len(self.exits)
            bst = self._block(stmt.body, st.copy())
            body_exits = self.exits[before:]
            raises = [e for e in body_exits if e.kind == "raise"]
            if stmt.handlers and raises:
                # raises may be caught: route the least-answered raise
                # state through every handler instead of escaping
                del self.exits[before:]
                self.exits.extend(e for e in body_exits
                                  if e.kind != "raise")
                hst_in = _PathState(
                    all(e.answered for e in raises),
                    any(e.armed for e in raises) or st.armed)
                h_falls = []
                for h in stmt.handlers:
                    h_falls.append(self._block(h.body, hst_in.copy()))
                broad = any(
                    h.type is None
                    or (isinstance(h.type, ast.Name)
                        and h.type.id in ("Exception", "BaseException"))
                    for h in stmt.handlers)
                if not broad:
                    # narrow handlers: the raise can still escape
                    self.exits.extend(raises)
            else:
                h_falls = [self._block(h.body, st.copy())
                           for h in stmt.handlers]
            tail = self._merge([bst] + h_falls) if stmt.handlers else bst
            if tail is not None and stmt.orelse:
                tail = self._block(stmt.orelse, tail)
            if stmt.finalbody:
                fin_in = tail.copy() if tail is not None else st.copy()
                fin = self._block(stmt.finalbody, fin_in)
                if tail is not None:
                    tail = fin
            return tail
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._expr_effects(item.context_expr, st)
            return self._block(stmt.body, st)
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return st  # nested defs are proven separately
        # plain statement: scan for answering calls / arming reads
        self._expr_effects(stmt, st)
        return st


def _prove_file(facts: _FileFacts) -> Tuple[Set[str], Dict[str, List[_Exit]]]:
    """Fixpoint: grow the set of local functions proven all-paths-
    answering (callable names, so ``self._send_batched`` counts once
    ``_send_batched`` is proven).  Returns (proven names, per-handler
    violating exits)."""
    proven: Set[str] = set()
    changed = True
    while changed:
        changed = False
        for qual, (fndef, _cls) in facts.funcs.items():
            name = fndef.name
            if name in proven:
                continue
            exits = _FuncProof(facts, fndef, proven).run()
            if exits and all(e.answered for e in exits):
                proven.add(name)
                changed = True
    handler_exits: Dict[str, List[_Exit]] = {}
    for qual, (fndef, cls) in facts.funcs.items():
        is_handler = fndef.name.startswith("handle_") or (
            fndef.name == "process" and "ServerSink" in cls)
        if not is_handler:
            continue
        exits = _FuncProof(facts, fndef, proven).run()
        bad = [e for e in exits if e.armed and not e.answered]
        if bad:
            handler_exits[qual] = bad
    return proven, handler_exits


# ---------------------------------------------------------------------------
# lint entry points
# ---------------------------------------------------------------------------

def _iter_protocol_paths(root: str) -> List[str]:
    return [os.path.join(root, m) for m in PROTOCOL_MODULES
            if os.path.exists(os.path.join(root, m))]


def extracted_alphabet(all_facts: List[_FileFacts],
                       reg: Registry) -> Set[str]:
    """The code's protocol vocabulary: registered meta keys, control
    kinds and abort reasons actually used, plus record magics and
    snapshot version tags.  EXTERNAL_META_KEYS are excluded — their
    lifecycle crosses the lint boundary, so no shipped model owns
    their delivery properties."""
    out: Set[str] = set()
    for facts in all_facts:
        for u in facts.uses:
            if u.kind in ("meta-write", "meta-read") \
                    and u.value in reg.meta_keys \
                    and u.value not in reg.external:
                out.add(u.value)
            elif u.kind in ("ctrl-send", "ctrl-handle") \
                    and u.value in reg.control:
                out.add(u.value)
            elif u.kind in ("abort-send", "abort-handle") \
                    and u.value in reg.abort:
                out.add(u.value)
        out |= facts.records
        out |= facts.snapshots
    return out


def lint_paths(paths: List[str], *, root: Optional[str] = None,
               registry: Optional[Registry] = None,
               drift_gate: bool = False) -> Tuple[List[Report], dict]:
    """Run the protocol passes over ``paths``.  Returns per-file Reports
    (source attached for caret rendering) plus a trailing package-level
    Report carrying the cross-file totality and drift findings, and a
    stats dict.  ``drift_gate=True`` additionally compares the extracted
    alphabet against the shipped models' declared union."""
    base = root or (os.path.commonpath([os.path.dirname(p)
                                        for p in paths]) if paths else "")
    reg = registry or load_registry(root)
    all_facts: List[_FileFacts] = []
    reports: List[Report] = []
    stats = {"files": len(paths), "keys": 0, "kinds": 0,
             "handlers": 0, "proven": 0, "models": 0}
    for path in paths:
        with open(path) as f:
            source = f.read()
        rel = os.path.relpath(path, base) if base else \
            os.path.basename(path)
        rep = Report(source)
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError as e:  # pragma: no cover - repo parses
            rep.add("meta-key-drift", ERROR, f"unparsable: {e}", path=rel)
            reports.append(rep)
            continue
        facts = _FileFacts(path, rel, source, tree, reg)
        all_facts.append(facts)
        # pass 1a: registry drift at every use site
        seen_drift = set()
        for u in facts.uses:
            known = (u.value in reg.meta_keys
                     if u.kind.startswith("meta") else
                     u.value in reg.control
                     if u.kind.startswith("ctrl") else
                     u.value in reg.abort)
            if not known and (u.value, u.func) not in seen_drift:
                seen_drift.add((u.value, u.func))
                what = {"meta": "meta key", "ctrl": "control kind",
                        "abor": "abort reason"}[u.kind[:4]]
                rep.add("meta-key-drift", ERROR,
                        f"protocol {what} {u.value!r} is not declared in "
                        "core/meta_keys.py (the registry is the lint's "
                        "alphabet source of truth)",
                        path=f"{rel}:{u.func}", pos=u.pos)
        # pass 1c: unanswered-path
        proven, handler_exits = _prove_file(facts)
        handlers = [q for q, (fd, cls) in facts.funcs.items()
                    if fd.name.startswith("handle_")
                    or (fd.name == "process" and "ServerSink" in cls)]
        stats["handlers"] += len(handlers)
        stats["proven"] += len(handlers) - len(handler_exits)
        for qual, exits in handler_exits.items():
            for e in exits:
                rep.add("unanswered-path", ERROR,
                        f"handler can {e.kind} after reading routing "
                        "meta without answering, shedding, aborting "
                        "(typed) or quarantining the request — a client "
                        "timeout waiting to happen",
                        path=f"{rel}:{qual}", pos=e.pos)
        reports.append(rep)

    # package-level: handler totality + model drift
    pkg = Report()
    sent: Dict[str, List[str]] = {}
    handled: Dict[str, List[str]] = {}
    for facts in all_facts:
        for u in facts.uses:
            if u.kind in ("meta-write", "ctrl-send"):
                sent.setdefault(u.value, []).append(
                    f"{facts.rel}:{u.func}")
            elif u.kind in ("meta-read", "ctrl-handle"):
                handled.setdefault(u.value, []).append(
                    f"{facts.rel}:{u.func}")
    registered = reg.meta_keys | reg.control
    stats["keys"] = len([k for k in sent.keys() | handled.keys()
                         if k in reg.meta_keys])
    stats["kinds"] = len([k for k in sent.keys() | handled.keys()
                          if k in reg.control])
    for kind in sorted(sent.keys() - handled.keys()):
        if kind not in registered or kind in reg.external:
            continue
        pkg.add("unhandled-message", ERROR,
                f"{kind!r} is sent/stamped (by {sent[kind][0]}"
                + (f" +{len(sent[kind]) - 1}" if len(sent[kind]) > 1
                   else "") + ") but no linted module ever reads or "
                "dispatches on it",
                path=f"alphabet:{kind}")
    for kind in sorted(handled.keys() - sent.keys()):
        if kind not in registered or kind in reg.external:
            continue
        pkg.add("dead-handler", WARNING,
                f"{kind!r} is handled (by {handled[kind][0]}"
                + (f" +{len(handled[kind]) - 1}"
                   if len(handled[kind]) > 1 else "")
                + ") but no linted module ever sends it",
                path=f"alphabet:{kind}")
    if drift_gate:
        from . import statemachine  # jax-free, deferred: fixture lint
        code_alpha = extracted_alphabet(all_facts, reg)
        model_alpha = statemachine.shipped_alphabet() - reg.external
        stats["models"] = len(statemachine.SHIPPED_MODELS)
        for kind in sorted(code_alpha - model_alpha):
            pkg.add("model-alphabet-drift", ERROR,
                    f"message kind {kind!r} is in the code's alphabet "
                    "but no shipped protocol model "
                    "(analysis/statemachine.py) declares it — extend a "
                    "model (or add one) so the kind's delivery "
                    "properties stay machine-checked",
                    path=f"model:{kind}")
        for kind in sorted(model_alpha - code_alpha):
            pkg.add("model-alphabet-surplus", WARNING,
                    f"shipped model declares {kind!r} but the code "
                    "never uses it — stale model alphabet",
                    path=f"model:{kind}")
    reports.append(pkg)
    return reports, stats


def lint_package(root: Optional[str] = None) -> Tuple[List[Report], dict]:
    root = root or package_root()
    return lint_paths(_iter_protocol_paths(root), root=root,
                      drift_gate=True)


def baseline_key(d: Diagnostic) -> str:
    """Stable baseline key: no line numbers (they drift); the path
    component pins file + function / alphabet kind."""
    return f"proto:{d.code}:{d.path}"
