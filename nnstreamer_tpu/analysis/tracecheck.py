"""Deep analysis pass: abstract shape execution + static resource budgeting.

The syntactic passes (capsflow/topology/purity) verify what elements
*declare*.  This pass verifies what their device code *actually does*:
after capsflow negotiation it executes every device-capable stage
SYMBOLICALLY — ``jax.ShapeDtypeStruct`` inputs derived from the negotiated
spec, traced through the stage's real closure with :func:`jax.eval_shape`
— and reports, in one run:

1. **shape/dtype contract violations** (``trace-shape-mismatch``): the
   traced output of a ``device_fn`` / framework ``pure_fn`` disagrees with
   the spec capsflow propagated downstream, with the field-level diff from
   :func:`~nnstreamer_tpu.core.caps.explain_mismatch`;
2. **tracing failures** (``trace-error``): ConcretizationTypeError from
   data-dependent shapes, dtype promotion explosions, arity bugs — the
   errors the runtime would hit at the first buffer, surfaced statically
   with the element path and source caret;
3. a **static resource report** (:class:`ResourceReport`): per-stage param
   bytes + abstract activation bytes, multiplied out over the bucket
   ladder (``pipeline/batching.ladder``), the ``data_parallel``
   replication plan (``pipeline/plan.replication_plan``) and the
   ``dispatch_depth`` in-flight window — yielding an estimated per-device
   HBM high-water mark and a recompile census (distinct compiled
   signatures), each checked against configurable budgets
   (``Config.hbm_budget_bytes`` / ``Config.max_compiled_variants``,
   ``hbm-budget`` / ``recompile-budget`` warnings anchored at the
   dominant stage).

Unlike the syntactic passes this one imports jax — but it still performs
**zero device dispatch**: ``eval_shape`` traces, it never compiles or
executes, and no tensor ever materializes (tests/test_deep_analysis.py
pins this with dispatch instrumented).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from ..core.caps import Caps, explain_mismatch
from ..core.config import get_config
from ..core.types import TensorFormat, TensorSpec, TensorsSpec
from ..elements.base import Element, SINK, SRC
from ..pipeline.batching import ladder as bucket_ladder, shard_bucket_for
from ..pipeline.graph import PipelineGraph
from ..pipeline.plan import (adaptive_variant_budget, mesh_plan,
                             replication_plan)
from ..pipeline.residency import FetchEdge, compute_floor_ms, fetch_ms
from .capsflow import SAFE_CONFIGURE, _element_class, _kahn_order, propagate
from .diagnostics import Diagnostic, ERROR, WARNING, node_label


def _mib(n: int) -> str:
    return f"{n / (1 << 20):.1f} MiB"


@dataclasses.dataclass
class StageResource:
    """Static resource estimate for one deep-traced stage (a device
    element, or a maximal linear chain the planner would fuse)."""

    label: str  # "a+b" for chains, mirroring FusedElement naming
    #: PER-CHIP planned param bytes: under a >1 ``model`` axis, leaves
    #: whose pspecs shard over ``model`` are already divided by M here
    #: (param_bytes/M for sheared leaves; replicated leaves full-size)
    param_bytes: int
    #: peak abstract activation bytes for ONE row (batch entry): max over
    #: the chain's links of input+output bytes
    act_row_bytes: int
    #: rows resident per device at the top of the bucket ladder
    rows_per_device: int
    #: distinct compiled signatures this stage contributes (0 = host path)
    variants: int
    batchable: bool = False
    #: would shard if a >1-wide data mesh existed (batchable, no host_post)
    shard_eligible: bool = False
    sharded: bool = False
    pos: Optional[int] = None  # source offset of the stage head
    #: paged KV block pool resident for the stage's lifetime (continuous
    #: LLM serving — filters/llm.py serving_plan)
    pool_bytes: int = 0
    #: device-resident aggregator ring carried between window dispatches
    #: (elements/aggregator.py device mode) — like the KV pool, resident
    #: for the stage's lifetime
    ring_bytes: int = 0
    #: device-resident TRAINING state (nns-learn, trainer/subplugin.py
    #: train_plan): optimizer moments + the streaming sample window,
    #: resident for the stage's lifetime; the transient per-step
    #: gradient tree is priced into act_row_bytes instead
    train_bytes: int = 0
    #: speculative-decoding draft model (continuous LLM serving,
    #: custom=draft:<preset>): INFORMATIONAL split of bytes ALREADY
    #: counted in param_bytes / pool_bytes above — the draft's params
    #: and its block pool (which shares the target allocator's
    #: n_blocks/block_size at the draft's own geometry).  Rendered in
    #: the report so "the draft is priced" is visible and gateable;
    #: excluded from hbm_bytes/by_category to avoid double counting.
    draft_param_bytes: int = 0
    draft_pool_bytes: int = 0
    #: sampler per-slot PRNG key state (continuous LLM serving with
    #: ``temperature > 0`` — serving_plan's ``prng_state_bytes``);
    #: tiny but ledger-reconciled like every resident category
    prng_bytes: int = 0
    #: per-step decode HBM TRAFFIC model (continuous LLM serving): K+V
    #: bytes the grouped-GQA kernel streams per live context token per
    #: decode step — priced at ``n_kv_heads`` (serving_plan's
    #: ``decode_bytes_per_ctx_token``); pricing at ``n_heads`` is the
    #: stale over-prediction the reconciliation regression pins.
    #: Traffic, not residency: excluded from ``hbm_bytes``.
    decode_bytes_per_ctx_token: int = 0
    #: query heads sharing one KV head's streamed blocks (H / Hkv)
    kv_groups: int = 1

    @property
    def hbm_bytes(self) -> int:
        """Per-device HBM this stage plans for: resident params + KV pool
        + aggregator ring + training state + sampler PRNG state +
        in-flight activations (dispatch window already multiplied into
        rows)."""
        return (self.param_bytes + self.pool_bytes + self.ring_bytes
                + self.train_bytes + self.prng_bytes
                + self.act_row_bytes * self.rows_per_device)


@dataclasses.dataclass
class ResourceReport:
    """The deep pass's static resource estimate for one pipeline."""

    stages: List[StageResource]
    batch_max: int
    data_parallel: int  # resolved replicas (1 = unsharded)
    #: resolved ``model``-axis width of the pipeline mesh (the
    #: ``pipeline/plan.mesh_plan`` arithmetic the runtime shares);
    #: param/pool bytes above are PER CHIP under this plan
    model_parallel: int = 1
    dispatch_depth: int = 1
    ladder: Tuple[int, ...] = ()
    hbm_budget_bytes: int = 0
    max_compiled_variants: int = 0
    #: adaptive bucket ladder enabled: batchable stages are priced at
    #: their full mint budget (``ladder_budget`` programs each — the
    #: worst case the runtime's AdaptiveLadder can ever compile), so the
    #: census stays closed by construction
    adaptive_buckets: bool = False
    ladder_budget: int = 0
    #: planned D2H per sink edge (pipeline/residency.py): what actually
    #: crosses to host per buffer, priced against the calibrated link
    #: when one is configured (Config.link_d2h_mbps)
    fetch_edges: List[FetchEdge] = dataclasses.field(default_factory=list)
    link_d2h_mbps: float = 0.0
    link_rtt_ms: float = 0.0

    @property
    def hbm_estimate(self) -> int:
        return sum(s.hbm_bytes for s in self.stages)

    @property
    def compiled_variants(self) -> int:
        return sum(s.variants for s in self.stages)

    def by_category(self) -> Dict[str, int]:
        """The HBM estimate split per ledger category — what nns-xray's
        runtime HBM ledger reconciles measured bytes against
        (utils/xray.py, docs/OBSERVABILITY.md "Predicted vs actual")."""
        return {
            "params": sum(s.param_bytes for s in self.stages),
            "kv_pool": sum(s.pool_bytes for s in self.stages),
            "agg_rings": sum(s.ring_bytes for s in self.stages),
            "activations": sum(s.act_row_bytes * s.rows_per_device
                               for s in self.stages),
            "train_state": sum(s.train_bytes for s in self.stages),
            "prng_state": sum(s.prng_bytes for s in self.stages),
        }

    def summary(self) -> str:
        return (f"{len(self.stages)} device stage(s), est HBM high-water "
                f"{_mib(self.hbm_estimate)}"
                + (f" (budget {_mib(self.hbm_budget_bytes)})"
                   if self.hbm_budget_bytes else "")
                + f", {self.compiled_variants} compiled signature(s)"
                + (f" (max {self.max_compiled_variants})"
                   if self.max_compiled_variants else ""))

    def render(self) -> str:
        lines = [
            "deep resource report "
            f"(batch_max={self.batch_max}, "
            f"buckets={','.join(map(str, self.ladder))}, "
            + (f"adaptive (budget {self.ladder_budget}/stage), "
               if self.adaptive_buckets else "")
            + f"data_parallel={self.data_parallel}, "
            f"model_parallel={self.model_parallel}, "
            f"dispatch_depth={self.dispatch_depth})"
        ]
        if not self.stages:
            lines.append("  no device stages traced")
        for s in self.stages:
            flags = "".join(
                f for f, on in (("B", s.batchable), ("S", s.sharded)) if on)
            lines.append(
                f"  {s.label}: params {_mib(s.param_bytes)}"
                + (f" (draft params {_mib(s.draft_param_bytes)})"
                   if s.draft_param_bytes else "")
                + ", "
                + (f"kv pool {_mib(s.pool_bytes)}"
                   + (f" (draft pool {_mib(s.draft_pool_bytes)})"
                      if s.draft_pool_bytes else "")
                   + ", " if s.pool_bytes else "")
                + (f"agg ring {_mib(s.ring_bytes)}, " if s.ring_bytes
                   else "")
                + (f"train state {_mib(s.train_bytes)}, " if s.train_bytes
                   else "")
                + (f"prng state {s.prng_bytes} B, " if s.prng_bytes
                   else "")
                + (f"decode traffic {s.decode_bytes_per_ctx_token} "
                   f"B/ctx-token (x{s.kv_groups} KV sharing), "
                   if s.decode_bytes_per_ctx_token and s.kv_groups > 1
                   else "")
                + f"act/row {_mib(s.act_row_bytes)}, "
                f"rows/dev {s.rows_per_device}, "
                f"programs {s.variants}"
                + (f" [{flags}]" if flags else ""))
        for e in self.fetch_edges:
            size = "?" if e.bytes_per_buffer < 0 else f"{e.bytes_per_buffer} B"
            via = f" via {e.reduced}" if e.reduced else ""
            priced = ""
            if self.link_d2h_mbps > 0 and e.bytes_per_buffer >= 0:
                # the RTT is shown but excluded from d2h_ms and the
                # fetch-bound decision: it amortizes behind the async
                # fetch window, link occupancy cannot (docs/FETCH.md)
                rtt = (f" + {self.link_rtt_ms:g} ms rtt/pull"
                       if self.link_rtt_ms > 0 else "")
                priced = (f" (d2h {e.d2h_ms:.2f} ms on "
                          f"{self.link_d2h_mbps:g} MB/s{rtt} vs compute "
                          f"floor {e.compute_floor_ms:.2f} ms)")
            lines.append(
                f"  fetch {e.sink} <- {e.producer}: {size}/buffer"
                f"{via}{priced}")
        lines.append("  totals: " + self.summary())
        return "\n".join(lines)


@dataclasses.dataclass
class _NodeTrace:
    """Per-node result of the abstract execution walk."""

    node: object
    element: Element
    in_bytes: int
    out_bytes: int
    param_bytes: int
    batchable: bool
    host_post: bool
    linear: bool  # single default-pad in/out edges (fusion-chain eligible)
    #: bytes of param leaves whose pspecs shard over the ``model`` axis
    #: (0 = no pspecs / nothing model-sharded): divided by M per chip
    param_shard_bytes: int = 0


def _trace_msg(e: BaseException) -> str:
    first = str(e).strip().splitlines()
    head = first[0] if first else repr(e)
    if len(head) > 300:
        head = head[:297] + "..."
    return f"{type(e).__name__}: {head}"


def _static(spec: TensorsSpec) -> TensorsSpec:
    return spec if spec.format == TensorFormat.STATIC else spec.replace(
        format=TensorFormat.STATIC)


def deep_check(
    graph: PipelineGraph,
    *,
    batch_max: Optional[int] = None,
    batch_buckets: Optional[List[int]] = None,
    adaptive_buckets: Optional[bool] = None,
    data_parallel: Optional[int] = None,
    model_parallel: Optional[int] = None,
    dispatch_depth: Optional[int] = None,
    hbm_budget_bytes: Optional[int] = None,
    max_compiled_variants: Optional[int] = None,
    link_d2h_mbps: Optional[float] = None,
    link_rtt_ms: Optional[float] = None,
    reconfig: Optional[Dict] = None,
    out_caps: Optional[Dict] = None,
) -> Tuple[List[Diagnostic], ResourceReport]:
    """Run the deep pass over a parsed graph.  Knobs default to the global
    :class:`~nnstreamer_tpu.core.config.Config` the runtime would use, so
    the report predicts what an actual ``Pipeline(desc)`` would plan.
    ``out_caps`` lets the caller hand over an existing capsflow
    :func:`propagate` result instead of re-running negotiation."""
    cfg = get_config()
    batch_max = max(1, batch_max if batch_max is not None else cfg.batch_max)
    # Normalize like BatchRunner does (sorted unique ascending):
    # bucket_for scans in order, so a raw [8,2,4] would collapse the
    # census to the first listed bucket and diverge from the runtime.
    buckets = list(batch_buckets if batch_buckets is not None
                   else cfg.batch_buckets) or None
    if buckets:
        buckets = sorted(set(buckets))
    adaptive = bool(adaptive_buckets if adaptive_buckets is not None
                    else cfg.adaptive_buckets)
    dp_knob = max(0, data_parallel if data_parallel is not None
                  else cfg.data_parallel)
    mp_knob = max(0, model_parallel if model_parallel is not None
                  else cfg.model_parallel)
    dispatch_depth = max(1, dispatch_depth if dispatch_depth is not None
                         else cfg.dispatch_depth)
    hbm_budget = (hbm_budget_bytes if hbm_budget_bytes is not None
                  else cfg.hbm_budget_bytes)
    max_variants = (max_compiled_variants if max_compiled_variants is not None
                    else cfg.max_compiled_variants)
    d2h_mbps = float(link_d2h_mbps if link_d2h_mbps is not None
                     else cfg.link_d2h_mbps)
    rtt_ms = float(link_rtt_ms if link_rtt_ms is not None
                   else cfg.link_fetch_rtt_ms)

    import jax  # backend init only — the pass never dispatches

    n_devices = len(jax.devices())  # what Pipeline._shared_mesh sizes against
    req_dp, req_mp = mesh_plan(dp_knob, mp_knob, batch_max, n_devices)
    # model what COULD run; the over-ask itself becomes a diagnostic below
    model_par = min(req_mp, n_devices)
    replicas = min(req_dp, max(1, n_devices // model_par))
    requested = req_dp  # the data-axis over-ask, kept for the diag below
    diags: List[Diagnostic] = []
    if out_caps is None:
        # capsflow's own diagnostics are the syntactic pass's to report;
        # here we only need the negotiated specs
        _, out_caps = propagate(graph)

    traces: Dict[int, _NodeTrace] = {}
    serving_stages: List[StageResource] = []
    for node in _kahn_order(graph):
        serving = _llm_serving_stage(node, diags, model_par)
        if serving is not None:
            # continuous LLM serving is priced STATICALLY (building the
            # element would materialize the full parameter set); True =
            # a serving stage that couldn't be priced, already diagnosed
            if isinstance(serving, StageResource):
                serving_stages.append(serving)
            continue
        train = _trainer_stage(node, diags, model_par)
        if train is not None:
            # tensor_trainer (nns-learn): priced statically via the
            # runtime's own train_plan arithmetic — optimizer state
            # abstracted via eval_shape, never materialized.  The
            # element is stateful (device window + opt moments), so the
            # generic stateless walk must skip it either way.
            if isinstance(train, StageResource):
                serving_stages.append(train)
            continue
        ring = _aggregator_stage(graph, node, out_caps, diags)
        if ring is not None:
            # device-resident aggregator (elements/aggregator.py device
            # mode): its HBM ring + 3-program census are priced here; the
            # element itself is stateful, so the generic (stateless)
            # trace walk must skip it either way
            if isinstance(ring, StageResource):
                serving_stages.append(ring)
            continue
        got = _trace_node(graph, node, out_caps, diags, model_par)
        if got is not None:
            traces[node.id] = got

    report = _resources(graph, traces, batch_max=batch_max, buckets=buckets,
                        replicas=replicas, model_par=model_par,
                        dispatch_depth=dispatch_depth,
                        hbm_budget=hbm_budget, max_variants=max_variants,
                        adaptive=adaptive)
    report.stages.extend(serving_stages)
    report.link_d2h_mbps = d2h_mbps
    report.link_rtt_ms = rtt_ms
    if reconfig:
        diags.extend(_reconfig_check(graph, reconfig))
    diags.extend(_fetch_check(graph, traces, out_caps, report))
    for t in traces.values():
        # Throwaway trace elements may hold real checkpoints (configure()
        # opened the framework) — release them now, not at GC.
        try:
            t.element.stop()
        except Exception:  # noqa: BLE001 - best-effort cleanup
            pass
    # Exactly when the runtime builds the mesh — model_parallel
    # configured (knob != 1: _build_mesh's mp_wanted, no shard-eligible
    # stage needed), or a shard-eligible stage with batching on — an
    # over-asked (data x model) plan fails start() (or the llm filter's
    # open()) with this same arithmetic; with model_parallel left at 1
    # and nothing shardable, the dp knob stays inert like it always was.
    if requested * req_mp > n_devices and (
            mp_knob != 1
            or (batch_max > 1
                and any(s.shard_eligible for s in report.stages))):
        top = next((s for s in report.stages if s.shard_eligible), None)
        if requested > 1 and req_mp > 1:
            plan = f"data_parallel={requested} x model_parallel={req_mp}"
        elif req_mp > 1:
            plan = f"model_parallel={req_mp}"
        else:
            plan = f"data_parallel={requested}"
        diags.append(Diagnostic(
            "data-parallel-devices", ERROR,
            f"{plan} needs {requested * req_mp} local devices, "
            f"have {n_devices} — start() will fail with PipelineError",
            path=top.label if top else "",
            pos=top.pos if top else None))
    diags.extend(_budget_diags(report))
    return diags, report


#: tensor_filter ``framework=`` names that resolve to the llm framework
_LLM_FRAMEWORKS = ("llm", "llamacpp", "llama.cpp")


def _reconfig_check(graph, reconfig: Dict) -> List[Diagnostic]:
    """``recompile-on-reconfig``: given a proposed runtime config change
    (``analyze(..., reconfig={"slots": 8})`` / ``lint --reconfig``),
    warn for every continuous-serving knob whose change would actually
    change a COMPILED program signature — the table lives in
    ``utils/elastic.SERVE_KNOB_SIGNATURE`` (slots is the decode
    program's row count, kv_blocks the pool's static shape, temperature
    a compiled-in sampler constant, ...).  Host-value knobs (max_new,
    prefill_budget, quotas, timeouts) pass silently: they are safe to
    mutate on a running loop.  The remediation for a flagged knob is the
    elastic drain path: ``Pipeline.drain_stream()`` every live stream →
    restart with the new (versioned) config → ``adopt_stream()`` —
    docs/SERVING.md "Elastic serving"."""
    from ..filters.base import parse_custom_options
    from ..utils.elastic import SERVE_KNOB_SIGNATURE, signature_changes

    diags: List[Diagnostic] = []
    first_serving = None
    for node in graph.nodes.values():
        if node.kind != "tensor_filter":
            continue
        if str(node.props.get("framework", "")).lower() \
                not in _LLM_FRAMEWORKS:
            continue
        opts = parse_custom_options(str(node.props.get("custom", "")))
        if str(opts.get("serve", "")).lower() != "continuous":
            continue
        if first_serving is None:
            first_serving = node
        for knob, old, new in signature_changes(opts, reconfig):
            diags.append(Diagnostic(
                "recompile-on-reconfig", WARNING,
                f"changing {knob}: "
                f"{'<default>' if old is None else old} -> {new} changes "
                "a compiled program signature (the standing loop's "
                "census is static in it) — a live mutation would "
                "recompile mid-serve; apply it behind a drain instead: "
                "Pipeline.drain_stream() each live stream, restart with "
                "the versioned config, adopt_stream() them back "
                "(docs/SERVING.md 'Elastic serving')",
                path=node_label(node), pos=node.pos))
    # node-independent: one finding per run, not one per serving filter
    unknown = [k for k in reconfig if k not in SERVE_KNOB_SIGNATURE]
    if unknown and first_serving is not None:
        diags.append(Diagnostic(
            "recompile-on-reconfig", WARNING,
            f"reconfig knob(s) {sorted(unknown)} are not in the "
            "documented runtime-mutable table "
            "(utils/elastic.SERVE_KNOB_SIGNATURE) — signature "
            "impact unknown, treat as recompile-requiring",
            path=node_label(first_serving), pos=first_serving.pos))
    return diags


def _llm_serving_stage(node, diags, model_par: int = 1):
    """Price a ``serve:continuous`` llm filter statically.

    Returns ``None`` when the node is not a continuous-serving llm
    filter, a :class:`StageResource` when priced, or ``True`` when it IS
    one but could not be priced (diagnostic already appended) — either
    way a non-None result means the generic trace walk must skip the
    node: the standing loop's programs have a CLOSED census by
    construction (``serving_plan``), so the ``invoke-dynamic`` flag that
    normally means "recompile per signature" does not apply; and
    building the element to trace it would materialize the full
    parameter set, which at 7B is exactly what a static pass must never
    do.

    The paged decode signature is static in every admission-state
    dimension (block tables / positions / occupancy change VALUES only).
    If the serving knobs themselves cannot be resolved to ints — the one
    way the signature could come to depend on occupancy — the stage gets
    the ``recompile-unbounded`` warning the census cannot bound."""
    if node.kind != "tensor_filter":
        return None
    if str(node.props.get("framework", "")).lower() not in _LLM_FRAMEWORKS:
        return None
    from ..filters.base import parse_custom_options

    opts = parse_custom_options(str(node.props.get("custom", "")))
    if str(opts.get("serve", "")).lower() != "continuous":
        return None
    label = node_label(node)
    from ..models import llama

    model = str(node.props.get("model") or "llama_tiny")
    cfg = llama.resolve_config(model, opts)
    if cfg is None:
        diags.append(Diagnostic(
            "serving-unpriced", WARNING,
            f"serve:continuous with model {model!r}: the config lives in "
            "the checkpoint file, which a static pass must not open — "
            "the paged KV pool cannot be priced (use a preset model name "
            "to budget it statically)",
            path=label, pos=node.pos))
        return True
    try:
        slots = int(opts.get("slots", 4))
        plan_kw = dict(
            slots=slots,
            block_size=max(1, int(opts.get("block_size", 16))),
            kv_blocks=max(0, int(opts.get("kv_blocks", 0))),
            prefill_chunk=max(1, int(opts.get("prefill_chunk", 32))),
        )
        int(opts.get("stream_chunk", 8))  # the decode chunk length
        spec_k = max(1, int(opts.get("spec_k", 4)))
        temperature = float(opts.get("temperature", 0.0))
    except (TypeError, ValueError):
        diags.append(Diagnostic(
            "recompile-unbounded", WARNING,
            "continuous decode signature depends on unresolvable serving "
            "knobs (slots/block_size/prefill_chunk/stream_chunk/spec_k "
            "must be integer literals) — the compiled-variant census "
            "cannot bound this stage",
            path=label, pos=node.pos))
        return True
    # Speculative decoding: the draft's params + its block pool (same
    # allocator geometry as the target's) are resident for the stage
    # lifetime — price them with the SAME shared arithmetic the loop
    # sizes with (serving_plan), and the program census grows 3 -> 5
    # (target/draft prefill, propose, verify, slot-token setter).
    draft_name = str(opts.get("draft", "") or "")
    draft_cfg = None
    if draft_name:
        draft_cfg = llama.resolve_config(draft_name, {
            "vocab": cfg.vocab, "max_seq": cfg.max_seq})
        if draft_cfg is None:
            diags.append(Diagnostic(
                "serving-unpriced", WARNING,
                f"draft model {draft_name!r} cannot be resolved "
                "statically — the llm filter's open() only accepts "
                "preset zoo names for draft: and will fail; the draft "
                "params/pool cannot be priced",
                path=label, pos=node.pos))
    from ..filters.llm import serving_plan

    dtype = str(opts.get("dtype", "bfloat16"))
    plan = serving_plan(cfg, dtype=dtype, draft_cfg=draft_cfg,
                        spec_k=spec_k, temperature=temperature,
                        **plan_kw)
    quant = str(opts.get("quant", "")).lower()
    param_dtype = str(opts.get("param_dtype", "float32"))
    # Tensor parallelism: the pipeline's resolved model axis, with the
    # deprecated custom=tp: alias honored when the pipeline knob is off
    # (Pipeline promotes the alias the same way at construction).
    ways = model_par
    if ways <= 1:
        try:
            ways = max(1, int(opts.get("tp", 1)))
        except (TypeError, ValueError):
            ways = 1
    params = llama.param_bytes_estimate(cfg, quant=quant,
                                        param_dtype=param_dtype)
    pool = plan["pool_bytes"]
    draft_params = (llama.param_bytes_estimate(
        draft_cfg, param_dtype=param_dtype)
        if draft_cfg is not None else 0)
    draft_pool = plan["draft_pool_bytes"]
    if ways > 1:
        problems = llama.tp_divisibility_problems(cfg, ways)
        if draft_cfg is not None:
            problems += [
                f"draft {p}" for p in
                llama.tp_divisibility_problems(draft_cfg, ways)]
        if problems:
            # open() raises the same arithmetic at runtime — surface it
            # statically with the dims named
            diags.append(Diagnostic(
                "model-divisibility", ERROR,
                f"model geometry does not divide model_parallel={ways}: "
                + "; ".join(problems)
                + " — the llm filter's open() will fail",
                path=label, pos=node.pos))
        else:
            # per-chip pricing: sheared leaves (the big mats + lm_head)
            # divide by M, embed/norms replicate; the paged KV pool
            # shards its head dim, so pool bytes divide too — target
            # and draft alike
            shard, repl = llama.param_bytes_split(cfg, quant=quant,
                                                  param_dtype=param_dtype)
            params = shard // ways + repl
            pool = pool // ways
            if draft_cfg is not None:
                dsh, drep = llama.param_bytes_split(
                    draft_cfg, param_dtype=param_dtype)
                draft_params = dsh // ways + drep
                draft_pool = draft_pool // ways
    # Per-slot in-flight activations of the decode step: the f32 logits
    # row dominates ([vocab] per slot per scan step — the k+1-wide
    # verify step multiplies it by spec_k+1 under speculation), plus
    # the hidden state at a couple of residencies — a deliberate
    # over-estimate that stays O(vocab + dim), nowhere near pool/param
    # scale.
    act_row = (4 * cfg.vocab * (spec_k + 1 if draft_cfg is not None
                                else 1) + 8 * cfg.dim)
    return StageResource(
        label=label, param_bytes=params + draft_params,
        act_row_bytes=act_row,
        rows_per_device=slots, variants=plan["programs"],
        batchable=False, shard_eligible=False, sharded=ways > 1,
        pos=node.pos, pool_bytes=pool + draft_pool,
        draft_param_bytes=draft_params, draft_pool_bytes=draft_pool,
        prng_bytes=plan["prng_state_bytes"],
        decode_bytes_per_ctx_token=plan["decode_bytes_per_ctx_token"],
        kv_groups=plan["kv_groups"])


def _trainer_stage(node, diags, model_par: int = 1):
    """Price a jax ``tensor_trainer`` stage statically (nns-learn).

    Returns ``None`` when the node is not a jax-framework trainer, a
    :class:`StageResource` when priced, or ``True`` when it is one but
    could not be priced (diagnostic appended).  The arithmetic is the
    runtime's own :func:`~nnstreamer_tpu.trainer.subplugin.train_plan`
    (the ``serving_plan`` shared-home discipline): param bytes from the
    model config, optimizer-state bytes from the optax tree ABSTRACTED
    via ``jax.eval_shape(tx.init, params)`` (no optimizer state ever
    materializes), the device-resident streaming window, and one
    transient gradient tree per step (activation-class).  Under a >1
    ``model`` axis the bundle's ``param_pspecs`` walk
    (:func:`_pspec_audit`) divides model-sharded leaves — params, their
    Adam moments, and their gradients — by M per chip.  The census is
    the trainer's fixed :data:`~nnstreamer_tpu.trainer.subplugin.
    TRAINER_PROGRAMS` program set (append / step / eval), verified live
    by nns-xray."""
    if node.kind != "tensor_trainer":
        return None
    fw = str(node.props.get("framework", "jax")).lower()
    if fw != "jax":
        return None
    label = node_label(node)
    from ..trainer.subplugin import train_plan

    try:
        plan = train_plan(dict(node.props))
    except Exception:  # noqa: BLE001 - unpriceable model config
        plan = None
    if plan is None:
        diags.append(Diagnostic(
            "training-unpriced", WARNING,
            f"tensor_trainer model {node.props.get('model')!r} cannot be "
            "resolved statically — optimizer-state/gradient HBM cannot "
            "be priced (use mlp:IN:...:OUT or a preset zoo name)",
            path=label, pos=node.pos))
        return True
    params = plan["param_bytes"]
    opt = plan["opt_bytes"]
    grads = plan["grad_bytes"]
    # trainer's own mesh prop: a model:M axis in it shards like the
    # pipeline's model_parallel would
    mesh_prop = str(node.props.get("mesh", "") or "")
    ways = model_par
    if "model:" in mesh_prop:
        try:
            ways = max(ways, int(
                mesh_prop.split("model:", 1)[1].split(",", 1)[0]))
        except ValueError:
            pass
    if ways > 1 and plan["pspecs"] is not None \
            and plan["params"] is not None:
        shard = _pspec_audit(plan["params"], plan["pspecs"], ways,
                             label, node.pos, diags)
        if params:
            frac_rep = (params - min(shard, params)) / params
            scale = frac_rep + (1 - frac_rep) / ways
            params = int(params * scale)
            # Adam moments and gradients mirror the param tree leaf for
            # leaf, so the same shard fraction divides them
            opt = int(opt * scale)
            grads = int(grads * scale)
    return StageResource(
        label=label, param_bytes=params,
        act_row_bytes=grads,  # one transient gradient tree per step
        rows_per_device=1, variants=plan["programs"],
        batchable=False, shard_eligible=False, sharded=ways > 1,
        pos=node.pos, train_bytes=opt + plan["window_bytes"])


#: compiled programs a device-mode aggregator runs for its LIFETIME (the
#: fixed-signature pin, elements/aggregator.py: ring init, append,
#: window+advance) — mirrored by tests/test_aggregator_device.py's
#: zero-recompile pin, the same discipline as PR 6's 3-program serving loop
AGGREGATOR_PROGRAMS = 3


def _aggregator_stage(graph, node, out_caps, diags):
    """Price a ``tensor_aggregator device=true`` stage statically.

    Returns ``None`` when the node is not a device-mode aggregator, a
    :class:`StageResource` when priced, or ``True`` when it is one but
    the upstream spec is unknown/flexible (diagnosed: the device ring
    needs a static window signature).  The ring is HBM-resident for the
    stage's lifetime — ``(frames_out + frames_in)`` frames of carry state
    written in-program (roll + dynamic-update-slice), so window advances
    never round-trip through host and never recompile: the census is the
    fixed :data:`AGGREGATOR_PROGRAMS`."""
    if node.kind != "tensor_aggregator":
        return None
    if str(node.props.get("device", "")).lower() not in ("true", "1", "yes"):
        return None
    label = node_label(node)
    ins = graph.in_edges(node.id)
    up = out_caps.get((ins[0].src, ins[0].src_pad)) if len(ins) == 1 else None
    spec = up.spec if up is not None else None
    if spec is None or spec.is_flexible or len(spec) != 1:
        diags.append(Diagnostic(
            "recompile-unbounded", WARNING,
            "tensor_aggregator device=true needs ONE static upstream "
            "tensor spec: the HBM ring's shape (and its zero-recompile "
            "pin) derive from it — a flexible stream would re-specialize "
            "the ring programs per signature",
            path=label, pos=node.pos))
        return True
    try:
        frames_in = max(1, int(node.props.get("frames_in", 1)))
        frames_out = max(1, int(node.props.get("frames_out", 1)))
    except (TypeError, ValueError):
        frames_in = frames_out = 1
    in_bytes = int(spec.nbytes)
    frame_bytes = in_bytes // frames_in
    # carry capacity is need + step frames (elements/aggregator.py):
    # valid can reach need-1 before an append of step more
    ring = (frames_out + frames_in) * frame_bytes
    out_bytes = frames_out * frame_bytes
    return StageResource(
        label=label, param_bytes=0, act_row_bytes=in_bytes + out_bytes,
        rows_per_device=1, variants=AGGREGATOR_PROGRAMS,
        batchable=False, shard_eligible=False, sharded=False,
        pos=node.pos, ring_bytes=ring)


def _pspec_audit(params, pspecs, model_par: int, label, pos,
                 diags: List[Diagnostic]) -> int:
    """Statically audit a bundle's ``param_pspecs`` against its param
    leaves under a ``model_parallel=model_par`` plan: returns the bytes
    of leaves that shard over ``model`` (for per-chip pricing) and
    appends

    * ``mesh-axis-missing`` — a pspec names an axis the pipeline's 2-D
      ``(data x model)`` mesh does not carry (seq/expert/pipe or a typo):
      placement would fail at the first sharded dispatch;
    * ``model-divisibility`` — a ``model``-sharded dim does not divide
      the model axis: ``device_put`` would reject the uneven shard.

    Both only fire when the plan actually places over ``model``
    (``model_par > 1``); a 1-wide model axis replicates and never reads
    the pspecs.  Leaf pairing and axis extraction ride the SAME walk
    the runtime places by (``parallel.sharding.iter_param_specs`` /
    ``spec_entry_axes``) so the audit can never drift from what
    ``shard_params`` would actually do."""
    from ..parallel.sharding import iter_param_specs, spec_entry_axes

    shard_bytes = 0
    bad_axes: set = set()
    bad_dims: List[str] = []

    for path, p, s in iter_param_specs(params, pspecs):
        shape = tuple(getattr(p, "shape", ()) or ())
        sharded = False
        for i, entry in enumerate(s or ()):
            for a in spec_entry_axes(entry):
                if a == "model":
                    sharded = True
                    if i < len(shape) and shape[i] % model_par:
                        bad_dims.append(f"{path}[{i}]={shape[i]}")
                elif a != "data":
                    bad_axes.add(str(a))
        if sharded:
            shard_bytes += int(getattr(p, "nbytes", 0) or 0)
    if model_par > 1 and bad_axes:
        diags.append(Diagnostic(
            "mesh-axis-missing", WARNING,
            f"param_pspecs name mesh axes {sorted(bad_axes)} that the "
            "pipeline's (data x model) mesh does not carry — those "
            "leaves cannot place at the first sharded dispatch "
            "(valid placement axes: 'data', 'model')",
            path=label, pos=pos))
    if model_par > 1 and bad_dims:
        shown = ", ".join(bad_dims[:4]) + (", ..." if len(bad_dims) > 4
                                           else "")
        diags.append(Diagnostic(
            "model-divisibility", ERROR,
            f"param dims sharded over 'model' do not divide "
            f"model_parallel={model_par}: {shown} — placement will fail",
            path=label, pos=pos))
    return shard_bytes


class _CapsIdentity:
    """Stand-in element for a fused-through capsfilter in the census walk
    (the runtime's ``_CapsFilter.device_fn`` identity, mirrored so chain
    merging — and therefore the recompile census and HBM estimate —
    agrees with what ``plan_stages`` actually fuses)."""

    name = "capsfilter"
    host_post = None

    def stop(self) -> None:
        pass


def _capsfilter_trace(graph, node, out_caps) -> Optional[_NodeTrace]:
    """Transparent-identity trace for a mid-chain caps pin: the planner
    fuses THROUGH capsfilters on static tensor streams (they are
    negotiation-time constraints, not runtime transforms), so the census
    walk must see them as zero-param, zero-new-activation chain links —
    not as chain breaks that would split one fused program into two and
    double-count its bucket ladder."""
    ins = graph.in_edges(node.id)
    outs = graph.out_edges(node.id)
    if len(ins) != 1 or ins[0].dst_pad != SINK:
        return None
    up = out_caps.get((ins[0].src, ins[0].src_pad))
    spec = up.spec if up is not None else None
    if spec is None or spec.is_flexible:
        return None  # nothing static to pin: stays a host pass-through
    down = out_caps.get((node.id, SRC))
    out_spec = (down.spec if down is not None else None) or spec
    linear = (len(outs) <= 1 and all(e.src_pad == SRC for e in outs))
    return _NodeTrace(
        node=node, element=_CapsIdentity(), in_bytes=spec.nbytes,
        out_bytes=int(out_spec.nbytes), param_bytes=0, batchable=False,
        host_post=False, linear=linear)


def _trace_node(graph, node, out_caps, diags,
                model_par: int = 1) -> Optional[_NodeTrace]:
    """Abstractly execute one node's device path; returns its trace record
    (for resource accounting) or None when the node has no device path."""
    if node.kind == "capsfilter":
        return _capsfilter_trace(graph, node, out_caps)
    cls = _element_class(node.kind)
    if cls is None or cls.device_fn is Element.device_fn:
        return None
    ins = graph.in_edges(node.id)
    if len(ins) != 1 or ins[0].dst_pad != SINK:
        return None  # device paths are single-sink by construction
    up = out_caps.get((ins[0].src, ins[0].src_pad))
    spec = up.spec if up is not None else None
    if spec is None:
        return None  # nothing negotiated to derive abstract inputs from
    label = node_label(node)
    if spec.is_flexible or bool(node.props.get("invoke_dynamic", False)):
        diags.append(Diagnostic(
            "recompile-unbounded", WARNING,
            "flexible/per-buffer shapes re-specialize the compiled program "
            "per signature — the recompile census cannot bound this stage "
            "(bucket flexible streams, or declare a static spec)",
            path=label, pos=node.pos))
        return None
    if node.kind not in SAFE_CONFIGURE and node.kind != "tensor_filter":
        return None  # configure touches the outside world: not traceable
    try:
        el = cls(dict(node.props), name=node.name or f"{node.kind}{node.id}")
    except Exception:  # noqa: BLE001 - capsflow already diagnosed this
        return None
    out_pads = sorted(
        {e.src_pad for e in graph.out_edges(node.id)}) or [SRC]
    try:
        produced = el.configure({SINK: up}, list(out_pads))
    except Exception:  # noqa: BLE001 - capsflow already diagnosed this
        return None
    # The real configure is strictly better informed than capsflow's
    # static transfer (it loads the framework and learns model I/O the
    # props never declared) — feed ITS caps to downstream nodes so the
    # whole deep walk sees what the runtime would negotiate.
    for pad in out_pads:
        got = produced.get(pad)
        if got is not None:
            out_caps[(node.id, pad)] = got

    try:
        got = el.abstract_invoke(spec)
    except Exception as e:  # noqa: BLE001 - the finding, not a crash
        diags.append(Diagnostic(
            "trace-error", ERROR,
            f"abstract execution failed: {_trace_msg(e)}",
            path=label, pos=node.pos))
        return None
    if got is None:
        return None
    traced_sds, declared = got
    traced = TensorsSpec(tuple(
        TensorSpec.from_shape(tuple(s.shape), s.dtype) for s in traced_sds))

    # The contract: what the trace produces must be what capsflow told
    # downstream to expect (falling back to the element's own declared
    # out spec when propagation had nothing static).
    down = out_caps.get((node.id, SRC))
    ref = (down.spec if down is not None else None) or declared
    if ref is not None and not ref.is_flexible \
            and not traced.is_compatible(_static(ref)):
        diags.append(Diagnostic(
            "trace-shape-mismatch", ERROR,
            "traced output disagrees with the negotiated downstream spec: "
            + explain_mismatch(Caps.tensors(traced), Caps.tensors(_static(ref))),
            path=f"{label}:src", pos=node.pos))

    try:
        params = int(el.param_bytes())
    except Exception:  # noqa: BLE001 - accounting probe only
        params = 0
    # 2-D placement audit: what the bundle's pspecs would shard over
    # `model` (priced per chip), plus the static axis/divisibility
    # diagnostics — zero device work, the params are already built.
    shard_bytes = 0
    try:
        bundle = getattr(getattr(el, "fw", None), "bundle", None)
        pspecs = getattr(bundle, "param_pspecs", None)
        if pspecs is not None and bundle.params is not None:
            shard_bytes = _pspec_audit(bundle.params, pspecs, model_par,
                                       node_label(node), node.pos, diags)
    except Exception:  # noqa: BLE001 - accounting probe only
        shard_bytes = 0
    try:
        batchable = bool(el.batch_capable())
    except Exception:  # noqa: BLE001 - capability probe only
        batchable = False
    outs = graph.out_edges(node.id)
    linear = (len(outs) <= 1 and all(e.src_pad == SRC for e in outs))
    return _NodeTrace(
        node=node, element=el, in_bytes=spec.nbytes, out_bytes=traced.nbytes,
        param_bytes=params, batchable=batchable,
        host_post=getattr(el, "host_post", None) is not None, linear=linear,
        param_shard_bytes=min(shard_bytes, params))


def _resources(graph, traces: Dict[int, _NodeTrace], *, batch_max, buckets,
               replicas, model_par, dispatch_depth, hbm_budget, max_variants,
               adaptive: bool = False) -> ResourceReport:
    """Merge traced nodes into planner-shaped stages (maximal linear chains
    fuse into ONE program, exactly the plan_stages rule) and multiply the
    per-stage estimates over the bucket ladder / replication plan."""
    lad = bucket_ladder(batch_max, buckets)
    chains: List[List[_NodeTrace]] = []
    consumed: set = set()
    for nid in traces:
        if nid in consumed:
            continue
        chain = [traces[nid]]
        consumed.add(nid)
        cur = nid
        while True:
            t = traces[cur]
            outs = graph.out_edges(cur)
            if not t.linear or len(outs) != 1:
                break
            nxt = outs[0].dst
            nt = traces.get(nxt)
            if (nt is None or nxt in consumed or not nt.linear
                    or outs[0].dst_pad != SINK
                    or len(graph.in_edges(nxt)) != 1):
                break
            chain.append(nt)
            consumed.add(nxt)
            cur = nxt
        chains.append(chain)

    stages: List[StageResource] = []
    for chain in chains:
        fused = len(chain) > 1
        # an unfused element without a batch path runs .process on HOST —
        # it compiles nothing and keeps nothing in HBM
        device = fused or chain[0].batchable \
            or chain[0].node.kind == "tensor_filter"
        if not device:
            continue
        batchable = fused or chain[0].batchable
        host_post = chain[-1].host_post
        shard_eligible = batchable and not host_post
        # a >1 model axis only reaches batchable stages when batching is
        # on (the runtime attaches the mesh to runners with batch_max>1)
        sharded = shard_eligible and (
            replicas > 1 or (model_par > 1 and batch_max > 1))
        n_buckets = 1
        rows = 1
        window = 1
        if batchable and batch_max > 1:
            window = dispatch_depth  # in-flight micro-batches per runner
            if sharded and replicas > 1:
                sb = sorted({shard_bucket_for(b, replicas, buckets)
                             for b in lad})
                n_buckets = len(sb)
                rows = sb[-1] // replicas
            else:
                n_buckets = len(lad)
                rows = lad[-1]
        # per-chip params: leaves the pspecs shard over `model` divide by
        # M when the stage actually places on a >1 model axis; the rest
        # (and every leaf of an unsharded stage) replicate full-size
        param_total = sum(t.param_bytes for t in chain)
        if sharded and model_par > 1:
            shard_part = sum(t.param_shard_bytes for t in chain)
            param_total = shard_part // model_par \
                + (param_total - shard_part)
        stages.append(StageResource(
            label="+".join(t.element.name for t in chain),
            param_bytes=param_total,
            act_row_bytes=max(t.in_bytes + t.out_bytes for t in chain),
            rows_per_device=rows * window,
            variants=n_buckets,
            batchable=batchable, shard_eligible=shard_eligible,
            sharded=sharded, pos=chain[0].node.pos))
    ladder_budget = 0
    if adaptive and batch_max > 1:
        # Worst-case census under the adaptive ladder: every batchable
        # stage priced at its full mint budget — the SAME arithmetic the
        # runtime hands each stage's AdaptiveLadder (plan.py), so minting
        # can never compile past what this report charged.  Minted sizes
        # never exceed the ladder top, so rows/HBM are unchanged.
        ladder_budget = adaptive_variant_budget(
            len(lad), sum(1 for s in stages if s.batchable),
            int(max_variants or 0))
        for s in stages:
            if s.batchable:
                s.variants = max(s.variants, ladder_budget)
    return ResourceReport(
        stages=stages, batch_max=batch_max, data_parallel=replicas,
        model_parallel=model_par, dispatch_depth=dispatch_depth, ladder=lad,
        hbm_budget_bytes=int(hbm_budget or 0),
        max_compiled_variants=int(max_variants or 0),
        adaptive_buckets=adaptive, ladder_budget=ladder_budget)


def _fetch_check(graph, traces: Dict[int, _NodeTrace], out_caps,
                 report: ResourceReport) -> List[Diagnostic]:
    """Price each sink edge's planned D2H bytes against the calibrated
    link (``Config.link_d2h_mbps`` / ``NNS_TPU_LINK_D2H_MBPS``, the bench
    ``link_calibration`` row) and flag ``fetch-bound`` pipelines — where
    the planned transfer time per buffer exceeds even the producing
    stages' HBM-roofline compute FLOOR, so no amount of compute overlap
    can hide the link — statically, before a chip is touched.

    The payload per edge is what the residency planner would actually
    ship: a producer whose device tail pairs ``device_fn`` with
    ``host_post`` crosses only its tiny traced device outputs (argmax ids,
    kept boxes); anything else crosses the negotiated spec.  The deep pass
    prices the pipeline AS WRITTEN — the runtime's reduced-output
    auto-selection can only shrink these numbers further (docs/FETCH.md).
    """
    diags: List[Diagnostic] = []
    # per-buffer compute floor: the slowest device stage bounds a
    # pipelined graph; each stage's floor is streaming its params + one
    # buffer's activations through HBM once
    floor_ms = max((compute_floor_ms(s.param_bytes + s.act_row_bytes)
                    for s in report.stages), default=0.0)
    for node in graph.nodes.values():
        cls = _element_class(node.kind)
        if cls is None or not getattr(cls, "is_sink", False):
            continue
        sink_label = node_label(node)
        for e in graph.in_edges(node.id):
            src_node = graph.nodes[e.src]
            t = traces.get(e.src)
            if t is not None:
                nbytes = t.out_bytes
                reduced = "fused host_post" if t.host_post else None
            else:
                up = out_caps.get((e.src, e.src_pad))
                spec = up.spec if up is not None else None
                nbytes = (-1 if spec is None or spec.is_flexible
                          else int(spec.nbytes))
                reduced = None
            edge = FetchEdge(sink=sink_label, producer=node_label(src_node),
                             bytes_per_buffer=nbytes, reduced=reduced,
                             compute_floor_ms=floor_ms)
            if report.link_d2h_mbps > 0 and nbytes >= 0:
                # bandwidth term ONLY: the RTT amortizes behind the async
                # fetch window (the whole point of fetch_depth), but link
                # OCCUPANCY is serial — bytes/bandwidth is the floor no
                # overlap can hide
                edge.d2h_ms = fetch_ms(nbytes, report.link_d2h_mbps)
                if report.stages and edge.d2h_ms > floor_ms:
                    diags.append(Diagnostic(
                        "fetch-bound", WARNING,
                        f"planned sink fetch of {nbytes} bytes/buffer "
                        f"occupies the calibrated d2h link for "
                        f"{edge.d2h_ms:.2f} ms ({report.link_d2h_mbps:g} "
                        f"MB/s), above the device stages' HBM-roofline "
                        f"compute floor of {floor_ms:.2f} ms — the "
                        "pipeline is fetch-bound: shrink what crosses "
                        "(fused sink reduction, reduced/native-stride "
                        "output, tensors/classmap decode modes) or "
                        "accept link-bound throughput",
                        path=sink_label, pos=node.pos))
            report.fetch_edges.append(edge)
    return diags


def _budget_diags(report: ResourceReport) -> List[Diagnostic]:
    """Budget checks, anchored at the dominant stage so the diagnostic
    carets point at the element to fix, not at the whole pipeline."""
    diags: List[Diagnostic] = []
    if report.hbm_budget_bytes and report.stages \
            and report.hbm_estimate > report.hbm_budget_bytes:
        top = max(report.stages, key=lambda s: s.hbm_bytes)
        diags.append(Diagnostic(
            "hbm-budget", WARNING,
            f"estimated HBM high-water {_mib(report.hbm_estimate)} exceeds "
            f"budget {_mib(report.hbm_budget_bytes)} (largest stage: "
            f"{_mib(top.hbm_bytes)} = params {_mib(top.param_bytes)} + "
            + (f"kv pool {_mib(top.pool_bytes)} + " if top.pool_bytes
               else "")
            + (f"train state {_mib(top.train_bytes)} + " if top.train_bytes
               else "")
            + f"{top.rows_per_device} row(s) x {_mib(top.act_row_bytes)}); "
            "shrink batch_max/buckets, raise data_parallel, or raise "
            "Config.hbm_budget_bytes"
            + (" (paged pools: shrink kv_blocks/slots — a smaller pool "
               "defers admission instead of overflowing)"
               if top.pool_bytes else "")
            + (" (training: shrink batch-size — the streaming window — "
               "or pick a lighter optimizer; sgd carries no moments)"
               if top.train_bytes else ""),
            path=top.label, pos=top.pos))
    if report.max_compiled_variants and report.stages \
            and report.compiled_variants > report.max_compiled_variants:
        top = max(report.stages, key=lambda s: s.variants)
        diags.append(Diagnostic(
            "recompile-budget", WARNING,
            f"{report.compiled_variants} distinct compiled signatures "
            f"(buckets x stages) exceed max_compiled_variants="
            f"{report.max_compiled_variants} (largest stage: {top.label} "
            f"with {top.variants}); trim batch_buckets or lower batch_max",
            path=top.label, pos=top.pos))
    return diags


# ---------------------------------------------------------------------------
# deep dogfood: abstract-trace the zoo our own plugin modules ship
# ---------------------------------------------------------------------------

#: zoo models the deep dogfood traces on every CI run: every bundled model
#: family that builds hermetically (no files, no net) with default opts.
ZOO_DOGFOOD = (
    "passthrough", "scaler", "average",
    "mobilenet_v1", "ssd_mobilenet", "posenet", "deeplab_mobilenet",
    "yolov5", "yolov8", "speech_commands",
)


def trace_zoo_models(names: Optional[Tuple[str, ...]] = None
                     ) -> Tuple[List[Diagnostic], int, int]:
    """Abstractly execute bundled zoo models against their own declared
    I/O specs: ``eval_shape`` through ``apply_fn`` with params AND inputs
    abstracted, diffing the traced output against ``bundle.out_spec``.
    Returns (diagnostics, traced count, skipped count)."""
    import jax

    from ..models import zoo

    diags: List[Diagnostic] = []
    traced = skipped = 0
    for name in names or ZOO_DOGFOOD:
        try:
            bundle = zoo.build(name, {})
        except Exception:  # noqa: BLE001 - optional deps may be absent
            skipped += 1
            continue
        if bundle.in_spec is None or bundle.out_spec is None:
            skipped += 1
            continue
        where = f"zoo:{name}"
        p_sds = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype)
            if hasattr(a, "shape") and hasattr(a, "dtype") else a,
            bundle.params)
        in_sds = tuple(jax.ShapeDtypeStruct(s.shape, s.dtype)
                       for s in bundle.in_spec)
        apply_fn = bundle.apply_fn

        def run(p, xs):
            out = apply_fn(p, *xs)
            return out if isinstance(out, (tuple, list)) else (out,)

        traced += 1
        try:
            out = jax.eval_shape(run, p_sds, in_sds)
        except Exception as e:  # noqa: BLE001 - the finding
            diags.append(Diagnostic(
                "trace-error", ERROR,
                f"abstract execution failed: {_trace_msg(e)}", path=where))
            continue
        got = TensorsSpec(tuple(
            TensorSpec.from_shape(tuple(s.shape), s.dtype) for s in out))
        declared = bundle.out_spec
        if not declared.is_flexible \
                and not got.is_compatible(_static(declared)):
            diags.append(Diagnostic(
                "trace-shape-mismatch", ERROR,
                "traced output disagrees with the bundle's declared "
                "out_spec: " + explain_mismatch(
                    Caps.tensors(got), Caps.tensors(_static(declared))),
                path=where))
    return diags, traced, skipped
