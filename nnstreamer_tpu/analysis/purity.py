"""jit-purity lint: AST pass over device functions and pure filter fns.

A ``device_fn`` hands the planner a *pure* ``arrays -> arrays`` function to
trace into a fused XLA program; a ``custom-easy`` model registered with
``jax_traceable=True`` makes the same promise.  Host side effects inside
those functions either break tracing outright (``.item()`` / ``float()`` on
a tracer raises ConcretizationTypeError) or silently poison the program
(``np.*`` math runs per-trace on host constants, Python RNG / ``time.*``
bake one host value into the compiled artifact, prints fire at trace time)
— and any of them silently disqualifies the element from fusion/batching.

The pass never imports JAX and never calls the functions: it reads source
via ``inspect``, resolves module aliases (``import numpy as np``) from the
function's globals, and walks the AST of the *pure parts*:

* for a ``device_fn`` method: every function defined INSIDE it (the
  returned closures) — the method body itself legitimately runs host-side
  spec math at plan time;
* for a registered traceable callable: the whole function.
"""

from __future__ import annotations

import ast
import inspect
import textwrap
import types
import weakref
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .diagnostics import Diagnostic, ERROR, WARNING

#: code -> severity for everything this pass can emit
CODES = {
    "jit-host-call": ERROR,      # numpy math / open() inside a traced fn
    "jit-host-sync": ERROR,      # .item() / float() / int() on a tracer
    "jit-rng": ERROR,            # Python or numpy RNG (use jax.random)
    "jit-host-time": WARNING,    # time.* baked in at trace time
    "jit-print": WARNING,        # fires once at trace time (jax.debug.print)
    "jit-global-mutation": ERROR,  # global/nonlocal writes from a traced fn
    "jit-state-mutation": WARNING,  # self.* assignment inside a traced fn
}

#: bare-name calls that are positively jit-legal even though they look
#: like framework plumbing: mesh collectives and sharding annotations
#: imported directly (``from jax.lax import psum``, ``from
#: jax.experimental.shard_map import shard_map``).  The sharded batching
#: device paths use these inside traced closures by design.
_JIT_LEGAL_NAMES = frozenset({
    "shard_map", "with_sharding_constraint", "psum", "pmean", "pmax",
    "pmin", "all_gather", "all_to_all", "ppermute", "axis_index",
})


def _classify_module(mod: str) -> Optional[str]:
    """Module name -> alias kind the linter's rules key on (None = a
    module we have no opinion about)."""
    if mod == "numpy":
        return "numpy"
    if mod == "numpy.random":
        return "rng"
    if mod == "time":
        return "time"
    if mod == "random":
        return "rng"
    if mod == "jax" or mod.startswith("jax."):
        # jax/jnp/jax.lax/jax.sharding/... — positively known jit-legal,
        # including when aliased to a suspicious name (``import jax.numpy
        # as np`` must never hit the numpy rules).
        return "jax"
    return None


def _module_aliases(namespace: Dict[str, object]) -> Dict[str, str]:
    """Names in ``namespace`` bound to host modules we care about."""
    out: Dict[str, str] = {}
    for nm, val in namespace.items():
        if not isinstance(val, types.ModuleType):
            continue
        kind = _classify_module(val.__name__)
        if kind is not None:
            out[nm] = kind
    return out


def _root_and_chain(expr) -> Tuple[Optional[str], List[str]]:
    """``np.random.default_rng`` -> ("np", ["random", "default_rng"])."""
    chain: List[str] = []
    while isinstance(expr, ast.Attribute):
        chain.append(expr.attr)
        expr = expr.value
    if isinstance(expr, ast.Name):
        return expr.id, list(reversed(chain))
    return None, list(reversed(chain))


class _PureFnLinter(ast.NodeVisitor):
    def __init__(self, aliases: Dict[str, str], where: str,
                 base_line: int = 0):
        # copy: function-local imports below SHADOW the module-level
        # aliases for this fn only (``import jax.numpy as np`` inside a
        # traced fn must beat a module-level ``import numpy as np``)
        self.aliases = dict(aliases)
        self.where = where
        self.base_line = base_line
        #: (code, msg, line, severity-override-or-None)
        self.found: List[Tuple[str, str, int, Optional[str]]] = []

    def _bind(self, name: str, mod: str) -> None:
        kind = _classify_module(mod)
        if kind is not None:
            self.aliases[name] = kind
        else:
            self.aliases.pop(name, None)  # shadowed by an unrelated module

    def visit_Import(self, node: ast.Import) -> None:
        for a in node.names:
            # plain ``import a.b`` binds the ROOT name; ``as`` binds the alias
            self._bind(a.asname or a.name.split(".")[0],
                       a.name if a.asname else a.name.split(".")[0])

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if not node.module:
            return
        for a in node.names:
            self._bind(a.asname or a.name, f"{node.module}.{a.name}")

    def _hit(self, code: str, msg: str, node,
             severity: Optional[str] = None) -> None:
        self.found.append(
            (code, msg, self.base_line + node.lineno, severity))

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        if isinstance(f, ast.Name):
            if f.id in _JIT_LEGAL_NAMES:
                pass  # collectives/sharding annotations: jit-legal
            elif f.id == "print":
                self._hit("jit-print",
                          "print() fires at trace time, not per buffer — "
                          "use jax.debug.print", node)
            elif f.id == "open":
                self._hit("jit-host-call", "file I/O inside a traced fn",
                          node)
            elif f.id in ("float", "int", "bool") and node.args and \
                    not isinstance(node.args[0], ast.Constant):
                # WARNING, not error: statically we cannot tell a traced
                # value from a plain host scalar (len(), shape math), and
                # only the former breaks under jit
                self._hit("jit-host-sync",
                          f"{f.id}() forces a host sync if its argument is "
                          "traced (ConcretizationTypeError under jit)",
                          node, severity=WARNING)
        elif isinstance(f, ast.Attribute):
            if f.attr == "item":
                self._hit("jit-host-sync",
                          ".item() forces a blocking device->host transfer "
                          "and breaks tracing", node)
            root, chain = _root_and_chain(f)
            kind = self.aliases.get(root) if root else None
            if kind == "jax":
                # Inside a traced fn, jax.* is the POINT: jnp math,
                # ``jax.lax`` collectives (psum / all_gather / ppermute),
                # ``shard_map`` and ``with_sharding_constraint`` are all
                # jit-legal — the sharded batching device paths lean on
                # them, and a false positive here would flunk the
                # dogfood gate.  Explicit branch so no later rule can
                # accidentally claim a jax-rooted call.
                pass
            elif kind == "numpy":
                if "random" in chain[:-1] or chain[-1].startswith("random"):
                    self._hit("jit-rng",
                              f"numpy RNG '{root}.{'.'.join(chain)}' is "
                              "host-side — use jax.random", node)
                else:
                    self._hit("jit-host-call",
                              f"host numpy call '{root}.{'.'.join(chain)}' "
                              "inside a traced fn (runs per trace, blocks "
                              "fusion) — use jax.numpy", node)
            elif kind == "rng":
                self._hit("jit-rng",
                          f"host RNG '{root}.{'.'.join(chain)}' — use "
                          "jax.random", node)
            elif kind == "time":
                self._hit("jit-host-time",
                          f"'{root}.{'.'.join(chain)}' is evaluated ONCE at "
                          "trace time and baked into the program", node)
        self.generic_visit(node)

    def visit_Global(self, node: ast.Global) -> None:
        self._hit("jit-global-mutation",
                  f"global {', '.join(node.names)} mutated from a traced fn",
                  node)

    def visit_Nonlocal(self, node: ast.Nonlocal) -> None:
        self._hit("jit-global-mutation",
                  f"nonlocal {', '.join(node.names)} mutated from a traced "
                  "fn", node)

    def visit_Assign(self, node: ast.Assign) -> None:
        for tgt in node.targets:
            if isinstance(tgt, ast.Attribute):
                root, chain = _root_and_chain(tgt)
                if root == "self":
                    self._hit("jit-state-mutation",
                              f"assignment to self.{'.'.join(chain)} inside "
                              "a traced fn runs at trace time only", node)
        self.generic_visit(node)


def _lint_fn_node(fn_node, aliases: Dict[str, str], where: str,
                  base_line: int) -> List[Tuple[str, str, int]]:
    linter = _PureFnLinter(aliases, where, base_line)
    body = fn_node.body if not isinstance(fn_node, ast.Lambda) \
        else [ast.Expr(fn_node.body)]
    for stmt in body:
        linter.visit(stmt)
    return linter.found


def _dedupe(found: Iterable[Tuple[str, str, int, Optional[str]]],
            where: str, pos: Optional[int] = None) -> List[Diagnostic]:
    out: List[Diagnostic] = []
    seen: Set[Tuple[str, str, int]] = set()
    for code, msg, line, severity in found:
        key = (code, msg, line)
        if key in seen:
            continue
        seen.add(key)
        out.append(Diagnostic(code, severity or CODES[code],
                              f"{msg} [line {line}]", path=where, pos=pos))
    return out


def _source_tree(obj) -> Optional[Tuple[ast.AST, int]]:
    """(parsed AST, 1-based first line) of ``obj``'s source, or None when
    source is unavailable/unparseable (builtins, REPL lambdas, ...)."""
    try:
        src, line = inspect.getsourcelines(obj)
    except (OSError, TypeError):
        return None
    try:
        tree = ast.parse(textwrap.dedent("".join(src)))
    except SyntaxError:
        return None
    return tree, line - 1


#: source parsing + AST walk results per function/class — Pipeline
#: construction with validate=True must not re-read files every time.
#: Weak keys: unregistered test callables don't pin their modules alive.
_fn_cache: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_cls_cache: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def _closure_aliases(fn, aliases: Dict[str, str]) -> Dict[str, str]:
    """Overlay closure-cell bindings onto the global alias map: a free
    variable bound in an enclosing scope (``import jax.numpy as np`` in
    the enclosing function) SHADOWS the module-level name, so resolve it
    from the live cell — module identity decides, not the alias name."""
    code = getattr(fn, "__code__", None)
    cells = getattr(fn, "__closure__", None)
    if code is None or not cells:
        return aliases
    out = dict(aliases)
    for name, cell in zip(code.co_freevars, cells):
        try:
            val = cell.cell_contents
        except ValueError:  # pragma: no cover - still-unbound cell
            continue
        if isinstance(val, types.ModuleType):
            kind = _classify_module(val.__name__)
            if kind is not None:
                out[name] = kind
            else:
                out.pop(name, None)
        else:
            out.pop(name, None)  # free var shadows a same-named module
    return out


def _callable_findings(fn) -> Tuple:
    try:
        return _fn_cache[fn]
    except (KeyError, TypeError):
        pass
    got = _source_tree(fn)
    found: Tuple = ()
    if got is not None:
        tree, base = got
        aliases = _closure_aliases(
            fn, _module_aliases(getattr(fn, "__globals__", {}) or {}))
        fns = [n for n in ast.walk(tree)
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda))]
        if fns:
            found = tuple(_lint_fn_node(fns[0], aliases, "", base))
    try:
        _fn_cache[fn] = found
    except TypeError:
        pass
    return found


def lint_callable(fn, where: str, *, pos: Optional[int] = None
                  ) -> List[Diagnostic]:
    """Lint a function that promises to be jit-traceable (its WHOLE body
    is the pure part) — e.g. a custom-easy model with jax_traceable=True."""
    return _dedupe(_callable_findings(fn), where, pos)


def _device_fn_findings(cls) -> Tuple:
    try:
        return _cls_cache[cls]
    except (KeyError, TypeError):
        pass
    found: Tuple = ()
    fn = cls.__dict__.get("device_fn")
    got = _source_tree(fn) if fn is not None else None
    if got is not None:
        tree, base = got
        mod = inspect.getmodule(cls)
        aliases = _module_aliases(vars(mod) if mod else {})
        outer = next((n for n in ast.walk(tree)
                      if isinstance(n, ast.FunctionDef)
                      and n.name == "device_fn"), None)
        if outer is not None:
            acc: List = []
            for n in ast.walk(outer):
                if n is outer:
                    continue
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                    acc.extend(_lint_fn_node(n, aliases, "", base))
            found = tuple(acc)
    try:
        _cls_cache[cls] = found
    except TypeError:
        pass
    return found


def lint_device_fn(cls, where: Optional[str] = None, *,
                   pos: Optional[int] = None) -> List[Diagnostic]:
    """Lint the pure closures a class's own ``device_fn`` builds.

    Only functions *defined inside* device_fn are checked: the method body
    itself runs host-side at plan time (spec math, prop parsing) and is
    allowed to use numpy.
    """
    where = where or f"{cls.__module__}.{cls.__name__}.device_fn"
    return _dedupe(_device_fn_findings(cls), where, pos)


def lint_graph(graph) -> List[Diagnostic]:
    """Purity pass over one pipeline: device_fns of every element kind in
    the graph, decoder sub-plugins selected by ``mode=``, and custom-easy
    models registered as jax_traceable."""
    from ..core.registry import (
        KIND_DECODER, KIND_ELEMENT, lookup)
    from ..elements.base import Element

    diags: List[Diagnostic] = []
    seen: Set[object] = set()
    for node in graph.nodes.values():
        cls = lookup(KIND_ELEMENT, node.kind)
        if cls is None:
            continue
        if cls not in seen and cls.__dict__.get("device_fn") is not None \
                and cls.__dict__["device_fn"] is not Element.device_fn:
            seen.add(cls)
            diags.extend(lint_device_fn(cls, pos=node.pos))
        if node.kind == "tensor_decoder" and node.props.get("mode"):
            dcls = lookup(KIND_DECODER, str(node.props["mode"]))
            if dcls is not None and dcls not in seen \
                    and dcls.__dict__.get("device_fn") is not None:
                seen.add(dcls)
                diags.extend(lint_device_fn(dcls, pos=node.pos))
        if node.kind == "tensor_filter" and \
                str(node.props.get("framework", "")).lower() == "custom-easy":
            from ..filters.custom_easy import _models

            entry = _models.get(str(node.props.get("model")))
            if entry is not None:
                fn, traceable = entry[0], entry[3]
                if traceable and fn not in seen:
                    seen.add(fn)
                    diags.extend(lint_callable(
                        fn, f"custom-easy:{node.props.get('model')}",
                        pos=node.pos))
    return diags


def lint_module(module) -> List[Diagnostic]:
    """Dogfood entry point: lint every device_fn defined in ``module``
    (element classes, decoder sub-plugins) — CI runs this over the
    framework's own plugin modules so a host-side regression in OUR
    shipped elements fails the gate."""
    diags: List[Diagnostic] = []
    for nm, obj in vars(module).items():
        if not isinstance(obj, type) or obj.__module__ != module.__name__:
            continue
        if "device_fn" in obj.__dict__:
            diags.extend(lint_device_fn(obj))
    return diags
