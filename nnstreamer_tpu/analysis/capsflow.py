"""Whole-graph caps/spec propagation — the analyzer's negotiation pass.

Propagates :class:`~nnstreamer_tpu.core.caps.Caps` through every edge of a
parsed graph in topological order, the way the runtime negotiates — but
*offline*: no device, no JAX, no model files, and it does not stop at the
first failure.  Three mechanisms, from cheapest to deepest:

1. **pad templates** (``Element.PAD_TEMPLATES``, class metadata): every
   edge's propagated caps are intersected with the downstream pad's
   template via :func:`~nnstreamer_tpu.core.caps.intersect_template`; a
   miss is a ``caps-mismatch`` diagnostic carrying the field-level reason
   (``media video/x-raw ⊄ other/tensors``).
2. **safe configure**: element kinds whose constructor+configure are pure
   caps math (sources, converter, transform, routing, video, ...) are
   instantiated and their real ``configure`` runs, so the analyzer
   computes exactly what the runtime would — an ``ElementError`` becomes
   a diagnostic and propagation continues with ANY so the REST of the
   graph still gets checked.
3. **static transfers** for kinds whose configure touches the outside
   world (``tensor_filter`` loads a model, query/edge elements open
   sockets): a pure-props transfer that still checks the upstream spec
   against declared/registered model I/O (``dtype uint8 ⊄ float32``).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..core.caps import (
    Caps,
    MediaType,
    explain_mismatch,
    intersect_template,
)
from ..core.registry import KIND_ELEMENT, lookup
from ..core.types import TensorFormat, TensorsSpec
from ..elements.base import Element, ElementError, SINK, SRC
from ..pipeline.graph import PipelineGraph
from .diagnostics import Diagnostic, ERROR, WARNING, edge_path, node_label

#: kinds whose __init__ + configure are pure caps/props math — safe to run
#: offline.  Anything NOT listed here (and without a static transfer below)
#: is treated as opaque: templates still apply, output caps become ANY.
SAFE_CONFIGURE = frozenset({
    "appsrc", "videotestsrc", "audiotestsrc",
    "tensor_converter", "tensor_transform", "tensor_aggregator",
    "tee", "queue", "join",
    "tensor_mux", "tensor_demux", "tensor_merge", "tensor_split",
    "tensor_if", "tensor_crop", "tensor_rateadjust",
    "tensor_sparse_enc", "tensor_sparse_dec",
    "videoconvert", "videoscale", "compositor",
    "tensor_debug", "tensor_sink", "fakesink",
    "tensor_reposink", "tensor_reposrc",
    "tensor_decoder",
    # nns-learn: configure() only emits the fixed stats spec — pure
    "tensor_trainer",
})


def propagate(
    graph: PipelineGraph,
) -> Tuple[List[Diagnostic], Dict[Tuple[int, str], Caps]]:
    """Run the pass.  Returns (diagnostics, out-caps per (node_id, pad))."""
    diags: List[Diagnostic] = []
    out_caps: Dict[Tuple[int, str], Caps] = {}

    for node in _kahn_order(graph):
        in_caps: Dict[str, Caps] = {}
        for e in graph.in_edges(node.id):
            up = out_caps.get((e.src, e.src_pad), Caps.any())
            in_caps[e.dst_pad] = up
            # pad-template admission check (pure class metadata)
            cls = _element_class(node.kind)
            if cls is not None and not up.is_any():
                tmpl = cls.pad_template(e.dst_pad)
                if intersect_template(up, tmpl) is None:
                    t0 = tmpl[0] if isinstance(tmpl, tuple) else tmpl
                    diags.append(Diagnostic(
                        "caps-mismatch", ERROR, explain_mismatch(up, t0),
                        path=edge_path(graph, e), pos=node.pos))
                    in_caps[e.dst_pad] = Caps.any()  # keep flowing

        out_pads = sorted(
            {e.src_pad for e in graph.out_edges(node.id)}) or [SRC]
        produced, node_diags = _transfer(graph, node, in_caps, out_pads)
        diags.extend(node_diags)
        for pad in out_pads:
            out_caps[(node.id, pad)] = produced.get(pad, Caps.any())

    diags.extend(_check_demux_arity(graph, out_caps))
    return diags, out_caps


def _kahn_order(graph: PipelineGraph):
    """Topological order that tolerates cycles: leftover (cyclic) nodes are
    simply skipped here — the topology pass reports the cycle itself."""
    indeg = {i: len(graph.in_edges(i)) for i in graph.nodes}
    ready = sorted(i for i, d in indeg.items() if d == 0)
    while ready:
        i = ready.pop(0)
        yield graph.nodes[i]
        for e in graph.out_edges(i):
            indeg[e.dst] -= 1
            if indeg[e.dst] == 0:
                ready.append(e.dst)
        ready.sort()


def _element_class(kind: str) -> Optional[type]:
    if kind == "capsfilter":
        return None
    cls = lookup(KIND_ELEMENT, kind)
    return cls if isinstance(cls, type) and issubclass(cls, Element) else None


def _transfer(graph, node, in_caps: Dict[str, Caps], out_pads: List[str]
              ) -> Tuple[Dict[str, Caps], List[Diagnostic]]:
    """Out caps for one node + any diagnostics it produced."""
    if node.kind == "capsfilter":
        src = next(iter(in_caps.values()), Caps.any())
        merged = src.intersect(node.caps or Caps.any())
        if merged is None:
            return (
                {p: node.caps for p in out_pads},
                [Diagnostic(
                    "caps-mismatch", ERROR,
                    explain_mismatch(src, node.caps),
                    path=f"{node_label(node)}:sink", pos=node.pos)],
            )
        return {p: merged for p in out_pads}, []

    if node.kind == "tensor_filter":
        return _filter_transfer(node, in_caps, out_pads)

    cls = _element_class(node.kind)
    if cls is None or node.kind not in SAFE_CONFIGURE:
        # opaque element: honor its src template, else ANY
        tmpl = Caps.any() if cls is None else cls.pad_template(SRC)
        t0 = tmpl[0] if isinstance(tmpl, tuple) else tmpl
        return {p: t0 for p in out_pads}, []

    try:
        el = cls(dict(node.props), name=node.name or f"{node.kind}{node.id}")
        produced = el.configure(dict(in_caps), list(out_pads))
        return dict(produced), []
    except (ElementError, ValueError, KeyError) as e:
        return (
            {p: Caps.any() for p in out_pads},
            [Diagnostic(
                "caps-incompat", ERROR, str(e),
                path=node_label(node), pos=node.pos)],
        )
    except Exception:  # noqa: BLE001 - environment-dependent (files, ...)
        return {p: Caps.any() for p in out_pads}, []


def _filter_transfer(node, in_caps: Dict[str, Caps], out_pads: List[str]
                     ) -> Tuple[Dict[str, Caps], List[Diagnostic]]:
    """Static tensor_filter transfer: NEVER loads a framework/model.

    Model I/O is taken from explicit ``input``/``output`` props, or — for
    ``framework=custom-easy`` — from the in-process model registry (a plain
    dict lookup).  The upstream spec is checked against the model input the
    same way configure() does, with input-combination selection applied.
    """
    diags: List[Diagnostic] = []
    props = node.props

    def bad_prop(msg: str) -> None:
        diags.append(Diagnostic("caps-incompat", ERROR, msg,
                                path=node_label(node), pos=node.pos))

    declared_in = declared_out = None
    try:
        if props.get("input"):
            declared_in = TensorsSpec.from_string(
                str(props["input"]), str(props.get("inputtype", "float32")))
        if props.get("output"):
            declared_out = TensorsSpec.from_string(
                str(props["output"]), str(props.get("outputtype", "float32")))
    except ValueError as e:  # malformed dims/dtype string is a FINDING,
        bad_prop(str(e))     # not an analyzer crash
        return {p: Caps.new(MediaType.TENSORS) for p in out_pads}, diags
    if str(props.get("framework", "")).lower() == "custom-easy":
        from ..filters.custom_easy import _models

        entry = _models.get(str(props.get("model")))
        if entry is not None:
            reg_in, reg_out = entry[1], entry[2]
            declared_in = declared_in or reg_in
            declared_out = declared_out or reg_out

    src = next(iter(in_caps.values()), Caps.any())
    up_spec = src.spec
    if up_spec is not None and not up_spec.is_flexible:
        combo = str(props.get("input_combination", "")).strip()
        if combo:
            try:
                idxs = [int(v) for v in combo.split(",")]
            except ValueError:
                bad_prop(f"input-combination {combo!r} is not a "
                         "comma-separated index list")
                idxs, up_spec = [], None
            if up_spec is not None and any(i >= len(up_spec) for i in idxs):
                diags.append(Diagnostic(
                    "caps-incompat", ERROR,
                    f"input-combination {idxs} out of range for upstream "
                    f"spec ({len(up_spec)} tensors)",
                    path=node_label(node), pos=node.pos))
                up_spec = None
            elif up_spec is not None:
                up_spec = TensorsSpec(
                    tuple(up_spec[i] for i in idxs), rate=up_spec.rate)
        if up_spec is not None and declared_in is not None \
                and not up_spec.is_compatible(declared_in):
            diags.append(Diagnostic(
                "caps-mismatch", ERROR,
                explain_mismatch(Caps.tensors(up_spec),
                                 Caps.tensors(declared_in)),
                path=f"{node_label(node)}:sink", pos=node.pos))

    out_spec = declared_out
    if out_spec is not None and bool(props.get("invoke_dynamic", False)):
        out_spec = out_spec.replace(format=TensorFormat.FLEXIBLE)
    caps = Caps.tensors(out_spec) if out_spec is not None else Caps.new(
        MediaType.TENSORS)
    return {p: caps for p in out_pads}, diags


def _check_demux_arity(graph, out_caps) -> List[Diagnostic]:
    """Numbered src pads past what the negotiated spec can supply.

    tensor_demux emits one stream per (picked) upstream tensor: a link from
    ``src_3`` of a demux whose input has 2 tensors can never see a buffer.
    """
    diags: List[Diagnostic] = []
    for node in graph.nodes.values():
        if node.kind != "tensor_demux":
            continue
        if str(node.props.get("by-meta", node.props.get("by_meta", ""))):
            # meta routing forwards the WHOLE buffer to one pad chosen
            # by a meta value: every pad can emit, the per-tensor arity
            # rule does not apply
            continue
        ins = graph.in_edges(node.id)
        if not ins:
            continue
        up = out_caps.get((ins[0].src, ins[0].src_pad))
        spec = up.spec if up is not None else None
        if spec is None or spec.is_flexible:
            continue
        pick = str(node.props.get("tensorpick", ""))
        try:
            idxs = ([int(v) for v in pick.split(",") if v != ""]
                    if pick else None)
        except ValueError:
            diags.append(Diagnostic(
                "caps-incompat", ERROR,
                f"tensorpick {pick!r} is not a comma-separated index list",
                path=node_label(node), pos=node.pos))
            continue
        n_out = len(idxs) if idxs else len(spec)
        if idxs and any(i >= len(spec) for i in idxs):
            diags.append(Diagnostic(
                "caps-incompat", ERROR,
                f"tensorpick {idxs} out of range for upstream spec "
                f"({len(spec)} tensors)", path=node_label(node),
                pos=node.pos))
            continue
        for e in graph.out_edges(node.id):
            base, sep, i = e.src_pad.rpartition("_")
            if sep and i.isdigit() and int(i) >= n_out:
                diags.append(Diagnostic(
                    "pad-arity", ERROR,
                    f"demux pad {e.src_pad} can never emit: input supplies "
                    f"{n_out} stream(s)", path=edge_path(graph, e),
                    pos=node.pos))
    return diags
